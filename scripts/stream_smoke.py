"""Streaming out-of-core smoke: sharded chunking + the H2D prefetch
ring on a 2-device CPU mesh.

CI gate for the streaming pipeline (docs/ARCHITECTURE.md "Streaming
out-of-core pipeline"): renders a tiny warehouse, forces a 2-device
virtual mesh, streams the fact through the chunked SPMD executor in
>= 3 launches, and proves two things off-hardware:

* **bit-identity** — distributed-chunked rows (values AND order) equal
  the single-chip chunked path and the numpy oracle, at prefetch depth
  0 and 2;
* **overlap** — with a latency-padded scan source (a stand-in for real
  disk/decode cost), the foreground scan stall ``io.scan.wait_s`` is
  >= 80% of the chunked execute wall when streaming synchronously
  (depth 0) and < 20% with the prefetch ring on (depth 2), measured on
  the repeat pass so compile time is out of the window.  The absorbed
  latency shows up in ``io.scan.wait_bg_s``/``engine.h2d.overlap_s``
  and the ring must actually serve hits (``io.prefetch.hit``).
"""
from __future__ import annotations

import os
import pathlib
import subprocess
import sys
import tempfile
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

N_DEV = 2
CHUNK_ROWS = 1000        # >= 3 launches at SF 0.002 (store_sales ~7k rows)
READ_SLEEP_S = 0.08      # synthetic disk/decode latency per shard read

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + f" --xla_force_host_platform_device_count={N_DEV}"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# row-mode spine: chunk outputs concatenate and the threaded __rowid__
# must restore the exact single-chip row order
SQL = ("select ss_item_sk, ss_quantity from store_sales "
       "where ss_quantity > 90")


class SlowTableChunkSource:
    """TableChunkSource with a per-read latency pad, standing in for a
    real out-of-core source (disk seek + parquet decode)."""

    def __init__(self, inner):
        self._inner = inner
        self.table = inner.table
        self.columns = inner.columns
        self.num_rows = inner.num_rows

    def column_meta(self):
        return self._inner.column_meta()

    def read(self, start, count):
        time.sleep(READ_SLEEP_S)
        return self._inner.read(start, count)


def chunked_exec(catalog, n_dev, depth, plan):
    from ndstpu.parallel import dplan, mesh as pmesh
    exe = dplan.DistributedPlanExecutor(
        catalog, pmesh.make_mesh(n_dev), shard_threshold_rows=500,
        broadcast_limit_rows=50, chunk_rows=CHUNK_ROWS,
        prefetch_depth=depth)
    return list(map(str, exe.execute_plan(plan).to_rows())), exe


def main() -> int:
    from ndstpu import obs
    from ndstpu.engine import physical
    from ndstpu.engine.session import Session
    from ndstpu.io import loader

    root = pathlib.Path(tempfile.mkdtemp(prefix="ndstpu_stream_smoke"))
    env = dict(os.environ, PYTHONPATH=str(REPO))
    for cmd in (
        [sys.executable, "-m", "ndstpu.datagen.driver", "local",
         "0.002", "2", str(root / "raw")],
        [sys.executable, "-m", "ndstpu.io.transcode",
         "--input_prefix", str(root / "raw"),
         "--output_prefix", str(root / "wh"),
         "--report_file", str(root / "load.txt")],
    ):
        print("+", " ".join(cmd), flush=True)
        subprocess.run(cmd, check=True, env=env,
                       stdout=subprocess.DEVNULL)

    assert len(jax.devices()) == N_DEV, \
        f"expected a {N_DEV}-device mesh, got {len(jax.devices())}"
    catalog = loader.load_catalog(str(root / "wh"))
    plan, _ = Session(catalog, backend="cpu").plan(SQL)
    oracle = list(map(str, physical.execute(plan, catalog).to_rows()))

    # latency-padded scan source: the overlap numbers below are about
    # hiding THIS cost behind compute
    fact = catalog.get("store_sales")
    loader.attach_stream_source(
        catalog, "store_sales", SlowTableChunkSource(
            loader.TableChunkSource(
                fact, "store_sales", ["ss_item_sk", "ss_quantity"])))

    failures = []

    single, exe1 = chunked_exec(catalog, 1, 2, plan)
    if not exe1._chunk_info[0]:
        failures.append("single-chip run did not chunk")
    if single != oracle:
        failures.append("single-chip chunked rows != numpy oracle")

    ratios = {}
    walls = {}
    for depth in (0, 2):
        rows, exe = chunked_exec(catalog, N_DEV, depth, plan)
        chunked, n_launches = exe._chunk_info[0], exe._chunk_info[1]
        if not chunked or n_launches < 3:
            failures.append(
                f"depth {depth}: expected >=3 chunked launches, got "
                f"chunked={chunked} n_launches={n_launches}")
        if rows != oracle:
            failures.append(
                f"depth {depth}: distributed-chunked rows are not "
                f"bit-identical to the oracle")
        # measure the repeat pass: same chunks, no compile in the wall
        before = obs.counters_snapshot()
        again = list(map(str, exe.execute_again().to_rows()))
        d = obs.counter_delta(before)
        if again != oracle:
            failures.append(f"depth {depth}: repeat pass rows differ")
        wall = d.get("engine.stream.execute_s", 0.0)
        wait = d.get("io.scan.wait_s", 0.0)
        ratio = wait / wall if wall else float("nan")
        ratios[depth] = ratio
        walls[depth] = wall
        hits = d.get("io.prefetch.hit", 0)
        print(f"  depth {depth}: execute_wall={wall:.3f}s "
              f"scan_wait={wait:.3f}s ({100 * ratio:.0f}%) "
              f"bg_wait={d.get('io.scan.wait_bg_s', 0.0):.3f}s "
              f"h2d_overlap={d.get('engine.h2d.overlap_s', 0.0):.3f}s "
              f"h2d_bytes={d.get('engine.h2d.bytes', 0)} "
              f"prefetch_hits={hits} launches={n_launches}",
              flush=True)
        if depth == 2 and hits == 0:
            failures.append("depth 2: prefetch ring served no hits")

    if not ratios[0] >= 0.8:
        failures.append(
            f"sync streaming should be scan-bound: io.scan.wait_s is "
            f"{100 * ratios[0]:.0f}% of the execute wall (want >= 80%)")
    if not ratios[2] < 0.2:
        failures.append(
            f"prefetch-on scan stall is {100 * ratios[2]:.0f}% of the "
            f"execute wall (want < 20%)")

    if failures:
        print("\nstream smoke FAILED:")
        for f in failures:
            print(f"  {f}")
        return 1
    print(f"\nstream smoke ok: {len(oracle)} rows bit-identical on a "
          f"{N_DEV}-device mesh at depth 0 and 2, scan stall "
          f"{100 * ratios[0]:.0f}% -> {100 * ratios[2]:.0f}% of the "
          f"execute wall ({walls[0]:.2f}s -> {walls[2]:.2f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
