"""Build (or reuse) the .bench_cache raw+warehouse pair for one SF.

Thin CLI over bench.ensure_warehouse (same artifact contract: tmp dir
renamed on success, .genfp source-fingerprint stamps) but with no phase
time caps and visible subprocess output, so SF10+ builds on a slow host
aren't killed mid-generation.

Usage: python scripts/build_wh.py <SF>
"""
from __future__ import annotations

import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import bench  # noqa: E402  (repo-root bench.py)


def main() -> int:
    sf = float(sys.argv[1]) if len(sys.argv) > 1 else 1.0
    wh = bench.ensure_warehouse(
        sf, quiet=False,
        on_phase=lambda p: print(f"phase: {p}", flush=True))
    print(f"warehouse ready: {wh}", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
