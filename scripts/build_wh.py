"""Build (or reuse) the .bench_cache raw+warehouse pair for one SF.

Same artifact contract as bench.py's _ensure_warehouse (tmp dir renamed
on success, .genfp source-fingerprint stamps) but with no phase time
caps, so SF10+ builds on a slow host aren't killed mid-generation.

Usage: python scripts/build_wh.py <SF>
"""
from __future__ import annotations

import os
import pathlib
import shutil
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import bench  # noqa: E402  (repo-root bench.py: stamp + source lists)


def main() -> int:
    sf = float(sys.argv[1]) if len(sys.argv) > 1 else 1.0
    tag = f"sf{sf:g}"
    cache = REPO / ".bench_cache"
    raw = cache / f"raw_{tag}"
    wh = cache / f"wh_{tag}"
    raw_fp = bench._src_fingerprint(bench._GEN_SRCS)
    wh_fp = bench._src_fingerprint(bench._WH_SRCS)
    for d, fp in ((raw, raw_fp), (wh, wh_fp)):
        if d.is_dir() and os.listdir(d) and not bench._stamp_ok(str(d), fp):
            print(f"stale stamp: rebuilding {d}", flush=True)
            shutil.rmtree(d, ignore_errors=True)
    pp = os.environ.get("PYTHONPATH", "")
    env = dict(os.environ,
               PYTHONPATH=f"{REPO}{os.pathsep}{pp}" if pp else str(REPO))
    for d in (f"{raw}_tmp_", f"{wh}_tmp_"):
        shutil.rmtree(d, ignore_errors=True)
    if not (wh.is_dir() and os.listdir(wh)):
        if not (raw.is_dir() and os.listdir(raw)):
            tmp = pathlib.Path(f"{raw}_tmp_")
            tmp.mkdir(parents=True, exist_ok=True)
            try:
                subprocess.run(
                    [sys.executable, "-m", "ndstpu.datagen.driver",
                     "local", f"{sf:g}", "2", str(tmp),
                     "--overwrite_output"],
                    check=True, env=env, cwd=str(REPO))
            except BaseException:
                shutil.rmtree(tmp, ignore_errors=True)
                raise
            (tmp / ".genfp").write_text(raw_fp)
            os.rename(tmp, raw)
            print(f"raw done: {raw}", flush=True)
        tmp = pathlib.Path(f"{wh}_tmp_")
        tmp.mkdir(parents=True, exist_ok=True)
        try:
            subprocess.run(
                [sys.executable, "-m", "ndstpu.io.transcode",
                 "--input_prefix", str(raw), "--output_prefix", str(tmp),
                 "--report_file", str(tmp / "load.txt")],
                check=True, env=env, cwd=str(REPO))
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        (tmp / ".genfp").write_text(wh_fp)
        os.rename(tmp, wh)
    print(f"warehouse ready: {wh}", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
