"""SF10 full-corpus power run + spot validation on the real chip.

VERDICT r4 #1: convert "beats a numpy interpreter at SF1" into a scale
claim.  Pipeline (expects .bench_cache/wh_sf10 to exist — bench.py's
_ensure_warehouse or scripts in this round build + stamp it):

1. full-corpus discover + steady pass at SF10 via scripts/warm_corpus.py
   machinery (per-query watchdog; persisted records + XLA cache) —
   writes .bench_cache/warm_report_sf10.json
2. spot validation: N queries run through the power CLI on BOTH engines
   (tpu vs numpy cpu) and compared by the validate CLI with reference
   epsilon semantics
3. assembles docs/SF10_BENCH.json: per-query discover/steady seconds,
   steady totals, the SF10 Load Test time, and validation verdicts

Usage:
    python scripts/sf10_bench.py [--validate_queries q3,q7,...]
    python scripts/sf10_bench.py --skip_corpus   # only validate+assemble
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
CACHE = REPO / ".bench_cache"

DEFAULT_VALIDATE = ("query3,query7,query15,query21,query26,query37,"
                    "query42,query43,query52,query55,query82,query96")


def run_corpus() -> None:
    env = dict(os.environ, NDSTPU_BENCH_SF="10",
               NDSTPU_WARM_QUERY_TIMEOUT_S=os.environ.get(
                   "NDSTPU_WARM_QUERY_TIMEOUT_S", "2400"))
    subprocess.run([sys.executable, str(REPO / "scripts" / "warm_corpus.py")],
                   check=True, env=env, cwd=str(REPO))


def run_validation(queries: str, out_dir: pathlib.Path) -> dict:
    wh = str(CACHE / "wh_sf10")
    streams = out_dir / "streams"
    subprocess.run([sys.executable, "-m", "ndstpu.queries.streamgen",
                    "--streams", "1", "--rngseed", "07291122510",
                    "--output_dir", str(streams)],
                   check=True, cwd=str(REPO))
    stream = str(streams / "query_0.sql")
    env = dict(os.environ,
               NDSTPU_XLA_CACHE_DIR=str(CACHE / "xla_cache_tpu"))
    for engine, prefix in (("tpu", "t"), ("cpu", "c")):
        subprocess.run(
            [sys.executable, "-m", "ndstpu.harness.power", stream, wh,
             str(out_dir / f"time_{prefix}.csv"), "--engine", engine,
             "--output_prefix", str(out_dir / prefix),
             "--compile_records", str(CACHE / "plans_sf10.pkl"),
             "--sub_queries", queries],
            check=True, env=env, cwd=str(REPO))
    r = subprocess.run(
        [sys.executable, "-m", "ndstpu.harness.validate",
         str(out_dir / "t"), str(out_dir / "c"), stream,
         "--ignore_ordering", "--sub_queries", queries],
        capture_output=True, text=True, cwd=str(REPO))
    passed = [q for q in queries.split(",")
              if f"Result match for {q} " in r.stdout]
    return {"queries": queries.split(","), "passed": passed,
            "all_match": "All queries match." in r.stdout,
            "validate_exit": r.returncode}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--validate_queries", default=DEFAULT_VALIDATE)
    ap.add_argument("--skip_corpus", action="store_true")
    ap.add_argument("--skip_validation", action="store_true")
    args = ap.parse_args()
    if not args.skip_corpus:
        run_corpus()
    report = {}
    warm_path = CACHE / "warm_report_sf10.json"
    if warm_path.exists():
        warm = json.loads(warm_path.read_text())
        steady = warm.get("steady", {})
        report["per_query"] = {
            q: {"discover_s": warm.get("discover", {}).get(q),
                "steady_s": s}
            for q, s in steady.items()}
        report["queries_steady"] = len(steady)
        report["steady_total_s"] = round(sum(steady.values()), 2)
        report["failed"] = warm.get("failed", {})
    for cand in (CACHE / "wh_sf10" / "load.txt",
                 CACHE / "wh_sf10_r5_load.txt"):
        try:
            for line in open(cand):
                if "Load Test Time" in line:
                    report["load_test_s"] = float(
                        line.split(":")[1].split()[0])
            if "load_test_s" in report:
                break
        except OSError:
            continue
    if not args.skip_validation:
        vdir = pathlib.Path("/tmp/sf10_validate")
        import shutil
        shutil.rmtree(vdir, ignore_errors=True)
        vdir.mkdir(parents=True)
        report["validation"] = run_validation(args.validate_queries, vdir)
    out = REPO / "docs" / "SF10_BENCH.json"
    out.write_text(json.dumps(report, indent=1))
    print(json.dumps({k: v for k, v in report.items()
                      if k != "per_query"}, indent=1))
    print(f"written: {out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
