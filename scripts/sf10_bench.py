"""SF10 full-corpus power run + spot validation on the real chip.

VERDICT r4 #1: convert "beats a numpy interpreter at SF1" into a scale
claim.  Pipeline (expects .bench_cache/wh_sf10 to exist — bench.py's
_ensure_warehouse or scripts in this round build + stamp it):

1. full-corpus discover + steady pass at SF10 via scripts/warm_corpus.py
   machinery (per-query watchdog; persisted records + XLA cache) —
   writes .bench_cache/warm_report_sf10.json
2. spot validation: N queries run through the power CLI on BOTH engines
   (tpu vs numpy cpu) and compared by the validate CLI with reference
   epsilon semantics
3. assembles docs/SF10_BENCH.json: per-query discover/steady seconds,
   steady totals, the SF10 Load Test time, and validation verdicts

Usage:
    python scripts/sf10_bench.py [--validate_queries q3,q7,...]
    python scripts/sf10_bench.py --skip_corpus   # only validate+assemble
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
CACHE = REPO / ".bench_cache"

DEFAULT_VALIDATE = ("query3,query7,query15,query21,query26,query37,"
                    "query42,query43,query52,query55,query82,query96")


def run_corpus() -> None:
    env = dict(os.environ, NDSTPU_BENCH_SF="10",
               NDSTPU_WARM_QUERY_TIMEOUT_S=os.environ.get(
                   "NDSTPU_WARM_QUERY_TIMEOUT_S", "2400"))
    subprocess.run([sys.executable, str(REPO / "scripts" / "warm_corpus.py")],
                   check=True, env=env, cwd=str(REPO))


def run_cpu_baseline(deadline_s: float) -> dict:
    """Serial numpy-interpreter power pass over the full corpus at SF10.

    Same denominator semantics as bench.py's cpu-baseline phase
    (reference analog: the power_run CPU path, nds/nds_power.py:183-304):
    wall clock around each result materialization, one process, same
    host.  Reuses bench._power_run with the CPU watchdog on — a
    deadline cut records whatever completed, and a single wedged numpy
    query costs at most NDSTPU_CPU_QUERY_TIMEOUT_S, never the whole
    remaining budget."""
    import time

    sys.path.insert(0, str(REPO))
    import bench
    from ndstpu.engine.session import Session
    from ndstpu.io import loader
    from ndstpu.queries import streamgen

    catalog = loader.load_catalog(str(CACHE / "wh_sf10"))
    queries = streamgen.render_power_corpus()
    times: dict = {}
    failed: list = []
    reasons: dict = {}
    per_q = float(os.environ.get("NDSTPU_CPU_QUERY_TIMEOUT_S", "900"))
    ran_all = bench._power_run(
        Session(catalog, backend="cpu"), queries, times, failed,
        stop_at=time.time() + deadline_s,
        rebuild=lambda: Session(catalog, backend="cpu"),
        watchdog=True, per_query_timeout=per_q, progress=True,
        hang_abort=0, reasons=reasons)
    complete = ran_all and len(times) == len(queries) and not failed
    out = {"cpu_times": times, "cpu_failed": reasons,
           "cpu_total_s": round(sum(times.values()), 2),
           "cpu_queries": len(times), "complete": complete,
           "fingerprint": _baseline_fingerprint()}
    # cache ONLY complete clean runs (bench.py's cpu-cache rule): a
    # deadline-cut or failing pass must not silently become the
    # denominator of every later SF10_BENCH assembly
    if complete:
        (CACHE / "cpu_times_sf10_power.json").write_text(json.dumps(out))
    return out


def _baseline_fingerprint() -> str:
    """Identity of (warehouse data, rendered corpus, interpreter
    sources) — bench.py's CPU-cache key, reused so an edit to the numpy
    interpreter, a template, or a warehouse rebuild all invalidate
    cached CPU times (stale-denominator hazard, bench.py:184-189)."""
    sys.path.insert(0, str(REPO))
    import bench
    from ndstpu.queries import streamgen
    return bench._corpus_fingerprint(str(CACHE / "wh_sf10"),
                                     streamgen.render_power_corpus())


def run_validation(queries: str, out_dir: pathlib.Path) -> dict:
    sys.path.insert(0, str(REPO))
    from ndstpu.queries.streamgen import BENCH_RNGSEED

    wh = str(CACHE / "wh_sf10")
    streams = out_dir / "streams"
    subprocess.run([sys.executable, "-m", "ndstpu.queries.streamgen",
                    "--streams", "1", "--rngseed", BENCH_RNGSEED,
                    "--output_dir", str(streams)],
                   check=True, cwd=str(REPO))
    stream = str(streams / "query_0.sql")
    env = dict(os.environ,
               NDSTPU_XLA_CACHE_DIR=str(CACHE / "xla_cache_tpu"))
    for engine, prefix in (("tpu", "t"), ("cpu", "c")):
        subprocess.run(
            [sys.executable, "-m", "ndstpu.harness.power", stream, wh,
             str(out_dir / f"time_{prefix}.csv"), "--engine", engine,
             "--output_prefix", str(out_dir / prefix),
             "--compile_records", str(CACHE / "plans_sf10.pkl"),
             "--sub_queries", queries],
            check=True, env=env, cwd=str(REPO))
    r = subprocess.run(
        [sys.executable, "-m", "ndstpu.harness.validate",
         str(out_dir / "t"), str(out_dir / "c"), stream,
         "--ignore_ordering", "--sub_queries", queries],
        capture_output=True, text=True, cwd=str(REPO))
    passed = [q for q in queries.split(",")
              if f"Result match for {q} " in r.stdout]
    return {"queries": queries.split(","), "passed": passed,
            "all_match": "All queries match." in r.stdout,
            "validate_exit": r.returncode}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--validate_queries", default=DEFAULT_VALIDATE)
    ap.add_argument("--skip_corpus", action="store_true")
    ap.add_argument("--skip_validation", action="store_true")
    ap.add_argument("--cpu_baseline_s", type=float, default=0.0,
                    help="seconds to spend on a full-corpus numpy CPU "
                         "baseline pass (0 = reuse cached / skip)")
    args = ap.parse_args()
    if not args.skip_corpus:
        run_corpus()
    report = {}
    cpu: dict = {}
    cpu_cache = CACHE / "cpu_times_sf10_power.json"
    if args.cpu_baseline_s > 0:
        cpu = run_cpu_baseline(args.cpu_baseline_s)
    elif cpu_cache.exists():
        cpu = json.loads(cpu_cache.read_text())
        # only complete runs are ever cached, but the warehouse, the
        # corpus, or the interpreter may have changed since — stale
        # denominators must not be reused
        if cpu.get("fingerprint") != _baseline_fingerprint():
            print("cpu baseline cache is stale (warehouse/corpus/"
                  "interpreter changed); ignoring", flush=True)
            cpu = {}
    if cpu:
        report["cpu_baseline"] = {k: v for k, v in cpu.items()
                                  if k != "cpu_times"}
    warm_path = CACHE / "warm_report_sf10.json"
    if warm_path.exists():
        warm = json.loads(warm_path.read_text())
        steady = warm.get("steady", {})
        report["per_query"] = {
            q: {"discover_s": warm.get("discover", {}).get(q),
                "steady_s": s}
            for q, s in steady.items()}
        report["queries_steady"] = len(steady)
        report["steady_total_s"] = round(sum(steady.values()), 2)
        report["failed"] = warm.get("failed", {})
        cpu_times = cpu.get("cpu_times", {})
        common = [q for q in steady if q in cpu_times]
        if common:
            import math
            for q in common:
                report["per_query"][q]["cpu_s"] = cpu_times[q]
            # one shared set for BOTH headline stats: zero-time entries
            # (sub-ms rounds to 0.0) are excluded from sums and geomean
            # alike, so the two numbers describe the same queries
            ratio_qs = [q for q in common
                        if steady[q] > 0 and cpu_times[q] > 0]
            tpu_c = sum(steady[q] for q in ratio_qs)
            cpu_c = sum(cpu_times[q] for q in ratio_qs)
            ratios = [cpu_times[q] / steady[q] for q in ratio_qs]
            report["vs_cpu_baseline"] = {
                "common_queries": len(common),
                "ratio_queries": len(ratio_qs),
                "tpu_steady_s": round(tpu_c, 2),
                "cpu_s": round(cpu_c, 2),
                "speedup": round(cpu_c / tpu_c, 3) if tpu_c else 0.0,
                "geomean_speedup": round(math.exp(
                    sum(math.log(r) for r in ratios) / len(ratios)), 3)
                if ratios else 0.0,
            }
    for cand in (CACHE / "wh_sf10" / "load.txt",
                 CACHE / "wh_sf10_r5_load.txt"):
        try:
            for line in open(cand):
                if "Load Test Time" in line:
                    report["load_test_s"] = float(
                        line.split(":")[1].split()[0])
            if "load_test_s" in report:
                break
        except OSError:
            continue
    if not args.skip_validation:
        vdir = pathlib.Path("/tmp/sf10_validate")
        import shutil
        shutil.rmtree(vdir, ignore_errors=True)
        vdir.mkdir(parents=True)
        report["validation"] = run_validation(args.validate_queries, vdir)
    out = REPO / "docs" / "SF10_BENCH.json"
    out.write_text(json.dumps(report, indent=1))
    print(json.dumps({k: v for k, v in report.items()
                      if k != "per_query"}, indent=1))
    print(f"written: {out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
