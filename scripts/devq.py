"""Dev loop: render + execute one or more query templates against a
pre-built warehouse (default /tmp/devwh/wh).  Usage:

    python scripts/devq.py query2 query4 ...
    python scripts/devq.py --all          # every template in the corpus
"""
import argparse
import sys
import time
import traceback

from ndstpu.engine.session import Session
from ndstpu.io import loader
from ndstpu.queries import streamgen


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("names", nargs="*")
    ap.add_argument("--all", action="store_true")
    ap.add_argument("--wh", default="/tmp/devwh/wh")
    ap.add_argument("--seed", default="07291122510")
    ap.add_argument("--show", action="store_true",
                    help="print first rows of each result")
    args = ap.parse_args()
    names = args.names
    if args.all:
        names = [t[:-4] for t in streamgen.list_templates()]
    sess = Session(loader.load_catalog(args.wh))
    failed = []
    for name in names:
        tpl = name if name.endswith(".tpl") else name + ".tpl"
        try:
            sql = streamgen.render_template(
                str(streamgen.TEMPLATE_DIR / tpl), args.seed, 0)
            t0 = time.time()
            out = None
            for stmt in [s for s in sql.split(";") if s.strip()]:
                out = sess.sql(stmt)
            dt = time.time() - t0
            nrows = out.num_rows if out is not None else 0
            print(f"OK   {name:10s} {nrows:6d} rows  {dt*1000:7.1f} ms")
            if args.show and out is not None:
                cols = out.column_names
                print("     " + " | ".join(cols))
                for i in range(min(5, out.num_rows)):
                    print("     " + " | ".join(
                        str(out.column(c).to_pylist()[i]) for c in cols))
        except Exception as e:
            failed.append(name)
            print(f"FAIL {name:10s} {type(e).__name__}: {e}")
            if len(names) == 1:
                traceback.print_exc()
    if failed:
        print(f"\n{len(failed)} failed: {' '.join(failed)}")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
