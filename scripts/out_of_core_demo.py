"""Out-of-core demonstration on real hardware: stream an SF10 fact
table (store_sales, ~28.8M rows, ~5 GB columnar) through the chunked
executor on one chip via ``spmd_chunk_rows``, and validate the result
against the numpy interpreter on the host.

This is the "SF >> HBM" scaling axis of SURVEY §5 (the reference's
analog is `spark.sql.files.maxPartitionBytes` scan chunking +
executor spill).  Writes docs/OUT_OF_CORE.json.

Usage:  python scripts/out_of_core_demo.py [chunk_rows]
Expects .bench_cache/sf10_wh/store_sales (scripts/ generation steps in
the r04 log; ndsgen -scale 10 -table store_sales + transcode).
"""

import json
import pathlib
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

CHUNK = int(sys.argv[1]) if len(sys.argv) > 1 else 4_000_000

SQL = ("select ss_store_sk, count(*) as n, sum(ss_ext_sales_price) as s, "
       "avg(ss_quantity) as q, min(ss_sold_date_sk) as dmin, "
       "max(ss_sold_date_sk) as dmax "
       "from store_sales group by ss_store_sk order by ss_store_sk")


def main():
    import jax

    from ndstpu.engine.session import Session
    from ndstpu.io import loader

    wh = str(REPO / ".bench_cache" / "sf10_wh")
    t0 = time.time()
    catalog = loader.load_catalog(wh, tables=["store_sales"])
    t_load = time.time() - t0
    n_rows = catalog.get("store_sales").num_rows
    print(f"loaded store_sales: {n_rows} rows in {t_load:.1f}s",
          flush=True)

    # chunked TPU path: facts stream through the device CHUNK rows at a
    # time (one compiled program per chunk shape, partials combined)
    sess = Session(catalog, backend="tpu", spmd_chunk_rows=CHUNK)
    t0 = time.time()
    tpu_rows = sess.sql(SQL).to_rows()
    t_first = time.time() - t0
    t0 = time.time()
    tpu_rows2 = sess.sql(SQL).to_rows()
    t_again = time.time() - t0
    assert getattr(sess, "_spmd_used", False), \
        "chunked executor did not engage (fell back to whole-fact path)"

    t0 = time.time()
    cpu_rows = Session(catalog, backend="cpu").sql(SQL).to_rows()
    t_cpu = time.time() - t0

    def canon(rows):
        out = []
        for r in rows:
            out.append(tuple(
                round(v, 4) if isinstance(v, float) else v for v in r))
        return out

    assert canon(tpu_rows) == canon(tpu_rows2), "re-execution differs"
    ok = canon(tpu_rows) == canon(cpu_rows)
    rec = {
        "table": "store_sales",
        "scale_factor": 10,
        "rows": int(n_rows),
        "chunk_rows": CHUNK,
        "n_chunks": -(-n_rows // CHUNK),
        "platform": str(jax.devices()),
        "sql": SQL,
        "tpu_chunked_first_s": round(t_first, 2),
        "tpu_chunked_again_s": round(t_again, 2),
        "cpu_numpy_s": round(t_cpu, 2),
        "rows_match_cpu": ok,
        "groups": len(tpu_rows),
    }
    out = REPO / "docs" / "OUT_OF_CORE.json"
    with open(out, "w") as f:
        json.dump(rec, f, indent=1)
    print(json.dumps(rec, indent=1), flush=True)
    assert ok, "chunked TPU result != numpy oracle"


if __name__ == "__main__":
    main()
