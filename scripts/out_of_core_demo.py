"""Out-of-core demonstration: stream a fact table through the chunked
executor and record before/after evidence for the streaming pipeline
(sharded chunking + parallel scan/decode + H2D prefetch ring).

Two modes, one artifact (docs/OUT_OF_CORE.json):

* **hardware** — ``.bench_cache/sf10_wh/store_sales`` exists (SF10,
  ~28.8M rows): stream it on the real accelerator at prefetch depth 0
  (the pre-pipeline synchronous behavior) and depth 2, validating
  against the numpy interpreter.  The "SF >> HBM" scaling axis of
  SURVEY §5 (the reference's analog is
  `spark.sql.files.maxPartitionBytes` scan chunking + executor spill).
* **cpu_synthetic** — no SF10 warehouse: render a tiny one, pad the
  scan source with synthetic disk/decode latency, and measure the same
  before/after walls + overlap counters on the virtual CPU backend.
  Hardware walls are marked pending in the artifact.

Usage:  python scripts/out_of_core_demo.py [chunk_rows]
"""

import json
import os
import pathlib
import subprocess
import sys
import tempfile
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

SQL = ("select ss_store_sk, count(*) as n, sum(ss_ext_sales_price) as s, "
       "avg(ss_quantity) as q, min(ss_sold_date_sk) as dmin, "
       "max(ss_sold_date_sk) as dmax "
       "from store_sales group by ss_store_sk order by ss_store_sk")

SYNTH_READ_SLEEP_S = 0.08   # per-read latency pad in cpu_synthetic mode


class SlowSource:
    """Latency-padded scan source for the synthetic mode."""

    def __init__(self, inner, sleep_s):
        self._inner, self._sleep = inner, sleep_s
        self.table = inner.table
        self.columns = inner.columns
        self.num_rows = inner.num_rows

    def column_meta(self):
        return self._inner.column_meta()

    def read(self, start, count):
        time.sleep(self._sleep)
        return self._inner.read(start, count)


def run_depth(catalog, chunk_rows, depth):
    """First + repeat execution at one prefetch depth, with the repeat
    pass's counter movement (compile excluded from that window)."""
    from ndstpu import obs
    from ndstpu.engine.session import Session

    sess = Session(catalog, backend="tpu", spmd_threshold=500,
                   spmd_chunk_rows=chunk_rows, spmd_prefetch_depth=depth)
    t0 = time.time()
    rows = sess.sql(SQL).to_rows()
    t_first = time.time() - t0
    before = obs.counters_snapshot()
    t0 = time.time()
    rows2 = sess.sql(SQL).to_rows()
    t_again = time.time() - t0
    delta = obs.counter_delta(before)
    assert getattr(sess, "_spmd_used", False), \
        "chunked executor did not engage (fell back to whole-fact path)"
    assert rows == rows2, "re-execution differs"
    wall = delta.get("engine.stream.execute_s", 0.0)
    return rows, {
        "prefetch_depth": depth,
        "first_s": round(t_first, 3),
        "again_s": round(t_again, 3),
        "execute_wall_s": round(wall, 3),
        "io.scan.wait_s": round(delta.get("io.scan.wait_s", 0.0), 3),
        "io.scan.wait_bg_s": round(
            delta.get("io.scan.wait_bg_s", 0.0), 3),
        "io.scan.wait_pct_of_wall": round(
            100.0 * delta.get("io.scan.wait_s", 0.0) / wall, 1)
        if wall else None,
        "engine.h2d.overlap_s": round(
            delta.get("engine.h2d.overlap_s", 0.0), 3),
        "engine.h2d.bytes": int(delta.get("engine.h2d.bytes", 0)),
        "io.prefetch.hit": int(delta.get("io.prefetch.hit", 0)),
        "io.prefetch.miss": int(delta.get("io.prefetch.miss", 0)),
    }


def main():
    import jax

    from ndstpu.io import loader

    sf10 = REPO / ".bench_cache" / "sf10_wh"
    hardware = (sf10 / "store_sales").exists()
    if hardware:
        mode = "hardware"
        chunk = int(sys.argv[1]) if len(sys.argv) > 1 else 4_000_000
        t0 = time.time()
        catalog = loader.load_catalog(str(sf10), tables=["store_sales"])
        print(f"loaded store_sales in {time.time() - t0:.1f}s",
              flush=True)
    else:
        mode = "cpu_synthetic"
        chunk = int(sys.argv[1]) if len(sys.argv) > 1 else 1000
        root = pathlib.Path(tempfile.mkdtemp(prefix="ndstpu_ooc_demo"))
        env = dict(os.environ, PYTHONPATH=str(REPO))
        for cmd in (
            [sys.executable, "-m", "ndstpu.datagen.driver", "local",
             "0.002", "2", str(root / "raw")],
            [sys.executable, "-m", "ndstpu.io.transcode",
             "--input_prefix", str(root / "raw"),
             "--output_prefix", str(root / "wh"),
             "--report_file", str(root / "load.txt")],
        ):
            print("+", " ".join(cmd), flush=True)
            subprocess.run(cmd, check=True, env=env,
                           stdout=subprocess.DEVNULL)
        catalog = loader.load_catalog(str(root / "wh"))
        fact = catalog.get("store_sales")
        cols = ["ss_store_sk", "ss_ext_sales_price", "ss_quantity",
                "ss_sold_date_sk"]
        loader.attach_stream_source(
            catalog, "store_sales",
            SlowSource(loader.TableChunkSource(fact, "store_sales",
                                               cols),
                       SYNTH_READ_SLEEP_S))

    n_rows = catalog.get("store_sales").num_rows
    rows_before, before = run_depth(catalog, chunk, 0)
    rows_after, after = run_depth(catalog, chunk, 2)
    assert rows_before == rows_after, "depth changed the result"

    from ndstpu.engine.session import Session
    t0 = time.time()
    cpu_rows = Session(catalog, backend="cpu").sql(SQL).to_rows()
    t_cpu = time.time() - t0

    def canon(rows):
        return [tuple(round(v, 4) if isinstance(v, float) else v
                      for v in r) for r in rows]

    ok = canon(rows_after) == canon(cpu_rows)
    rec = {
        "pipeline": ("sharded chunking + parallel scan/decode + "
                     "H2D prefetch ring (docs/ARCHITECTURE.md "
                     "'Streaming out-of-core pipeline')"),
        "mode": mode,
        "table": "store_sales",
        "rows": int(n_rows),
        "chunk_rows": chunk,
        "n_chunks": -(-n_rows // chunk),
        "platform": str(jax.devices()),
        "sql": SQL,
        "synthetic_read_sleep_s": (None if hardware
                                   else SYNTH_READ_SLEEP_S),
        "before_sync_stream": before,
        "after_prefetch_ring": after,
        "cpu_numpy_s": round(t_cpu, 2),
        "rows_match_cpu": ok,
        "groups": len(rows_after),
        "hardware_walls": ("this run" if hardware else
                           "pending re-run on TPU hardware; previous "
                           "pre-pipeline SF10 run: first 188.38s / "
                           "again 138.82s at chunk_rows=4000000 on "
                           "[TPU v5 lite0]"),
    }
    out = REPO / "docs" / "OUT_OF_CORE.json"
    with open(out, "w") as f:
        json.dump(rec, f, indent=1)
        f.write("\n")
    print(json.dumps(rec, indent=1), flush=True)
    assert ok, "chunked result != numpy oracle"


if __name__ == "__main__":
    main()
