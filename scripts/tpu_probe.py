"""Quick real-TPU probe: time q3 on the compiled replay path.

Run 1 = eager discovery on host CPU backend + jit compile for TPU.
Run 2+ = one XLA program on the TPU per execution.
"""

import sys
import time

sys.path.insert(0, ".")

import jax  # noqa: E402

from ndstpu.engine.session import Session  # noqa: E402
from ndstpu.io import loader  # noqa: E402
from ndstpu.queries import streamgen  # noqa: E402

wh = sys.argv[1] if len(sys.argv) > 1 else "/tmp/vfy/pq"
print("default device:", jax.devices()[0])

t0 = time.time()
catalog = loader.load_catalog(wh)
print(f"load_catalog: {time.time() - t0:.2f}s")

sess = Session(catalog, backend="tpu")
sql = streamgen.render_template(
    str(streamgen.TEMPLATE_DIR / "query3.tpl"), "07291122510", 0)

for i in range(4):
    t0 = time.time()
    out = sess.sql(sql)
    rows = out.to_rows()
    print(f"run {i}: {time.time() - t0:.3f}s  rows={len(rows)}")

exe = sess._jax_executor()
cp = exe._compiled[sql]
print("compilable:", cp.compilable)
