"""Multichip smoke: the SPMD executor on a 2-device CPU mesh.

CI gate alongside the chaos/throughput smokes: renders a tiny warehouse,
forces a **2-device** virtual mesh (the suite's 8-device conftest never
exercises the minimal multi-chip topology), and drives one query from
each newly-distributed plan class end to end through ``Session``
(backend tpu-spmd):

* an EXISTS semi join whose build side contains the fact
  (dplan._reduce_build: no host build of the sharded table);
* a ranking window over a partition-colocating exchange;
* a Sort+LIMIT row tail finalized as a per-device top-k;
* a plain star-join aggregate (the baseline spine).

Each result must be row-identical to the numpy interpreter, the SPMD
path must actually be used (no silent single-chip fallback), and the
``engine.spmd.host_gather_bytes`` counter must tick — the evidence
counter behind the "only the small result gathers" claim.
"""
from __future__ import annotations

import os
import pathlib
import subprocess
import sys
import tempfile

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

N_DEV = 2

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + f" --xla_force_host_platform_device_count={N_DEV}"
    ).strip()
# SPMD defects must fail the smoke, not degrade to single-chip
os.environ.setdefault("NDSTPU_SPMD_STRICT", "1")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

QUERIES = {
    "semi_build_reduce": """
        select count(*) as n from customer
        where exists (select 1 from store_sales
                      where ss_customer_sk = c_customer_sk)
    """,
    "window_rank": """
        select ss_store_sk, ss_item_sk,
               rank() over (partition by ss_store_sk
                            order by ss_net_paid desc) as rnk
        from store_sales where ss_net_paid > 90
    """,
    "sort_limit_tail": """
        select ss_item_sk, ss_net_paid from store_sales
        where ss_quantity > 10
        order by ss_net_paid desc, ss_item_sk limit 25
    """,
    "star_join_agg": """
        select i_class, sum(ss_ext_sales_price) as s
        from store_sales, item where ss_item_sk = i_item_sk
        group by i_class order by s desc
    """,
}


def main() -> int:
    from ndstpu import obs
    from ndstpu.engine.session import Session
    from ndstpu.io import loader

    root = pathlib.Path(tempfile.mkdtemp(prefix="ndstpu_mc_smoke"))
    env = dict(os.environ, PYTHONPATH=str(REPO))
    for cmd in (
        [sys.executable, "-m", "ndstpu.datagen.driver", "local",
         "0.002", "2", str(root / "raw")],
        [sys.executable, "-m", "ndstpu.io.transcode",
         "--input_prefix", str(root / "raw"),
         "--output_prefix", str(root / "wh"),
         "--report_file", str(root / "load.txt")],
    ):
        print("+", " ".join(cmd), flush=True)
        subprocess.run(cmd, check=True, env=env,
                       stdout=subprocess.DEVNULL)

    assert len(jax.devices()) == N_DEV, \
        f"expected a {N_DEV}-device mesh, got {len(jax.devices())}"
    catalog = loader.load_catalog(str(root / "wh"))
    spmd = Session(catalog, backend="tpu-spmd", spmd_threshold=500)
    cpu = Session(catalog, backend="cpu")

    failures = []
    for name, sql in QUERIES.items():
        before = obs.counters_snapshot()
        spmd._spmd_used = False
        got = spmd.sql(sql).to_rows()
        want = cpu.sql(sql).to_rows()
        delta = obs.counter_delta(before)
        gathered = delta.get("engine.spmd.host_gather_bytes", 0)
        used = getattr(spmd, "_spmd_used", False)
        ok = used and got == want and gathered > 0
        print(f"  {'OK  ' if ok else 'FAIL'} {name}: {len(got)} rows, "
              f"spmd_used={used}, host_gather_bytes={gathered}",
              flush=True)
        if not used:
            failures.append(f"{name}: SPMD path not used")
        if got != want:
            failures.append(f"{name}: rows differ from numpy oracle "
                            f"({len(got)} vs {len(want)})")
        if not gathered:
            failures.append(f"{name}: host_gather_bytes did not tick")

    if failures:
        print("\nmultichip smoke FAILED:")
        for f in failures:
            print(f"  {f}")
        return 1
    print(f"\nmultichip smoke ok: {len(QUERIES)} plan classes "
          f"distributed on a {N_DEV}-device mesh, row-equal, "
          "host-gather evidence present")
    return 0


if __name__ == "__main__":
    sys.exit(main())
