"""Serve smoke: the always-on query service under its four fates.

CI gate for ndstpu/serve (docs/ROBUSTNESS.md "Serving lifecycle").
One tiny warehouse, a serial ``power.py`` ground truth, then four
server runs:

1. **Clean** — 3 concurrent clients through ``throughput --mode
   serve`` produce per-query parquet outputs **byte-identical** to the
   serial power runs (same writer, same engine, shared-session serving
   must change nothing).
2. **Dispatch faults** — a server booted with guaranteed
   ``serve.dispatch`` transient faults: the injected failures reach
   the CLIENT as typed transient errors and its retry loop converges
   to results byte-identical to serial anyway.
3. **SIGTERM drain** — a query is sent, and while it is in flight the
   server gets SIGTERM: the in-flight query still completes with an
   ok response (zero dropped), follow-up requests get the typed
   draining answer, the process exits 0, and the journal ends with the
   clean-shutdown marker.
4. **SIGKILL + warm restart** — the server is kill -9'd mid-flight;
   the blocked client reconnects-and-retries into the restarted
   server and completes; a seen-shape query after restart compiles
   NOTHING new (``engine.cache.compiled.miss`` delta == 0 over the
   ``stats`` op) and returns the pre-kill answer.

Engine is ``tpu`` (jaxexec; runs on the CPU platform under
``JAX_PLATFORMS=cpu``) so the compile cache — the thing warm restart
exists to preserve — is actually in play.
"""
from __future__ import annotations

import json
import os
import pathlib
import signal
import subprocess
import sys
import tempfile
import threading
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

SUBQ = "query3,query96"
STREAMS = ("1", "2", "3")


def env_for(**extra) -> dict:
    env = dict(os.environ, PYTHONPATH=str(REPO),
               JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"))
    env.pop("NDSTPU_FAULTS", None)
    env.update({k: v for k, v in extra.items() if v is not None})
    return env


def run(cmd, **kw):
    print("+", " ".join(map(str, cmd)), flush=True)
    return subprocess.run([str(c) for c in cmd], **kw)


def start_server(root: pathlib.Path, tag: str, sock: pathlib.Path,
                 out: pathlib.Path, faults_spec=None,
                 timeout_s=None) -> subprocess.Popen:
    cmd = [sys.executable, "-m", "ndstpu.harness.serve", "server",
           "--socket", sock, "--input_prefix", root / "wh",
           "--engine", "tpu", "--output_prefix", out,
           "--output_format", "parquet",
           "--state_dir", root / f"state_{tag}",
           "--ledger", root / f"ledger_{tag}.jsonl",
           "--slots", "2"]
    if timeout_s is not None:
        cmd += ["--query_timeout_s", str(timeout_s)]
    log = open(root / f"server_{tag}.log", "a")  # restart appends
    print("+", " ".join(map(str, cmd)),
          f"   [NDSTPU_FAULTS={faults_spec}]" if faults_spec else "",
          flush=True)
    return subprocess.Popen(
        [str(c) for c in cmd], env=env_for(NDSTPU_FAULTS=faults_spec),
        stdout=log, stderr=subprocess.STDOUT)


def client(sock, **kw):
    from ndstpu.serve.client import ServeClient
    cli = ServeClient(str(sock), **kw)
    assert cli.wait_ready(180.0), f"server on {sock} never got ready"
    return cli


def parquet_tree(prefix: pathlib.Path) -> dict:
    """relpath -> bytes for every parquet part under prefix."""
    return {str(p.relative_to(prefix)): p.read_bytes()
            for p in sorted(prefix.rglob("part-0.parquet"))}


def assert_byte_identical(got: pathlib.Path, want: pathlib.Path,
                          leg: str) -> int:
    g, w = parquet_tree(got), parquet_tree(want)
    assert set(g) == set(w), \
        f"{leg}: output sets differ: {sorted(set(g) ^ set(w))}"
    for rel in w:
        assert g[rel] == w[rel], \
            f"{leg}: {rel} differs from the serial power run"
    return len(w)


def journal_events(path: pathlib.Path) -> list:
    events = []
    for line in path.read_text().splitlines():
        try:
            events.append(json.loads(line).get("event"))
        except ValueError:
            pass  # torn tail from the SIGKILL leg
    return events


def main() -> int:
    root = pathlib.Path(tempfile.mkdtemp(prefix="ndstpu_serve_smoke"))
    py = [sys.executable, "-m"]
    run(py + ["ndstpu.datagen.driver", "local", "0.002", "2",
              root / "raw"], check=True, env=env_for())
    run(py + ["ndstpu.io.transcode", "--input_prefix", root / "raw",
              "--output_prefix", root / "wh",
              "--report_file", root / "load.txt",
              "--output_format", "ndslake"],
        check=True, env=env_for(), stdout=subprocess.DEVNULL)
    run(py + ["ndstpu.queries.streamgen", "--output_dir",
              root / "streams", "--rngseed", "07291122510",
              "--streams", "4"],  # query_0 is the power stream; we
        # drive 3 concurrent serve clients off streams 1..3
        check=True, env=env_for(), stdout=subprocess.DEVNULL)

    # ---- serial ground truth: power.py, one stream at a time --------
    serial = root / "serial_out"
    for sid in STREAMS:
        run(py + ["ndstpu.harness.power",
                  root / "streams" / f"query_{sid}.sql", root / "wh",
                  root / f"serial_time_{sid}.csv",
                  "--engine", "tpu", "--input_format", "ndslake",
                  "--output_prefix", serial / f"query_{sid}",
                  "--sub_queries", SUBQ],
            check=True, env=env_for(), stdout=subprocess.DEVNULL)
    n_outputs = len(parquet_tree(serial))
    assert n_outputs == len(STREAMS) * len(SUBQ.split(",")), \
        f"serial baseline wrote {n_outputs} outputs"

    from ndstpu.harness import power
    from ndstpu.serve.client import ServeClient, ServerDraining

    # ---- leg 1: clean — concurrent clients == serial, bytewise ------
    sock1 = root / "s1.sock"
    out1 = root / "serve_out1"
    srv1 = start_server(root, "leg1", sock1, out1)
    try:
        r = run(py + ["ndstpu.harness.throughput", "1,2,3",
                      "--concurrent", "3", "--mode", "serve",
                      "--serve_socket", sock1,
                      "--overlap_report", root / "overlap_serve.json",
                      "--", sys.executable, "-m",
                      "ndstpu.harness.power",
                      str(root / "streams") + "/query_{}.sql",
                      root / "wh", str(root) + "/serve_time_{}.csv",
                      "--input_format", "ndslake",
                      "--output_prefix", out1,
                      "--sub_queries", SUBQ], env=env_for())
        assert r.returncode == 0, f"throughput --mode serve rc={r.returncode}"
        n = assert_byte_identical(out1, serial, "leg1")
        ov = json.loads((root / "overlap_serve.json").read_text())
        assert ov["format"] == "ndstpu-throughput-overlap-v1"
        assert ov["mode"] == "serve"
        assert all(s["returncode"] == 0 for s in ov["streams"])
        assert all(s["failures"] == 0 for s in ov["streams"])
        print(f"leg 1 OK: {n} concurrent-serve outputs byte-identical "
              f"to serial power")
    finally:
        srv1.send_signal(signal.SIGTERM)
        srv1.wait(timeout=120)

    # ---- leg 2: injected serve.dispatch faults, client retries ------
    sock2 = root / "s2.sock"
    out2 = root / "serve_out2"
    srv2 = start_server(root, "leg2", sock2, out2,
                        faults_spec="serve.dispatch:transient:1:seedS:times=3")
    try:
        cli = client(sock2, retries=8)
        qd = power.get_query_subset(
            power.gen_sql_from_stream(root / "streams" / "query_1.sql"),
            SUBQ.split(","))
        for qname, sql in qd.items():
            resp = cli.sql(sql, name=f"query_1/{qname}")
            assert resp["status"] == "ok", resp
        assert cli.retried >= 1, \
            "dispatch faults were injected but the client never retried"
        cli.close()
        got = parquet_tree(out2)
        want = parquet_tree(serial)
        for rel in got:
            assert got[rel] == want[rel], \
                f"leg2: {rel} differs from serial after faulted retries"
        assert len(got) == len(SUBQ.split(","))
        log2 = (root / "server_leg2.log").read_text()
        assert "[faults] injected" in log2, \
            "server log records no injected dispatch fault"
        print(f"leg 2 OK: client retried through {cli.retried} "
              f"injected dispatch faults to serial-identical bytes")
    finally:
        srv2.send_signal(signal.SIGTERM)
        srv2.wait(timeout=120)

    # ---- leg 3: SIGTERM drain with a query in flight ----------------
    sock3 = root / "s3.sock"
    srv3 = start_server(root, "leg3", sock3, root / "serve_out3")
    qd = power.get_query_subset(
        power.gen_sql_from_stream(root / "streams" / "query_1.sql"),
        SUBQ.split(","))
    (q1_name, q1_sql), (q2_name, _) = list(qd.items())[:2]
    cli = client(sock3, retries=2, connect_timeout_s=5.0)
    got: dict = {}

    def inflight():
        # fresh server: the first query compiles, so it is still in
        # flight when the SIGTERM below lands mid-execution
        got["resp"] = cli.sql(q1_sql, name=f"drain/{q1_name}")

    th = threading.Thread(target=inflight, daemon=True)
    th.start()
    time.sleep(0.5)
    srv3.send_signal(signal.SIGTERM)
    th.join(180.0)
    assert not th.is_alive(), "in-flight query never answered"
    assert got["resp"]["status"] == "ok", \
        f"in-flight query dropped by drain: {got['resp']}"
    # post-drain requests get the typed draining answer (or a closed
    # socket once the server is fully gone) — never silence
    try:
        cli.sql("SELECT 1", name=q2_name)
        raise AssertionError("post-drain request was accepted")
    except (ServerDraining, OSError, ConnectionError):
        pass
    cli.close()
    assert srv3.wait(timeout=120) == 0, \
        f"SIGTERM drain exited rc={srv3.returncode}"
    ev3 = journal_events(root / "state_leg3" / "serve_journal.jsonl")
    assert ev3[-1] == "clean-shutdown", ev3
    assert "query" in ev3, "drained run journaled no queries"
    print("leg 3 OK: SIGTERM drained with the in-flight query "
          "answered, rc=0, clean-shutdown journaled")

    # ---- leg 4: SIGKILL mid-flight + warm restart, zero compiles ----
    sock4 = root / "s4.sock"
    out4 = root / "serve_out4"
    srv4 = start_server(root, "leg4", sock4, out4)
    cli = client(sock4)
    first = cli.sql(qd[q1_name])  # collect mode: data comes back
    assert first["status"] == "ok"
    cli.close()
    kill_cli = ServeClient(str(sock4), retries=30,
                           connect_timeout_s=180.0)
    killed: dict = {}

    def through_the_kill():
        killed["resp"] = kill_cli.sql(qd[q2_name])

    th = threading.Thread(target=through_the_kill, daemon=True)
    th.start()
    time.sleep(0.3)
    srv4.kill()  # SIGKILL: no drain, no flush — the journal and the
    srv4.wait(timeout=60)  # incremental compile records are all that survive
    print(f"leg 4: SIGKILLed pid {srv4.pid} mid-flight; restarting")
    srv4b = start_server(root, "leg4", sock4, out4)  # same state_dir
    try:
        th.join(300.0)
        assert not th.is_alive(), \
            "client never recovered through the SIGKILL"
        assert killed["resp"]["status"] == "ok", killed["resp"]
        assert kill_cli.retried >= 1, \
            "mid-kill client reports no reconnect/retry"
        kill_cli.close()

        cli2 = client(sock4)
        miss_before = cli2.request({"op": "stats"})["counters"].get(
            "engine.cache.compiled.miss", 0)
        again = cli2.sql(qd[q1_name])  # seen shape, pre-kill compile
        miss_after = cli2.request({"op": "stats"})["counters"].get(
            "engine.cache.compiled.miss", 0)
        assert again["status"] == "ok"
        assert again["data"] == first["data"], \
            "warm-restarted answer differs from the pre-kill answer"
        assert miss_after == miss_before, \
            (f"warm restart recompiled a seen shape: compiled.miss "
             f"{miss_before} -> {miss_after}")
        ev4 = journal_events(root / "state_leg4" /
                             "serve_journal.jsonl")
        # two boots, and the first one never got to mark itself clean
        assert ev4.count("server-start") == 2
        assert "clean-shutdown" not in ev4
        cli2.close()
        print("leg 4 OK: client reconnect-retried through SIGKILL; "
              "seen-shape query after warm restart compiled nothing "
              f"(miss {miss_before} -> {miss_after})")
    finally:
        srv4b.send_signal(signal.SIGTERM)
        srv4b.wait(timeout=120)

    print("serve smoke OK: clean parity, faulted-retry parity, "
          "SIGTERM drain, SIGKILL warm restart all held")
    import shutil
    shutil.rmtree(root, ignore_errors=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
