"""Spine-sharing smoke: cached common spines must not change results.

CI gate for the runtime spine cache (ndstpu/engine/spine.py +
ndstpu/engine/session.py splicing): renders a tiny warehouse and TWO
IDENTICAL query streams (throughput streams are normally
param-divergent, so the second stream file is a byte-for-byte copy of
the first — every spine value-key recurs by construction), runs the
same in-process throughput invocation twice over a shared Session —
once with sharing on (default), once under ``NDSTPU_SPINES=0`` — and
asserts

* both phases exit 0;
* the sharing-on phase measured at least one ``engine.spine.hit``
  (visible as ``extra.spine_hits`` on its ledger entries);
* every query's result CSV is byte-identical between the two phases,
  including row order — splicing a cached spine table may never
  change what a query returns.
"""
from __future__ import annotations

import json
import os
import pathlib
import shutil
import subprocess
import sys
import tempfile

REPO = pathlib.Path(__file__).resolve().parent.parent
SUB_QUERIES = "query3,query52,query96"


def run(cmd, **kw):
    print("+", " ".join(map(str, cmd)), flush=True)
    return subprocess.run([str(c) for c in cmd], **kw)


def main() -> int:
    root = pathlib.Path(tempfile.mkdtemp(prefix="ndstpu_spine_smoke"))
    env = dict(os.environ, PYTHONPATH=str(REPO),
               JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"))
    py = [sys.executable, "-m"]
    run(py + ["ndstpu.datagen.driver", "local", "0.002", "2",
              root / "raw"], check=True, env=env)
    run(py + ["ndstpu.io.transcode", "--input_prefix", root / "raw",
              "--output_prefix", root / "wh",
              "--report_file", root / "load.txt",
              "--output_format", "ndslake"],
        check=True, env=env, stdout=subprocess.DEVNULL)
    run(py + ["ndstpu.queries.streamgen", "--output_dir",
              root / "streams", "--rngseed", "07291122510",
              "--streams", "2"],
        check=True, env=env, stdout=subprocess.DEVNULL)
    # streams 1 and 2 must render IDENTICAL literals so every spine
    # value-key occurs twice; stream 2 becomes a copy of stream 1
    shutil.copyfile(root / "streams" / "query_1.sql",
                    root / "streams" / "query_2.sql")

    ledgers = {}
    for phase, spines in (("on", "1"), ("off", "0")):
        ledgers[phase] = root / f"ledger_{phase}.jsonl"
        penv = dict(env, NDSTPU_SPINES=spines)
        r = run(py + ["ndstpu.harness.throughput", "1,2",
                      "--concurrent", "2", "--mode", "inproc",
                      "--overlap_report", root / f"overlap_{phase}.json",
                      "--",
                      sys.executable, "-m", "ndstpu.harness.power",
                      str(root / "streams") + "/query_{}.sql",
                      root / "wh",
                      str(root) + f"/time_{phase}_{{}}.csv",
                      "--input_format", "ndslake",
                      "--output_prefix",
                      str(root) + f"/out_{phase}_{{}}",
                      "--output_format", "csv",
                      "--ledger", ledgers[phase],
                      "--sub_queries", SUB_QUERIES],
                env=penv)
        assert r.returncode == 0, \
            f"spines={spines} phase exited {r.returncode}"

    # >= 1 measured engine.spine.hit: the splice path annotates the
    # query span, and the inproc exporter copies spine_hits into the
    # ledger entry's extra (ndstpu/harness/scheduler.py)
    hits = bytes_saved = 0
    for line in ledgers["on"].read_text().splitlines():
        entry = json.loads(line)
        extra = entry.get("extra") or {}
        hits += extra.get("spine_hits") or 0
        bytes_saved += extra.get("spine_bytes_saved") or 0
    assert hits >= 1, \
        "sharing-on phase recorded no engine.spine.hit in its ledger"
    off_hits = sum(
        (json.loads(line).get("extra") or {}).get("spine_hits") or 0
        for line in ledgers["off"].read_text().splitlines())
    assert off_hits == 0, \
        f"NDSTPU_SPINES=0 phase still recorded {off_hits} spine hit(s)"

    # byte-identical results, row order included
    compared = 0
    for sid in ("1", "2"):
        for q in SUB_QUERIES.split(","):
            a = root / f"out_on_{sid}" / q / "part-0.csv"
            b = root / f"out_off_{sid}" / q / "part-0.csv"
            assert a.exists() and b.exists(), \
                f"missing result output for stream {sid} {q}"
            assert a.read_bytes() == b.read_bytes(), \
                (f"stream {sid} {q}: sharing-on result differs from "
                 f"sharing-off ({a} vs {b})")
            compared += 1
    print(f"smoke OK: {hits} spine hit(s), "
          f"{bytes_saved} bytes saved, {compared} result files "
          "byte-identical with sharing off")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
