#!/usr/bin/env python
"""Corpus-wide static cost-model lint (NDS6xx).

Sweeps every part of the power corpus through the static cost
estimator (ndstpu/analysis/cost.py) — parse → plan → optimize over a
ZERO-ROW schema catalog, so no warehouse, no data, no jax — and emits
the per-part cost report: estimated output cardinality with its
confidence band, predicted exchange placement per spine join (the
same ``choose_strategy`` the runtime dplan advisor uses), predicted
per-device working set, and predicted collective traffic.

Emits:

* ``COST_LINT.json`` / ``COST_LINT.md`` (repo root): per-part
  estimates + placements plus NDS6xx diagnostics.  Deterministic (no
  timestamps) so committed copies only change when the plans or the
  model change.
* NDS6xx diagnostics: NDS601 broadcast build over the replication
  budget (cost model demotes to shuffle), NDS602 spill-risk working
  set over the device budget, NDS603 exchange-heavy plan, NDS604
  static-vs-observed misestimate (only with ``--calibrate``).  With
  ``--baseline [PATH]``: exit nonzero iff a diagnostic is NOT in the
  committed baseline (docs/cost_lint_baseline.json).
* With ``--calibrate LEDGER``: join static row estimates against the
  run ledger's observed output cardinalities (``extra.result_rows``,
  stamped by harness/power.py), write per-query misestimate ratios
  into COST_LINT.json, and emit NDS604 where the ratio exceeds the
  threshold.
* With ``--write-baseline``: regenerate the baseline from this sweep.

Usage:
    python scripts/cost_lint.py                      # artifacts only
    python scripts/cost_lint.py --baseline           # CI gate
    python scripts/cost_lint.py --write-baseline     # accept current set
    python scripts/cost_lint.py --calibrate ledger.jsonl
"""

import argparse
import json
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

DEFAULT_BASELINE = REPO / "docs" / "cost_lint_baseline.json"


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--baseline", nargs="?", const=str(DEFAULT_BASELINE),
                    default=None, metavar="PATH",
                    help="gate against this baseline (default: "
                         "docs/cost_lint_baseline.json); exit 1 on new "
                         "diagnostics")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from this sweep")
    ap.add_argument("--json", default=str(REPO / "COST_LINT.json"))
    ap.add_argument("--md", default=str(REPO / "COST_LINT.md"))
    ap.add_argument("--rngseed", default="07291122510",
                    help="stream seed (pinned bench seed by default so "
                         "the artifact is reproducible)")
    ap.add_argument("--stream", type=int, default=0)
    ap.add_argument("--scale_factor", type=float, default=1.0,
                    help="scale factor for the base cardinalities")
    ap.add_argument("--n_dev", type=int, default=8,
                    help="mesh size assumed for the working-set model "
                         "(the suite's virtual mesh by default)")
    ap.add_argument("--calibrate", default=None, metavar="LEDGER",
                    help="run-ledger JSONL with observed output "
                         "cardinalities (extra.result_rows): writes "
                         "per-query misestimate ratios and emits NDS604")
    ap.add_argument("--sub_queries", default=None,
                    help="comma-separated query-part subset (CI tiny run)")
    return ap


def sweep(args):
    """part -> CostReport plus per-part analysis errors."""
    from ndstpu import analysis
    from ndstpu.analysis import cost
    from ndstpu.engine.session import Session
    from ndstpu.queries import streamgen

    sess = Session(analysis.schema_catalog())
    tables = analysis.schema_tables()
    subset = set(args.sub_queries.split(",")) if args.sub_queries else None

    reports, errors = {}, {}
    for name, sql in streamgen.render_power_corpus(
            rngseed=args.rngseed, stream=args.stream):
        if subset is not None and name not in subset:
            continue
        try:
            plan, _cols = sess.plan(sql)
            reports[name] = cost.audit_cost(
                plan, tables, query=name,
                scale_factor=args.scale_factor, n_dev=args.n_dev)
        except Exception as e:
            errors[name] = f"{type(e).__name__}: {e}"
    return reports, errors


def run_lint(args) -> int:
    from ndstpu.analysis import cost
    from ndstpu.analysis import diagnostics as diag_mod

    reports, errors = sweep(args)
    diags = [d for r in reports.values() for d in r.diagnostics]

    calibration_block = None
    if args.calibrate:
        observed = cost.observed_rows_from_ledger(args.calibrate)
        estimated = {q: r.root for q, r in reports.items()}
        calib = cost.Calibration.from_pairs(
            {q: est.rows for q, est in estimated.items()}, observed)
        diags += cost.misestimate_diags(estimated, observed)
        calibration_block = {
            "ledger": args.calibrate,
            "queries_observed": len(calib.ratios),
            "dispersion": round(calib.dispersion, 4),
            "ratios": {q: round(r, 4)
                       for q, r in sorted(calib.ratios.items())},
        }

    budget, budget_source = cost.cost_budget_bytes()
    counts = {"broadcast": 0, "shuffle": 0, "build-reduce": 0}
    for r in reports.values():
        for k, v in r.placement_counts().items():
            counts[k] += v
    meta = {
        "rngseed": args.rngseed,
        "stream": args.stream,
        "scale_factor": args.scale_factor,
        "n_dev": args.n_dev,
        "parts": len(reports),
        "errors": errors,
        "budget_bytes": budget,
        "budget_source": budget_source,
        "placements": counts,
    }

    out = {"meta": meta,
           "queries": {q: r.as_dict()
                       for q, r in sorted(reports.items())},
           "diagnostics": [d.as_dict()
                           for d in diag_mod.sort_diagnostics(diags)]}
    if calibration_block is not None:
        out["calibration"] = calibration_block
    pathlib.Path(args.json).write_text(
        json.dumps(out, indent=2, sort_keys=True) + "\n")

    lines = ["# Static cost-model lint", ""]
    for k, v in sorted(meta.items()):
        lines.append(f"- **{k}**: {v}")
    lines += [
        "",
        f"{meta['parts']} corpus parts estimated under a "
        f"{budget} B device budget ({budget_source}): "
        f"{counts['broadcast']} broadcast, {counts['shuffle']} "
        f"shuffle, {counts['build-reduce']} build-reduce join "
        f"placements predicted; {len(diags)} NDS6xx diagnostic(s).",
        "",
        "| query | est rows | band | working set B | exchange B "
        "| bcast | shuf | reduce |",
        "|---|---|---|---|---|---|---|---|"]
    for q, r in sorted(reports.items()):
        pc = r.placement_counts()
        ws = r.working_set_bytes if r.working_set_bytes is not None \
            else "?"
        lines.append(
            f"| {q} | {r.root.rows:.0f} "
            f"| [{r.root.lo:g}, {r.root.hi:g}]x | {ws} "
            f"| {r.exchange_bytes} | {pc['broadcast']} "
            f"| {pc['shuffle']} | {pc['build-reduce']} |")
    if calibration_block is not None:
        lines += ["", "## Calibration", "",
                  f"- ledger: `{calibration_block['ledger']}`",
                  f"- queries observed: "
                  f"{calibration_block['queries_observed']}",
                  f"- ratio dispersion (geometric): "
                  f"{calibration_block['dispersion']}"]
        if calibration_block["ratios"]:
            lines += ["", "| query | observed / estimated |", "|---|---|"]
            for q, ratio in sorted(calibration_block["ratios"].items()):
                lines.append(f"| {q} | {ratio} |")
    if diags:
        lines += ["", "## Diagnostics", ""]
        for d in diag_mod.sort_diagnostics(diags):
            lines.append(f"- `{d.query}` {d.code} [{d.path}]: "
                         f"{d.message}")
    pathlib.Path(args.md).write_text("\n".join(lines) + "\n")

    print(f"cost-lint: {meta['parts']} parts, "
          f"{sum(counts.values())} placements predicted "
          f"({counts}), {len(diags)} diagnostic(s) -> {args.json}")
    if errors:
        print(f"cost-lint: {len(errors)} part(s) failed analysis: "
              f"{sorted(errors)}", file=sys.stderr)

    if args.write_baseline:
        DEFAULT_BASELINE.write_text(diag_mod.baseline_dump(diags))
        print(f"cost-lint: baseline rewritten -> {DEFAULT_BASELINE}")

    if args.baseline is not None:
        bpath = pathlib.Path(args.baseline)
        if not bpath.exists():
            print(f"cost-lint: baseline {bpath} missing "
                  "(run --write-baseline)", file=sys.stderr)
            return 2
        accepted = diag_mod.baseline_load(bpath.read_text())
        new = diag_mod.new_against_baseline(diags, accepted)
        if new:
            print(f"cost-lint: {len(new)} diagnostic(s) not in baseline:",
                  file=sys.stderr)
            for d in new:
                print(f"  {d.query} {d.code} [{d.path}]: {d.message}",
                      file=sys.stderr)
            return 1
        print(f"cost-lint: clean against baseline "
              f"({len(accepted)} accepted)")
    return 0


def main(argv=None) -> int:
    return run_lint(build_parser().parse_args(argv))


if __name__ == "__main__":
    raise SystemExit(main())
