"""Global-dictionary audit: sidecar inventory + corpus coverage gate.

Two halves, one artifact pair:

* **inventory** — every ``_GLOBAL_DICTS.json`` sidecar in the
  warehouse, per column: version count, latest entry size (values and
  encoded UTF-8 bytes, the engine/spine.py byte model), content hash.
  This is the ground truth for "which string columns have a frozen
  warehouse-wide code space" (ndstpu/io/gdict.py).
* **coverage sweep** — every corpus part (all 103) is planned
  statically and its base-table scans walked (plan.Scan); a part is
  ``covered`` when every string column of every table it scans holds a
  frozen global dictionary, ``nostrings`` when it touches none.  An
  ``uncovered`` part is one that would still hit the per-call
  dictionary paths: build-side translation on string joins (NDS307),
  string-table streaming rejection, unbound string literals.

Artifacts: ``DICT_AUDIT.json`` / ``DICT_AUDIT.md`` (repo root,
deterministic — no timestamps).  Baseline gate
(``docs/dict_audit_baseline.json``): a part that was covered may not
regress to uncovered/error, and the uncovered total may not grow;
accept intentional changes with ``--write-baseline``.

Usage::

    JAX_PLATFORMS=cpu python scripts/dict_audit.py [warehouse_dir]
        [--baseline] [--write-baseline] [--sub_queries query1,...]

Without a warehouse argument a tiny SF-0.002 warehouse is generated
and transcoded (the spmd_coverage.py pattern).  Exits nonzero on
baseline regression.  NDSTPU_GLOBAL_DICTS=0 empties the inventory and
turns every string-touching part uncovered — the audit reports what
the kill switch costs.
"""

import argparse
import json
import os
import pathlib
import subprocess
import sys
import tempfile

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

BASELINE_PATH = REPO / "docs" / "dict_audit_baseline.json"

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def sidecar_inventory(warehouse: str) -> dict:
    """Per-table, per-column dictionary stats from the sidecars."""
    from ndstpu.io import gdict

    inv = {}
    for table in sorted(os.listdir(warehouse)):
        tdir = os.path.join(warehouse, table)
        if not os.path.isdir(tdir):
            continue
        doc = gdict._read_sidecar(tdir)
        if doc is None:
            continue
        cols = {}
        for col, entries in sorted((doc.get("columns") or {}).items()):
            ent = gdict._select_entry(entries, None)
            if ent is None:
                continue
            cols[col] = {
                "versions": len(entries),
                "values": len(ent["values"]),
                "bytes": gdict.dictionary_nbytes(ent["values"]),
                "hash": ent.get("hash"),
                "table_version": ent.get("table_version"),
            }
        if cols:
            inv[table] = cols
    return inv


def string_columns(catalog) -> dict:
    """table -> {column -> has frozen dict} over the resident catalog.
    A column counts as covered when the loader attached a GlobalDict
    to it (columnar.Column.gdict), i.e. resident codes ARE the global
    code space."""
    out = {}
    for name, t in sorted(catalog.tables.items()):
        cols = {}
        for cn, c in t.columns.items():
            if c.ctype.kind == "string":
                cols[cn.split(".")[-1]] = c.gdict is not None
        if cols:
            out[name] = cols
    return out


def sweep(catalog, sub_queries=None, verbose=True):
    """Per-part coverage statuses: covered | nostrings |
    uncovered:<table.col,...> | error."""
    from ndstpu.engine import plan as plan_mod
    from ndstpu.engine.session import Session
    from ndstpu.queries import streamgen

    strs = string_columns(catalog)
    sess = Session(catalog, backend="cpu")
    statuses = {}
    for name, sql in streamgen.render_power_corpus(
            rngseed="07291122510", stream=0):
        if sub_queries is not None and name not in sub_queries:
            continue
        try:
            plan, _ = sess.plan(sql)
        except Exception as e:
            statuses[name] = f"error: {type(e).__name__}: {e}"
            continue
        scanned = {n.table for n in plan.walk()
                   if isinstance(n, plan_mod.Scan)}
        missing = sorted(
            f"{t}.{c}" for t in scanned
            for c, covered in strs.get(t, {}).items() if not covered)
        if missing:
            statuses[name] = "uncovered:" + ",".join(missing)
        elif any(t in strs for t in scanned):
            statuses[name] = "covered"
        else:
            statuses[name] = "nostrings"
        if verbose:
            print(f"  {statuses[name].split(':')[0].upper():9s} {name}",
                  flush=True)
    return statuses


def summarize(statuses: dict) -> dict:
    buckets = {"covered": 0, "nostrings": 0, "uncovered": 0, "error": 0}
    for st in statuses.values():
        buckets[st.split(":")[0]] += 1
    return buckets


def check_baseline(statuses: dict, inv: dict, baseline: dict) -> list:
    """Regressions vs the committed baseline, restricted to probed
    parts: covered parts must stay covered, errors are regressions
    outright, the uncovered count may not grow, and no audited column's
    dictionary may disappear."""
    problems = []
    base_parts = baseline.get("parts", {})
    for name, st in sorted(statuses.items()):
        kind = st.split(":")[0]
        was = (base_parts.get(name) or "").split(":")[0]
        if kind == "error":
            problems.append(f"{name}: {st}")
        elif was in ("covered", "nostrings") and kind == "uncovered":
            problems.append(f"{name}: {st}, was {was}")
        elif not was and kind == "uncovered":
            problems.append(f"{name}: {st}, not in baseline")
    probed = set(statuses)
    now_unc = summarize(statuses)["uncovered"]
    was_unc = sum(1 for n, s in base_parts.items()
                  if n in probed and s.split(":")[0] == "uncovered")
    if now_unc > was_unc:
        problems.append(
            f"uncovered parts grew: {now_unc} vs baseline {was_unc}")
    for table, cols in sorted((baseline.get("inventory") or {}).items()):
        for col in sorted(cols):
            if col not in (inv.get(table) or {}):
                problems.append(
                    f"dictionary lost: {table}.{col} in baseline "
                    f"inventory but no sidecar entry now")
    return problems


def write_artifacts(inv: dict, statuses: dict, json_path, md_path):
    buckets = summarize(statuses)
    doc = {
        "meta": {"tool": "scripts/dict_audit.py",
                 "enabled": _enabled()},
        "summary": buckets,
        "inventory": inv,
        "parts": statuses,
    }
    pathlib.Path(json_path).write_text(
        json.dumps(doc, indent=2, sort_keys=True) + "\n")

    lines = ["# Global-dictionary audit", ""]
    lines.append(
        f"- layer enabled: {_enabled()} (NDSTPU_GLOBAL_DICTS)")
    lines.append("- parts: " + ", ".join(
        f"{buckets[k]} {k}" for k in sorted(buckets)))
    lines += ["", "## Sidecar inventory", "",
              "| table | column | versions | values | bytes | hash |",
              "|---|---|---|---|---|---|"]
    for table, cols in sorted(inv.items()):
        for col, st in sorted(cols.items()):
            lines.append(f"| {table} | {col} | {st['versions']} "
                         f"| {st['values']} | {st['bytes']} "
                         f"| `{st['hash']}` |")
    lines += ["", "## Corpus coverage", "",
              "| part | status |", "|---|---|"]
    for name, st in sorted(statuses.items()):
        lines.append(f"| {name} | {st} |")
    lines.append("")
    pathlib.Path(md_path).write_text("\n".join(lines))


def _enabled() -> bool:
    from ndstpu.io import gdict
    return gdict.enabled()


def build_tiny_warehouse() -> str:
    tmp = tempfile.mkdtemp(prefix="dictaudit")
    data = os.path.join(tmp, "raw")
    wh = os.path.join(tmp, "wh")
    env = dict(os.environ, PYTHONPATH=str(REPO))
    subprocess.run(["python", "-m", "ndstpu.datagen.driver", "local",
                    "0.002", "2", data], check=True, env=env)
    subprocess.run(["python", "-m", "ndstpu.io.transcode",
                    "--input_prefix", data, "--output_prefix", wh,
                    "--report_file", os.path.join(wh, "load.txt")],
                   check=True, env=env, stdout=subprocess.DEVNULL)
    return wh


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="global-dictionary sidecar inventory + corpus "
                    "coverage gate")
    ap.add_argument("warehouse", nargs="?")
    ap.add_argument("--baseline", action="store_true")
    ap.add_argument("--write-baseline", action="store_true")
    ap.add_argument("--sub_queries")
    ap.add_argument("--json", default=str(REPO / "DICT_AUDIT.json"))
    ap.add_argument("--md", default=str(REPO / "DICT_AUDIT.md"))
    args = ap.parse_args(argv)

    from ndstpu.io import loader

    wh = args.warehouse or build_tiny_warehouse()
    sub = set(args.sub_queries.split(",")) if args.sub_queries else None

    inv = sidecar_inventory(wh)
    catalog = loader.load_catalog(wh)
    statuses = sweep(catalog, sub_queries=sub)

    buckets = summarize(statuses)
    n_cols = sum(len(c) for c in inv.values())
    n_bytes = sum(st["bytes"] for c in inv.values() for st in c.values())
    print(f"\n== {n_cols} dictionary columns over {len(inv)} tables, "
          f"{n_bytes} encoded bytes ==")
    print("parts:", json.dumps(buckets, sort_keys=True))

    write_artifacts(inv, statuses, args.json, args.md)
    print(f"artifacts: {args.json} {args.md}")

    if args.write_baseline:
        BASELINE_PATH.parent.mkdir(parents=True, exist_ok=True)
        BASELINE_PATH.write_text(json.dumps(
            {"parts": statuses, "summary": buckets,
             "inventory": {t: sorted(c) for t, c in inv.items()}},
            indent=2, sort_keys=True) + "\n")
        print(f"baseline written: {BASELINE_PATH}")
        return 0
    if args.baseline:
        if not BASELINE_PATH.exists():
            print(f"no baseline at {BASELINE_PATH}; run with "
                  "--write-baseline first", file=sys.stderr)
            return 2
        baseline = json.loads(BASELINE_PATH.read_text())
        problems = check_baseline(statuses, inv, baseline)
        if problems:
            print("\ndict-audit regressions vs baseline:")
            for p in problems:
                print(f"  {p}")
            return 1
        print("\nbaseline ok: no dictionary-coverage regression")
    return 0


if __name__ == "__main__":
    sys.exit(main())
