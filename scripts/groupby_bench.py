"""A/B the group-by strategies on the real TPU inside actual power-run
queries (VERDICT round-1 item 3: measure the Pallas path in a power run,
not just a microbenchmark).

Runs a group-by-heavy query subset under each NDSTPU_GROUPBY mode in a
fresh subprocess (the mode is baked into traced programs at executor
init), timing the compiled-replay steady state (second run). Prints a
per-query table and writes docs/GROUPBY_BENCH.json.

Usage:  python scripts/groupby_bench.py [warehouse_dir] [--modes a,b,c]
"""

from __future__ import annotations

import json
import os
import pathlib
import subprocess
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

# brand/category/channel aggregates — the scan->filter->group-by spine
QUERIES = ["query3", "query7", "query42", "query52", "query55", "query43"]


def run_mode(mode: str, wh: str) -> dict:
    code = f"""
import json, sys, time
sys.path.insert(0, {str(REPO)!r})
from ndstpu.engine.session import Session
from ndstpu.io import loader
from ndstpu.queries import streamgen
catalog = loader.load_catalog({wh!r})
sess = Session(catalog, backend="tpu")
out = {{}}
for q in {QUERIES!r}:
    parts = streamgen.render_template_parts(
        str(streamgen.TEMPLATE_DIR / (q + ".tpl")), "07291122510", 0)
    for name, sql in parts:
        sess.sql(sql).to_rows()          # discovery
        sess.sql(sql).to_rows()          # compile + first replay
        t0 = time.time()
        sess.sql(sql).to_rows()          # steady-state replay
        out[name] = round(time.time() - t0, 4)
print("RESULT " + json.dumps(out))
"""
    # APPEND to PYTHONPATH: clobbering it drops /root/.axon_site's
    # sitecustomize, so the child can't register the axon PJRT plugin
    # that its inherited JAX_PLATFORMS=axon demands
    pp = os.environ.get("PYTHONPATH", "")
    env = dict(os.environ, NDSTPU_GROUPBY=mode,
               PYTHONPATH=f"{REPO}{os.pathsep}{pp}" if pp else str(REPO))
    t0 = time.time()
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=3600)
    if r.returncode != 0:
        print(f"mode {mode} FAILED:\n{r.stderr[-2000:]}", file=sys.stderr)
        return {}
    for line in r.stdout.splitlines():
        if line.startswith("RESULT "):
            out = json.loads(line[len("RESULT "):])
            out["__wall__"] = round(time.time() - t0, 1)
            return out
    return {}


def main() -> None:
    wh = sys.argv[1] if len(sys.argv) > 1 and not sys.argv[1].startswith(
        "--") else str(REPO / ".bench_cache" / "wh_sf1")
    modes = ["sort", "auto", "pallas"]
    for a in sys.argv:
        if a.startswith("--modes"):
            modes = a.split("=", 1)[1].split(",")
    results = {}
    for mode in modes:
        print(f"== mode {mode} ==", flush=True)
        results[mode] = run_mode(mode, wh)
        for k, v in results[mode].items():
            print(f"  {k:24s} {v}", flush=True)
    qnames = sorted(set().union(*[set(r) for r in results.values()]) -
                    {"__wall__"})
    print(f"\n{'query':24s} " + " ".join(f"{m:>9s}" for m in modes))
    for q in qnames:
        row = " ".join(f"{results[m].get(q, float('nan')):9.4f}"
                       for m in modes)
        print(f"{q:24s} {row}")
    with open(REPO / "docs" / "GROUPBY_BENCH.json", "w") as f:
        json.dump(results, f, indent=1)


if __name__ == "__main__":
    main()
