"""Chaos smoke: injected faults + SIGKILL + resume on the full bench.

CI gate for the robustness layer (docs/ROBUSTNESS.md).  One tiny
corpus, five bench runs:

A. **Baseline** — the 5-phase bench fault-free; committed query
   outputs + composite metric are the ground truth.
B. **Fault crash** — same config in a fresh root with a guaranteed
   ``io.write`` transient fault.  That layer has no retry wrapper by
   design (the journal/markers make re-running cheaper than retrying a
   torn write), so the bench must die nonzero after injecting.
C. **Kill mid-power** — ``--resume`` with ``plan`` transient faults
   (absorbed by the retry layer) and ``execute`` hang faults (slow the
   first two queries so the kill window is deterministic); SIGKILL the
   whole process group as soon as the power progress journal records
   its first completed query.
D. **Kill mid-throughput** — ``--resume`` again with the same faults:
   power must skip the journaled queries, retry-recover the injected
   faults on the rest, and append retry-annotated ledger entries; the
   group is SIGKILLed right after ``power_test`` lands in
   ``RUN_STATE.json``.
E. **Clean resume** — ``--resume`` with faults off must skip every
   journaled phase, finish throughput/maintenance, and produce query
   results identical to the baseline (parquet-level equality) plus a
   positive composite metric.

Faults are injected at 3 sites (``io.write``, ``plan``, ``execute``)
across 2 kinds (transient, hang); a standalone power run then injects
an ``execute`` *permanent* fault and asserts it surfaces classified —
``faultTaxonomy.counts.permanent`` in the sidecar and a
``failed-permanent`` sentinel verdict — never as a silent skip.

Two SIGKILL epilogues follow the bench scenarios:

G. **Kill mid-ingest** — SIGKILL ``ndstpu.harness.ingest`` mid-run
   over a tiny synthetic lake warehouse and resume it: the intent/done
   journal plus crash retraction (io lake ``abort_to_version``) must
   land the resumed run on snapshot versions and contents identical to
   an uninterrupted control (the full interleaved-vs-quiesced
   differential is scripts/ingest_smoke.py's job).
H. **Kill the query server mid-flight** — a ``ndstpu.harness.serve``
   server is SIGKILLed while a client's query is wedged in an injected
   ``execute`` hang; a healthy incarnation is started on the same
   socket + state dir and the client's reconnect-and-retry loop must
   converge, unattended, to results identical to an uninterrupted
   control server (the compile-cache warm-restart proof lives in
   scripts/serve_smoke.py leg 4 — this scenario gates the client-side
   crash contract).
I. **Kill the fleet supervisor** — a 2-replica
   ``ndstpu.serve.fleet`` supervisor is SIGKILLed while its replicas
   serve.  The replicas (own process sessions) must keep answering
   supervisor-less; a supervisor restarted over the same ``run_dir``
   must **re-adopt** the live replicas from probe state — same pids,
   ``serve.fleet.adopted >= 2``, zero restarts, no double-start —
   then drain the fleet cleanly on SIGTERM (the load-bearing fleet
   proofs live in scripts/fleet_smoke.py — this scenario gates the
   supervisor's own crash contract).
"""
from __future__ import annotations

import json
import os
import pathlib
import signal
import subprocess
import sys
import tempfile
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

PHASES = {"data_gen", "load_test", "generate_query_stream",
          "power_test", "throughput_test_1", "maintenance_test_1",
          "throughput_test_2", "maintenance_test_2"}

TEMPLATES = ["query3.tpl", "query42.tpl", "query96.tpl"]

# B: the io.write probe fires once (driver journal append — the layer
#    with markers instead of retries) and crashes the run.
# C/D: plan transients are retry-absorbed; the execute hangs make the
#    first two queries take ~6s each so the SIGKILL deterministically
#    lands with queries still outstanding.
CRASH_FAULTS = "io.write:transient:1.0:seed3:times=1"
CHAOS_FAULTS = ("plan:transient:1.0:seed5:times=1,"
                "execute:hang:1.0:seedH:times=2:hang=6")


def make_cfg(root: pathlib.Path, tpl_dir: pathlib.Path) -> pathlib.Path:
    import yaml
    cfg = {
        "data_gen": {"scale_factor": 0.002, "parallel": 2,
                     "data_path": str(root / "raw"), "skip": False},
        "load_test": {"warehouse_path": str(root / "wh"),
                      "warehouse_format": "ndslake",
                      "report_file": str(root / "load.txt"),
                      "skip": False},
        "generate_query_stream": {
            # pinned: spec 4.3.1 chains the rngseed from the load end
            # TIMESTAMP, which would give baseline and chaos runs
            # different query parameters — results must be comparable
            "num_streams": 3, "rngseed": "07291122510",
            "template_dir": str(tpl_dir),
            "stream_output_path": str(root / "streams"), "skip": False},
        "power_test": {"engine": "cpu",
                       "report_file": str(root / "power.csv"),
                       "output_prefix": str(root / "out"),
                       "skip": False},
        "throughput_test": {"report_base": str(root / "tt"),
                            "skip": False},
        "maintenance_test": {"report_base": str(root / "dm"),
                             "skip": False},
        "metrics": {"metrics_report": str(root / "metrics.csv")},
        "observability": {"ledger": str(root / "ledger.jsonl")},
    }
    path = root / "bench.yml"
    path.write_text(yaml.safe_dump(cfg))
    return path


def base_env(**extra) -> dict:
    env = dict(os.environ, PYTHONPATH=str(REPO),
               JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"))
    env.pop("NDSTPU_FAULTS", None)
    env.update({k: v for k, v in extra.items() if v is not None})
    return env


def bench_cmd(cfg: pathlib.Path, resume: bool = False) -> list:
    cmd = [sys.executable, "-m", "ndstpu.harness.bench", str(cfg)]
    if resume:
        cmd.append("--resume")
    return cmd


def run_logged(cmd, env, log: pathlib.Path, check_rc=None) -> int:
    print("+", " ".join(map(str, cmd)), flush=True)
    with open(log, "w") as f:
        rc = subprocess.run([str(c) for c in cmd], env=env, stdout=f,
                            stderr=subprocess.STDOUT,
                            timeout=1200).returncode
    print(f"  -> rc={rc} (log: {log})", flush=True)
    if check_rc is not None:
        assert rc == check_rc, \
            f"expected rc={check_rc}, got {rc}:\n{log.read_text()[-4000:]}"
    return rc


def run_until_killed(cmd, env, log: pathlib.Path, trigger,
                     what: str, timeout_s: float = 900.0) -> None:
    """Start the bench in its own process group, SIGKILL the whole
    group the moment ``trigger()`` is true.  The group kill takes the
    in-flight phase subprocess down with the driver — the same shape as
    an OOM-killer or operator ``kill -9`` on the session."""
    print("+", " ".join(map(str, cmd)), f"   [kill on: {what}]",
          flush=True)
    with open(log, "w") as f:
        p = subprocess.Popen([str(c) for c in cmd], env=env, stdout=f,
                             stderr=subprocess.STDOUT,
                             start_new_session=True)
        t0 = time.time()
        try:
            while not trigger():
                if p.poll() is not None:
                    raise AssertionError(
                        f"bench exited rc={p.returncode} before "
                        f"'{what}' ever happened:\n"
                        f"{log.read_text()[-4000:]}")
                if time.time() - t0 > timeout_s:
                    raise AssertionError(f"timed out waiting for {what}")
                time.sleep(0.05)
        finally:
            try:
                os.killpg(os.getpgid(p.pid), signal.SIGKILL)
            except ProcessLookupError:
                pass
        p.wait()
    print(f"  -> SIGKILLed after {time.time() - t0:.1f}s on: {what}",
          flush=True)


def read_jsonl(path: pathlib.Path) -> list:
    recs = []
    if not path.exists():
        return recs
    for line in path.read_text().splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            recs.append(json.loads(line))
        except ValueError:
            pass  # torn tail from a kill — exactly what resume tolerates
    return recs


def completed_queries(progress: pathlib.Path) -> set:
    return {r["query"] for r in read_jsonl(progress)
            if r.get("query") and not r.get("failed")}


def main() -> int:
    work = pathlib.Path(tempfile.mkdtemp(prefix="ndstpu_chaos"))
    tpl_dir = work / "tpl"
    tpl_dir.mkdir()
    import shutil
    from ndstpu.queries import streamgen
    for t in TEMPLATES:
        shutil.copy(streamgen.TEMPLATE_DIR / t, tpl_dir / t)

    # ---- A. fault-free baseline -------------------------------------
    root_a = work / "baseline"
    root_a.mkdir()
    cfg_a = make_cfg(root_a, tpl_dir)
    run_logged(bench_cmd(cfg_a), base_env(), work / "a.log", check_rc=0)
    base_done = completed_queries(
        pathlib.Path(str(root_a / "power.csv") + ".progress.jsonl"))
    assert base_done, "baseline recorded no completed queries"
    base_metrics = dict(
        line.split(",", 1) for line in
        (root_a / "metrics.csv").read_text().splitlines())
    assert int(base_metrics["metric"]) > 0

    # ---- B. injected io.write fault crashes the run -----------------
    root_b = work / "chaos"
    root_b.mkdir()
    cfg_b = make_cfg(root_b, tpl_dir)
    rc = run_logged(bench_cmd(cfg_b),
                    base_env(NDSTPU_FAULTS=CRASH_FAULTS),
                    work / "b.log")
    assert rc != 0, "io.write fault did not fail the bench"
    assert "[faults] injected" in (work / "b.log").read_text(), \
        "no [faults] injection line in the crashed run's log"

    run_state = root_b / "RUN_STATE.json"
    progress = pathlib.Path(str(root_b / "power.csv") +
                            ".progress.jsonl")

    # ---- C. resume, SIGKILL mid-power -------------------------------
    run_until_killed(
        bench_cmd(cfg_b, resume=True),
        base_env(NDSTPU_FAULTS=CHAOS_FAULTS),
        work / "c.log",
        trigger=lambda: bool(completed_queries(progress)),
        what="first completed query in the power progress journal")
    killed_done = completed_queries(progress)
    assert killed_done and killed_done < base_done, \
        (f"kill window missed: journal has {sorted(killed_done)} of "
         f"{sorted(base_done)} — power finished before the SIGKILL")
    phases_c = {r.get("phase") for r in read_jsonl(run_state)}
    assert "load_test" in phases_c and "power_test" not in phases_c, \
        f"unexpected journaled phases after mid-power kill: {phases_c}"

    # ---- D. resume (skip journaled queries), SIGKILL mid-throughput -
    run_until_killed(
        bench_cmd(cfg_b, resume=True),
        base_env(NDSTPU_FAULTS=CHAOS_FAULTS),
        work / "d.log",
        trigger=lambda: "power_test" in
        {r.get("phase") for r in read_jsonl(run_state)},
        what="power_test journaled in RUN_STATE.json")
    d_log = (work / "d.log").read_text()
    assert "[faults] injected" in d_log
    assert "[resume]" in d_log, "resume run D skipped nothing"

    # ---- E. clean resume runs to completion -------------------------
    run_logged(bench_cmd(cfg_b, resume=True), base_env(),
               work / "e.log", check_rc=0)
    e_log = (work / "e.log").read_text()
    assert "[resume] phase power_test already completed" in e_log, \
        "final resume re-ran the power phase"

    # every phase journaled; queries finished before the kills were
    # skipped, the rest ran — union must equal the baseline set
    phases = {r.get("phase") for r in read_jsonl(run_state)}
    assert PHASES <= phases, f"missing phases in RUN_STATE: " \
        f"{sorted(PHASES - phases)}"
    assert completed_queries(progress) == base_done

    # the power sidecar survives run E (phase skipped) and proves the
    # mid-power resume: run D carried over run C's completed queries
    sidecar = json.loads(
        (pathlib.Path(str(root_b / "power.csv") + ".metrics.json"))
        .read_text())
    assert sidecar.get("resumed"), \
        "power sidecar records no resumed (journal-skipped) queries"
    assert set(sidecar["resumed"]) == killed_done

    # ledger assertions: appended entries parse, and the retry layer
    # annotated the recovered injected faults with the attempt count
    ledger = read_jsonl(root_b / "ledger.jsonl")
    assert ledger, "run ledger is empty"
    retried = [e for e in ledger
               if (e.get("extra") or {}).get("retry_attempts", 0) >= 2]
    assert retried, "no ledger entry carries retry_attempts >= 2 " \
        "(injected transient faults were not retry-recovered)"

    # composite metric + query results match the fault-free baseline
    chaos_metrics = dict(
        line.split(",", 1) for line in
        (root_b / "metrics.csv").read_text().splitlines())
    assert set(chaos_metrics) == set(base_metrics)
    assert int(chaos_metrics["metric"]) > 0
    for k in ("scale_factor", "num_streams", "queries_per_stream"):
        assert chaos_metrics[k] == base_metrics[k], k
    import pyarrow.parquet as pq
    for q in sorted(base_done):
        a = pq.read_table(root_a / "out" / q)
        b = pq.read_table(root_b / "out" / q)
        assert a.equals(b), f"{q}: chaos-run result differs from baseline"
    print(f"results identical to baseline for {len(base_done)} queries")

    # ---- F. a permanent fault surfaces classified, never vanishes ---
    perm_log = root_b / "power_perm.csv"
    run_logged(
        [sys.executable, "-m", "ndstpu.harness.power",
         root_b / "streams" / "query_0.sql", root_b / "wh", perm_log,
         "--engine", "cpu", "--sub_queries", "query3",
         "--ledger", root_b / "ledger_perm.jsonl",
         "--scale_factor", "0.002"],
        base_env(NDSTPU_FAULTS="execute:permanent:1.0:seedP:times=1"),
        work / "f.log")
    perm_sidecar = json.loads(
        pathlib.Path(str(perm_log) + ".metrics.json").read_text())
    tax = (perm_sidecar.get("faultTaxonomy") or {}).get("counts") or {}
    assert tax.get("permanent", 0) >= 1, \
        f"permanent fault missing from sidecar taxonomy: {tax}"
    verdicts = ((perm_sidecar.get("sentinel") or {}).get("counts")
                or {})
    assert verdicts.get("failed-permanent", 0) >= 1, \
        f"no failed-permanent sentinel verdict: {verdicts}"

    # ---- G. SIGKILL mid-ingest resumes to a baseline-identical ------
    # snapshot (harness/ingest.py journal + abort_to_version; the full
    # differential lives in scripts/ingest_smoke.py — this scenario
    # keeps the crash shape in the one-command chaos gate)
    from ndstpu.io import lake
    import numpy as np
    import pyarrow as pa
    wh_g = work / "ingest_wh"
    wh_g.mkdir()
    for t in ("alpha", "beta"):
        at = pa.table({"k": np.arange(8, dtype=np.int64),
                       "v": np.arange(8, dtype=np.float64)})
        lake.create_table("ndslake", str(wh_g / t), at)
    wh_g_ctl = work / "ingest_wh_ctl"
    shutil.copytree(wh_g, wh_g_ctl)
    ingest_cmd = [sys.executable, "-m", "ndstpu.harness.ingest",
                  wh_g, "--synthetic", "4"]
    ctl_cmd = list(ingest_cmd)
    ctl_cmd[3] = wh_g_ctl
    run_logged(ctl_cmd, base_env(), work / "g_ctl.log", check_rc=0)
    g_log = work / "g.log"
    run_until_killed(
        ingest_cmd + ["--batch_pause_s", "2.0"], base_env(), g_log,
        trigger=lambda: "done (attempts=" in
        (g_log.read_text() if g_log.exists() else ""),
        what="first journaled-done ingest micro-batch")
    run_logged(ingest_cmd + ["--resume"], base_env(),
               work / "g_resume.log", check_rc=0)
    assert "journaled done" in (work / "g_resume.log").read_text(), \
        "ingest resume re-applied an already-done micro-batch"
    assert lake.versions_vector(str(wh_g)) == \
        lake.versions_vector(str(wh_g_ctl)), \
        "SIGKILLed+resumed ingest landed on different snapshot versions"
    assert lake.warehouse_epoch(str(wh_g)) == \
        lake.warehouse_epoch(str(wh_g_ctl))
    for t in ("alpha", "beta"):
        a = lake.read(str(wh_g / t)).sort_by(
            [("k", "ascending"), ("v", "ascending")])
        b = lake.read(str(wh_g_ctl / t)).sort_by(
            [("k", "ascending"), ("v", "ascending")])
        assert a.equals(b), f"{t}: resumed contents differ from control"
    print("ingest SIGKILL scenario OK: resumed to control-identical "
          "snapshot versions and contents")

    # ---- H. SIGKILL the query server mid-flight; client recovers ----
    import threading

    from ndstpu.harness import power
    from ndstpu.serve.client import ServeClient

    def start_serve(sock, state_dir, log_path, env=None):
        cmd = [sys.executable, "-m", "ndstpu.harness.serve", "server",
               "--socket", str(sock),
               "--input_prefix", str(root_b / "wh"),
               "--engine", "cpu", "--state_dir", str(state_dir),
               "--ledger", "none"]
        print("+", " ".join(cmd), flush=True)
        f = open(log_path, "a")
        return subprocess.Popen(cmd, env=env or base_env(), stdout=f,
                                stderr=subprocess.STDOUT)

    qd_h = power.get_query_subset(
        power.gen_sql_from_stream(str(root_b / "streams" /
                                      "query_1.sql")),
        ["query3", "query96"])

    # uninterrupted control server: the ground-truth answers
    sock_ctl = work / "serve_ctl.sock"
    p_ctl = start_serve(sock_ctl, work / "serve_state_ctl",
                        work / "h_ctl.log")
    cli = ServeClient(str(sock_ctl))
    assert cli.wait_ready(120.0), "control server never got ready"
    control = [cli.sql(sql, max_rows=100000)["data"]
               for sql in qd_h.values()]
    cli.close()
    p_ctl.terminate()
    assert p_ctl.wait(timeout=120) == 0, "control drain exited nonzero"

    # chaos server: the first execute wedges in an injected hang, so
    # the SIGKILL deterministically lands with the query in flight
    sock_h = work / "serve_h.sock"
    state_h = work / "serve_state_h"
    h_log = work / "h_serve.log"
    p_h = start_serve(
        sock_h, state_h, h_log,
        env=base_env(NDSTPU_FAULTS="execute:hang:1.0:seedH:times=1:"
                                   "hang=60"))
    cli_h = ServeClient(str(sock_h), retries=30,
                        connect_timeout_s=180.0)
    assert cli_h.wait_ready(120.0), "chaos server never got ready"
    answers: list = []

    def pump():
        for sql in qd_h.values():
            answers.append(cli_h.sql(sql, max_rows=100000)["data"])

    th = threading.Thread(target=pump, daemon=True)
    th.start()
    t0 = time.time()
    while "[faults] injected" not in \
            (h_log.read_text() if h_log.exists() else ""):
        assert time.time() - t0 < 60, "hang fault never injected"
        assert p_h.poll() is None, "chaos server died on its own"
        time.sleep(0.05)
    p_h.kill()  # SIGKILL mid-hung-query: no drain, no goodbye
    p_h.wait(timeout=60)
    print(f"  -> serve SIGKILLed mid-flight after "
          f"{time.time() - t0:.1f}s; restarting healthy", flush=True)
    p_h2 = start_serve(sock_h, state_h, h_log)  # same socket + state
    th.join(240.0)
    assert not th.is_alive(), \
        "client never recovered through the server SIGKILL"
    assert answers == control, \
        "reconnect-and-retry answers differ from the control server"
    assert cli_h.retried >= 1, \
        "client claims it never retried across the kill"
    cli_h.close()
    p_h2.terminate()
    assert p_h2.wait(timeout=120) == 0
    starts = [r.get("event") for r in
              read_jsonl(state_h / "serve_journal.jsonl")]
    assert starts.count("server-start") == 2, starts
    print("serve SIGKILL scenario OK: client reconnect-retried to "
          f"control-identical results for {len(control)} queries")

    # ---- I. SIGKILL the fleet supervisor; replicas keep serving -----
    fleet_dir = work / "fleet_i"
    health_path = fleet_dir / "FLEET_HEALTH.json"

    def start_fleet_i(log_path: pathlib.Path) -> subprocess.Popen:
        cmd = [sys.executable, "-m", "ndstpu.harness.serve", "fleet",
               "--replicas", "2",
               "--input_prefix", str(root_b / "wh"),
               "--engine", "cpu", "--run_dir", str(fleet_dir),
               "--ledger", "none", "--probe_interval_s", "0.25"]
        print("+", " ".join(cmd), flush=True)
        f = open(log_path, "a")
        return subprocess.Popen(cmd, env=base_env(), stdout=f,
                                stderr=subprocess.STDOUT)

    def fleet_doc() -> dict:
        try:
            return json.loads(health_path.read_text())
        except (OSError, ValueError):
            return {}

    def wait_fleet(cond, what: str, timeout_s: float = 600.0) -> dict:
        t0 = time.time()
        while True:
            doc = fleet_doc()
            reps = doc.get("replicas") or []
            if len(reps) == 2 and all(r.get("ready") for r in reps) \
                    and cond(doc):
                return doc
            assert time.time() - t0 < timeout_s, \
                f"fleet never reached {what}: {doc}"
            time.sleep(0.25)

    p_sup = start_fleet_i(work / "i_fleet.log")
    doc_i = wait_fleet(lambda d: True, "2 ready replicas")
    pids_before = sorted(r["pid"] for r in doc_i["replicas"])
    endpoints_i = doc_i["endpoints"]

    p_sup.kill()  # SIGKILL the supervisor ONLY: no drain, no goodbye
    p_sup.wait(timeout=60)

    # replicas were launched in their own sessions: they must keep
    # serving, supervisor-less
    cli_i = ServeClient(endpoints_i, retries=4)
    sql_i = next(iter(qd_h.values()))
    orphan = cli_i.sql(sql_i, max_rows=100000)["data"]
    assert orphan == control[0], \
        "orphaned replicas answered differently from the control"

    # a supervisor restarted over the same run_dir probes the same
    # stable endpoints and re-adopts the live replicas — same pids,
    # no double-start, no restarts
    p_sup2 = start_fleet_i(work / "i_fleet.log")
    doc_i = wait_fleet(
        lambda d: d.get("supervisor_pid") == p_sup2.pid,
        "re-adoption by the restarted supervisor")
    pids_after = sorted(r["pid"] for r in doc_i["replicas"])
    assert pids_after == pids_before, \
        (f"restarted supervisor double-started replicas: "
         f"{pids_before} -> {pids_after}")
    assert all(r.get("adopted") for r in doc_i["replicas"]), \
        doc_i["replicas"]
    assert doc_i["counters"].get("serve.fleet.adopted", 0) >= 2, \
        doc_i["counters"]
    assert all(not r.get("restarts") for r in doc_i["replicas"]), \
        doc_i["replicas"]
    again = cli_i.sql(sql_i, max_rows=100000)["data"]
    assert again == control[0]
    cli_i.close()
    p_sup2.terminate()
    assert p_sup2.wait(timeout=180) == 0, \
        "re-adopting supervisor failed to drain on SIGTERM"
    print("fleet supervisor SIGKILL scenario OK: replicas served "
          f"supervisor-less; restart re-adopted pids {pids_after} "
          "without double-starting")

    print("chaos smoke OK: crash + 5 SIGKILLs resumed to "
          "baseline-identical results; permanent fault surfaced "
          "classified")
    shutil.rmtree(work, ignore_errors=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
