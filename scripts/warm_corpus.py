"""Full-corpus warm + steady-state timing on the current JAX platform.

Phase 1 discovers+warms every query (compile at discovery), persisting
size-plan records incrementally; phase 2 times a pure steady-state pass.
Writes JSON to .bench_cache/warm_report_sf{SF}.json.  A per-query
watchdog abandons a wedged compile in its daemon thread and keeps going.
"""
import json, os, pathlib, sys, threading, time

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
SF = f"{float(os.environ.get('NDSTPU_BENCH_SF', '1')):g}"
PER_Q = float(os.environ.get("NDSTPU_WARM_QUERY_TIMEOUT_S", "900"))

import jax
jax.config.update("jax_compilation_cache_dir",
                  str(REPO / ".bench_cache" / "xla_cache_tpu"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)

from ndstpu.engine.session import Session
from ndstpu.io import loader
from ndstpu.queries import streamgen

catalog = loader.load_catalog(str(REPO / ".bench_cache" / f"wh_sf{SF}"))
sess = Session(catalog, backend="tpu")
rec = str(REPO / ".bench_cache" / f"plans_sf{SF}.pkl")
try:
    print("preloaded", sess.preload_compiled(rec), flush=True)
except Exception as e:
    print("preload failed:", e, flush=True)

queries = streamgen.render_power_corpus()

# cheap-first ordering (NDSTPU_WARM_ORDER=<warm_report.json>): under a
# deadline, warming in ascending known cost covers the most queries
# before the q4/q11/q14/q67 compile monsters; unknown queries keep
# corpus order, after the known-cheap ones
_order = os.environ.get("NDSTPU_WARM_ORDER")
if _order:
    try:
        _known = json.load(open(_order)).get("discover", {})
        queries.sort(key=lambda q: (_known.get(q[0]) is None,
                                    _known.get(q[0], 0.0)))
        print(f"ordered by {_order}", flush=True)
    except Exception as e:
        print(f"order file unusable ({e}); corpus order", flush=True)

# overall deadline (NDSTPU_WARM_DEADLINE_S, wall seconds from start):
# when exceeded, remaining discover work is skipped; steady gets a
# bounded grace window past it (replays cost ~0.1-2s each, but a wedged
# TPU turns every replay into a PER_Q hang — the grace cap keeps that
# worst case from overrunning the deadline by hours).  Partial warm
# reports and caches are still written and valid.
_DEADLINE = time.time() + float(
    os.environ.get("NDSTPU_WARM_DEADLINE_S", "1e12"))

def run_one(sess, sql, slot):
    try:
        out = sess.sql(sql)
        out.to_rows()
        slot["ok"] = True
    except Exception as e:
        slot["err"] = f"{type(e).__name__}: {e}"

report = {"discover": {}, "steady": {}, "failed": {}}
only = set(sys.argv[1:])
for phase in ("discover", "steady"):
    # a complete steady section keeps the report usable as a timing
    # artifact even when discovery was cut, so steady runs past the
    # deadline — but only within a bounded grace window (~5s per
    # discovered query, 10min floor) measured from when steady STARTS
    # (a discover query that began just under the deadline may run up
    # to PER_Q past it; anchoring grace at _DEADLINE would then skip
    # steady entirely).  The cap exists so a post-discover TPU wedge
    # (every replay hanging for PER_Q) cannot overrun by hours.
    cutoff = _DEADLINE
    if phase == "steady":
        cutoff = max(_DEADLINE, time.time()) + \
            max(600.0, 5.0 * len(report["discover"]))
    for name, sql in queries:
        if time.time() > cutoff:
            print(f"== deadline hit in {phase}; stopping ==", flush=True)
            break
        if only and name not in only: continue
        if name in report["failed"]: continue
        if phase == "steady" and name not in report["discover"]:
            continue  # deadline-cut in discover: nothing to replay
        slot = {}
        th = threading.Thread(target=run_one, args=(sess, sql, slot), daemon=True)
        t0 = time.time()
        th.start(); th.join(PER_Q)
        dt = round(time.time() - t0, 3)
        if th.is_alive():
            report["failed"][name] = f"hang>{PER_Q}s in {phase}"
            print(f"{phase} {name}: HANG", flush=True)
            sess = Session(catalog, backend="tpu")
            try: sess.preload_compiled(rec)
            except Exception: pass
            continue
        if "err" in slot:
            report["failed"][name] = slot["err"]
            print(f"{phase} {name}: ERR {slot['err'][:200]}", flush=True)
            continue
        report[phase][name] = dt
        print(f"{phase} {name}: {dt}s", flush=True)
        if phase == "discover":
            try: sess.save_compiled(rec)
            except Exception as e: print("save failed:", e, flush=True)
    tot = sum(report[phase].values())
    print(f"== {phase} total {tot:.1f}s over {len(report[phase])} queries ==", flush=True)
with open(REPO / ".bench_cache" / f"warm_report_sf{SF}.json", "w") as f:
    json.dump(report, f, indent=1)

# phase 3 (opt-out: NDSTPU_WARM_RECHECK=0): replay the corpus once in a
# FRESH subprocess.  Segment-bearing queries compile a slightly
# different program variant from preloaded records than from the
# in-discovery warm context (same HLO text, different XLA cache key —
# root cause still open, docs/STATUS.md); the fresh pass pays each
# variant once and seeds the persistent cache so every later process
# (the power CLI, bench.py run 1) goes straight to compiled replay.
if os.environ.get("NDSTPU_WARM_RECHECK", "1") != "0":
    import subprocess
    # skip queries the discover/steady watchdog recorded as hung — the
    # child has no per-query watchdog, so replaying a wedged compile
    # would block this script (and sf10_bench.py above it) forever;
    # honor the same `only` CLI filter the first two phases use
    skip = set(report["failed"])
    # hand the child the SAME (name, sql) list this process warmed —
    # re-rendering in the child could silently diverge from the
    # parent's corpus (seed, render args) and warm the wrong queries
    # only queries that completed discovery: recheck re-pays program
    # VARIANTS of warmed queries — a deadline-cut query would pay its
    # whole cold compile here, without the parent's watchdog
    replay = [(name, sql) for name, sql in queries
              if name in report["discover"] and name not in skip
              and (not only or name in only)]
    if not replay:
        print("== recheck phase: nothing to replay ==", flush=True)
        raise SystemExit(0)
    qfile = REPO / ".bench_cache" / f"recheck_sf{SF}.json"
    with open(qfile, "w") as f:
        json.dump(replay, f)
    code = (
        "import sys, time, json, os; sys.path.insert(0, %r);\n"
        "import jax;\n"
        "jax.config.update('jax_compilation_cache_dir', %r);\n"
        "jax.config.update('jax_persistent_cache_min_compile_time_secs', 2.0);\n"
        "from ndstpu.engine.session import Session;\n"
        "from ndstpu.io import loader;\n"
        "cat = loader.load_catalog(%r);\n"
        "s = Session(cat, backend='tpu');\n"
        "print('recheck preloaded', s.preload_compiled(%r), flush=True)\n"
        "qs = json.load(open(%r))\n"
        "for name, sql in qs:\n"
        "    t0 = time.time()\n"
        "    try:\n"
        "        s.sql(sql).to_rows()\n"
        "        print(f'recheck {name}: {time.time()-t0:.2f}s', flush=True)\n"
        "    except Exception as e:\n"
        "        print(f'recheck {name}: ERR {e}', flush=True)\n"
    ) % (str(REPO), str(REPO / ".bench_cache" / "xla_cache_tpu"),
         str(REPO / ".bench_cache" / f"wh_sf{SF}"), rec, str(qfile))
    print("== recheck phase (fresh subprocess) ==", flush=True)
    # a whole-corpus ceiling keeps a wedged variant compile from
    # hanging the orchestration that invoked us; scale with the number
    # of queries actually replayed (most replay in seconds, a variant
    # compile costs ~20-95s)
    n = max(1, len(replay))
    ceiling = float(os.environ.get("NDSTPU_WARM_RECHECK_TIMEOUT_S",
                                   "7200"))
    # the global deadline bounds the WHOLE script, recheck included
    # (grant a minimum floor so a deadline hit mid-discover still seeds
    # at least the cheap variants)
    ceiling = min(ceiling, max(600.0, _DEADLINE - time.time()))
    try:
        subprocess.run([sys.executable, "-c", code], cwd=str(REPO),
                       timeout=min(PER_Q * max(4.0, 0.25 * n), ceiling))
    except subprocess.TimeoutExpired:
        print("== recheck phase timed out; persistent cache keeps "
              "whatever compiled ==", flush=True)
