"""Doc/artifact honesty lint CLI (ndstpu/obs/artifact_lint.py).

Fails (exit 1) when committed prose cites an artifact that is not in
the tree (including the root ``PLAN_LINT.*`` / ``CANON_AUDIT.*`` /
``MQO_AUDIT.*`` / ``DICT_AUDIT.*`` sweeps), or when a ``docs/*.json``
artifact pins
``engine_defaults``
that no longer match the engine source and is not stamped stale.

    python scripts/doc_lint.py [--root PATH]

Runs in CI after the functional suite (.github/workflows/test.yml) and
as a tier-1 test (tests/test_doc_lint.py), so a doc that cites a ghost
artifact cannot merge.
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from ndstpu.obs import artifact_lint  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--root", default=os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), help="repo root to lint")
    args = ap.parse_args(argv)
    findings = artifact_lint.lint_repo(args.root)
    for f in findings:
        print(f"doc-lint: {f}")
    if findings:
        print(f"doc-lint: {len(findings)} finding(s)", file=sys.stderr)
        return 1
    print("doc-lint: clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
