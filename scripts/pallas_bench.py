"""Micro-bench: XLA segment_sum vs the pallas one-hot MXU kernel on the
real chip (the grouped-aggregation hot op at NDS power-run shapes).

Usage:  python scripts/pallas_bench.py [rows] [segments]

Prints per-variant wall times and a JSON summary line.  Falls back to
interpret mode (and says so) when no TPU is attached.
"""

import json
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from ndstpu.ops import segsum  # noqa: E402


def timeit(fn, *args, reps=5):
    fn(*args)  # compile
    jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def main():
    rows = int(sys.argv[1]) if len(sys.argv) > 1 else 4_000_000
    segs = int(sys.argv[2]) if len(sys.argv) > 2 else 1024
    platform = jax.devices()[0].platform
    interpret = platform not in ("tpu", "axon")
    rng = np.random.RandomState(0)
    vals_f = jnp.asarray(rng.uniform(-100, 100, rows).astype(np.float32))
    vals_d = jnp.asarray(rng.randint(-10**9, 10**9, rows).astype(np.int64))
    gid = jnp.asarray(rng.randint(0, segs, rows).astype(np.int32))
    mask = jnp.asarray(rng.rand(rows) < 0.8)

    @jax.jit
    def xla_f32(v, g, m):
        return jax.ops.segment_sum(jnp.where(m, v, 0.0), g,
                                   num_segments=segs)

    @jax.jit
    def xla_i64(v, g, m):
        return jax.ops.segment_sum(
            jnp.where(m, v.astype(jnp.int64), 0), g, num_segments=segs)

    import functools
    pl_f32 = functools.partial(segsum.segment_sum_f32,
                               num_segments=segs, interpret=interpret)
    pl_dec = functools.partial(segsum.segment_sum_decimal,
                               num_segments=segs, interpret=interpret)

    t_xla_f = timeit(xla_f32, vals_f, gid, mask)
    t_pl_f = timeit(lambda v, g, m: pl_f32(v, g, m), vals_f, gid, mask)
    t_xla_i = timeit(xla_i64, vals_d, gid, mask)
    t_pl_d = timeit(lambda v, g, m: pl_dec(v, g, m)[0], vals_d, gid, mask)

    # correctness spot-check against XLA
    a = np.asarray(xla_f32(vals_f, gid, mask))
    b = np.asarray(pl_f32(vals_f, gid, mask))
    np.testing.assert_allclose(a, b, rtol=3e-4, atol=1.0)
    ai = np.asarray(xla_i64(vals_d, gid, mask))
    bi = np.asarray(pl_dec(vals_d, gid, mask)[0])
    np.testing.assert_array_equal(ai, bi)

    print(f"platform={platform} interpret={interpret} "
          f"rows={rows} segs={segs}")
    print(f"xla  segment_sum f32 : {t_xla_f*1e3:9.3f} ms")
    print(f"pallas one-hot   f32 : {t_pl_f*1e3:9.3f} ms "
          f"({t_xla_f/t_pl_f:.2f}x)")
    print(f"xla  segment_sum i64 : {t_xla_i*1e3:9.3f} ms")
    print(f"pallas limbs     i64 : {t_pl_d*1e3:9.3f} ms "
          f"({t_xla_i/t_pl_d:.2f}x)")
    print(json.dumps({
        "rows": rows, "segs": segs, "platform": platform,
        "xla_f32_ms": round(t_xla_f * 1e3, 3),
        "pallas_f32_ms": round(t_pl_f * 1e3, 3),
        "xla_i64_ms": round(t_xla_i * 1e3, 3),
        "pallas_i64_ms": round(t_pl_d * 1e3, 3),
    }))


if __name__ == "__main__":
    main()
