"""Micro-bench: XLA segment_sum vs the pallas one-hot MXU kernel on the
real chip (the grouped-aggregation hot op at NDS power-run shapes).

Usage:  python scripts/pallas_bench.py [rows] [segments]

Prints per-variant wall times and a JSON summary line.  Falls back to
interpret mode (and says so) when no TPU is attached.
"""

import json
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from ndstpu.ops import segsum  # noqa: E402


def timeit_device(step, reps=20):
    """Device-time per step: run `step` REPS times inside one jitted
    fori_loop (carry-chained so iterations cannot be hoisted) and force
    completion with device_get.  Host-side block_until_ready resolves
    EARLY over the axon tunnel, so per-call host timing measures only
    dispatch; the amortized loop + a real fetch measures the device.

    ``step(carry: f32 scalar) -> f32 scalar`` must fold the carry into
    its inputs and its output into the return."""

    @jax.jit
    def loop():
        return jax.lax.fori_loop(
            0, reps, lambda i, c: step(c), jnp.float32(0))

    jax.device_get(loop())  # compile + one full execution
    t0 = time.perf_counter()
    jax.device_get(loop())
    total = time.perf_counter() - t0

    # subtract the fixed dispatch+fetch round trip (measured empty-ish)
    @jax.jit
    def empty():
        return jnp.float32(0)

    jax.device_get(empty())
    samples = []
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_get(empty())
        samples.append(time.perf_counter() - t0)
    rtt = sorted(samples)[1]
    # floor at 1us: tiny shapes can finish inside one round trip and a
    # zero would blow up the ratio prints
    return max(total - rtt, 1e-6 * reps) / reps


def main():
    rows = int(sys.argv[1]) if len(sys.argv) > 1 else 4_000_000
    segs = int(sys.argv[2]) if len(sys.argv) > 2 else 1024
    platform = jax.devices()[0].platform
    interpret = platform not in ("tpu", "axon")
    rng = np.random.RandomState(0)
    vals_f = jnp.asarray(rng.uniform(-100, 100, rows).astype(np.float32))
    vals_d = jnp.asarray(rng.randint(-10**9, 10**9, rows).astype(np.int64))
    gid = jnp.asarray(rng.randint(0, segs, rows).astype(np.int32))
    mask = jnp.asarray(rng.rand(rows) < 0.8)

    @jax.jit
    def xla_f32(v, g, m):
        return jax.ops.segment_sum(jnp.where(m, v, 0.0), g,
                                   num_segments=segs)

    @jax.jit
    def xla_i64(v, g, m):
        return jax.ops.segment_sum(
            jnp.where(m, v.astype(jnp.int64), 0), g, num_segments=segs)

    import functools
    pl_f32 = functools.partial(segsum.segment_sum_f32,
                               num_segments=segs, interpret=interpret)
    pl_dec = functools.partial(segsum.segment_sum_decimal,
                               num_segments=segs, interpret=interpret)

    t_xla_f = timeit_device(
        lambda c: xla_f32(vals_f + c * 0, gid, mask)[0])
    t_pl_f = timeit_device(
        lambda c: pl_f32(vals_f + c * 0, gid, mask)[0])
    t_xla_i = timeit_device(
        lambda c: xla_i64(vals_d + c.astype(jnp.int64), gid,
                          mask)[0].astype(jnp.float32) * 0)
    t_pl_d = timeit_device(
        lambda c: pl_dec(vals_d + c.astype(jnp.int64), gid,
                         mask)[0][0].astype(jnp.float32) * 0)

    # correctness spot-check against XLA
    a = np.asarray(xla_f32(vals_f, gid, mask))
    b = np.asarray(pl_f32(vals_f, gid, mask))
    np.testing.assert_allclose(a, b, rtol=3e-4, atol=1.0)
    ai = np.asarray(xla_i64(vals_d, gid, mask))
    bi = np.asarray(pl_dec(vals_d, gid, mask)[0])
    np.testing.assert_array_equal(ai, bi)

    print(f"platform={platform} interpret={interpret} "
          f"rows={rows} segs={segs}")
    print(f"xla  segment_sum f32 : {t_xla_f*1e3:9.3f} ms")
    print(f"pallas one-hot   f32 : {t_pl_f*1e3:9.3f} ms "
          f"({t_xla_f/t_pl_f:.2f}x)")
    print(f"xla  segment_sum i64 : {t_xla_i*1e3:9.3f} ms")
    print(f"pallas limbs     i64 : {t_pl_d*1e3:9.3f} ms "
          f"({t_xla_i/t_pl_d:.2f}x)")
    print(json.dumps({
        "rows": rows, "segs": segs, "platform": platform,
        "xla_f32_ms": round(t_xla_f * 1e3, 3),
        "pallas_f32_ms": round(t_pl_f * 1e3, 3),
        "xla_i64_ms": round(t_xla_i * 1e3, 3),
        "pallas_i64_ms": round(t_pl_d * 1e3, 3),
    }))


if __name__ == "__main__":
    main()
