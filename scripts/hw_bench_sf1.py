"""Five-phase NDS benchmark on the real chip at SF1 + artifact capture.

Runs the full orchestrator (ndstpu/harness/bench.py) from
bench_hw_sf1.yml, then snapshots the phase reports into
docs/HW_BENCH_SF1.json so the metric run is reviewable from the repo
(the raw run dir lives in /tmp and does not survive the machine).

Execution strategy note (recorded in the artifact): the stream seed is
PINNED to the warmed bench corpus seed (bench_hw_sf1.yml `rngseed:`,
the orchestrated form of the reference stream generator's explicit
--rngseed), so the power phase (stream 0) replays the compiled TPU
programs scripts/warm_corpus.py built.  Streams 1-4 combine the seed
with their stream index, so throughput/maintenance still carry fresh
per-stream parameter draws; those one-shot queries run the engine's
eager discovery path (NDSTPU_WARM_REPLAY=0) — paying a 20-95 s XLA
compile per query would never amortize inside a single execution.
"""
from __future__ import annotations

import csv
import json
import os
import pathlib
import subprocess
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
RUN = pathlib.Path("/tmp/nds_hw")


def _read_csv(path: pathlib.Path):
    try:
        with open(path) as f:
            return list(csv.reader(f))
    except OSError:
        return None


def main() -> int:
    t0 = time.time()
    env = dict(os.environ,
               NDSTPU_WARM_REPLAY="0",
               NDSTPU_XLA_CACHE_DIR=str(
                   REPO / ".bench_cache" / "xla_cache_tpu"))
    cfg = REPO / "ndstpu" / "harness" / "bench_hw_sf1.yml"
    # the replay claim below must be derived, not asserted: if the warm
    # artifacts are absent (e.g. after an environment reset) the power
    # phase silently pays full discovery and the committed artifact
    # would otherwise still read as a warm steady-state run
    records = REPO / ".bench_cache" / "plans_sf1.pkl"
    records_present = records.exists()
    r = subprocess.run(
        [sys.executable, "-m", "ndstpu.harness.bench", str(cfg)],
        env=env, cwd=str(REPO))
    art: dict = {
        "config": str(cfg.relative_to(REPO)),
        "exit_code": r.returncode,
        "wall_s": round(time.time() - t0, 1),
        # the pin is a reproducibility deviation from spec 4.3.1 seed
        # chaining — recorded so the artifact is not mistaken for a
        # fresh-draw cold run (review finding, 2026-08-02)
        "rngseed_pinned": True,
        "compile_records_present": records_present,
        "execution_strategy": (
            "stream seed pinned to the warmed bench corpus seed "
            "(bench_hw_sf1.yml rngseed: bench): the power phase "
            + ("replays compiled TPU programs"
               if records_present else
               "had NO compile records — it paid full discovery, "
               "treat power numbers as cold")
            + "; streams 1-4 draw fresh per-stream parameters and run "
            "one-shot eager discovery (NDSTPU_WARM_REPLAY=0) because "
            "a per-query XLA compile cannot amortize in a single "
            "execution"),
    }
    metrics = _read_csv(RUN / "metrics.csv")
    if metrics:
        art["metrics"] = {row[0]: row[1] for row in metrics if len(row) == 2}
    for line in (RUN / "load_report.txt").read_text().splitlines() \
            if (RUN / "load_report.txt").exists() else []:
        if "Load Test Time" in line or "RNGSEED" in line:
            art.setdefault("load_report", []).append(line.strip())
    power = _read_csv(RUN / "power_time.csv")
    if power:
        art["power_per_query_s"] = {
            row[1]: round(float(row[2]) / 1000, 3)
            for row in power
            if len(row) >= 3 and row[1].startswith("query")}
        art["power_queries"] = len(art["power_per_query_s"])
    for fs, streams in (("tt1", (1, 2)), ("tt2", (3, 4))):
        tot = {}
        for i in streams:
            rows = _read_csv(RUN / f"tt_time_{i}.csv")
            if rows:
                tot[f"stream_{i}_queries"] = sum(
                    1 for row in rows
                    if len(row) >= 3 and row[1].startswith("query"))
        if tot:
            art[fs] = tot
    for i in (1, 2, 3, 4):
        rows = _read_csv(RUN / f"dm_time_{i}.csv")
        if rows:
            # rows: (app_id, LF_*/DF_* function, milliseconds); trailer
            # rows carry start/end/elapsed in seconds
            art.setdefault("maintenance", {})[f"stream_{i}"] = {
                row[1]: round(float(row[2]) / 1000, 3)
                for row in rows
                if len(row) >= 3 and (row[1].startswith("LF_")
                                      or row[1].startswith("DF_"))}
    out = REPO / "docs" / "HW_BENCH_SF1.json"
    out.write_text(json.dumps(art, indent=1))
    print(json.dumps({k: v for k, v in art.items()
                      if k not in ("power_per_query_s", "maintenance")},
                     indent=1))
    print(f"written: {out}")
    return r.returncode


if __name__ == "__main__":
    raise SystemExit(main())
