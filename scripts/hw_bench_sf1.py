"""Five-phase NDS benchmark on the real chip at SF1 + artifact capture.

Runs the full orchestrator (ndstpu/harness/bench.py) from
bench_hw_sf1.yml, then snapshots the phase reports into
docs/HW_BENCH_SF1.json so the metric run is reviewable from the repo
(the raw run dir lives in /tmp and does not survive the machine).

Execution strategy note (recorded in the artifact): the stream seed is
PINNED to the warmed bench corpus seed (bench_hw_sf1.yml `rngseed:`,
the orchestrated form of the reference stream generator's explicit
--rngseed), so the power phase (stream 0) replays the compiled TPU
programs scripts/warm_corpus.py built.  Streams 1-4 combine the seed
with their stream index, so throughput/maintenance carry deterministic
per-stream draws (distinct per stream, identical across runs); those
one-shot queries run the engine's eager discovery path
(NDSTPU_WARM_REPLAY=0) — paying a 20-95 s XLA compile per query would
never amortize inside a single execution.  Because the draws repeat
across runs, throughput numbers are only cold when the persistent XLA
cache starts empty: a rerun against a populated cache serves those
same programs from disk.
"""
from __future__ import annotations

import csv
import json
import os
import pathlib
import subprocess
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
RUN = pathlib.Path("/tmp/nds_hw")


def _read_csv(path: pathlib.Path):
    try:
        with open(path) as f:
            return list(csv.reader(f))
    except OSError:
        return None


def main() -> int:
    t0 = time.time()
    xla_cache = REPO / ".bench_cache" / "xla_cache_tpu"
    env = dict(os.environ,
               NDSTPU_WARM_REPLAY="0",
               NDSTPU_XLA_CACHE_DIR=str(xla_cache))
    cfg = REPO / "ndstpu" / "harness" / "bench_hw_sf1.yml"
    import yaml
    with open(cfg) as f:
        cfg_params = yaml.safe_load(f)
    stream_cfg = cfg_params.get("generate_query_stream", {})
    # the pin is a reproducibility deviation from spec 4.3.1 seed
    # chaining — DERIVED from the config, not asserted, so an edited
    # yml cannot silently invalidate the recorded claim
    rngseed_pinned = "rngseed" in stream_cfg
    # resolved through the orchestrator's own resolver so the "bench"
    # sentinel -> streamgen.BENCH_RNGSEED mapping (and the unquoted-seed
    # validation) lives in exactly one place; the load report is only
    # consulted for unpinned seeds, which never reach this branch
    from ndstpu.harness.bench import resolve_stream_rngseed
    rngseed_resolved = resolve_stream_rngseed(
        stream_cfg, load_report_file="") if rngseed_pinned else None
    # the replay claim below must be derived, not asserted: if the warm
    # artifacts are absent (e.g. after an environment reset) the power
    # phase silently pays full discovery — and records alone are not
    # enough: without a populated persistent XLA cache the warm-up
    # replay still compiles every program from scratch
    records = REPO / ".bench_cache" / "plans_sf1.pkl"
    records_present = records.exists()
    xla_cache_present = xla_cache.is_dir() and any(xla_cache.iterdir())
    warm_artifacts = records_present and xla_cache_present
    r = subprocess.run(
        [sys.executable, "-m", "ndstpu.harness.bench", str(cfg)],
        env=env, cwd=str(REPO))
    art: dict = {
        "config": str(cfg.relative_to(REPO)),
        "exit_code": r.returncode,
        "wall_s": round(time.time() - t0, 1),
        "rngseed_pinned": rngseed_pinned,
        "rngseed_resolved": rngseed_resolved,
        "spec_compliance": {
            "spec_compliant_seed": not rngseed_pinned,
            "note": ("spec 4.3.1 chains RNGSEED from the load end "
                     "timestamp unconditionally (reference "
                     "nds_bench.py:413-414); a pinned seed trades "
                     "compliance for warm-cache reproducibility"),
        },
        "compile_records_present": records_present,
        "xla_cache_present": xla_cache_present,
        "execution_strategy": (
            ("stream seed pinned to the warmed bench corpus seed "
             f"(bench_hw_sf1.yml rngseed, resolved {rngseed_resolved}): "
             if rngseed_pinned else
             "stream seed chained from the load end timestamp "
             "(spec 4.3.1 — corpus differs from the warmed one): ")
            + "the power phase "
            + ("replays compiled TPU programs"
               if warm_artifacts and rngseed_pinned else
               "lacked warm artifacts (records and/or XLA cache) — it "
               "paid discovery/compile, treat power numbers as cold")
            + "; streams 1-4 carry deterministic per-stream draws "
            "(distinct per stream, identical across runs) and run "
            "one-shot eager discovery (NDSTPU_WARM_REPLAY=0) because "
            "a per-query XLA compile cannot amortize in a single "
            "execution; their numbers are cold only against an empty "
            "XLA cache"),
    }
    # tracer ground truth (power sidecar): the per-query compile_s the
    # engine actually measured adjudicates the warm-replay claim above
    sidecar = RUN / "power_time.csv.metrics.json"
    if sidecar.exists():
        try:
            pm = json.loads(sidecar.read_text())
            totals = pm.get("totals", {})
            art["power_attribution"] = totals
            art["power_cold_queries"] = totals.get("cold_queries")
            art["power_warm_replay_measured"] = (
                totals.get("n_queries", 0) > 0
                and totals.get("cold_queries", 1) == 0)
        except (ValueError, OSError) as e:
            art["power_attribution_error"] = str(e)
    metrics = _read_csv(RUN / "metrics.csv")
    if metrics:
        art["metrics"] = {row[0]: row[1] for row in metrics if len(row) == 2}
    for line in (RUN / "load_report.txt").read_text().splitlines() \
            if (RUN / "load_report.txt").exists() else []:
        if "Load Test Time" in line or "RNGSEED" in line:
            art.setdefault("load_report", []).append(line.strip())
    power = _read_csv(RUN / "power_time.csv")
    if power:
        art["power_per_query_s"] = {
            row[1]: round(float(row[2]) / 1000, 3)
            for row in power
            if len(row) >= 3 and row[1].startswith("query")}
        art["power_queries"] = len(art["power_per_query_s"])
    for fs, streams in (("tt1", (1, 2)), ("tt2", (3, 4))):
        tot = {}
        for i in streams:
            rows = _read_csv(RUN / f"tt_time_{i}.csv")
            if rows:
                tot[f"stream_{i}_queries"] = sum(
                    1 for row in rows
                    if len(row) >= 3 and row[1].startswith("query"))
        if tot:
            art[fs] = tot
    for i in (1, 2, 3, 4):
        rows = _read_csv(RUN / f"dm_time_{i}.csv")
        if rows:
            # rows: (app_id, LF_*/DF_* function, milliseconds); trailer
            # rows carry start/end/elapsed in seconds
            art.setdefault("maintenance", {})[f"stream_{i}"] = {
                row[1]: round(float(row[2]) / 1000, 3)
                for row in rows
                if len(row) >= 3 and (row[1].startswith("LF_")
                                      or row[1].startswith("DF_"))}
    out = REPO / "docs" / "HW_BENCH_SF1.json"
    out.write_text(json.dumps(art, indent=1))
    print(json.dumps({k: v for k, v in art.items()
                      if k not in ("power_per_query_s", "maintenance")},
                     indent=1))
    print(f"written: {out}")
    return r.returncode


if __name__ == "__main__":
    raise SystemExit(main())
