"""Ingest differential smoke: live HTAP vs quiesced batch, bit-exact.

CI gate for the crash-consistent continuous-ingest layer
(docs/ROBUSTNESS.md "Ingest commit protocol", docs/ARCHITECTURE.md
snapshot pinning).  One tiny corpus, four phases:

1. **Interleaved** — query threads pin snapshots
   (``Session.pin_snapshot``) and run fixed queries against one shared
   Session while a `MicroBatchIngestor` applies real LF_*/DF_* refresh
   functions concurrently.  Every observation is keyed by the pin's
   ``warehouse_epoch``; a live (unpinned) spine-cached query rides
   along so an ingest commit demonstrably drops the stale spine entry
   (``engine.snapshot.stale_drops`` >= 1).
2. **Quiesced ground truth** — the SAME refresh functions replayed one
   batch at a time over a pristine copy, recording each boundary
   epoch's query digests.  Every interleaved observation must be
   byte-identical to the quiesced digest of its epoch: concurrency may
   only change *which* epochs a query sees, never *what* an epoch
   contains.
3. **Chaos** — the interleaved run again with
   ``ingest.commit:transient:1.0:times=1`` injected: the first lake
   commit dies pre-publish, the retry retracts + GCs the orphan
   manifest, and the run must land on the SAME final epoch and
   truth-identical per-epoch digests, with ``engine.ingest.retries``
   >= 1.
4. **SIGKILL mid-ingest** — the ingest CLI
   (``python -m ndstpu.harness.ingest``) killed -9 after its first
   journaled batch, then ``--resume``d: final per-table snapshot
   versions, warehouse epoch, and table contents must equal an
   uninterrupted control run, and every ``CURRENT`` pointer must stay
   readable (old or new, never torn).

Writes ``INGEST_DIFF.json`` (a per-run artifact, like RUN_STATE.json —
never committed) next to the work dir for the CI log.
"""
from __future__ import annotations

import hashlib
import json
import os
import pathlib
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

# insert + delete refresh functions the SQL frontend fully plans (the
# returns-side LF_* need non-equi left joins — a pre-existing planner
# gap, not an ingest one)
FUNCS = ["LF_SS", "LF_WS", "DF_SS"]

QUERIES = {
    "agg_ss": "SELECT COUNT(ss_item_sk) AS c, SUM(ss_quantity) AS s "
              "FROM store_sales",
    "agg_ws": "SELECT COUNT(ws_item_sk) AS c, SUM(ws_quantity) AS s "
              "FROM web_sales",
    "join_ss": "SELECT d_year, COUNT(ss_item_sk) AS c "
               "FROM store_sales JOIN date_dim "
               "ON ss_sold_date_sk = d_date_sk "
               "WHERE d_moy = 11 GROUP BY d_year",
}

# the unpinned ride-along that exercises the spine cache across epochs
SPINE_QUERY = ("SELECT ss_store_sk, SUM(ss_quantity) AS s "
               "FROM store_sales GROUP BY ss_store_sk")

CHAOS_FAULTS = "ingest.commit:transient:1.0:seedI:times=1"


def digest(table) -> str:
    """Order-insensitive content hash of an engine result table: rows
    stringified (nulls as NULL), sorted, hashed."""
    import numpy as np
    cols = {}
    for name, col in table.columns.items():
        arr = np.asarray(col.data)
        if col.dictionary is not None:
            arr = np.asarray(col.dictionary)[arr]
        vals = arr.astype(str).astype(object)
        if col.valid is not None:
            vals[~np.asarray(col.valid)] = "NULL"
        cols[name] = vals
    names = sorted(cols)
    rows = sorted(zip(*(cols[k] for k in names))) if names else []
    h = hashlib.sha256()
    h.update("|".join(names).encode())
    for r in rows:
        h.update(("\x1f".join(r) + "\x1e").encode())
    return h.hexdigest()[:24]


def run_queries(sess, pin=None) -> dict:
    return {name: digest(sess.sql(text, pin=pin))
            for name, text in QUERIES.items()}


def assert_no_torn(warehouse: str) -> None:
    from ndstpu.io import lake
    for t in lake.lake_tables(warehouse):
        root = os.path.join(warehouse, t)
        v = lake.current_version(root)          # CURRENT parses
        assert lake.read(root, version=v).num_rows >= 0, t


def make_session(warehouse: str):
    from ndstpu.engine import spine as spine_mod
    from ndstpu.engine.session import Session
    from ndstpu.io import loader
    sess = Session(loader.load_catalog(warehouse), warehouse=warehouse)
    sess.spine_cache = spine_mod.SpineCache(64 << 20, None)
    return sess


def make_batches(sess, refresh_dir: str):
    from ndstpu.harness import maintenance
    maintenance.register_staging_views(sess, refresh_dir)
    queries = maintenance.get_maintenance_queries(sess, FUNCS)

    def sql_batch(stmts):
        def apply():
            for s in stmts:
                sess.sql(s)
        return apply
    return [(fn, sql_batch(queries[fn])) for fn in FUNCS]


def interleaved_run(warehouse: str, refresh_dir: str,
                    observations: dict) -> dict:
    """Phase 1/3 body: 2 pinned-query threads + 1 ingest thread over
    one shared Session.  Records digest observations keyed
    (epoch, query) into ``observations`` and returns run stats."""
    from ndstpu.harness.ingest import MicroBatchIngestor
    sess = make_session(warehouse)
    batches = make_batches(sess, refresh_dir)
    ing = MicroBatchIngestor(warehouse, sess=sess)
    done = threading.Event()
    errors = []
    obs_lock = threading.Lock()

    def observe(pin, results):
        with obs_lock:
            for name, dig in results.items():
                key = (pin.epoch, name)
                prev = observations.setdefault(key, dig)
                assert prev == dig, \
                    f"same-epoch divergence at {key}: {prev} vs {dig}"

    def query_worker():
        try:
            while True:
                pin = sess.pin_snapshot()
                observe(pin, run_queries(sess, pin=pin))
                sess.sql(SPINE_QUERY)  # unpinned: drives spine churn
                if done.is_set():
                    break
        except BaseException as e:                    # noqa: BLE001
            errors.append(e)
            done.set()

    def ingest_worker():
        try:
            ing.run(batches, batch_pause_s=0.3)
        except BaseException as e:                    # noqa: BLE001
            errors.append(e)
        finally:
            done.set()

    threads = [threading.Thread(target=query_worker) for _ in range(2)]
    threads.append(threading.Thread(target=ingest_worker))
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
    if errors:
        raise errors[0]
    # one final pinned round so the post-ingest epoch is always observed
    pin = sess.pin_snapshot()
    observe(pin, run_queries(sess, pin=pin))
    return {"final_epoch": pin.epoch,
            "records": [r["batch"] for r in ing.records()
                        if r.get("event") == "done"]}


def quiesced_truth(warehouse: str, refresh_dir: str) -> dict:
    """Phase 2: replay the same batches one at a time, recording every
    boundary epoch's digests — the ground truth."""
    from ndstpu.io import lake
    sess = make_session(warehouse)
    batches = make_batches(sess, refresh_dir)
    truth = {}
    epochs = [lake.warehouse_epoch(warehouse)]
    truth[epochs[-1]] = run_queries(sess)
    for _name, apply in batches:
        apply()
        epochs.append(lake.warehouse_epoch(warehouse))
        truth[epochs[-1]] = run_queries(sess)
    return {"epochs": epochs, "digests": truth}


def check_against_truth(observations: dict, truth: dict,
                        what: str) -> None:
    for (epoch, name), dig in sorted(observations.items()):
        assert epoch in truth["digests"], \
            f"{what}: observed epoch {epoch} is not a batch boundary " \
            f"(truth epochs: {truth['epochs']})"
        want = truth["digests"][epoch][name]
        assert dig == want, \
            f"{what}: {name}@{epoch} = {dig}, quiesced truth {want}"


def counters() -> dict:
    from ndstpu import obs
    return dict(obs.counters_snapshot())


def counter_delta(before: dict, after: dict, name: str) -> float:
    return after.get(name, 0) - before.get(name, 0)


def run_until_killed(cmd, env, log: pathlib.Path, trigger, what: str,
                     timeout_s: float = 600.0) -> None:
    print("+", " ".join(map(str, cmd)), f"   [kill on: {what}]",
          flush=True)
    with open(log, "w") as f:
        p = subprocess.Popen([str(c) for c in cmd], env=env, stdout=f,
                             stderr=subprocess.STDOUT,
                             start_new_session=True)
        t0 = time.time()
        try:
            while not trigger():
                if p.poll() is not None:
                    raise AssertionError(
                        f"ingest exited rc={p.returncode} before "
                        f"'{what}':\n{log.read_text()[-4000:]}")
                if time.time() - t0 > timeout_s:
                    raise AssertionError(f"timed out waiting for {what}")
                time.sleep(0.05)
        finally:
            try:
                os.killpg(os.getpgid(p.pid), signal.SIGKILL)
            except ProcessLookupError:
                pass
        p.wait()
    print(f"  -> SIGKILLed after {time.time() - t0:.1f}s on: {what}",
          flush=True)


def run_logged(cmd, env, log: pathlib.Path) -> None:
    print("+", " ".join(map(str, cmd)), flush=True)
    with open(log, "w") as f:
        rc = subprocess.run([str(c) for c in cmd], env=env, stdout=f,
                            stderr=subprocess.STDOUT,
                            timeout=600).returncode
    assert rc == 0, f"rc={rc}:\n{log.read_text()[-4000:]}"


def table_contents_equal(wh_a: str, wh_b: str) -> None:
    from ndstpu.io import lake
    tables = lake.lake_tables(wh_a)
    assert tables == lake.lake_tables(wh_b)
    for t in tables:
        a = lake.read(os.path.join(wh_a, t))
        b = lake.read(os.path.join(wh_b, t))
        order = [(c, "ascending") for c in a.column_names]
        assert a.sort_by(order).equals(b.sort_by(order)), \
            f"{t}: contents diverge between {wh_a} and {wh_b}"


def main() -> int:
    from ndstpu.faults import injector
    injector.uninstall()  # phases install their own specs
    work = pathlib.Path(tempfile.mkdtemp(prefix="ndstpu_ingest"))
    raw, raw_1 = work / "raw", work / "raw_1"
    env = dict(os.environ, PYTHONPATH=str(REPO),
               JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"))
    env.pop("NDSTPU_FAULTS", None)

    # ---- phase 0: corpus + pristine copies --------------------------
    run_logged([sys.executable, "-m", "ndstpu.datagen.driver", "local",
                "0.002", "2", raw], env, work / "gen.log")
    run_logged([sys.executable, "-m", "ndstpu.datagen.driver", "local",
                "0.002", "2", raw_1, "--update", "1"],
               env, work / "gen1.log")
    run_logged([sys.executable, "-m", "ndstpu.io.transcode",
                "--input_prefix", raw, "--output_prefix", work / "wh",
                "--report_file", work / "load.txt",
                "--output_format", "ndslake"], env, work / "load.log")
    for name in ("wh_truth", "wh_chaos", "wh_kill", "wh_kill_ctl"):
        shutil.copytree(work / "wh", work / name)

    # ---- phase 2 first: quiesced ground truth -----------------------
    truth = quiesced_truth(str(work / "wh_truth"), str(raw_1))
    assert len(truth["epochs"]) == len(FUNCS) + 1
    print(f"truth: {len(truth['epochs'])} boundary epochs "
          f"{truth['epochs']}", flush=True)

    # ---- phase 1: interleaved ingest + pinned queries ---------------
    c0 = counters()
    observations: dict = {}
    live = interleaved_run(str(work / "wh"), str(raw_1), observations)
    c1 = counters()
    check_against_truth(observations, truth, "interleaved")
    assert_no_torn(str(work / "wh"))
    seen_epochs = sorted({e for e, _ in observations})
    assert len(seen_epochs) >= 2, \
        f"interleaving observed only {seen_epochs} — no epoch motion"
    assert live["final_epoch"] == truth["epochs"][-1]
    stale = counter_delta(c0, c1, "engine.snapshot.stale_drops")
    pinned = counter_delta(c0, c1, "engine.snapshot.pinned")
    commits = counter_delta(c0, c1, "engine.ingest.commits")
    assert stale >= 1, "no stale spine drop across an ingest commit"
    assert pinned >= len(observations) / len(QUERIES)
    assert commits >= len(FUNCS)
    print(f"interleaved: {len(observations)} observations over "
          f"{len(seen_epochs)} epochs, {int(commits)} commits, "
          f"stale_drops={int(stale)}", flush=True)

    # ---- phase 3: chaos — injected commit fault, same differential --
    injector.install(CHAOS_FAULTS)
    try:
        chaos_obs: dict = {}
        chaos = interleaved_run(str(work / "wh_chaos"), str(raw_1),
                                chaos_obs)
    finally:
        injector.uninstall()
    c2 = counters()
    check_against_truth(chaos_obs, truth, "chaos")
    assert_no_torn(str(work / "wh_chaos"))
    retries = counter_delta(c1, c2, "engine.ingest.retries")
    assert retries >= 1, \
        "injected ingest.commit fault was never retried"
    assert chaos["final_epoch"] == truth["epochs"][-1], \
        "chaos run landed on a different final epoch than the " \
        "quiesced sequence — retraction did not restore the trajectory"
    table_contents_equal(str(work / "wh_chaos"), str(work / "wh_truth"))
    print(f"chaos: retries={int(retries)}, final epoch matches truth",
          flush=True)

    # ---- phase 4: SIGKILL mid-ingest, resume to identical snapshot --
    ingest_cmd = [sys.executable, "-m", "ndstpu.harness.ingest",
                  work / "wh_kill", "--refresh_data_path", raw_1,
                  "--funcs", ",".join(FUNCS)]
    ctl_cmd = list(ingest_cmd)
    ctl_cmd[3] = work / "wh_kill_ctl"
    run_logged(ctl_cmd, env, work / "kill_ctl.log")
    kill_log = work / "kill.log"
    run_until_killed(
        ingest_cmd + ["--batch_pause_s", "2.0"], env, kill_log,
        trigger=lambda: "done (attempts=" in
        (kill_log.read_text() if kill_log.exists() else ""),
        what="first journaled-done ingest batch")
    assert_no_torn(str(work / "wh_kill"))       # old or new, never torn
    run_logged(ingest_cmd + ["--resume"], env, work / "kill_resume.log")
    assert "journaled done" in (work / "kill_resume.log").read_text()

    from ndstpu.io import lake
    vk = lake.versions_vector(str(work / "wh_kill"))
    vc = lake.versions_vector(str(work / "wh_kill_ctl"))
    assert vk == vc, \
        f"resumed versions {vk} != uninterrupted control {vc}"
    ek = lake.warehouse_epoch(str(work / "wh_kill"))
    assert ek == lake.warehouse_epoch(str(work / "wh_kill_ctl"))
    assert ek == truth["epochs"][-1]
    table_contents_equal(str(work / "wh_kill"), str(work / "wh_kill_ctl"))
    print(f"sigkill: resumed to identical final snapshot "
          f"(epoch {ek}, versions match control)", flush=True)

    # ---- artifact ---------------------------------------------------
    diff = {
        "format": "ndstpu-ingest-diff-v1",
        "funcs": FUNCS,
        "queries": sorted(QUERIES),
        "truth_epochs": truth["epochs"],
        "interleaved": {
            "observations": len(observations),
            "epochs_observed": seen_epochs,
            "commits": int(commits),
            "stale_drops": int(stale),
            "pinned": int(pinned),
        },
        "chaos": {
            "retries": int(retries),
            "final_epoch": chaos["final_epoch"],
            "epochs_observed": sorted({e for e, _ in chaos_obs}),
        },
        "sigkill": {
            "final_versions": vk,
            "final_epoch": ek,
        },
    }
    (work / "INGEST_DIFF.json").write_text(json.dumps(diff, indent=1))
    print(f"ingest smoke OK: interleaved == quiesced across "
          f"{len(truth['epochs'])} epochs, chaos retried, SIGKILL "
          f"resumed bit-exact (INGEST_DIFF: {work / 'INGEST_DIFF.json'})")
    shutil.rmtree(work, ignore_errors=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
