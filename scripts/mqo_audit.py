#!/usr/bin/env python
"""Corpus-wide multi-query-optimization (common-spine) audit.

Sweeps every part of the power corpus through the static analyzer's
subtree pass (ndstpu/analysis/spines.py) — parse → plan → optimize →
per-subtree canonicalization over a ZERO-ROW schema catalog, so no
warehouse, no data, no jax — and builds the cross-corpus common-spine
index: which canonical subtrees ("spines") recur across DIFFERENT
query parts, and whether the runtime spine cache
(ndstpu/engine/spine.py) could legally materialize each one once and
splice it into every consumer.

Emits:

* ``MQO_AUDIT.json`` / ``MQO_AUDIT.md`` (repo root): the shared-spine
  index (fingerprint → consuming parts, byte estimate, shareability
  verdict) plus NDS5xx diagnostics.  Deterministic (no timestamps) so
  committed copies only change when the plans or the analyzer change.
* NDS5xx diagnostics per shared spine: NDS501 shared-spine candidate,
  NDS502 param-divergent (shared shape, different literal bindings —
  compile-shareable but not result-shareable), NDS503 order-sensitive
  (sort/window/limit inside — splicing could reorder rows), NDS504
  estimated bytes over the materialization budget (memplan row-width
  model).  With ``--baseline [PATH]``: exit nonzero iff a diagnostic
  is NOT in the committed baseline (docs/mqo_audit_baseline.json).
* With ``--write-baseline``: regenerate the baseline from this sweep.

Usage:
    python scripts/mqo_audit.py                      # artifacts only
    python scripts/mqo_audit.py --baseline           # CI gate
    python scripts/mqo_audit.py --write-baseline     # accept current set
"""

import argparse
import json
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

DEFAULT_BASELINE = REPO / "docs" / "mqo_audit_baseline.json"


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--baseline", nargs="?", const=str(DEFAULT_BASELINE),
                    default=None, metavar="PATH",
                    help="gate against this baseline (default: "
                         "docs/mqo_audit_baseline.json); exit 1 on new "
                         "diagnostics")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from this sweep")
    ap.add_argument("--json", default=str(REPO / "MQO_AUDIT.json"))
    ap.add_argument("--md", default=str(REPO / "MQO_AUDIT.md"))
    ap.add_argument("--rngseed", default="07291122510",
                    help="stream seed (pinned bench seed by default so "
                         "the artifact is reproducible)")
    ap.add_argument("--stream", type=int, default=0)
    ap.add_argument("--scale_factor", type=float, default=1.0,
                    help="scale factor for the NDS504 byte estimates")
    ap.add_argument("--sub_queries", default=None,
                    help="comma-separated query-part subset (CI tiny run)")
    return ap


def sweep(args):
    """part -> [SpineSite, ...] plus per-part analysis errors."""
    from ndstpu import analysis
    from ndstpu.engine.session import Session
    from ndstpu.queries import streamgen

    sess = Session(analysis.schema_catalog())
    tables = analysis.schema_tables()
    subset = set(args.sub_queries.split(",")) if args.sub_queries else None

    per_sites, errors = {}, {}
    for name, sql in streamgen.render_power_corpus(
            rngseed=args.rngseed, stream=args.stream):
        if subset is not None and name not in subset:
            continue
        try:
            res = analysis.analyze_sql(sess, name, sql, tables=tables,
                                       scale_factor=args.scale_factor,
                                       spine_pass=True)
            per_sites[name] = res.spine_sites or []
        except Exception as e:
            errors[name] = f"{type(e).__name__}: {e}"
            per_sites[name] = []
    return per_sites, errors


def run_audit(args) -> int:
    from ndstpu.analysis import diagnostics as diag_mod
    from ndstpu.analysis import spines

    per_sites, errors = sweep(args)
    budget, budget_source = spines.spine_budget_bytes()
    index, diags = spines.build_index(per_sites, budget_bytes=budget)
    doc = spines.index_to_doc(index, budget_bytes=budget)

    meta = {
        "rngseed": args.rngseed,
        "stream": args.stream,
        "scale_factor": args.scale_factor,
        "parts": len(per_sites),
        "errors": errors,
        "subtrees_indexed": doc["subtrees_indexed"],
        "budget_bytes": doc["budget_bytes"],
        "budget_source": budget_source,
    }
    meta.update(doc["summary"])

    out = {"meta": meta,
           "shared_spines": doc["shared_spines"],
           "diagnostics": [d.as_dict()
                           for d in diag_mod.sort_diagnostics(diags)]}
    pathlib.Path(args.json).write_text(
        json.dumps(out, indent=2, sort_keys=True) + "\n")

    lines = ["# Multi-query optimization audit (common spines)", ""]
    for k, v in sorted(meta.items()):
        lines.append(f"- **{k}**: {v}")
    lines += [
        "",
        f"{meta['shared_spine_candidates']} canonical subtrees are "
        f"shareable across >= 2 parts of the corpus "
        f"({meta['param_divergent']} of them param-divergent: one "
        "compiled shape, different literal bindings, so only "
        "value-identical renderings share a materialized result). "
        f"{meta['order_sensitive']} recurring subtrees are "
        "order-sensitive and excluded; "
        f"{meta['over_budget']} exceed the materialization budget.",
        "",
        "| fingerprint | kind | parts | n | value sets | est bytes "
        "| shareable |",
        "|---|---|---|---|---|---|---|"]
    for s in doc["shared_spines"]:
        qs = ", ".join(s["queries"])
        share = "yes" if s["shareable"] else f"**no** ({s['reason']})"
        lines.append(
            f"| `{s['fingerprint']}` | {s['kind']} | {qs} "
            f"| {s['n_queries']} | {s['n_value_sets']} "
            f"| {s['est_bytes'] if s['est_bytes'] is not None else '?'} "
            f"| {share} |")
    if diags:
        lines += ["", "## Diagnostics", ""]
        for d in diag_mod.sort_diagnostics(diags):
            lines.append(f"- `{d.query}` {d.code} [{d.path}]: "
                         f"{d.message}")
    pathlib.Path(args.md).write_text("\n".join(lines) + "\n")

    print(f"mqo-audit: {meta['parts']} parts, "
          f"{meta['subtrees_indexed']} subtrees indexed, "
          f"{meta['shared_spine_candidates']} shared-spine candidate(s), "
          f"{len(diags)} diagnostic(s) -> {args.json}")
    if errors:
        print(f"mqo-audit: {len(errors)} part(s) failed analysis: "
              f"{sorted(errors)}", file=sys.stderr)

    if args.write_baseline:
        DEFAULT_BASELINE.write_text(diag_mod.baseline_dump(diags))
        print(f"mqo-audit: baseline rewritten -> {DEFAULT_BASELINE}")

    if args.baseline is not None:
        bpath = pathlib.Path(args.baseline)
        if not bpath.exists():
            print(f"mqo-audit: baseline {bpath} missing "
                  "(run --write-baseline)", file=sys.stderr)
            return 2
        accepted = diag_mod.baseline_load(bpath.read_text())
        new = diag_mod.new_against_baseline(diags, accepted)
        if new:
            print(f"mqo-audit: {len(new)} diagnostic(s) not in baseline:",
                  file=sys.stderr)
            for d in new:
                print(f"  {d.query} {d.code} [{d.path}]: {d.message}",
                      file=sys.stderr)
            return 1
        print(f"mqo-audit: clean against baseline "
              f"({len(accepted)} accepted)")
    return 0


def main(argv=None) -> int:
    return run_audit(build_parser().parse_args(argv))


if __name__ == "__main__":
    raise SystemExit(main())
