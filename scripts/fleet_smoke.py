"""Fleet smoke: the replicated serving fleet under its four fates.

CI gate for ndstpu/serve/fleet.py (docs/ROBUSTNESS.md "Fleet
lifecycle").  One tiny warehouse, a serial ``power.py`` ground truth,
then fleet runs of N replicas x M failover clients
(``throughput --mode serve`` with a comma-separated fleet spec):

1. **Clean** — M concurrent clients over N replicas produce per-query
   parquet outputs **byte-identical** to the serial power runs, with
   per-replica attribution in the overlap report.  Then a FRESH
   replica booted with ``--aot_corpus`` + the fleet's shared compile
   records serves its first seen-shape query with
   ``engine.cache.compiled.miss`` delta 0.
2. **Replica SIGKILL mid-flight** — one serving replica is kill -9'd
   while clients stream; they fail over (``client.failovers >= 1``),
   ZERO queries fail, outputs stay byte-identical, and the supervisor
   restarts the dead replica with backoff.
3. **Rolling restart** — SIGHUP to the supervisor rolls every replica
   (drain one, others serve) while clients stream: zero failed
   queries, byte-identical outputs, every replica restarted exactly
   once.
4. **Memory-model backpressure** — ``NDSTPU_HBM_BYTES`` clamped +
   ``--queue_depth auto`` derive per-replica admission depth 1 from
   the memplan budget: overloaded replicas shed early, retries land
   on siblings, outputs stay byte-identical; the run prints the shed
   vs single-queueing-server p99 comparison (asserted only under
   NDSTPU_FLEET_SMOKE_STRICT=1 — CI boxes are too noisy for a hard
   latency gate).

Engine is ``tpu`` (jaxexec under JAX_PLATFORMS=cpu) so the shared
compile-record artifact — the thing that makes replica boots
zero-new-compiles — is actually in play.
"""
from __future__ import annotations

import argparse
import json
import os
import pathlib
import signal
import subprocess
import sys
import tempfile
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

SUBQ = "query3,query42,query96"


def env_for(**extra) -> dict:
    env = dict(os.environ, PYTHONPATH=str(REPO),
               JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"))
    env.pop("NDSTPU_FAULTS", None)
    env.pop("NDSTPU_HBM_BYTES", None)
    env.update({k: str(v) for k, v in extra.items() if v is not None})
    return env


def run(cmd, **kw):
    print("+", " ".join(map(str, cmd)), flush=True)
    return subprocess.run([str(c) for c in cmd], **kw)


def parquet_tree(prefix: pathlib.Path) -> dict:
    return {str(p.relative_to(prefix)): p.read_bytes()
            for p in sorted(prefix.rglob("part-0.parquet"))}


def assert_byte_identical(got: pathlib.Path, want: pathlib.Path,
                          leg: str) -> int:
    g, w = parquet_tree(got), parquet_tree(want)
    assert set(g) == set(w), \
        f"{leg}: output sets differ: {sorted(set(g) ^ set(w))}"
    for rel in w:
        assert g[rel] == w[rel], \
            f"{leg}: {rel} differs from the serial power run"
    return len(w)


def start_fleet(root: pathlib.Path, tag: str, replicas: int,
                out: pathlib.Path, aot_corpus=None,
                compile_records=None, queue_depth="64",
                env=None) -> subprocess.Popen:
    cmd = [sys.executable, "-m", "ndstpu.harness.serve", "fleet",
           "--input_prefix", root / "wh", "--engine", "tpu",
           "--replicas", str(replicas),
           "--run_dir", root / f"fleet_{tag}",
           "--output_prefix", out, "--output_format", "parquet",
           "--queue_depth", queue_depth,
           "--probe_interval_s", "0.25"]
    if aot_corpus:
        cmd += ["--aot_corpus", aot_corpus]
    if compile_records:
        cmd += ["--compile_records", compile_records]
    log = open(root / f"fleet_{tag}.log", "a")
    print("+", " ".join(map(str, cmd)), flush=True)
    return subprocess.Popen([str(c) for c in cmd],
                            env=env or env_for(),
                            stdout=log, stderr=subprocess.STDOUT)


def fleet_health(root: pathlib.Path, tag: str) -> dict:
    path = root / f"fleet_{tag}" / "FLEET_HEALTH.json"
    try:
        return json.loads(path.read_text())
    except (OSError, ValueError):
        return {}


def wait_fleet_ready(root: pathlib.Path, tag: str, n: int,
                     timeout_s: float = 600.0) -> dict:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        doc = fleet_health(root, tag)
        reps = doc.get("replicas") or []
        if len(reps) == n and all(r.get("ready") for r in reps):
            return doc
        time.sleep(0.25)
    raise AssertionError(
        f"fleet {tag} never got {n} replicas ready: "
        f"{fleet_health(root, tag)}")


def throughput_serve(root: pathlib.Path, endpoints: str, streams: str,
                     out: pathlib.Path, report: pathlib.Path,
                     **popen_kw) -> subprocess.Popen:
    cmd = [sys.executable, "-m", "ndstpu.harness.throughput", streams,
           "--mode", "serve", "--serve_socket", endpoints,
           "--overlap_report", report,
           "--", sys.executable, "-m", "ndstpu.harness.power",
           str(root / "streams") + "/query_{}.sql", root / "wh",
           str(root) + "/t_{}.csv", "--input_format", "ndslake",
           "--output_prefix", out, "--sub_queries", SUBQ]
    print("+", " ".join(map(str, cmd)), flush=True)
    return subprocess.Popen([str(c) for c in cmd], env=env_for(),
                            **popen_kw)


def wait_first_output(out: pathlib.Path, timeout_s: float = 600.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if list(out.rglob("part-0.parquet")):
            return
        time.sleep(0.1)
    raise AssertionError(f"no output ever appeared under {out}")


def one_shot_health(endpoint: str) -> dict:
    from ndstpu.serve.client import ServeClient
    cli = ServeClient(endpoint, retries=0, connect_timeout_s=3.0)
    try:
        return cli.health()
    except Exception as e:  # noqa: BLE001 — dead replica is data too
        return {"alive": False, "error": str(e)}
    finally:
        cli.close()


def check_overlap(report: pathlib.Path, leg: str,
                  want_failovers: bool = False) -> dict:
    ov = json.loads(report.read_text())
    assert ov["mode"] == "serve", ov.get("mode")
    assert all(s["returncode"] == 0 for s in ov["streams"]), \
        f"{leg}: a stream failed: {ov['streams']}"
    assert all(s["failures"] == 0 for s in ov["streams"]), \
        f"{leg}: failed queries: {ov['streams']}"
    total = ov.get("failovers_total", 0)
    if want_failovers:
        assert total >= 1, \
            f"{leg}: clients never failed over (failovers_total=0)"
    return ov


def max_p99_ms(endpoints: list) -> float:
    """Worst per-tenant ok-p99 across the given replicas."""
    from ndstpu.serve.client import ServeClient
    worst = 0.0
    for ep in endpoints:
        cli = ServeClient(ep, retries=0, connect_timeout_s=3.0)
        try:
            slo = cli.stats().get("slo") or {}
            for doc in (slo.get("tenants") or {}).values():
                worst = max(worst, float(doc.get("p99_ms") or 0.0))
        except Exception:  # noqa: BLE001 — evidence only
            pass
        finally:
            cli.close()
    return worst


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--clients", type=int, default=4)
    args = ap.parse_args()
    n_rep, n_cli = args.replicas, args.clients
    streams = ",".join(str(i) for i in range(1, n_cli + 1))

    root = pathlib.Path(tempfile.mkdtemp(prefix="ndstpu_fleet_smoke"))
    py = [sys.executable, "-m"]
    run(py + ["ndstpu.datagen.driver", "local", "0.002", "2",
              root / "raw"], check=True, env=env_for())
    run(py + ["ndstpu.io.transcode", "--input_prefix", root / "raw",
              "--output_prefix", root / "wh",
              "--report_file", root / "load.txt",
              "--output_format", "ndslake"],
        check=True, env=env_for(), stdout=subprocess.DEVNULL)
    run(py + ["ndstpu.queries.streamgen", "--output_dir",
              root / "streams", "--rngseed", "07291122510",
              "--streams", str(n_cli + 1)],
        check=True, env=env_for(), stdout=subprocess.DEVNULL)

    from ndstpu.harness import power

    # a mini AOT corpus: just the SUBQ blocks of stream 1 (single-
    # statement templates keep their stream markers, so the subset
    # file re-parses with gen_sql_from_stream)
    qd1 = power.get_query_subset(
        power.gen_sql_from_stream(root / "streams" / "query_1.sql"),
        SUBQ.split(","))
    corpus = root / "aot_corpus.sql"
    corpus.write_text("\n".join(qd1.values()))

    # ---- serial ground truth ----------------------------------------
    serial = root / "serial_out"
    for sid in streams.split(","):
        run(py + ["ndstpu.harness.power",
                  root / "streams" / f"query_{sid}.sql", root / "wh",
                  root / f"serial_time_{sid}.csv",
                  "--engine", "tpu", "--input_format", "ndslake",
                  "--output_prefix", serial / f"query_{sid}",
                  "--sub_queries", SUBQ],
            check=True, env=env_for(), stdout=subprocess.DEVNULL)
    n_serial = len(parquet_tree(serial))
    assert n_serial == n_cli * len(SUBQ.split(",")), \
        f"serial baseline wrote {n_serial} outputs"

    # ---- scenario 1: clean fleet parity + per-replica attribution ---
    out1 = root / "out1"
    fl1 = start_fleet(root, "s1", n_rep, out1, aot_corpus=corpus)
    shared_records = None
    try:
        doc = wait_fleet_ready(root, "s1", n_rep)
        endpoints = doc["endpoints"]
        shared_records = doc["shared_compile_records"]
        rep1 = root / "overlap1.json"
        r = throughput_serve(root, endpoints, streams, out1, rep1)
        assert r.wait(timeout=1200) == 0, "scenario 1 throughput failed"
        n = assert_byte_identical(out1, serial, "scenario1")
        ov = check_overlap(rep1, "scenario1")
        attrib = ov.get("replica_health") or {}
        assert len(attrib) == n_rep, \
            f"overlap report lacks per-replica attribution: {attrib}"
        served = {ep: h.get("ok", 0) for ep, h in attrib.items()}
        print(f"scenario 1 OK: {n} fleet outputs byte-identical to "
              f"serial; per-replica ok counts {served}")
    finally:
        fl1.send_signal(signal.SIGTERM)
        fl1.wait(timeout=180)

    # ---- scenario 1b: fresh --aot_corpus replica, zero compiles -----
    from ndstpu.serve.client import ServeClient
    sock1b = root / "s1b.sock"
    cmd = [sys.executable, "-m", "ndstpu.harness.serve", "server",
           "--socket", sock1b, "--input_prefix", root / "wh",
           "--engine", "tpu", "--state_dir", root / "state_1b",
           "--compile_records", shared_records,
           "--aot_corpus", corpus, "--bind_early",
           "--replica_id", "fresh", "--ledger", "none"]
    log = open(root / "server_1b.log", "w")
    print("+", " ".join(map(str, cmd)), flush=True)
    srv1b = subprocess.Popen([str(c) for c in cmd], env=env_for(),
                             stdout=log, stderr=subprocess.STDOUT)
    try:
        cli = ServeClient(str(sock1b), retries=8,
                          connect_timeout_s=180.0)
        assert cli.wait_ready(300.0), "aot replica never ready"
        probe = cli.probe()
        assert probe["replica_id"] == "fresh"
        assert (probe.get("aot") or {}).get("planned", 0) >= \
            len(SUBQ.split(",")), f"aot precompile missing: {probe}"
        miss0 = cli.request({"op": "stats"})["counters"].get(
            "engine.cache.compiled.miss", 0)
        first = cli.sql(next(iter(qd1.values())))
        miss1 = cli.request({"op": "stats"})["counters"].get(
            "engine.cache.compiled.miss", 0)
        assert first["status"] == "ok"
        assert miss1 == miss0, \
            (f"fresh --aot_corpus replica compiled on its first "
             f"seen-shape query: miss {miss0} -> {miss1}")
        cli.close()
        print(f"scenario 1b OK: fresh aot replica served its first "
              f"seen-shape query with compiled.miss delta 0")
    finally:
        srv1b.send_signal(signal.SIGTERM)
        srv1b.wait(timeout=120)

    # ---- scenario 2: replica SIGKILL mid-flight ---------------------
    out2 = root / "out2"
    fl2 = start_fleet(root, "s2", n_rep, out2,
                      compile_records=shared_records)
    try:
        doc = wait_fleet_ready(root, "s2", n_rep)
        endpoints = doc["endpoints"]
        rep2 = root / "overlap2.json"
        r = throughput_serve(root, endpoints, streams, out2, rep2)
        wait_first_output(out2)
        # kill a replica that is actually serving connections
        victim = None
        for rdoc in fleet_health(root, "s2")["replicas"]:
            h = one_shot_health(rdoc["endpoint"])
            if h.get("alive") and h.get("connections", 0) >= 1:
                victim = rdoc
                break
        victim = victim or fleet_health(root, "s2")["replicas"][0]
        print(f"scenario 2: SIGKILL {victim['replica_id']} "
              f"pid={victim['pid']} mid-flight")
        os.kill(int(victim["pid"]), signal.SIGKILL)
        assert r.wait(timeout=1200) == 0, \
            "scenario 2 throughput failed after replica kill"
        n = assert_byte_identical(out2, serial, "scenario2")
        ov = check_overlap(rep2, "scenario2", want_failovers=True)
        # the supervisor restarted the victim
        deadline = time.monotonic() + 120
        restarted = False
        while time.monotonic() < deadline and not restarted:
            for rdoc in (fleet_health(root, "s2").get("replicas")
                         or []):
                if rdoc["replica_id"] == victim["replica_id"] and \
                        rdoc.get("restarts", 0) >= 1 and \
                        rdoc.get("ready"):
                    restarted = True
            time.sleep(0.25)
        assert restarted, "supervisor never restarted the victim"
        print(f"scenario 2 OK: {n} outputs byte-identical through a "
              f"replica SIGKILL; failovers="
              f"{ov['failovers_total']}, zero failed "
              f"queries, victim restarted")
    finally:
        fl2.send_signal(signal.SIGTERM)
        fl2.wait(timeout=300)

    # ---- scenario 3: rolling restart under load ---------------------
    out3 = root / "out3"
    fl3 = start_fleet(root, "s3", n_rep, out3,
                      compile_records=shared_records)
    try:
        doc = wait_fleet_ready(root, "s3", n_rep)
        endpoints = doc["endpoints"]
        rep3 = root / "overlap3.json"
        r = throughput_serve(root, endpoints, streams, out3, rep3)
        wait_first_output(out3)
        print("scenario 3: SIGHUP -> rolling restart of all replicas")
        fl3.send_signal(signal.SIGHUP)
        assert r.wait(timeout=1800) == 0, \
            "scenario 3 throughput failed during rolling restart"
        n = assert_byte_identical(out3, serial, "scenario3")
        ov = check_overlap(rep3, "scenario3")
        retries = {s["stream"]: s["client_retries"]
                   for s in ov["streams"]}
        # the sweep rolls one replica at a time (N-1 stay ready the
        # whole way), so the load can finish before the last replica
        # has been rolled — poll until the sweep has visited all N
        deadline = time.monotonic() + 300.0
        doc = wait_fleet_ready(root, "s3", n_rep, timeout_s=300.0)
        while time.monotonic() < deadline:
            doc = wait_fleet_ready(root, "s3", n_rep, timeout_s=300.0)
            rolled = [rd for rd in doc["replicas"]
                      if rd.get("restarts", 0) >= 1 and rd.get("ready")]
            if len(rolled) == n_rep:
                break
            time.sleep(0.25)
        assert doc["counters"].get(
            "serve.fleet.rolling_restarts", 0) >= 1, doc["counters"]
        rolled = [rd for rd in doc["replicas"]
                  if rd.get("restarts", 0) >= 1]
        assert len(rolled) == n_rep, \
            f"rolling restart missed replicas: {doc['replicas']}"
        print(f"scenario 3 OK: {n} outputs byte-identical through a "
              f"rolling restart of {n_rep} replicas; zero failed "
              f"queries (client retries per stream: {retries})")
    finally:
        fl3.send_signal(signal.SIGTERM)
        fl3.wait(timeout=300)

    # ---- scenario 4: memory-model backpressure ----------------------
    # a clamped device budget + queue_depth auto => admission depth 1
    # per replica: overload sheds early and retries land on siblings
    out4 = root / "out4"
    clamp_env = env_for(NDSTPU_HBM_BYTES=str(192 << 20))
    fl4 = start_fleet(root, "s4", n_rep, out4,
                      compile_records=shared_records,
                      queue_depth="auto", env=clamp_env)
    try:
        doc = wait_fleet_ready(root, "s4", n_rep)
        endpoints = doc["endpoints"]
        h0 = one_shot_health(endpoints.split(",")[0])
        model = h0.get("admission_model") or {}
        assert model.get("budget_source") == "env", model
        assert h0.get("queue_depth") == model.get("depth"), h0
        rep4 = root / "overlap4.json"
        r = throughput_serve(root, endpoints, streams, out4, rep4)
        assert r.wait(timeout=1800) == 0, "scenario 4 throughput failed"
        n = assert_byte_identical(out4, serial, "scenario4")
        ov = check_overlap(rep4, "scenario4")
        attrib = ov.get("replica_health") or {}
        sheds = sum(h.get("overloaded", 0) for h in attrib.values())
        failovers = ov.get("failovers_total", 0)
        assert sheds >= 1 or failovers >= 1, \
            (f"memory-starved fleet never shed or failed over "
             f"(sheds={sheds} failovers={failovers})")
        fleet_p99 = max_p99_ms(endpoints.split(","))
    finally:
        fl4.send_signal(signal.SIGTERM)
        fl4.wait(timeout=300)

    # control: ONE server with the static depth-64 queue, same load —
    # every request queues behind a single admission gate
    sock4b = root / "s4b.sock"
    out4b = root / "out4b"
    cmd = [sys.executable, "-m", "ndstpu.harness.serve", "server",
           "--socket", sock4b, "--input_prefix", root / "wh",
           "--engine", "tpu", "--output_prefix", out4b,
           "--output_format", "parquet",
           "--state_dir", root / "state_4b",
           "--compile_records", shared_records,
           "--queue_depth", "64", "--ledger", "none"]
    log = open(root / "server_4b.log", "w")
    print("+", " ".join(map(str, cmd)), flush=True)
    srv4b = subprocess.Popen([str(c) for c in cmd], env=env_for(),
                             stdout=log, stderr=subprocess.STDOUT)
    try:
        r = throughput_serve(root, str(sock4b), streams, out4b,
                             root / "overlap4b.json")
        assert r.wait(timeout=1800) == 0, "scenario 4 control failed"
        control_p99 = max_p99_ms([str(sock4b)])
    finally:
        srv4b.send_signal(signal.SIGTERM)
        srv4b.wait(timeout=180)
    verdict = ("beats" if fleet_p99 and control_p99
               and fleet_p99 <= control_p99 else "does not beat")
    print(f"scenario 4 OK: {n} outputs byte-identical under clamped "
          f"HBM (depth={model.get('depth')}, sheds={sheds}, "
          f"failovers={failovers}); shed-and-failover p99 "
          f"{fleet_p99:.0f}ms {verdict} single-queue p99 "
          f"{control_p99:.0f}ms")
    if os.environ.get("NDSTPU_FLEET_SMOKE_STRICT") == "1":
        assert fleet_p99 <= control_p99, \
            (f"strict mode: fleet p99 {fleet_p99:.0f}ms worse than "
             f"queueing control {control_p99:.0f}ms")

    print(f"fleet smoke OK: clean parity, aot zero-compile, replica "
          f"kill, rolling restart, memory backpressure all held "
          f"({n_rep} replicas x {n_cli} clients)")
    import shutil
    shutil.rmtree(root, ignore_errors=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
