"""Per-query device-time / transfer / flops attribution over the corpus.

Answers the question wall-clock alone cannot (SURVEY §5 — the reference
records only wall ms): is a query dispatch-bound (fixed host<->device
round-trip floor), transfer-bound (result bytes over the link),
compute-bound (device execution), or host-bound (python planning/arg
prep)?

Writes docs/ATTRIBUTION.json and docs/ATTRIBUTION.md with, per query:
wall s, host-prep s, device s, fetch s, fetched bytes, program count,
XLA cost-analysis flops, achieved flops/s, and the bound class.  CPU
interpreter times from the bench cache (.bench_cache/cpu_times_sf1.json)
are joined in so the "losing" queries are directly classified.

Usage (uses the bench warehouse + persisted compile records):
    python scripts/attrib_corpus.py [--sf 1] [--queries q1,q2,...]
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

# attribution flag must be set before the executor is constructed
os.environ["NDSTPU_ATTRIB"] = "1"

# single v5e chip bf16 peak (for a utilization denominator; SQL kernels
# are int64/f64-emulation heavy, so utilization is expected to be tiny —
# the point is the RELATIVE classification, not a big MFU number)
PEAK_FLOPS = 394e12


def classify(wall: float, a: dict, ack_rtt: float,
             get_rtt: float) -> str:
    """Strip the tunnel's fixed latencies out of the raw spans before
    deciding what dominates: block_until_ready pays the completion-ack
    latency and device_get a fixed transfer round trip, so a trivial
    query reads as ~2x RTT of "device+fetch" that is really neither."""
    # the completion ack on a REAL program behaves like a fetch (the
    # trivial-program ack probe reads ~0 because its result rides back
    # on the execute response), so strip get_rtt from both spans
    rtt = max(ack_rtt, get_rtt)
    dev = max(0.0, a["device_s"] - rtt)
    xfer = max(0.0, a["fetch_s"] - rtt)
    host = a["host_prep_s"] + max(
        0.0, wall - a["host_prep_s"] - a["device_s"] - a["fetch_s"])
    floor = max(rtt / 2, 0.02)
    if dev < floor and xfer < floor and host < floor and \
            a["fetched_bytes"] < 2e6:
        return "dispatch-floor"
    spans = {"host": host, "compute": dev, "transfer": xfer}
    return max(spans, key=spans.get)


def measure_rtt(jax):
    """(completion-ack latency, fixed device_get latency) medians on a
    trivial warm program."""
    import jax.numpy as jnp
    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros(8, jnp.int32)
    y = f(x)
    y.block_until_ready()
    acks, gets = [], []
    for _ in range(5):
        t0 = time.perf_counter()
        z = f(x)
        z.block_until_ready()
        acks.append(time.perf_counter() - t0)
    # fresh result array per sample: device_get memoizes the fetched
    # value on the ArrayImpl, so re-getting y measures a local cache hit
    ys = [f(jnp.full(8, i, jnp.int32)) for i in range(5)]
    jax.block_until_ready(ys)
    for z in ys:
        t0 = time.perf_counter()
        jax.device_get(z)
        gets.append(time.perf_counter() - t0)
    return sorted(acks)[2], sorted(gets)[2]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sf", default="1")
    ap.add_argument("--queries", help="comma-separated subset")
    ap.add_argument("--out_json", default=str(REPO / "docs" / "ATTRIBUTION.json"))
    ap.add_argument("--out_md", default=str(REPO / "docs" / "ATTRIBUTION.md"))
    args = ap.parse_args()

    import jax
    jax.config.update("jax_compilation_cache_dir",
                      str(REPO / ".bench_cache" / "xla_cache_tpu"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
    from ndstpu.engine.session import Session
    from ndstpu.io import loader
    from ndstpu.queries import streamgen

    wh = str(REPO / ".bench_cache" / f"wh_sf{args.sf}")
    sess = Session(loader.load_catalog(wh), backend="tpu")
    rec = str(REPO / ".bench_cache" / f"plans_sf{args.sf}.pkl")
    try:
        n = sess.preload_compiled(rec)
        print(f"preloaded {n} compile records")
    except Exception as e:  # noqa: BLE001
        print(f"no compile records: {e}")

    queries = streamgen.render_power_corpus()
    if args.queries:
        want = set(args.queries.split(","))
        queries = [(n, s) for n, s in queries if n in want]

    cpu_times = {}
    try:
        with open(REPO / ".bench_cache" / f"cpu_times_sf{args.sf}.json") as f:
            cpu_times = json.load(f)["cpu_times"]
    except Exception:
        pass

    ack_rtt, get_rtt = measure_rtt(jax)
    print(f"tunnel latencies: completion-ack={ack_rtt*1000:.0f}ms "
          f"device_get={get_rtt*1000:.0f}ms")

    exe = sess._jax_executor()
    rows = []
    for name, sql in queries:
        # pass 1 warms (discovery/compile or preloaded-record replay),
        # pass 2 is the measured steady state
        try:
            sess.sql(sql).to_rows()
            exe.last_attribution = None
            t0 = time.perf_counter()
            sess.sql(sql).to_rows()
            wall = time.perf_counter() - t0
        except Exception as e:  # noqa: BLE001
            rows.append({"query": name, "error": f"{type(e).__name__}: {e}"})
            continue
        a = exe.last_attribution
        if a is None:
            rows.append({"query": name, "wall_s": round(wall, 4),
                         "bound": "eager-fallback"})
            continue
        flops = a.get("flops")
        entry = {
            "query": name,
            "wall_s": round(wall, 4),
            **a,
            "bound": classify(wall, a, ack_rtt, get_rtt),
        }
        if flops:
            dev = max(a["device_s"] - ack_rtt, 1e-9)
            entry["achieved_flops_per_s"] = round(flops / dev, 1)
            entry["utilization_pct"] = round(
                100.0 * flops / dev / PEAK_FLOPS, 4)
        if name in cpu_times:
            entry["cpu_s"] = cpu_times[name]
            entry["beats_cpu"] = wall < cpu_times[name]
        rows.append(entry)
        print(f"{name}: wall={wall:.3f}s dev={a['device_s']:.3f}s "
              f"fetch={a['fetch_s']:.3f}s ({a['fetched_bytes']} B) "
              f"-> {entry['bound']}")

    out = {"sf": args.sf, "peak_flops": PEAK_FLOPS,
           "ack_rtt_s": round(ack_rtt, 4), "get_rtt_s": round(get_rtt, 4),
           "queries": rows}
    pathlib.Path(args.out_json).write_text(json.dumps(out, indent=1))

    losers = [r for r in rows if r.get("beats_cpu") is False]
    md = ["# Per-query device-time attribution (real chip, SF" +
          args.sf + ")", "",
          "Spans per steady replay: host-prep (python arg build + plan "
          "cache), device (block_until_ready after dispatch), fetch "
          "(device->host result transfer).  The axon tunnel imposes a "
          "~80 ms fixed round trip on every fetch; `dispatch-floor` "
          "marks queries whose wall is that latency, not work.", "",
          "## Queries losing to the CPU interpreter", "",
          "| query | wall s | cpu s | device s | fetch s | bytes | bound |",
          "|---|---|---|---|---|---|---|"]
    for r in sorted(losers, key=lambda r: -(r.get("wall_s") or 0)):
        md.append(f"| {r['query']} | {r.get('wall_s')} | {r.get('cpu_s')}"
                  f" | {r.get('device_s')} | {r.get('fetch_s')} | "
                  f"{r.get('fetched_bytes')} | {r.get('bound')} |")
    counts: dict = {}
    for r in rows:
        counts[r.get("bound", "error")] = counts.get(
            r.get("bound", "error"), 0) + 1
    md += ["", "## Bound-class counts (all queries)", "",
           "| class | queries |", "|---|---|"]
    md += [f"| {k} | {v} |" for k, v in sorted(counts.items())]
    pathlib.Path(args.out_md).write_text("\n".join(md) + "\n")
    print(f"\n{len(rows)} queries attributed; "
          f"{len(losers)} losing to CPU; classes: {counts}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
