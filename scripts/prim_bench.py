"""Primitive microbench on the live chip, tunnel-overhead-corrected.

The axon tunnel costs ~0.1 s per dispatched program, so single-op
timings are meaningless.  Each case is measured as ONE jitted program
chaining the op k times with a data dependency (defeats CSE/DCE), for
k in {1, 9}; per-op cost = (t9 - t1) / 8.

    python scripts/prim_bench.py [--n 4194304]
"""
from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np


def timeit(build, args, k, reps=4):
    f = jax.jit(lambda *xs: build(k, *xs))
    o = f(*args)  # compile
    _ = np.asarray(jax.tree_util.tree_leaves(o)[0].ravel()[0])
    best = float("inf")
    for _ in range(reps):
        t0 = time.time()
        o = f(*args)
        _ = np.asarray(jax.tree_util.tree_leaves(o)[0].ravel()[0])
        best = min(best, time.time() - t0)
    return best


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1 << 22)
    ap.add_argument("--segs", type=int, default=1024)
    args = ap.parse_args()
    n, nseg = args.n, args.segs
    rng = np.random.default_rng(0)
    perm32 = jnp.asarray(rng.permutation(n).astype(np.int32))
    i32 = jnp.asarray(rng.integers(0, 1 << 20, n).astype(np.int32))
    i64 = i32.astype(jnp.int64)
    f32 = jnp.asarray(rng.random(n).astype(np.float32))
    f64 = f32.astype(jnp.float64)
    seg = jnp.asarray(rng.integers(0, nseg, n).astype(np.int32))

    # each builder: (k, *arrays) -> output, chaining k data-dependent ops
    def ew(k, a):
        for _ in range(k):
            a = a * 2 + 1
        return a

    def gather(k, a, p):
        for _ in range(k):
            a = a[p]
        return a

    def scat_set(k, a, p):
        for _ in range(k):
            a = jnp.zeros_like(a).at[p].set(a)
        return a

    def segsum(k, a, s):
        acc = jnp.zeros((nseg,), a.dtype)
        for _ in range(k):
            out = jax.ops.segment_sum(a, s, num_segments=nseg)
            acc = acc + out
            a = a + 1
        return acc

    def segsum_n(k, a, p):
        acc = jnp.zeros_like(a)
        for _ in range(k):
            out = jax.ops.segment_sum(a, p, num_segments=a.shape[0])
            acc = acc + out
            a = a + 1
        return acc

    def sort1(k, a):
        for i in range(k):
            a = jax.lax.sort(a + i)
        return a

    def sortpair(k, a):
        io = jax.lax.iota(jnp.int32, a.shape[0])
        for i in range(k):
            a, io = jax.lax.sort((a + i, io), num_keys=1, is_stable=True)
        return a + io

    def sortpair5(k, a):
        io = jax.lax.iota(jnp.int32, a.shape[0])
        ps = [io + j for j in range(4)]
        for i in range(k):
            res = jax.lax.sort((a + i,) + tuple(ps), num_keys=1,
                               is_stable=True)
            a, ps = res[0], list(res[1:])
        for p in ps:
            a = a + p
        return a

    def csum(k, a):
        for _ in range(k):
            a = jnp.cumsum(a) % (1 << 20)
        return a

    def ssearch(k, a, b):
        s = jax.lax.sort(a)
        acc = jnp.zeros_like(b)
        for i in range(k):
            acc = acc + jnp.searchsorted(s, b + i)
        return acc

    def onehot_mm(k, vals, s):
        # segment sum as (segs x rows_tile) one-hot matmuls, f32
        acc = jnp.zeros((nseg,), jnp.float32)
        for i in range(k):
            oh = (s[None, :] == jnp.arange(nseg, dtype=jnp.int32)[:, None])
            acc = acc + oh.astype(jnp.float32) @ vals
            vals = vals + 1
        return acc

    cases = [
        ("elementwise_i32", ew, (i32,)),
        ("elementwise_i64", ew, (i64,)),
        ("elementwise_f64", ew, (f64,)),
        ("gather_perm_i32", gather, (i32, perm32)),
        ("gather_perm_f64", gather, (f64, perm32)),
        ("scatter_set_perm_i32", scat_set, (i32, perm32)),
        (f"segsum_{nseg}_i32", segsum, (i32, seg)),
        (f"segsum_{nseg}_f32", segsum, (f32, seg)),
        (f"segsum_{nseg}_i64", segsum, (i64, seg)),
        ("segsum_nseg=n_i32", segsum_n, (i32, perm32)),
        ("sort_i32", sort1, (i32,)),
        ("sort_i64", sort1, (i64,)),
        ("sort_pair_i32", sortpair, (i32,)),
        ("sort_pair_i32_4pay", sortpair5, (i32,)),
        ("cumsum_i32", csum, (i32,)),
        ("searchsorted_i32", ssearch, (jax.lax.sort(i32), i32)),
    ]
    if nseg <= 4096:
        cases.append((f"onehot_mm_{nseg}_f32", onehot_mm, (f32, seg)))
    print(f"n = {n}, segs = {nseg}")
    for name, build, xs in cases:
        try:
            t1 = timeit(build, xs, 1)
            t9 = timeit(build, xs, 9)
            per = (t9 - t1) / 8
            print(f"{name:24s} per-op={per*1e3:8.2f} ms   "
                  f"(t1={t1*1e3:7.1f} t9={t9*1e3:7.1f})", flush=True)
        except Exception as e:  # noqa: BLE001
            print(f"{name:24s} FAIL {type(e).__name__}: {e}", flush=True)


if __name__ == "__main__":
    main()
