"""Warm the SF-N power-run caches query by query, with visibility and a
per-query watchdog: a query whose compile/execution hangs (wedged remote
compile RPC) is abandoned after --timeout seconds in a daemon thread and
the loop continues, so one pathological program cannot block the rest of
the corpus from warming."""

from __future__ import annotations

import os
import pathlib
import sys
import threading
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def main() -> None:
    # normalize the tag exactly like bench.py (f"{SF:g}"), so "1.0"
    # warms the same wh_sf1 / plans_sf1.pkl paths the bench reads
    sf = f"{float(os.environ.get('NDSTPU_BENCH_SF', '1')):g}"
    per_q = float(os.environ.get("NDSTPU_WARM_QUERY_TIMEOUT_S", "1500"))
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      str(REPO / ".bench_cache" / "xla_cache_tpu"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
    from ndstpu.engine.session import Session
    from ndstpu.io import loader
    from ndstpu.queries import streamgen

    wh = str(REPO / ".bench_cache" / f"wh_sf{sf}")
    catalog = loader.load_catalog(wh)
    sess = Session(catalog, backend="tpu")
    rec = str(REPO / ".bench_cache" / f"plans_sf{sf}.pkl")
    try:
        print("preloaded", sess.preload_compiled(rec), flush=True)
    except Exception as e:
        print("preload failed:", e, flush=True)

    queries = streamgen.render_power_corpus()
    start = sys.argv[1] if len(sys.argv) > 1 else None
    skipping = start is not None
    from bench import _run_one  # shared per-query worker (repo root)
    for name, sql in queries:
        if skipping:
            if name == start:
                skipping = False
            else:
                continue
        slot: dict = {}
        th = threading.Thread(target=_run_one, args=(sess, sql, slot),
                              daemon=True)
        t0 = time.time()
        th.start()
        th.join(per_q)
        if th.is_alive():
            print(f"HANG {name} (> {per_q:.0f}s) — abandoned", flush=True)
        elif not slot.get("ok"):
            print(f"FAIL {name}: {str(slot.get('err'))[:200]}", flush=True)
        else:
            print(f"OK   {name} {round(time.time() - t0, 1)}", flush=True)
        try:
            sess.save_compiled(rec)
        except Exception:
            pass


if __name__ == "__main__":
    main()
