"""Localize the power-CLI program-variant recompile (STATUS roadmap 4).

Observation: for segment-bearing queries, a fresh process that preloads
size-plan records compiles a *different XLA cache key* than the process
that originally discovered the query — the persistent cache misses and
the first power-CLI run pays a surprise compile even though the HLO
"looks" identical.

Method: run the SAME query twice, in two fresh subprocesses —
  A) discover: no records, full eager discovery + warm replay
  B) records:  preload .bench_cache/plans_sf<SF>.pkl, straight replay
— with ``jax._src.cache_key.get`` wrapped to record, per compiled
program: the module sym_name, the final cache key, the sha256 of each
key COMPONENT (computation / jax_lib versions / XLA flags / compile
options / accelerator config / compression), and the serialized MLIR
text.  The parent aligns programs by (sym_name, occurrence index) and
reports the first component whose hash differs; when it is the
computation itself, a unified diff of the MLIR localizes the divergent
op.

Usage:
    python scripts/variant_probe.py query1            # orchestrate
    python scripts/variant_probe.py --child discover query1 out.json
"""

from __future__ import annotations

import difflib
import json
import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
CACHE = REPO / ".bench_cache"
SF = f"{float(os.environ.get('NDSTPU_BENCH_SF', '1')):g}"
OUT = CACHE / "variant_probe"


def child(mode: str, qname: str, out_path: str) -> None:
    sys.path.insert(0, str(REPO))
    import hashlib

    import jax

    jax.config.update("jax_compilation_cache_dir",
                      str(CACHE / "xla_cache_tpu"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)

    from jax._src import cache_key as ck

    calls: list = []
    orig_get = ck.get

    def probed_get(module, devices, compile_options, backend,
                   compression_algorithm="zstandard",
                   ignore_callbacks=ck.IgnoreCallbacks.NO):
        key = orig_get(module, devices, compile_options, backend,
                       compression_algorithm, ignore_callbacks)
        # recompute each component hash exactly as cache_key.get does,
        # via its own private helpers (version-pinned jax 0.9.0)
        comp = {}
        try:
            def h(fn):
                o = hashlib.sha256()
                fn(o)
                return o.digest().hex()

            comp["computation"] = h(
                lambda o: ck._hash_computation(o, module,
                                               ignore_callbacks))
            comp["backend version"] = h(
                lambda o: ck._hash_platform(o, backend))
            comp["XLA flags"] = h(lambda o: ck._hash_xla_flags(
                o, ck.get_flag_prefixes()))
            comp["compile_options"] = h(
                lambda o: ck._hash_serialized_compile_options(
                    o, compile_options,
                    strip_device_assignment=(backend.platform == "gpu")))
            comp["accelerator_config"] = h(
                lambda o: ck._hash_accelerator_config(o, devices))
        except Exception as e:  # noqa: BLE001 — helper drift: keep key
            comp["error"] = f"{type(e).__name__}: {e}"
        idx = len(calls)
        mlir_path = f"{out_path}.{mode}.{idx}.mlir"
        try:
            with open(mlir_path, "w") as f:
                f.write(str(module))
        except Exception:  # noqa: BLE001
            mlir_path = None
        try:
            from jax._src.lib.mlir import ir
            name = ir.StringAttr(
                module.operation.attributes["sym_name"]).value
        except Exception:  # noqa: BLE001
            name = "?"
        calls.append({"sym_name": name, "key": key, "components": comp,
                      "mlir": mlir_path})
        return key

    ck.get = probed_get

    from ndstpu.engine.session import Session
    from ndstpu.io import loader
    from ndstpu.queries import streamgen

    catalog = loader.load_catalog(str(CACHE / f"wh_sf{SF}"))
    sess = Session(catalog, backend="tpu")
    if mode == "records":
        n = sess.preload_compiled(str(CACHE / f"plans_sf{SF}.pkl"))
        print(f"preloaded {n} records", flush=True)
    queries = dict(streamgen.render_power_corpus())
    sql = queries[qname]
    sess.sql(sql).to_rows()
    with open(out_path, "w") as f:
        json.dump(calls, f, indent=1)
    print(f"{mode}: {len(calls)} cache-key computations", flush=True)


def _align(a: list, b: list):
    """Pair program records by (sym_name, occurrence index)."""
    from collections import defaultdict
    occ_a: dict = defaultdict(list)
    occ_b: dict = defaultdict(list)
    for r in a:
        occ_a[r["sym_name"]].append(r)
    for r in b:
        occ_b[r["sym_name"]].append(r)
    pairs, only_a, only_b = [], [], []
    for name in {*occ_a, *occ_b}:
        xs, ys = occ_a.get(name, []), occ_b.get(name, [])
        for i in range(max(len(xs), len(ys))):
            if i < len(xs) and i < len(ys):
                pairs.append((f"{name}#{i}", xs[i], ys[i]))
            elif i < len(xs):
                only_a.append(f"{name}#{i}")
            else:
                only_b.append(f"{name}#{i}")
    return pairs, only_a, only_b


def main() -> int:
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        child(sys.argv[2], sys.argv[3], sys.argv[4])
        return 0
    qname = sys.argv[1] if len(sys.argv) > 1 else "query1"
    OUT.mkdir(parents=True, exist_ok=True)
    outs = {}
    for mode in ("discover", "records"):
        out = OUT / f"{qname}.{mode}.json"
        outs[mode] = out
        print(f"== child: {mode} ==", flush=True)
        subprocess.run(
            [sys.executable, __file__, "--child", mode, qname, str(out)],
            check=True, cwd=str(REPO))
    a = json.load(open(outs["discover"]))
    b = json.load(open(outs["records"]))
    pairs, only_a, only_b = _align(a, b)
    if only_a:
        print(f"programs only in discover: {only_a}")
    if only_b:
        print(f"programs only in records:  {only_b}")
    n_diff = 0
    for tag, ra, rb in pairs:
        if ra["key"] == rb["key"]:
            print(f"{tag}: MATCH ({ra['key'][-16:]})")
            continue
        n_diff += 1
        print(f"{tag}: KEY DIFFERS")
        ca, cb = ra["components"], rb["components"]
        named = False
        for name in sorted({**ca, **cb}):
            if ca.get(name) == cb.get(name):
                continue
            named = True
            print(f"  component '{name}' differs "
                  f"({str(ca.get(name, 'MISSING'))[:12]} vs "
                  f"{str(cb.get(name, 'MISSING'))[:12]})")
            if name == "computation" and ra["mlir"] and rb["mlir"]:
                ta = open(ra["mlir"]).read().splitlines()
                tb = open(rb["mlir"]).read().splitlines()
                d = list(difflib.unified_diff(
                    ta, tb, "discover", "records", lineterm="", n=1))
                print(f"  mlir diff: {len(d)} lines (first 60 below)")
                for line in d[:60]:
                    print(f"    {line}")
        if not named:
            # the differing input must be one the probe does not
            # recompute (jax_lib version / compression / custom_hook)
            print("  no recomputed component differs — divergence is "
                  "in jax_lib version, compression, or custom_hook")
    print(f"== {n_diff} differing program(s) over {len(pairs)} pairs ==")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
