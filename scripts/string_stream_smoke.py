"""String streaming smoke: the global-dictionary layer end to end.

CI gate for ndstpu/io/gdict.py (docs/ARCHITECTURE.md "Global
dictionary layer"): renders a tiny warehouse, forces a 2-device
virtual mesh, and runs a string-keyed join + string group-by with the
string table as the sharded fact, proving off-hardware that:

* **SPMD string join, no translation** — the probe side shards
  directly on frozen global-dictionary codes
  (``engine.dict.identity_joins`` ticks; before the layer, string keys
  went through a per-query build-dictionary searchsorted translation);
* **out-of-core string streaming** — the same query streams the
  string fact chunk-wise through ``ParquetChunkSource`` (>= 3
  launches) bit-identical to the resident run: every chunk decodes
  against the same frozen sidecar dictionary, which is exactly the
  invariant that made string tables streamable at all;
* **kill-switch parity** — a subprocess with ``NDSTPU_GLOBAL_DICTS=0``
  (per-call dictionaries, translate-path joins) produces byte-identical
  rows, and its chunk source rejects the string table
  (``StreamUnsupported``) as it did before the layer existed.

Usage::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=2 \
        python scripts/string_stream_smoke.py [warehouse_dir]
"""
from __future__ import annotations

import json
import os
import pathlib
import subprocess
import sys
import tempfile

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

N_DEV = 2
CHUNK_ROWS = 1000        # customer_address ~5k rows at SF 0.002
SHARD_THRESHOLD = 500    # makes the string table the sharded fact

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + f" --xla_force_host_platform_device_count={N_DEV}"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# string-keyed join (probe and build share one frozen column dict, so
# the identity fast path engages) + string group key + sorted output:
# any code-space disagreement anywhere surfaces as a row diff
SQL = ("select ca.ca_state, count(*) as cnt from customer_address ca "
       "join (select distinct ca_state as st from customer_address "
       "where ca_address_sk < 500) d on ca.ca_state = d.st "
       "group by ca.ca_state order by ca.ca_state")


def dist_rows(catalog, chunk_rows=None):
    from ndstpu.engine.session import Session
    from ndstpu.parallel import dplan, mesh as pmesh
    plan, _ = Session(catalog, backend="cpu").plan(SQL)
    kw = {"chunk_rows": chunk_rows} if chunk_rows else {}
    exe = dplan.DistributedPlanExecutor(
        catalog, pmesh.make_mesh(N_DEV),
        shard_threshold_rows=SHARD_THRESHOLD, **kw)
    return list(map(str, exe.execute_plan(plan).to_rows())), exe


def subprocess_probe(wh: str) -> dict:
    """Re-exec this script with the layer disabled: distributed rows
    on the translate path + whether the chunk source rejects strings."""
    env = dict(os.environ, PYTHONPATH=str(REPO),
               NDSTPU_GLOBAL_DICTS="0")
    out = subprocess.run(
        [sys.executable, __file__, "--_probe", wh],
        check=True, env=env, capture_output=True, text=True)
    return json.loads(out.stdout.splitlines()[-1])


def probe_mode(wh: str) -> int:
    from ndstpu.io import loader
    catalog = loader.load_catalog(wh)
    rows, _ = dist_rows(catalog)
    try:
        loader.ParquetChunkSource(wh, "customer_address")
        reject = None
    except loader.StreamUnsupported as e:
        reject = str(e)
    print(json.dumps({"rows": rows, "stream_reject": reject}))
    return 0


def main() -> int:
    if len(sys.argv) > 1 and sys.argv[1] == "--_probe":
        return probe_mode(sys.argv[2])

    from ndstpu import obs
    from ndstpu.engine import physical
    from ndstpu.engine.session import Session
    from ndstpu.io import loader

    if len(sys.argv) > 1:
        wh = sys.argv[1]
    else:
        root = pathlib.Path(tempfile.mkdtemp(prefix="ndstpu_strsmoke"))
        env = dict(os.environ, PYTHONPATH=str(REPO))
        for cmd in (
            [sys.executable, "-m", "ndstpu.datagen.driver", "local",
             "0.002", "2", str(root / "raw")],
            [sys.executable, "-m", "ndstpu.io.transcode",
             "--input_prefix", str(root / "raw"),
             "--output_prefix", str(root / "wh"),
             "--report_file", str(root / "load.txt")],
        ):
            print("+", " ".join(cmd), flush=True)
            subprocess.run(cmd, check=True, env=env,
                           stdout=subprocess.DEVNULL)
        wh = str(root / "wh")

    assert len(jax.devices()) == N_DEV, \
        f"expected a {N_DEV}-device mesh, got {len(jax.devices())}"
    catalog = loader.load_catalog(wh)
    plan, _ = Session(catalog, backend="cpu").plan(SQL)
    oracle = list(map(str, physical.execute(plan, catalog).to_rows()))
    if not oracle:
        return print("smoke broken: empty oracle result") or 1

    failures = []

    # resident distributed: identity fast path, no translation
    before = obs.counters_snapshot()
    resident, _ = dist_rows(catalog)
    d = obs.counter_delta(before)
    ident = d.get("engine.dict.identity_joins", 0)
    if resident != oracle:
        failures.append("resident distributed rows != numpy oracle")
    if not ident:
        failures.append(
            "string join did not take the global-code identity path "
            "(engine.dict.identity_joins did not tick)")

    # out-of-core: stream the string fact chunk-wise
    loader.attach_stream_source(
        catalog, "customer_address",
        loader.ParquetChunkSource(wh, "customer_address"))
    streamed, exe = dist_rows(catalog, chunk_rows=CHUNK_ROWS)
    chunked, n_launches = exe._chunk_info[0], exe._chunk_info[1]
    if not chunked or n_launches < 3:
        failures.append(
            f"expected >= 3 chunked launches over the string fact, got "
            f"chunked={chunked} n_launches={n_launches}")
    if streamed != oracle:
        failures.append(
            "chunk-streamed string rows are not bit-identical to the "
            "resident oracle")

    # kill switch: translate-path rows byte-identical, streaming rejected
    probe = subprocess_probe(wh)
    if probe["rows"] != oracle:
        failures.append(
            "NDSTPU_GLOBAL_DICTS=0 translate-path rows differ from the "
            "global-dict rows")
    if not probe["stream_reject"]:
        failures.append(
            "NDSTPU_GLOBAL_DICTS=0 chunk source should reject string "
            "columns (StreamUnsupported) but did not")

    if failures:
        print("\nstring stream smoke FAILED:")
        for f in failures:
            print(f"  {f}")
        return 1
    print(f"\nstring stream smoke ok: {len(oracle)} rows bit-identical "
          f"across resident / {n_launches}-launch chunked stream / "
          f"kill-switch translate path on a {N_DEV}-device mesh "
          f"(identity_joins={ident})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
