"""Hardware validation artifact: run the power CLI twice on the real
chip's session host (--engine tpu and --engine cpu), validate the per-
query outputs against each other with the validator CLI, and write the
per-query Pass/Fail table to VALIDATE_r{N}.json at the repo root.

The reference's correctness story is exactly this two-config diff over
the full corpus (/root/reference/nds/nds_validate.py:217-296); r03's
gap was that the differential only ever ran with JAX forced to CPU.

Usage:  python scripts/hw_validate.py [round_tag]   (default r04)
"""

import json
import os
import pathlib
import re
import subprocess
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
TAG = sys.argv[1] if len(sys.argv) > 1 else "r04"
SF = f"{float(os.environ.get('NDSTPU_BENCH_SF', '1')):g}"
WH = str(REPO / ".bench_cache" / f"wh_sf{SF}")
WORK = REPO / ".bench_cache" / f"hwval_{TAG}"


def main():
    WORK.mkdir(parents=True, exist_ok=True)
    # APPEND to PYTHONPATH: clobbering it would drop the host's
    # sitecustomize dir (axon PJRT plugin registration) and the child
    # power run could not initialize the TPU backend
    pp = os.environ.get("PYTHONPATH", "")
    env = dict(os.environ,
               PYTHONPATH=f"{REPO}{os.pathsep}{pp}" if pp else str(REPO))
    stream_dir = WORK / "streams"
    subprocess.run([sys.executable, "-m", "ndstpu.queries.streamgen",
                    "--streams", "1", "--rngseed", "07291122510",
                    "--output_dir", str(stream_dir)],
                   check=True, env=env, cwd=REPO)
    stream = str(stream_dir / "query_0.sql")

    runs = {}
    for engine in ("tpu", "cpu"):
        out = WORK / f"out_{engine}"
        js = WORK / f"js_{engine}"
        js.mkdir(exist_ok=True)
        t0 = time.time()
        cmd = [sys.executable, "-m", "ndstpu.harness.power", stream, WH,
               str(WORK / f"time_{engine}.csv"), "--engine", engine,
               "--output_prefix", str(out), "--output_format", "parquet",
               "--json_summary_folder", str(js)]
        if engine == "tpu":
            cmd += ["--compile_records",
                    str(REPO / ".bench_cache" / f"plans_sf{SF}.pkl"),
                    "--xla_cache_dir",
                    str(REPO / ".bench_cache" / "xla_cache_tpu")]
        r = subprocess.run(cmd, env=env, cwd=REPO)
        runs[engine] = {"rc": r.returncode,
                        "elapsed_s": round(time.time() - t0, 1)}
        print(f"{engine} power run rc={r.returncode} "
              f"{runs[engine]['elapsed_s']}s", flush=True)

    val = subprocess.run(
        [sys.executable, "-m", "ndstpu.harness.validate",
         str(WORK / "out_tpu"), str(WORK / "out_cpu"), stream,
         "--ignore_ordering",
         "--json_summary_folder", str(WORK / "js_tpu")],
        env=env, cwd=REPO, capture_output=True, text=True)
    print(val.stdout[-4000:], flush=True)
    if val.stderr:
        print("STDERR:", val.stderr[-2000:], flush=True)

    # collect per-query status from the updated TPU summaries
    statuses = {}
    for f in sorted((WORK / "js_tpu").glob("*.json")):
        with open(f) as fh:
            s = json.load(fh)
        q = s.get("query")
        if q:
            statuses[q] = s.get("queryValidationStatus",
                                s.get("queryStatus"))
    # normalize: list status -> scalar
    statuses = {q: (v[0] if isinstance(v, list) and v else v)
                for q, v in statuses.items()}
    n_pass = sum(1 for v in statuses.values() if v == "Pass")
    artifact = {
        "round": TAG,
        "scale_factor": float(SF),
        "platform": None,
        "engines": runs,
        "queries": dict(sorted(
            statuses.items(),
            key=lambda kv: [int(x) if x.isdigit() else x
                            for x in re.split(r"(\d+)", kv[0])])),
        "n_pass": n_pass,
        "n_total": len(statuses),
        "validator": "ndstpu.harness.validate --ignore_ordering "
                     "(epsilon 1e-5; q65/q67/q78 carve-outs per "
                     "reference nds_validate.py:146-237)",
    }
    try:
        import jax
        artifact["platform"] = str(jax.devices())
    except Exception:
        pass
    out_path = REPO / f"VALIDATE_{TAG}.json"
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=1)
    print(f"wrote {out_path}: {n_pass}/{len(statuses)} Pass", flush=True)


if __name__ == "__main__":
    main()
