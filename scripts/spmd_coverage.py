"""Distributed-coverage probe: which corpus query parts run under the
tpu-spmd executor, and why the rest fall back.

Usage:  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
            python scripts/spmd_coverage.py [warehouse_dir]

Renders every template part, plans it, and attempts the distributed
executor with a tiny shard threshold; prints a per-part verdict and a
histogram of DistUnsupported reasons.  Guides which dplan gaps matter.
"""

import collections
import os
import pathlib
import subprocess
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# jax may already be imported (axon sitecustomize): switch the platform
# via config before any backend initializes, like tests/conftest.py
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def main():
    from ndstpu.engine.session import Session
    from ndstpu.io import loader
    from ndstpu.parallel import dplan, mesh as pmesh
    from ndstpu.queries import streamgen

    if len(sys.argv) > 1:
        wh = sys.argv[1]
    else:
        tmp = tempfile.mkdtemp(prefix="spmdcov")
        data = os.path.join(tmp, "raw")
        wh = os.path.join(tmp, "wh")
        env = dict(os.environ, PYTHONPATH=os.getcwd())
        subprocess.run(["python", "-m", "ndstpu.datagen.driver", "local",
                        "0.002", "2", data], check=True, env=env)
        subprocess.run(["python", "-m", "ndstpu.io.transcode",
                        "--input_prefix", data, "--output_prefix", wh,
                        "--report_file", os.path.join(wh, "load.txt")],
                       check=True, env=env, stdout=subprocess.DEVNULL)

    catalog = loader.load_catalog(wh)
    mesh = pmesh.make_mesh(8)
    sess = Session(catalog, backend="cpu")

    reasons = collections.Counter()
    ok, fell = [], []
    for tpl in streamgen.list_templates():
        for name, sql in streamgen.render_template_parts(
                str(streamgen.TEMPLATE_DIR / tpl), "07291122510", 0):
            try:
                plan, _ = sess.plan(sql)
            except Exception as e:  # planner issue, not a dist gap
                reasons[f"PLAN: {e}"] += 1
                fell.append((name, f"PLAN: {e}"))
                continue
            try:
                dplan.execute_distributed(catalog, mesh, plan,
                                          shard_threshold_rows=500)
                ok.append(name)
                print(f"  OK   {name}", flush=True)
            except dplan.DistUnsupported as e:
                reasons[str(e)] += 1
                fell.append((name, str(e)))
                print(f"  FALL {name}: {e}", flush=True)
            except Exception as e:
                reasons[f"ERROR {type(e).__name__}: {e}"] += 1
                fell.append((name, f"ERROR {type(e).__name__}: {e}"))
                print(f"  ERR  {name}: {type(e).__name__}: {e}", flush=True)

    total = len(ok) + len(fell)
    print(f"\n== {len(ok)}/{total} parts distributed ==")
    for reason, cnt in reasons.most_common():
        print(f"{cnt:4d}  {reason}")
    print("\nfallback parts:")
    for name, reason in fell:
        print(f"  {name}: {reason}")


if __name__ == "__main__":
    main()
