"""Distributed full-corpus differential + NDS3xx coverage gate.

Every corpus query part must execute under the tpu-spmd executor on an
8-device virtual mesh AND produce rows equal to the single-process numpy
interpreter — the distributed analog of the reference's differential
validation loop (/root/reference/nds/nds_validate.py:217-260): outputs
are compared for EVERY query, not merely executed.

On top of the differential, the script emits **per-code NDS3xx counts**
(the DistUnsupported raise-site codes from the shared registry in
ndstpu/analysis/lowering.py) and gates them against a committed baseline
(docs/spmd_coverage_baseline.json): a part that distributed at the
baseline may never silently fall back again, and no NDS3xx code's count
may grow.  Accept intentional changes with --write-baseline.

Usage:  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
            python scripts/spmd_coverage.py [warehouse_dir] [--no-assert]
                [--baseline] [--write-baseline]
                [--sub_queries query1,query10,...]

Prints a per-part verdict (OK/ROWDIFF/FALL/ERR) and exits nonzero when
any part falls back or mismatches (unless --no-assert), or when
--baseline finds a regression.  The same row comparison is enforced in
CI by tests/test_parallel.py::test_dist_full_corpus_row_equal; the
--baseline gate is its own CI step over a corpus subset.
"""

import collections
import json
import os
import pathlib
import subprocess
import sys
import tempfile

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

BASELINE_PATH = REPO / "docs" / "spmd_coverage_baseline.json"

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# jax may already be imported (axon sitecustomize): switch the platform
# via config before any backend initializes, like tests/conftest.py
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def rows_match(want, got, eps=1e-5):
    """Validator-semantics comparison: row sets equal within epsilon
    (nds_validate.py:194-215 analog), order-insensitive."""
    if len(want) != len(got):
        return False

    def key(r):
        return tuple((v is None, str(v)) for v in r)

    for rw, rg in zip(sorted(want, key=key), sorted(got, key=key)):
        if len(rw) != len(rg):
            return False
        for vw, vg in zip(rw, rg):
            if vw is None or vg is None:
                if not (vw is None and vg is None):
                    return False
            elif isinstance(vw, float) or isinstance(vg, float):
                fw, fg = float(vw), float(vg)
                if fw != fg and abs(fw - fg) > \
                        eps * max(1.0, abs(fw), abs(fg)):
                    return False
            elif vw != vg:
                return False
    return True


def run_corpus(catalog, mesh, shard_threshold_rows=500, verbose=True,
               sub_queries=None, extras=None):
    """(ok, mismatched, fell) lists over every corpus part.  Fallbacks
    carry the NDS3xx diagnostic code of the DistUnsupported raise site
    (the shared registry in ndstpu/analysis/lowering.py names them),
    so the per-reason summary groups by analyzer code.

    `extras`, when a dict, receives: per-part status map ("ok" |
    "<NDS3xx>" | "mismatch" | "error"), attempt-code counts over parts
    that DID distribute (failed-candidate codes the executor recovered
    from), and the count of existence-join build sides reduced
    distributed (dplan._reduce_build engagements)."""
    from ndstpu.engine import physical
    from ndstpu.engine.session import Session
    from ndstpu.parallel import dplan
    from ndstpu.queries import streamgen

    sess = Session(catalog, backend="cpu")
    dev_cache: dict = {}
    ok, mism, fell = [], [], []
    statuses = {}
    attempt_codes = collections.Counter()
    build_reduced = 0
    for name, sql in streamgen.render_power_corpus(
            rngseed="07291122510", stream=0):
        if sub_queries is not None and name not in sub_queries:
            continue
        try:
            plan, _ = sess.plan(sql)
        except Exception as e:  # planner issue, not a dist gap
            fell.append((name, f"PLAN: {e}"))
            statuses[name] = "error"
            continue
        try:
            want = physical.execute(plan, catalog).to_rows()
        except Exception as e:  # oracle (numpy interpreter) defect
            fell.append((name, f"ORACLE: {type(e).__name__}: {e}"))
            statuses[name] = "error"
            continue
        try:
            exe = dplan.DistributedPlanExecutor(
                catalog, mesh,
                shard_threshold_rows=shard_threshold_rows,
                dev_cache=dev_cache)
            got = exe.execute_plan(plan).to_rows()
        except dplan.DistUnsupported as e:
            code = getattr(e, "code", None) or "uncoded"
            fell.append((name, f"{code}: {e}"))
            statuses[name] = code
            if verbose:
                print(f"  FALL {name}: {code}: {e}", flush=True)
            continue
        except Exception as e:
            fell.append((name, f"ERROR {type(e).__name__}: {e}"))
            statuses[name] = "error"
            if verbose:
                print(f"  ERR  {name}: {type(e).__name__}: {e}",
                      flush=True)
            continue
        attempt_codes.update(exe.attempt_codes)
        build_reduced += len(exe.build_reduced)
        if rows_match(want, got):
            ok.append(name)
            statuses[name] = "ok"
            if verbose:
                print(f"  OK   {name} ({len(got)} rows)", flush=True)
        else:
            mism.append((name, len(want), len(got)))
            statuses[name] = "mismatch"
            if verbose:
                print(f"  ROWDIFF {name}: {len(want)} vs {len(got)}",
                      flush=True)
    if extras is not None:
        extras["statuses"] = statuses
        extras["attempt_codes"] = dict(attempt_codes)
        extras["build_reduced"] = build_reduced
    return ok, mism, fell


def code_counts(statuses):
    """Per-NDS3xx-code fallback counts (plus mismatch/error buckets)."""
    return dict(collections.Counter(
        st for st in statuses.values() if st != "ok"))


def check_baseline(statuses, baseline):
    """Regressions of `statuses` vs the committed per-part baseline,
    restricted to the probed parts (subset runs gate their subset):

    * a part that was "ok" at the baseline must stay "ok";
    * "mismatch"/"error" are regressions regardless of the baseline;
    * a probed part missing from the baseline must be "ok" (anything
      else needs a conscious --write-baseline);
    * per-code totals over probed parts may not exceed the baseline's.
    """
    problems = []
    base_parts = baseline.get("parts", {})
    for name, st in sorted(statuses.items()):
        was = base_parts.get(name)
        if st in ("mismatch", "error"):
            problems.append(f"{name}: {st} (baseline {was or 'absent'})")
        elif was == "ok" and st != "ok":
            problems.append(f"{name}: fell back with {st}, was ok")
        elif was is None and st != "ok":
            problems.append(f"{name}: {st} not in baseline")
    probed = set(statuses)
    base_sub = {n: s for n, s in base_parts.items() if n in probed}
    now = collections.Counter(code_counts(statuses))
    was = collections.Counter(code_counts(base_sub))
    for code in sorted(now):
        if now[code] > was.get(code, 0):
            problems.append(
                f"{code}: {now[code]} part(s), baseline {was.get(code, 0)}")
    return problems


def main():
    from ndstpu.io import loader
    from ndstpu.parallel import mesh as pmesh

    assert_ok = "--no-assert" not in sys.argv
    use_baseline = "--baseline" in sys.argv
    write_baseline = "--write-baseline" in sys.argv
    sub_queries = None
    argv = sys.argv[1:]
    skip = set()
    for i, a in enumerate(argv):
        if a == "--sub_queries" and i + 1 < len(argv):
            sub_queries = set(argv[i + 1].split(","))
            skip.add(i + 1)
        elif a.startswith("--sub_queries="):
            sub_queries = set(a.split("=", 1)[1].split(","))
    args = [a for i, a in enumerate(argv)
            if not a.startswith("--") and i not in skip]
    if args:
        wh = args[0]
    else:
        tmp = tempfile.mkdtemp(prefix="spmdcov")
        data = os.path.join(tmp, "raw")
        wh = os.path.join(tmp, "wh")
        env = dict(os.environ, PYTHONPATH=os.getcwd())
        subprocess.run(["python", "-m", "ndstpu.datagen.driver", "local",
                        "0.002", "2", data], check=True, env=env)
        subprocess.run(["python", "-m", "ndstpu.io.transcode",
                        "--input_prefix", data, "--output_prefix", wh,
                        "--report_file", os.path.join(wh, "load.txt")],
                       check=True, env=env, stdout=subprocess.DEVNULL)

    catalog = loader.load_catalog(wh)
    mesh = pmesh.make_mesh(8)
    extras: dict = {}
    ok, mism, fell = run_corpus(catalog, mesh, sub_queries=sub_queries,
                                extras=extras)

    total = len(ok) + len(mism) + len(fell)
    print(f"\n== {len(ok)}/{total} parts distributed AND row-equal ==")
    reasons = collections.Counter(r for _, r in fell)
    for reason, cnt in reasons.most_common():
        print(f"{cnt:4d}  {reason}")
    for name, nw, ng in mism:
        print(f"  ROWDIFF {name}: want {nw} rows, got {ng}")
    counts = code_counts(extras["statuses"])
    print("\nper-code NDS3xx fallback counts:",
          json.dumps(counts, sort_keys=True) or "{}")
    print("attempt codes on distributed parts (recovered candidates):",
          json.dumps(extras["attempt_codes"], sort_keys=True))
    print(f"existence-join build sides reduced distributed: "
          f"{extras['build_reduced']}")

    if write_baseline:
        BASELINE_PATH.parent.mkdir(parents=True, exist_ok=True)
        BASELINE_PATH.write_text(json.dumps(
            {"parts": extras["statuses"], "code_counts": counts,
             "distributed": len(ok), "total": total},
            indent=2, sort_keys=True) + "\n")
        print(f"baseline written: {BASELINE_PATH}")
        return
    if use_baseline:
        if not BASELINE_PATH.exists():
            print(f"no baseline at {BASELINE_PATH}; run with "
                  "--write-baseline first", file=sys.stderr)
            sys.exit(2)
        baseline = json.loads(BASELINE_PATH.read_text())
        problems = check_baseline(extras["statuses"], baseline)
        if problems:
            print("\nSPMD coverage regressions vs baseline:")
            for p in problems:
                print(f"  {p}")
            sys.exit(1)
        print("\nbaseline ok: no SPMD coverage regression")
        return
    if assert_ok and (mism or fell):
        sys.exit(1)


if __name__ == "__main__":
    main()
