"""Distributed full-corpus differential: every corpus query part must
execute under the tpu-spmd executor on an 8-device virtual mesh AND
produce rows equal to the single-process numpy interpreter.

This is the distributed analog of the reference's differential
validation loop (/root/reference/nds/nds_validate.py:217-260): outputs
are compared for EVERY query, not merely executed.

Usage:  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
            python scripts/spmd_coverage.py [warehouse_dir] [--no-assert]

Prints a per-part verdict (OK/ROWDIFF/FALL/ERR) and exits nonzero when
any part falls back or mismatches (unless --no-assert).  The same
comparison is enforced in CI by tests/test_parallel.py::
test_dist_full_corpus_row_equal.
"""

import collections
import os
import pathlib
import subprocess
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# jax may already be imported (axon sitecustomize): switch the platform
# via config before any backend initializes, like tests/conftest.py
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def rows_match(want, got, eps=1e-5):
    """Validator-semantics comparison: row sets equal within epsilon
    (nds_validate.py:194-215 analog), order-insensitive."""
    if len(want) != len(got):
        return False

    def key(r):
        return tuple((v is None, str(v)) for v in r)

    for rw, rg in zip(sorted(want, key=key), sorted(got, key=key)):
        if len(rw) != len(rg):
            return False
        for vw, vg in zip(rw, rg):
            if vw is None or vg is None:
                if not (vw is None and vg is None):
                    return False
            elif isinstance(vw, float) or isinstance(vg, float):
                fw, fg = float(vw), float(vg)
                if fw != fg and abs(fw - fg) > \
                        eps * max(1.0, abs(fw), abs(fg)):
                    return False
            elif vw != vg:
                return False
    return True


def run_corpus(catalog, mesh, shard_threshold_rows=500, verbose=True):
    """(ok, mismatched, fell) lists over every corpus part.  Fallbacks
    carry the NDS3xx diagnostic code of the DistUnsupported raise site
    (the shared registry in ndstpu/analysis/lowering.py names them),
    so the per-reason summary groups by analyzer code."""
    from ndstpu.engine import physical
    from ndstpu.engine.session import Session
    from ndstpu.parallel import dplan
    from ndstpu.queries import streamgen

    sess = Session(catalog, backend="cpu")
    dev_cache: dict = {}
    ok, mism, fell = [], [], []
    for name, sql in streamgen.render_power_corpus(
            rngseed="07291122510", stream=0):
        try:
            plan, _ = sess.plan(sql)
        except Exception as e:  # planner issue, not a dist gap
            fell.append((name, f"PLAN: {e}"))
            continue
        try:
            want = physical.execute(plan, catalog).to_rows()
        except Exception as e:  # oracle (numpy interpreter) defect
            fell.append((name, f"ORACLE: {type(e).__name__}: {e}"))
            continue
        try:
            exe = dplan.DistributedPlanExecutor(
                catalog, mesh,
                shard_threshold_rows=shard_threshold_rows,
                dev_cache=dev_cache)
            got = exe.execute_plan(plan).to_rows()
        except dplan.DistUnsupported as e:
            code = getattr(e, "code", None) or "uncoded"
            fell.append((name, f"{code}: {e}"))
            if verbose:
                print(f"  FALL {name}: {code}: {e}", flush=True)
            continue
        except Exception as e:
            fell.append((name, f"ERROR {type(e).__name__}: {e}"))
            if verbose:
                print(f"  ERR  {name}: {type(e).__name__}: {e}",
                      flush=True)
            continue
        if rows_match(want, got):
            ok.append(name)
            if verbose:
                print(f"  OK   {name} ({len(got)} rows)", flush=True)
        else:
            mism.append((name, len(want), len(got)))
            if verbose:
                print(f"  ROWDIFF {name}: {len(want)} vs {len(got)}",
                      flush=True)
    return ok, mism, fell


def main():
    from ndstpu.io import loader
    from ndstpu.parallel import mesh as pmesh

    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    assert_ok = "--no-assert" not in sys.argv
    if args:
        wh = args[0]
    else:
        tmp = tempfile.mkdtemp(prefix="spmdcov")
        data = os.path.join(tmp, "raw")
        wh = os.path.join(tmp, "wh")
        env = dict(os.environ, PYTHONPATH=os.getcwd())
        subprocess.run(["python", "-m", "ndstpu.datagen.driver", "local",
                        "0.002", "2", data], check=True, env=env)
        subprocess.run(["python", "-m", "ndstpu.io.transcode",
                        "--input_prefix", data, "--output_prefix", wh,
                        "--report_file", os.path.join(wh, "load.txt")],
                       check=True, env=env, stdout=subprocess.DEVNULL)

    catalog = loader.load_catalog(wh)
    mesh = pmesh.make_mesh(8)
    ok, mism, fell = run_corpus(catalog, mesh)

    total = len(ok) + len(mism) + len(fell)
    print(f"\n== {len(ok)}/{total} parts distributed AND row-equal ==")
    reasons = collections.Counter(r for _, r in fell)
    for reason, cnt in reasons.most_common():
        print(f"{cnt:4d}  {reason}")
    for name, nw, ng in mism:
        print(f"  ROWDIFF {name}: want {nw} rows, got {ng}")
    if assert_ok and (mism or fell):
        sys.exit(1)


if __name__ == "__main__":
    main()
