#!/usr/bin/env python
"""Corpus-wide canonicalization audit.

Renders the power corpus under several RNGSEED x stream combinations —
each combo substitutes different literals into the same 99 templates —
canonicalizes every part's optimized plan (zero-row schema catalog, no
warehouse, no jax), and checks that each part **collapses**: every
rendering maps to ONE canonical cache key, i.e. one compiled XLA program
would serve all probed permutations with literals bound at runtime.

Emits:

* ``CANON_AUDIT.json`` / ``CANON_AUDIT.md`` (repo root): per-part
  fingerprint/cache-key sets, slot counts, and the collapse verdict.
  Deterministic (no timestamps) so committed copies only change when the
  plans or the canonicalizer change.
* ``NDS404`` diagnostics for parts that fail to collapse.  With
  ``--baseline [PATH]``: exit nonzero iff a diagnostic is NOT in the
  committed baseline (docs/canon_audit_baseline.json).
* With ``--write-baseline``: regenerate the baseline from this sweep.

Usage:
    python scripts/canon_audit.py                      # artifacts only
    python scripts/canon_audit.py --baseline           # CI gate
    python scripts/canon_audit.py --write-baseline     # accept current set
"""

import argparse
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

DEFAULT_BASELINE = REPO / "docs" / "canon_audit_baseline.json"
# the pinned bench seed plus one fresh seed; two streams each — four
# renderings per part, every literal choice re-drawn
DEFAULT_RNGSEEDS = "07291122510,19980713042"
DEFAULT_STREAMS = "0,1"


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--baseline", nargs="?", const=str(DEFAULT_BASELINE),
                    default=None, metavar="PATH",
                    help="gate against this baseline (default: "
                         "docs/canon_audit_baseline.json); exit 1 on new "
                         "diagnostics")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from this sweep")
    ap.add_argument("--json", default=str(REPO / "CANON_AUDIT.json"))
    ap.add_argument("--md", default=str(REPO / "CANON_AUDIT.md"))
    ap.add_argument("--rngseeds", default=DEFAULT_RNGSEEDS,
                    help="comma-separated stream seeds to probe")
    ap.add_argument("--streams", default=DEFAULT_STREAMS,
                    help="comma-separated stream numbers to probe")
    ap.add_argument("--sub_queries", default=None,
                    help="comma-separated query-part subset (CI tiny run)")
    return ap


def sweep(args):
    """part -> {combo: (cache_key, fingerprint, n_bind, n_shape)} plus
    per-part canonicalization errors (part -> message)."""
    from ndstpu import analysis
    from ndstpu.engine.session import Session
    from ndstpu.queries import streamgen

    sess = Session(analysis.schema_catalog())
    tables = analysis.schema_tables()
    subset = set(args.sub_queries.split(",")) if args.sub_queries else None
    seeds = [s.strip() for s in args.rngseeds.split(",") if s.strip()]
    streams = [int(s) for s in args.streams.split(",") if s.strip()]

    per_part, errors = {}, {}
    for seed in seeds:
        for stream in streams:
            combo = f"seed={seed}/stream={stream}"
            for name, sql in streamgen.render_power_corpus(
                    rngseed=seed, stream=stream):
                if subset is not None and name not in subset:
                    continue
                try:
                    plan, _cols = sess.plan(sql)
                    res = analysis.canonicalize(plan, tables=tables,
                                                query=name)
                except Exception as e:
                    errors[name] = f"{combo}: {type(e).__name__}: {e}"
                    continue
                per_part.setdefault(name, {})[combo] = (
                    res.cache_key, res.fingerprint,
                    len(res.bindable), len(res.shape_affecting))
    return per_part, errors, seeds, streams


def run_audit(args) -> int:
    from ndstpu.analysis import diagnostics as diag_mod

    per_part, errors, seeds, streams = sweep(args)
    n_combos = len(seeds) * len(streams)

    # A part COLLAPSES when every probed rendering maps to one canonical
    # fingerprint — one compiled structure serves all of them.  Shape-
    # affecting residue (varying cache keys on one fingerprint) is
    # reported but is not a failure: those slots carry their own NDS401/
    # 402/403 diagnostics in the plan-lint baseline.
    parts, diags = {}, []
    for name in sorted(set(per_part) | set(errors)):
        combos = per_part.get(name, {})
        keys = sorted({k for k, _, _, _ in combos.values()})
        fps = sorted({f for _, f, _, _ in combos.values()})
        collapsed = (len(fps) == 1 and name not in errors
                     and len(combos) == n_combos)
        parts[name] = {
            "collapsed": collapsed,
            "one_program": collapsed and len(keys) == 1,
            "cache_keys": keys,
            "fingerprints": fps,
            "bindable": max((b for _, _, b, _ in combos.values()),
                            default=0),
            "shape": max((s for _, _, _, s in combos.values()),
                         default=0),
        }
        if name in errors:
            parts[name]["error"] = errors[name]
        if not collapsed:
            why = (errors.get(name) or
                   f"{len(fps)} distinct fingerprints over "
                   f"{len(combos)} renderings")
            diags.append(diag_mod.Diagnostic(
                code="NDS404", query=name, path="corpus",
                message=why))

    n_collapsed = sum(1 for p in parts.values() if p["collapsed"])
    meta = {
        "rngseeds": seeds,
        "streams": streams,
        "combos": n_combos,
        "parts": len(parts),
        "collapsed": n_collapsed,
        "one_program": sum(1 for p in parts.values()
                           if p["one_program"]),
        "failed": sorted(n for n, p in parts.items()
                         if not p["collapsed"]),
    }

    import json
    doc = {"meta": meta, "parts": parts,
           "diagnostics": [d.as_dict()
                           for d in diag_mod.sort_diagnostics(diags)]}
    pathlib.Path(args.json).write_text(
        json.dumps(doc, indent=2, sort_keys=True) + "\n")

    lines = ["# Canonicalization audit", ""]
    for k, v in sorted(meta.items()):
        lines.append(f"- **{k}**: {v}")
    lines += ["",
              f"{n_collapsed}/{len(parts)} parts collapse to a single "
              "canonical fingerprint across all probed renderings "
              f"({meta['one_program']} of them to a single cache key, "
              "i.e. no shape-affecting residue varies).", "",
              "| part | collapsed | fingerprints | cache keys "
              "| bindable | shape |",
              "|---|---|---|---|---|---|"]
    for name, p in sorted(parts.items()):
        mark = "yes" if p["collapsed"] else "**NO**"
        lines.append(f"| {name} | {mark} | {len(p['fingerprints'])} "
                     f"| {len(p['cache_keys'])} | {p['bindable']} "
                     f"| {p['shape']} |")
    if diags:
        lines += ["", "## Failures", ""]
        for d in diag_mod.sort_diagnostics(diags):
            lines.append(f"- `{d.query}` {d.code}: {d.message}")
    pathlib.Path(args.md).write_text("\n".join(lines) + "\n")

    print(f"canon-audit: {len(parts)} parts, {n_collapsed} collapsed, "
          f"{len(diags)} failure(s) over {n_combos} renderings "
          f"-> {args.json}")

    if args.write_baseline:
        DEFAULT_BASELINE.write_text(diag_mod.baseline_dump(diags))
        print(f"canon-audit: baseline rewritten -> {DEFAULT_BASELINE}")

    if args.baseline is not None:
        bpath = pathlib.Path(args.baseline)
        if not bpath.exists():
            print(f"canon-audit: baseline {bpath} missing "
                  "(run --write-baseline)", file=sys.stderr)
            return 2
        accepted = diag_mod.baseline_load(bpath.read_text())
        new = diag_mod.new_against_baseline(diags, accepted)
        if new:
            print(f"canon-audit: {len(new)} part(s) regressed vs "
                  "baseline:", file=sys.stderr)
            for d in new:
                print(f"  {d.query} {d.code}: {d.message}",
                      file=sys.stderr)
            return 1
        print(f"canon-audit: clean against baseline "
              f"({len(accepted)} accepted)")
    return 0


def main(argv=None) -> int:
    return run_audit(build_parser().parse_args(argv))


if __name__ == "__main__":
    raise SystemExit(main())
