#!/usr/bin/env python
"""Corpus-wide static plan lint.

Sweeps every query part of the power corpus through the static analyzer
(ndstpu/analysis/) — parse → plan → optimize over a ZERO-ROW schema
catalog, so no warehouse, no data, no jax — and emits:

* ``PLAN_LINT.json`` / ``PLAN_LINT.md`` (repo root): every NDS1xx/2xx/3xx
  diagnostic plus the per-part device-vs-fallback verdict.  Both are
  deterministic (no timestamps) so committed copies only change when the
  plans or the analyzer change.
* With ``--baseline [PATH]``: exit nonzero iff a diagnostic is NOT in the
  committed baseline (docs/plan_lint_baseline.json) — the CI gate fails
  only on *new* findings.
* With ``--write-baseline``: regenerate the baseline from this sweep.

Usage:
    python scripts/plan_lint.py                      # artifacts only
    python scripts/plan_lint.py --baseline           # CI gate
    python scripts/plan_lint.py --write-baseline     # accept current set
"""

import argparse
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

DEFAULT_BASELINE = REPO / "docs" / "plan_lint_baseline.json"


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--baseline", nargs="?", const=str(DEFAULT_BASELINE),
                    default=None, metavar="PATH",
                    help="gate against this baseline (default: "
                         "docs/plan_lint_baseline.json); exit 1 on new "
                         "diagnostics")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from this sweep")
    ap.add_argument("--json", default=str(REPO / "PLAN_LINT.json"))
    ap.add_argument("--md", default=str(REPO / "PLAN_LINT.md"))
    ap.add_argument("--rngseed", default="07291122510",
                    help="stream seed (pinned bench seed by default so "
                         "the artifact is reproducible)")
    ap.add_argument("--stream", type=int, default=0)
    ap.add_argument("--scale_factor", type=float, default=1.0,
                    help="scale factor for overflow advisories (NDS103)")
    ap.add_argument("--sub_queries", default=None,
                    help="comma-separated query-part subset")
    return ap


def run_lint(args) -> int:
    from ndstpu import analysis
    from ndstpu.analysis import diagnostics as diag_mod
    from ndstpu.engine.session import Session
    from ndstpu.queries import streamgen

    sess = Session(analysis.schema_catalog())
    tables = analysis.schema_tables()
    subset = set(args.sub_queries.split(",")) if args.sub_queries else None

    diags, verdicts, fps = [], {}, {}
    per_sites, subtree_counts = {}, {}
    for name, sql in streamgen.render_power_corpus(
            rngseed=args.rngseed, stream=args.stream):
        if subset is not None and name not in subset:
            continue
        res = analysis.analyze_sql(sess, name, sql, tables=tables,
                                   scale_factor=args.scale_factor,
                                   spine_pass=True)
        verdicts[name] = res.verdict
        diags.extend(res.diagnostics)
        if res.canon is not None:
            fps[name] = {"fingerprint": res.canon.fingerprint,
                         "bindable": len(res.canon.bindable),
                         "shape": len(res.canon.shape_affecting)}
        sites = res.spine_sites or []
        per_sites[name] = sites
        subtree_counts[name] = {
            "candidates": len(sites),
            "shareable": sum(1 for s in sites if s.shareable),
            "eligible": len(analysis.spines.eligible_sites(sites)),
        }

    # cross-query pass: the spine index only exists over the whole
    # sweep (NDS5xx diagnoses subtrees shared by >= 2 parts, so a
    # subset run's diagnostic set stays a subset of the baseline)
    spine_index, spine_diags = analysis.spines.build_index(per_sites)
    diags.extend(spine_diags)
    spine_summary = analysis.spines.index_to_doc(spine_index)["summary"]

    meta = {
        "rngseed": args.rngseed,
        "stream": args.stream,
        "scale_factor": args.scale_factor,
        "parts": len(verdicts),
        "device": sum(1 for v in verdicts.values() if v == "device"),
        "fallback": sorted(q for q, v in verdicts.items()
                           if v == "fallback"),
        "spines": spine_summary,
    }
    pathlib.Path(args.json).write_text(
        diag_mod.to_json(diags, dict(meta, canon_fingerprints=fps,
                                     subtree_counts=subtree_counts)))
    md = diag_mod.to_markdown(diags, meta)
    if fps:
        md += ("\n## Canonical fingerprints\n\n"
               "| part | fingerprint | bindable slots | shape slots |\n"
               "|---|---|---|---|\n")
        md += "".join(
            f"| {q} | `{e['fingerprint']}` | {e['bindable']} "
            f"| {e['shape']} |\n" for q, e in sorted(fps.items()))
    if subtree_counts:
        md += ("\n## Subtree spine candidates (full index: "
               "MQO_AUDIT.json via scripts/mqo_audit.py)\n\n"
               "| part | candidate subtrees | shareable | "
               "eligible (outermost) |\n|---|---|---|---|\n")
        md += "".join(
            f"| {q} | {c['candidates']} | {c['shareable']} "
            f"| {c['eligible']} |\n"
            for q, c in sorted(subtree_counts.items()))
    pathlib.Path(args.md).write_text(md)
    print(f"plan-lint: {meta['parts']} parts, {meta['device']} device, "
          f"{len(meta['fallback'])} fallback, {len(diags)} diagnostics "
          f"-> {args.json}")

    if args.write_baseline:
        DEFAULT_BASELINE.write_text(diag_mod.baseline_dump(diags))
        print(f"plan-lint: baseline rewritten -> {DEFAULT_BASELINE}")

    if args.baseline is not None:
        bpath = pathlib.Path(args.baseline)
        if not bpath.exists():
            print(f"plan-lint: baseline {bpath} missing "
                  "(run --write-baseline)", file=sys.stderr)
            return 2
        accepted = diag_mod.baseline_load(bpath.read_text())
        new = diag_mod.new_against_baseline(diags, accepted)
        if new:
            print(f"plan-lint: {len(new)} diagnostic(s) not in baseline:",
                  file=sys.stderr)
            for d in new:
                print(f"  {d.query} {d.code} [{d.severity}] {d.path}: "
                      f"{d.message}", file=sys.stderr)
            return 1
        print(f"plan-lint: clean against baseline "
              f"({len(accepted)} accepted)")
    return 0


def main(argv=None) -> int:
    return run_lint(build_parser().parse_args(argv))


if __name__ == "__main__":
    raise SystemExit(main())
