"""Regression sentinel CLI (ndstpu/obs/sentinel.py).

Classifies power-run sidecars (``<time_log>.metrics.json``) against the
run ledger's best-known-warm baselines and exits nonzero on genuine
warm-path regressions.  The compile/execute split means a first compile
is classified ``cold-compile``, never ``regressed``.

    # judge one or more runs, write the artifact trail
    python scripts/regression_check.py /tmp/nds_hw/power_time.csv.metrics.json \\
        --ledger .bench_cache/ledger.jsonl --out REGRESSIONS.json

    # no-hardware CI mode: ingest committed history and verify the
    # classifier on it + synthetic cases
    python scripts/regression_check.py --selftest
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from ndstpu.obs import ledger as ledger_mod  # noqa: E402
from ndstpu.obs import sentinel  # noqa: E402


def selftest() -> int:
    """Classifier checks that need no hardware: replay the committed
    warm-run history through ingest + classify and assert the invariants
    the sentinel promises (a warm steady-state rerun of the same data is
    never flagged; cold compiles are never regressions)."""
    led = ledger_mod.Ledger(path=None, load=False)
    ingested = led.ingest_history(REPO)
    print(f"selftest: ingested {sum(ingested.values())} historical "
          f"entries from {len(ingested)} artifacts "
          f"({len(led.queries())} distinct queries)")
    warm_doc = os.path.join(REPO, "docs", "WARM_R5_SF1.json")
    if os.path.exists(warm_doc):
        with open(warm_doc) as f:
            steady = json.load(f).get("steady", {})
        qsums = [{"query": q, "wall_s": w, "compile_s": 0.0,
                  "execute_s": w} for q, w in steady.items()]
        res = sentinel.classify_run(qsums, led, engine="tpu",
                                    scale_factor="1")
        counts = res["counts"]
        print(f"selftest: steady-state replay counts: {counts}")
        assert not res["regressions"], (
            f"replaying the committed steady-state against its own "
            f"ledger flagged regressions: {res['regressions']}")
        assert counts.get("cold-compile", 0) == 0, counts
    # synthetic verdict table
    v = sentinel.classify_query("q", 60.0, 55.0, 5.0, 1.0)
    assert v["verdict"] == "cold-compile", v
    v = sentinel.classify_query("q", 2.0, 0.0, 2.0, 1.0)
    assert v["verdict"] == "regressed", v
    v = sentinel.classify_query("q", 0.5, 0.0, 0.5, 1.0)
    assert v["verdict"] == "improved", v
    v = sentinel.classify_query("q", 1.1, 0.0, 1.1, 1.0)
    assert v["verdict"] == "flat", v
    v = sentinel.classify_query("q", 1.0, 0.0, 1.0, None)
    assert v["verdict"] == "new", v
    print("selftest: OK")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("sidecars", nargs="*",
                    help="power sidecar(s): <time_log>.metrics.json")
    ap.add_argument("--ledger", default=None,
                    help="ledger JSONL (default $NDSTPU_LEDGER or "
                         ".bench_cache/ledger.jsonl)")
    ap.add_argument("--ingest-history", action="store_true",
                    help="also ingest committed history artifacts "
                         "(BENCH_r*.json, docs/WARM_R5_SF1.json, "
                         "*.metrics.json) as baselines")
    ap.add_argument("--engine", default=None,
                    help="baseline scope override (default: from each "
                         "sidecar)")
    ap.add_argument("--scale_factor", default=None)
    ap.add_argument("--out", default="REGRESSIONS.json",
                    help="JSON verdict artifact ('' disables)")
    ap.add_argument("--md", default="REGRESSIONS.md",
                    help="markdown verdict table ('' disables)")
    ap.add_argument("--selftest", action="store_true",
                    help="no-hardware classifier checks (CI)")
    args = ap.parse_args(argv)
    if args.selftest:
        return selftest()
    if not args.sidecars:
        ap.error("no sidecars given (or use --selftest)")
    led = ledger_mod.Ledger(args.ledger or ledger_mod.default_path(REPO))
    if args.ingest_history:
        ingested = led.ingest_history(REPO)
        print(f"ingested {sum(ingested.values())} historical entries "
              f"from {len(ingested)} artifacts")
    all_verdicts = []
    engine = args.engine
    scale_factor = args.scale_factor
    for path in args.sidecars:
        with open(path) as f:
            sc = json.load(f)
        queries = sc.get("queries") or []
        res = sentinel.classify_run(
            queries, led,
            engine=engine or sc.get("engine"),
            scale_factor=scale_factor or sc.get("scale_factor"))
        engine = engine or sc.get("engine")
        all_verdicts.extend(res["verdicts"])
    counts: dict = {}
    for v in all_verdicts:
        counts[v["verdict"]] = counts.get(v["verdict"], 0) + 1
    result = {
        "format": "ndstpu-regressions-v1",
        "engine": engine,
        "scale_factor": scale_factor,
        "rel_tol": sentinel.REL_TOL,
        "abs_floor_s": sentinel.ABS_FLOOR_S,
        "counts": counts,
        "regressions": [v["query"] for v in all_verdicts
                        if v["verdict"] == "regressed"],
        "verdicts": all_verdicts,
    }
    paths = sentinel.write_reports(result, args.out or None,
                                   args.md or None)
    print(sentinel.markdown_table(result))
    for k, p in paths.items():
        print(f"wrote {k}: {p}")
    if result["regressions"]:
        print(f"REGRESSIONS: {result['regressions']}", file=sys.stderr)
        return 1
    print("no warm-path regressions")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
