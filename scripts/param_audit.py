"""Audit: every distribution-drawn template parameter must land on the
generated data's value domain.

This is the guard for the bug class the reference's dsqgen/dsdgen
pairing prevents by construction (both read the same .dst tables —
nds/nds_gen_query_stream.py:57-72): a parameter list that matches ZERO
generated rows silently turns a benchmark query into a no-op (the
historical query10 county-list bug).

For each template/stream and each `dist(...)`/`distlist(u)` parameter:

* locate the column the parameter predicates on (from the template
  body: `s_state = '[STATE]'` -> store.s_state),
* check the drawn value against the generated warehouse column,
* aggregate per (template, param): hit-rate over streams and the
  weight MASS of the distribution present in the data.

Failure criterion (deterministic in --rngseed): a param whose
distribution mass present in the data is < --min_mass (default 0.5).
Small conditioned tables (12 stores) legitimately miss tail values, so
single-draw misses are reported but only mass decides pass/fail.

Usage:
    python scripts/param_audit.py --data DIR [--streams 4]
    python scripts/param_audit.py --gen-dims /tmp/audit_dims --sf 1
(--gen-dims generates just the dimension tables it needs, ~20s at SF1.)
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from ndstpu import schema  # noqa: E402
from ndstpu.check import check_build  # noqa: E402
from ndstpu.queries import streamgen  # noqa: E402

# column substring -> (table, column) the audit reads; ordered so the
# conditioned store_* columns win (mirror of the template-sweep rules)
COLUMNS = [
    ("s_gmt_offset", ("store", "s_gmt_offset")),
    ("ca_gmt_offset", ("customer_address", "ca_gmt_offset")),
    ("s_county", ("store", "s_county")),
    ("cc_county", ("call_center", "cc_county")),
    ("ca_county", ("customer_address", "ca_county")),
    ("s_state", ("store", "s_state")),
    ("ca_state", ("customer_address", "ca_state")),
    ("w_state", ("warehouse", "w_state")),
    ("s_city", ("store", "s_city")),
    ("ca_city", ("customer_address", "ca_city")),
    ("i_category", ("item", "i_category")),
    ("i_class", ("item", "i_class")),
    ("i_color", ("item", "i_color")),
    ("cd_marital_status", ("customer_demographics", "cd_marital_status")),
    ("cd_education_status", ("customer_demographics",
                             "cd_education_status")),
    ("cd_gender", ("customer_demographics", "cd_gender")),
    ("hd_buy_potential", ("household_demographics", "hd_buy_potential")),
    ("sm_carrier", ("ship_mode", "sm_carrier")),
    ("r_reason_desc", ("reason", "r_reason_desc")),
]

DIM_TABLES = sorted({t for _, (t, _) in COLUMNS})


def gen_dims(out_dir: Path, sf: float) -> None:
    tool = check_build()
    out_dir.mkdir(parents=True, exist_ok=True)
    for t in DIM_TABLES:
        subprocess.run([str(tool), "-scale", str(sf), "-dir", str(out_dir),
                        "-table", t], check=True)


def column_values(data_dir: Path, table: str, column: str) -> set:
    idx = schema.get_schemas(True)[table].column_names.index(column)
    vals = set()
    for path in sorted(data_dir.glob(f"{table}_*.dat")) or \
            sorted(data_dir.glob(f"{table}/*.dat")):
        with open(path) as f:
            for line in f:
                fields = line.rstrip("\n").split("|")
                if idx < len(fields):
                    vals.add(fields[idx])
    return vals


def norm(v: str) -> str:
    """numeric-looking values compare numerically (ca_gmt_offset is
    written as '-5.00'; the parameter renders as '-5')"""
    try:
        return repr(float(v))
    except ValueError:
        return v


def template_param_columns(tpl_path: Path):
    """{param: (table, column)} for dist-drawn params, located from the
    body line(s) the parameter appears in."""
    text = tpl_path.read_text()
    params, body = streamgen._parse_template(text)
    out = {}
    for name, (kind, vals) in params.items():
        if kind not in ("dist", "distlist", "distlistu"):
            continue
        hits = []
        for ln in body.splitlines():
            if f"[{name}]" in ln or f"[{name}." in ln:
                for col, target in COLUMNS:
                    if col in ln:
                        hits.append(target)
        if hits:
            # conditioned store columns first (same rule as the sweep)
            hits.sort(key=lambda t: 0 if t[0] == "store" else 1)
            out[name] = (hits[0], vals[0])
        else:
            out[name] = (None, vals[0])
    return out


def run_audit(data_dir: Path, rngseed: str, streams: int,
              min_mass: float, template_dir=None) -> dict:
    col_cache: dict = {}

    def values_for(table, column):
        if (table, column) not in col_cache:
            col_cache[(table, column)] = {
                norm(v) for v in column_values(data_dir, table, column)}
        return col_cache[(table, column)]

    d = Path(template_dir) if template_dir else streamgen.TEMPLATE_DIR
    report = {"params": [], "failures": []}
    for tpl in streamgen.list_templates(template_dir):
        tpl_path = d / tpl
        pcols = template_param_columns(tpl_path)
        if not pcols:
            continue
        for name, (target, dname) in pcols.items():
            if target is None:
                report["failures"].append(
                    {"template": tpl, "param": name, "dist": dname,
                     "error": "no target column found in template body"})
                continue
            table, column = target
            data_vals = values_for(table, column)
            dist = streamgen._DISTRIBUTIONS[dname]
            total_w = sum(w for _, w in dist)
            mass = sum(w for v, w in dist if norm(v) in data_vals) / total_w
            hits = misses = 0
            missed_vals = []
            for s in range(streams):
                drawn = streamgen.render_params(str(tpl_path), rngseed, s)[name]
                for v in (drawn if isinstance(drawn, list) else [drawn]):
                    if norm(v) in data_vals:
                        hits += 1
                    else:
                        misses += 1
                        missed_vals.append(v)
            entry = {"template": tpl, "param": name, "dist": dname,
                     "column": f"{table}.{column}",
                     "mass_present": round(mass, 4),
                     "draw_hits": hits, "draw_misses": misses,
                     "missed_values": sorted(set(missed_vals))}
            report["params"].append(entry)
            if mass < min_mass:
                report["failures"].append(entry)
    report["n_params"] = len(report["params"])
    report["n_failures"] = len(report["failures"])
    return report


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", help="warehouse dir of generated .dat files")
    ap.add_argument("--gen-dims",
                    help="generate the needed dimension tables here first")
    ap.add_argument("--sf", type=float, default=1.0)
    ap.add_argument("--rngseed", default="0")
    ap.add_argument("--streams", type=int, default=4)
    ap.add_argument("--min_mass", type=float, default=0.5)
    ap.add_argument("--template_dir")
    ap.add_argument("--out", help="write the JSON report here")
    args = ap.parse_args()
    if args.gen_dims:
        gen_dims(Path(args.gen_dims), args.sf)
        data_dir = Path(args.gen_dims)
    elif args.data:
        data_dir = Path(args.data)
    else:
        ap.error("need --data or --gen-dims")
    report = run_audit(data_dir, args.rngseed, args.streams,
                       args.min_mass, args.template_dir)
    if args.out:
        Path(args.out).write_text(json.dumps(report, indent=2))
    worst = sorted(report["params"], key=lambda e: e["mass_present"])[:8]
    for e in worst:
        print(f"{e['template']}:{e['param']} -> {e['column']} "
              f"mass={e['mass_present']} hits={e['draw_hits']} "
              f"misses={e['draw_misses']}")
    print(f"{report['n_params']} dist params audited, "
          f"{report['n_failures']} failures")
    return 1 if report["failures"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
