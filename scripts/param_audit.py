"""Audit: every distribution-drawn template parameter must land on the
generated data's value domain.

This is the guard for the bug class the reference's dsqgen/dsdgen
pairing prevents by construction (both read the same .dst tables —
nds/nds_gen_query_stream.py:57-72): a parameter list that matches ZERO
generated rows silently turns a benchmark query into a no-op (the
historical query10 county-list bug).

For each template/stream and each `dist(...)`/`distlist(u)` parameter:

* locate the column the parameter predicates on from the CANONICALIZER's
  slot->column bindings (ndstpu/analysis/canon.py): the template is
  rendered once, each part's optimized plan is canonicalized over the
  zero-row schema catalog, and the drawn value is matched to the slot
  that carries it — so attribution comes from the plan the engine
  actually runs, not from a hand-maintained substring table that could
  drift from the templates,
* check the drawn value against the generated warehouse column,
* aggregate per (template, param): hit-rate over streams and the
  weight MASS of the distribution present in the data.

Failure criterion (deterministic in --rngseed): a param whose
distribution mass present in the data is < --min_mass (default 0.5).
Small conditioned tables (12 stores) legitimately miss tail values, so
single-draw misses are reported but only mass decides pass/fail.

Usage:
    python scripts/param_audit.py --data DIR [--streams 4]
    python scripts/param_audit.py --gen-dims /tmp/audit_dims --sf 1
(--gen-dims generates just the dimension tables it needs, ~20s at SF1.)
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from ndstpu import schema  # noqa: E402
from ndstpu.check import check_build  # noqa: E402
from ndstpu.queries import streamgen  # noqa: E402

_DIST_KINDS = ("dist", "distlist", "distlistu")

# lazy singletons: the schema-catalog planner session + schema tables
# the canonicalizer attributes slots against (no data, no jax)
_ANALYSIS_CTX = None


def _analysis_ctx():
    global _ANALYSIS_CTX
    if _ANALYSIS_CTX is None:
        from ndstpu import analysis
        from ndstpu.engine.session import Session
        _ANALYSIS_CTX = (Session(analysis.schema_catalog()),
                         analysis.schema_tables())
    return _ANALYSIS_CTX


def dim_tables(template_dir=None) -> list:
    """Dimension tables any dist-drawn parameter predicates, from the
    canonicalizer's attributions (replaces the old hand-rolled list)."""
    d = Path(template_dir) if template_dir else streamgen.TEMPLATE_DIR
    tabs = set()
    for tpl in streamgen.list_templates(template_dir):
        for target, _dname in template_param_columns(d / tpl).values():
            if target is not None:
                tabs.add(target[0])
    return sorted(tabs)


def gen_dims(out_dir: Path, sf: float, template_dir=None) -> None:
    tool = check_build()
    out_dir.mkdir(parents=True, exist_ok=True)
    for t in dim_tables(template_dir):
        subprocess.run([str(tool), "-scale", str(sf), "-dir", str(out_dir),
                        "-table", t], check=True)


def column_values(data_dir: Path, table: str, column: str) -> set:
    idx = schema.get_schemas(True)[table].column_names.index(column)
    vals = set()
    for path in sorted(data_dir.glob(f"{table}_*.dat")) or \
            sorted(data_dir.glob(f"{table}/*.dat")):
        with open(path) as f:
            for line in f:
                fields = line.rstrip("\n").split("|")
                if idx < len(fields):
                    vals.add(fields[idx])
    return vals


def norm(v: str) -> str:
    """numeric-looking values compare numerically (ca_gmt_offset is
    written as '-5.00'; the parameter renders as '-5')"""
    try:
        return repr(float(v))
    except ValueError:
        return v


_TPC_CACHE: dict = {}


def template_param_columns(tpl_path: Path, rngseed: str = "0",
                           streams: int = 4):
    """{param: ((table, column) | None, distname)} for dist-drawn params,
    attributed through the canonicalizer: render the template over a few
    probe streams, lift every literal of every part's optimized plan into
    slots, and match each drawn value to the slot(s) carrying it — the
    slot's source column is the column the engine actually filters on.
    Candidate columns are INTERSECTED across probe streams so value
    collisions ('M' is both a gender and a marital status) resolve as
    soon as one stream draws a value unique to the real column."""
    from ndstpu.analysis import canon

    ck = (str(tpl_path), rngseed, streams)
    if ck in _TPC_CACHE:
        return _TPC_CACHE[ck]
    params, _body = streamgen._parse_template(tpl_path.read_text())
    dists = {name: vals[0] for name, (kind, vals) in params.items()
             if kind in _DIST_KINDS}
    if not dists:
        _TPC_CACHE[ck] = {}
        return {}
    sess, tables = _analysis_ctx()
    cand: dict = {name: None for name in dists}  # running intersection
    for stream in range(streams):
        exact: dict = {}   # norm(value) -> {(table, column)}
        raw: dict = {}     # str(value)  -> {(table, column)} (LIKE etc.)
        for pname, sql in streamgen.render_template_parts(
                str(tpl_path), rngseed, stream):
            plan, _cols = sess.plan(sql)
            res = canon.canonicalize(plan, tables=tables, query=pname)
            for s in res.slots:
                if s.column is None:
                    continue
                vals = s.value if isinstance(s.value, tuple) \
                    else (s.value,)
                for v in vals:
                    exact.setdefault(norm(str(v)), set()).add(s.column)
                    if isinstance(v, str):
                        raw.setdefault(v, set()).add(s.column)
        drawn = streamgen.render_params(str(tpl_path), rngseed, stream)
        for name in dists:
            dv = drawn.get(name)
            cols: set = set()
            for v in (dv if isinstance(dv, list) else [dv]):
                cols |= exact.get(norm(str(v)), set())
                if isinstance(v, str) and v:
                    # templates may decorate the drawn value with LIKE
                    # wildcards ('[BP]%' -> LIKE '0-500%'); match those —
                    # alongside exact hits, so a coincidental exact
                    # collision ('Unknown' is also an education level)
                    # still intersects away across streams
                    for lit, cset in raw.items():
                        rest = lit[len(v):]
                        if lit.startswith(v) and rest and \
                                all(ch in "%_" for ch in rest):
                            cols |= cset
            if not cols:
                continue
            inter = cols if cand[name] is None else cand[name] & cols
            cand[name] = inter or cand[name] | cols
    out = {}
    for name, dname in dists.items():
        cols = cand[name]
        if cols:
            # conditioned store columns first (same rule as the sweep)
            target = sorted(
                cols, key=lambda t: (0 if t[0] == "store" else 1, t))[0]
            out[name] = (target, dname)
        else:
            out[name] = (None, dname)
    _TPC_CACHE[ck] = out
    return out


def run_audit(data_dir: Path, rngseed: str, streams: int,
              min_mass: float, template_dir=None) -> dict:
    col_cache: dict = {}

    def values_for(table, column):
        if (table, column) not in col_cache:
            col_cache[(table, column)] = {
                norm(v) for v in column_values(data_dir, table, column)}
        return col_cache[(table, column)]

    d = Path(template_dir) if template_dir else streamgen.TEMPLATE_DIR
    report = {"params": [], "failures": []}
    for tpl in streamgen.list_templates(template_dir):
        tpl_path = d / tpl
        pcols = template_param_columns(tpl_path)
        if not pcols:
            continue
        for name, (target, dname) in pcols.items():
            if target is None:
                report["failures"].append(
                    {"template": tpl, "param": name, "dist": dname,
                     "error": "no predicating column found in the "
                              "canonicalized plans"})
                continue
            table, column = target
            data_vals = values_for(table, column)
            dist = streamgen._DISTRIBUTIONS[dname]
            total_w = sum(w for _, w in dist)
            mass = sum(w for v, w in dist if norm(v) in data_vals) / total_w
            hits = misses = 0
            missed_vals = []
            for s in range(streams):
                drawn = streamgen.render_params(str(tpl_path), rngseed, s)[name]
                for v in (drawn if isinstance(drawn, list) else [drawn]):
                    if norm(v) in data_vals:
                        hits += 1
                    else:
                        misses += 1
                        missed_vals.append(v)
            entry = {"template": tpl, "param": name, "dist": dname,
                     "column": f"{table}.{column}",
                     "mass_present": round(mass, 4),
                     "draw_hits": hits, "draw_misses": misses,
                     "missed_values": sorted(set(missed_vals))}
            report["params"].append(entry)
            if mass < min_mass:
                report["failures"].append(entry)
    report["n_params"] = len(report["params"])
    report["n_failures"] = len(report["failures"])
    return report


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", help="warehouse dir of generated .dat files")
    ap.add_argument("--gen-dims",
                    help="generate the needed dimension tables here first")
    ap.add_argument("--sf", type=float, default=1.0)
    ap.add_argument("--rngseed", default="0")
    ap.add_argument("--streams", type=int, default=4)
    ap.add_argument("--min_mass", type=float, default=0.5)
    ap.add_argument("--template_dir")
    ap.add_argument("--out", help="write the JSON report here")
    args = ap.parse_args()
    if args.gen_dims:
        gen_dims(Path(args.gen_dims), args.sf)
        data_dir = Path(args.gen_dims)
    elif args.data:
        data_dir = Path(args.data)
    else:
        ap.error("need --data or --gen-dims")
    report = run_audit(data_dir, args.rngseed, args.streams,
                       args.min_mass, args.template_dir)
    if args.out:
        Path(args.out).write_text(json.dumps(report, indent=2))
    worst = sorted(report["params"], key=lambda e: e["mass_present"])[:8]
    for e in worst:
        print(f"{e['template']}:{e['param']} -> {e['column']} "
              f"mass={e['mass_present']} hits={e['draw_hits']} "
              f"misses={e['draw_misses']}")
    print(f"{report['n_params']} dist params audited, "
          f"{report['n_failures']} failures")
    return 1 if report["failures"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
