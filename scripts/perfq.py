"""Per-query TPU perf probe: compile time, steady-state time, HLO op mix.

    python scripts/perfq.py query1 query3 query6
    python scripts/perfq.py --hlo query6        # also dump op histogram

Uses the bench warehouse (.bench_cache/wh_sf1) and the persistent XLA
cache, so numbers match what bench.py will see.
"""
from __future__ import annotations

import argparse
import collections
import pathlib
import re
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("names", nargs="+")
    ap.add_argument("--sf", default="1")
    ap.add_argument("--hlo", action="store_true",
                    help="dump StableHLO op histogram per part")
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--no-cache", action="store_true",
                    help="skip the persistent XLA compile cache")
    args = ap.parse_args()

    import jax
    if not args.no_cache:
        jax.config.update("jax_compilation_cache_dir",
                          str(REPO / ".bench_cache" / "xla_cache_tpu"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
    from ndstpu.engine.session import Session
    from ndstpu.io import loader
    from ndstpu.queries import streamgen

    wh = str(REPO / ".bench_cache" / f"wh_sf{args.sf}")
    sess = Session(loader.load_catalog(wh), backend="tpu")

    for name in args.names:
        tpl = name if name.endswith(".tpl") else name + ".tpl"
        parts = streamgen.render_template_parts(
            str(streamgen.TEMPLATE_DIR / tpl), "07291122510", 0)
        for pname, sql in parts:
            t0 = time.time()
            out = sess.sql(sql)
            out.to_rows()
            t_first = time.time() - t0
            steadies = []
            for _ in range(args.reps - 1):
                t0 = time.time()
                out = sess.sql(sql)
                out.to_rows()
                steadies.append(time.time() - t0)
            steady = min(steadies) if steadies else float("nan")
            cp = sess.compiled_plan(sql)
            mode = "jit" if (cp is not None and cp.compilable) else "EAGER"
            print(f"{pname:16s} {mode:5s} first={t_first:7.2f}s "
                  f"steady={steady:7.3f}s rows={out.num_rows}",
                  flush=True)
            if args.hlo and cp is not None and cp.fn is not None:
                from ndstpu.engine import jaxexec
                exe = sess._jax_executor()
                ops = collections.Counter()
                # segmented queries: run each segment to materialize the
                # device-resident arg the parent's lowering needs, and
                # histogram the segment programs too
                targs = {t: exe._accel_args(t, cols)
                         for t, cols in cp.table_cols.items()}
                skipped_segs = 0
                for fp in (cp.seg_fps or ()):
                    scp = exe._seg_compiled[fp]
                    if not scp.compilable:
                        # fallback-isolated segment: replay runs it on
                        # the host; feed its result like _replay does
                        host = exe.execute_to_host(scp.plan)
                        targs[jaxexec._seg_argname(fp)] = \
                            exe._seg_host_args(scp, host)
                        skipped_segs += 1
                        continue
                    if scp.fn is None:
                        scp.fn = exe._build_jit(scp)
                    sargs = {t: exe._accel_args(t, c)
                             for t, c in scp.table_cols.items()}
                    (sout, salive), _ok = scp.fn(sargs)
                    targs[jaxexec._seg_argname(fp)] = (sout, salive)
                    stxt = scp.fn.lower(sargs).as_text()
                    ops.update(re.findall(r"stablehlo\.(\w+)", stxt))
                if skipped_segs:
                    print(f"  ({skipped_segs} host-fallback segs "
                          f"not in histogram)", flush=True)
                txt = cp.fn.lower(targs).as_text()
                ops.update(re.findall(r"stablehlo\.(\w+)", txt))
                total = sum(ops.values())
                top = ", ".join(f"{k}:{v}" for k, v in ops.most_common(18))
                nseg = len(cp.seg_fps or ())
                print(f"  ops={total} (parent+{nseg} segs)  {top}",
                      flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
