"""Throughput-mode smoke: both scheduler shapes over one tiny corpus.

CI gate for the in-process multi-stream scheduler
(ndstpu/harness/scheduler.py): renders a tiny warehouse + 2 query
streams, runs the SAME throughput invocation in ``--mode process``
(spec-faithful N-process fan-out) and ``--mode inproc`` (shared
session, compile-once), and asserts

* both modes exit 0 and write the overlap report;
* the inproc device-level ``max_concurrent`` stays <= the admission
  slots while the stream walls still overlap;
* the time-log contract holds in both modes (bench's throughput
  elapsed parses either).

Wall-clocks are printed side by side; inproc is expected to win (one
warehouse load instead of N), but on a CI box timing is only logged —
a slower-than-process inproc run prints a WARNING rather than failing
the build on scheduler noise.
"""
from __future__ import annotations

import json
import os
import pathlib
import subprocess
import sys
import tempfile
import time

REPO = pathlib.Path(__file__).resolve().parent.parent


def run(cmd, **kw):
    print("+", " ".join(map(str, cmd)), flush=True)
    return subprocess.run([str(c) for c in cmd], **kw)


def main() -> int:
    root = pathlib.Path(tempfile.mkdtemp(prefix="ndstpu_tp_smoke"))
    env = dict(os.environ, PYTHONPATH=str(REPO),
               JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"))
    py = [sys.executable, "-m"]
    run(py + ["ndstpu.datagen.driver", "local", "0.002", "2",
              root / "raw"], check=True, env=env)
    run(py + ["ndstpu.io.transcode", "--input_prefix", root / "raw",
              "--output_prefix", root / "wh",
              "--report_file", root / "load.txt",
              "--output_format", "ndslake"],
        check=True, env=env, stdout=subprocess.DEVNULL)
    # stream 0 is the power stream; throughput uses streams 1..N
    run(py + ["ndstpu.queries.streamgen", "--output_dir",
              root / "streams", "--rngseed", "07291122510",
              "--streams", "3"],
        check=True, env=env, stdout=subprocess.DEVNULL)

    walls = {}
    for mode in ("process", "inproc"):
        overlap = root / f"overlap_{mode}.json"
        t0 = time.time()
        r = run(py + ["ndstpu.harness.throughput", "1,2",
                      "--concurrent", "2", "--mode", mode,
                      "--overlap_report", overlap, "--",
                      sys.executable, "-m", "ndstpu.harness.power",
                      str(root / "streams") + "/query_{}.sql",
                      root / "wh",
                      str(root) + f"/time_{mode}_{{}}.csv",
                      "--input_format", "ndslake",
                      "--sub_queries", "query3,query96"],
                env=env)
        walls[mode] = time.time() - t0
        assert r.returncode == 0, f"--mode {mode} exited {r.returncode}"
        assert overlap.exists(), f"--mode {mode} wrote no overlap report"
        ov = json.loads(overlap.read_text())
        assert ov["format"] == "ndstpu-throughput-overlap-v1"
        assert ov["mode"] == mode
        assert {s["stream"] for s in ov["streams"]} == {"1", "2"}
        assert all(s["returncode"] == 0 for s in ov["streams"])
        if mode == "inproc":
            assert ov["max_concurrent"] <= 2, \
                "admission gate exceeded its slots"
            assert ov["device_timeline"]["slots"] == 2
            assert ov["pairwise_overlap_s"]["1&2"] > 0, \
                "inproc streams did not overlap"
        for i in (1, 2):
            text = (root / f"time_{mode}_{i}.csv").read_text()
            assert "Power Start Time" in text, \
                f"--mode {mode} stream {i}: time-log contract broken"
    print(f"smoke OK: process={walls['process']:.1f}s "
          f"inproc={walls['inproc']:.1f}s "
          f"(speedup x{walls['process'] / max(walls['inproc'], 1e-9):.2f})")
    if walls["inproc"] >= walls["process"]:
        # timing on shared CI runners is advisory, not a gate
        print("WARNING: inproc was not faster than process mode on "
              "this run (tiny corpus + CI noise); correctness "
              "assertions above all held")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
