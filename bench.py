"""Benchmark entry point: NDS power-run elapsed, TPU backend vs CPU.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Pipeline (mirrors the reference power run, nds/nds_power.py:183-304):
generate raw data (cached) -> transcode to parquet warehouse (cached) ->
render the query stream -> execute every query serially on the JAX/TPU
backend (wall-clock around each result materialization), and on the
numpy CPU reference interpreter as the baseline (the analog of the
reference's power_run_cpu Spark path).

value       = TPU-backend power-run elapsed seconds (warm, best of 2)
vs_baseline = CPU elapsed / TPU elapsed  (>1 means TPU wins)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
CACHE = os.path.join(REPO, ".bench_cache")
SF = float(os.environ.get("NDSTPU_BENCH_SF", "0.05"))


def _ensure_warehouse() -> str:
    tag = f"sf{SF}"
    raw = os.path.join(CACHE, f"raw_{tag}")
    wh = os.path.join(CACHE, f"wh_{tag}")
    env = dict(os.environ, PYTHONPATH=REPO)
    if not os.path.isdir(raw) or not os.listdir(raw):
        os.makedirs(raw, exist_ok=True)
        subprocess.run(
            [sys.executable, "-m", "ndstpu.datagen.driver", "local",
             str(SF), "2", raw],
            check=True, env=env, stdout=subprocess.DEVNULL)
    if not os.path.isdir(wh) or not os.listdir(wh):
        os.makedirs(wh, exist_ok=True)
        subprocess.run(
            [sys.executable, "-m", "ndstpu.io.transcode",
             "--input_prefix", raw, "--output_prefix", wh,
             "--report_file", os.path.join(wh, "load.txt")],
            check=True, env=env, stdout=subprocess.DEVNULL)
    return wh


def _power_run(sess, queries, failures=None) -> float:
    t0 = time.time()
    for name, sql in queries:
        try:
            out = sess.sql(sql)
            # materialize like collect() (nds_power.py:124-134)
            out.to_rows()
        except Exception as e:  # keep the run alive (transient compile
            # infra errors must not zero a 99-query benchmark)
            print(f"BENCH-ERROR {name}: {type(e).__name__}: {e}",
                  file=sys.stderr)
            if failures is not None:
                failures.append(name)
    return time.time() - t0


def main() -> None:
    global SF
    if "--quick" in sys.argv:
        SF = min(SF, 0.01)
    sys.path.insert(0, REPO)
    # persistent XLA compile cache: repeated bench runs skip the ~40s
    # per-query first-compile on the real TPU.  jax is pre-imported by
    # sitecustomize in this image, so env vars are too late — use config.
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(CACHE, "xla_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    wh = _ensure_warehouse()

    from ndstpu.engine.session import Session
    from ndstpu.io import loader
    from ndstpu.queries import streamgen

    queries = []
    for tpl in streamgen.list_templates():
        queries.extend(streamgen.render_template_parts(
            str(streamgen.TEMPLATE_DIR / tpl), "07291122510", 0))

    catalog = loader.load_catalog(wh)
    cpu_sess = Session(catalog, backend="cpu")
    tpu_sess = Session(catalog, backend="tpu")

    cpu_fail: list = []
    cpu_s = _power_run(cpu_sess, queries, cpu_fail)
    if cpu_fail:
        print(f"BENCH-WARNING: {len(cpu_fail)} baseline queries failed: "
              f"{cpu_fail}", file=sys.stderr)
    # persisted size-plan records skip the per-query eager discovery
    # pass; with the XLA cache warm, run1 is then already compiled replay
    rec_path = os.path.join(CACHE, f"plans_sf{SF}.pkl")
    try:
        tpu_sess.preload_compiled(rec_path)
    except Exception:
        pass  # stale/corrupt records: discovery path still works
    # run1 = discovery (or preloaded replay), run2 = trace+compile(+cache)
    # and replay, run3 = pure compiled replay — the steady-state number
    n_runs = int(os.environ.get("NDSTPU_BENCH_RUNS", "3"))
    # engine changes invalidate the persistent XLA cache, making run1 a
    # full 103-query recompile (~30s each over the tunnel) — a wall
    # budget keeps the bench reporting SOMETHING instead of being killed
    budget_s = float(os.environ.get("NDSTPU_BENCH_BUDGET_S", "2700"))
    bench_t0 = time.time()
    runs, fail_lists = [], []
    for ri in range(n_runs):
        failures: list = []
        runs.append(_power_run(tpu_sess, queries, failures))
        fail_lists.append(failures)
        try:  # persist incrementally: a crash must not lose the records
            tpu_sess.save_compiled(rec_path)
        except Exception:
            pass
        if time.time() - bench_t0 > budget_s and ri + 1 < n_runs:
            print(f"BENCH-WARNING: wall budget {budget_s}s exceeded "
                  f"after run {ri + 1}/{n_runs}; stopping early",
                  file=sys.stderr)
            break
    # a run where queries errored did less work — never report it
    clean = [t for t, f in zip(runs, fail_lists) if not f]
    tpu_s = min(clean) if clean else min(runs)
    for i, f in enumerate(fail_lists):
        if f:
            print(f"BENCH-WARNING: run {i + 1}: {len(f)} queries failed: "
                  f"{f}", file=sys.stderr)
    failed_queries = sorted(set().union(*fail_lists)) if not clean else []

    result = {
        "metric": f"nds_power_run_elapsed_sf{SF}_"
                  f"{len(queries)}q",
        "value": round(tpu_s, 4),
        "unit": "s",
        "vs_baseline": round(cpu_s / tpu_s, 4) if tpu_s > 0 else 0.0,
    }
    if failed_queries:  # every run had failures: mark the number tainted
        result["failed_queries"] = failed_queries
    print(json.dumps(result))


if __name__ == "__main__":
    main()
