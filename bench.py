"""Benchmark entry point: NDS power-run elapsed, TPU backend vs CPU.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Pipeline (mirrors the reference power run, nds/nds_power.py:183-304):
generate raw data (cached) -> transcode to parquet warehouse (cached) ->
render the query stream -> execute every query serially on the numpy CPU
reference interpreter (the baseline — the analog of the reference's
power_run_cpu Spark path, measured on the same host) and on the JAX/TPU
backend (wall-clock around each result materialization).

value       = TPU-backend power-run elapsed seconds (best complete run)
vs_baseline = CPU elapsed / TPU elapsed over the common measured queries
              (>1 means TPU wins); geomean of per-query speedups is also
              reported.

Robustness contract (the driver kills this process at an unknown wall
limit): EVERY phase runs under one global deadline, and SIGTERM/SIGINT/
SIGALRM or an unhandled exception still emit the JSON line built from
whatever completed — the reference's report always gets written
(nds/nds_power.py:251-288); so does ours.
"""

from __future__ import annotations

import atexit
import json
import math
import os
import shutil
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
CACHE = os.path.join(REPO, ".bench_cache")
SF = float(os.environ.get("NDSTPU_BENCH_SF", "1"))
# default calibrated against the driver's observed kill point: r02 and
# r03 both ended by SIGTERM at elapsed_s ~1798 while the old 2400 s
# default meant the deadline machinery (early stop, steady-subset pass,
# clean _emit) never engaged — leave ~60 s of slack before the kill
BUDGET_S = float(os.environ.get("NDSTPU_BENCH_BUDGET_S", "1740"))
T0 = time.time()
DEADLINE = T0 + BUDGET_S

# -- partial-result state, emitted exactly once ------------------------------

STATE = {
    "sf": SF,
    "n_queries": 0,
    "cpu_times": {},     # name -> seconds (numpy interpreter baseline)
    "cpu_failed": [],
    "tpu_runs": [],      # list of {"times": {name: s}, "failed": [...],
                         #          "complete": bool}
    "phase": "init",
}
_EMITTED = False


def _remaining() -> float:
    return DEADLINE - time.time()


def _build_result() -> dict:
    nq = STATE["n_queries"]
    cpu_times = STATE["cpu_times"]
    runs = STATE["tpu_runs"]
    complete = [r for r in runs if r["complete"] and not r["failed"]]
    pool = complete or [r for r in runs if r["times"]]
    # coverage first, then time: a deadline-cut 10-query run must never
    # shadow a full run as the headline number
    best = min(pool, key=lambda r: (-len(r["times"]),
                                    sum(r["times"].values()))) \
        if pool else None
    tpu_times = best["times"] if best else {}
    common = [q for q in tpu_times if q in cpu_times]
    tpu_s = sum(tpu_times.values())
    cpu_common = sum(cpu_times[q] for q in common)
    tpu_common = sum(tpu_times[q] for q in common)
    result = {
        "metric": f"nds_power_run_sf{SF:g}_{nq}q_tpu_vs_numpy_cpu",
        "value": round(tpu_s, 4) if tpu_times else 0.0,
        "unit": "s",
        "vs_baseline": round(cpu_common / tpu_common, 4)
        if tpu_common > 0 and common else 0.0,
        "baseline": "numpy CPU interpreter, same host, serial power run",
        "queries_measured_tpu": len(tpu_times),
        "queries_measured_cpu": len(cpu_times),
        "phase_reached": STATE["phase"],
        "elapsed_s": round(time.time() - T0, 1),
    }
    if common:
        ratios = [cpu_times[q] / tpu_times[q] for q in common
                  if tpu_times[q] > 0 and cpu_times[q] > 0]
        if ratios:
            result["geomean_speedup"] = round(
                math.exp(sum(math.log(r) for r in ratios) / len(ratios)), 4)
        result["cpu_elapsed_common_s"] = round(cpu_common, 4)
    if best and best["failed"]:
        result["failed_queries"] = sorted(best["failed"])
    if STATE["cpu_failed"]:
        result["cpu_failed_queries"] = sorted(STATE["cpu_failed"])
    partial = (not complete) or len(cpu_times) < nq or nq == 0
    if partial:
        result["partial"] = True
    return result


def _emit(trailer: str = "") -> None:
    global _EMITTED
    if _EMITTED:
        return
    _EMITTED = True
    result = _build_result()
    if trailer:
        result["note"] = trailer
    print(json.dumps(result), flush=True)
    # per-query detail for the record, not on the contract line
    detail = {"cpu_times": STATE["cpu_times"],
              "tpu_runs": STATE["tpu_runs"]}
    try:
        with open(os.path.join(CACHE, f"last_run_sf{SF:g}.json"), "w") as f:
            json.dump(detail, f, indent=1)
    except OSError:
        pass


def _on_signal(signum, frame):  # noqa: ARG001
    _emit(f"terminated by signal {signum} in phase {STATE['phase']}")
    os._exit(0)


def _install_handlers() -> None:
    for s in (signal.SIGTERM, signal.SIGINT):
        signal.signal(s, _on_signal)
    if hasattr(signal, "SIGALRM"):
        signal.signal(signal.SIGALRM, _on_signal)
        # backstop: fire shortly after the soft deadline so a stuck
        # native call can't ride past the driver's own kill
        signal.alarm(int(BUDGET_S + 120))
    atexit.register(_emit)


# -- phases ------------------------------------------------------------------

def _setup_xla_cache() -> None:
    """Persistent XLA cache holding ONLY the expensive TPU whole-query
    replay programs (portable across hosts — TPU code doesn't depend on
    the host CPU).  Round 1's cache persisted every tiny XLA:CPU eager
    program too (min_compile_time=0); loading those on a different host
    warns about SIGILL-able AOT code and can poison the run, so the
    legacy dir is dropped and the threshold now skips sub-2s compiles
    (eager host ops never reach it; 30-60s query compiles always do)."""
    import jax
    legacy = os.path.join(CACHE, "xla_cache")
    if os.path.isdir(legacy):
        shutil.rmtree(legacy, ignore_errors=True)
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(CACHE, "xla_cache_tpu"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)


def _src_fingerprint(rels) -> str:
    import hashlib
    h = hashlib.sha256()
    for rel in rels:
        try:
            with open(os.path.join(REPO, rel), "rb") as f:
                h.update(f.read())
        except OSError:
            h.update(rel.encode())
    return h.hexdigest()[:16]


# identity of the build pipeline per artifact: raw data depends only on
# the generator; the warehouse additionally on the transcoder/schema.
# An SF-only tag silently kept pre-change data alive across generator
# changes (e.g. the r04 distribution skew); a single shared stamp would
# waste a full datagen phase on transcoder-only edits.
_GEN_SRCS = ("ndstpu/datagen/ndsgen.cpp", "ndstpu/datagen/driver.py")
_WH_SRCS = _GEN_SRCS + ("ndstpu/io/transcode.py", "ndstpu/schema.py")
# the CPU baseline is a function of (data, queries, interpreter): cached
# times must not survive interpreter changes, or vs_baseline silently
# compares against a stale denominator
_CPU_SRCS = ("ndstpu/engine/physical.py", "ndstpu/engine/expr.py",
             "ndstpu/engine/columnar.py", "ndstpu/engine/optimizer.py",
             "ndstpu/engine/planner.py", "ndstpu/engine/plan.py")


def _stamp_ok(d: str, fp: str) -> bool:
    try:
        with open(os.path.join(d, ".genfp")) as f:
            return f.read().strip() == fp
    except OSError:
        return False


def ensure_warehouse(sf: float, datagen_timeout=None,
                     transcode_timeout=None, quiet: bool = True,
                     on_phase=None) -> str:
    """Build (or reuse) the warehouse for one SF.  Each phase writes
    into a _tmp_ dir renamed only on success: a timeout/SIGTERM
    mid-build must not leave a truncated dir that later runs mistake
    for a complete cache (and silently benchmark forever).  Dirs carry
    a .genfp stamp of the generator sources; a stamp mismatch forces a
    rebuild.  Shared artifact contract for bench.py (deadline-capped,
    quiet) and scripts/build_wh.py (uncapped, verbose)."""
    tag = f"sf{sf:g}"
    raw = os.path.join(CACHE, f"raw_{tag}")
    wh = os.path.join(CACHE, f"wh_{tag}")
    raw_fp = _src_fingerprint(_GEN_SRCS)
    wh_fp = _src_fingerprint(_WH_SRCS)
    for d, fp in ((raw, raw_fp), (wh, wh_fp)):
        if os.path.isdir(d) and os.listdir(d) and not _stamp_ok(d, fp):
            if not quiet:
                print(f"stale stamp: rebuilding {d}", flush=True)
            shutil.rmtree(d, ignore_errors=True)
    # append, don't clobber: the host env may carry a sitecustomize dir
    # (e.g. the axon PJRT plugin registration) on PYTHONPATH
    pp = os.environ.get("PYTHONPATH", "")
    env = dict(os.environ,
               PYTHONPATH=f"{REPO}{os.pathsep}{pp}" if pp else REPO)
    for d in (raw + "_tmp_", wh + "_tmp_"):   # stale partials from kills
        shutil.rmtree(d, ignore_errors=True)
    out = subprocess.DEVNULL if quiet else None

    def _limit(t):   # timeouts may be callables (deadline-relative)
        return t() if callable(t) else t

    if not os.path.isdir(wh) or not os.listdir(wh):
        if not os.path.isdir(raw) or not os.listdir(raw):
            if on_phase:
                on_phase("datagen")
            tmp = raw + "_tmp_"
            os.makedirs(tmp, exist_ok=True)
            try:
                subprocess.run(
                    [sys.executable, "-m", "ndstpu.datagen.driver",
                     "local", f"{sf:g}", "2", tmp, "--overwrite_output"],
                    check=True, env=env, stdout=out, cwd=REPO,
                    timeout=_limit(datagen_timeout))
            except BaseException:
                shutil.rmtree(tmp, ignore_errors=True)
                raise
            with open(os.path.join(tmp, ".genfp"), "w") as f:
                f.write(raw_fp)
            os.rename(tmp, raw)
        if on_phase:
            on_phase("transcode")
        tmp = wh + "_tmp_"
        os.makedirs(tmp, exist_ok=True)
        try:
            subprocess.run(
                [sys.executable, "-m", "ndstpu.io.transcode",
                 "--input_prefix", raw, "--output_prefix", tmp,
                 "--report_file", os.path.join(tmp, "load.txt")],
                check=True, env=env, stdout=out, cwd=REPO,
                timeout=_limit(transcode_timeout))
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        with open(os.path.join(tmp, ".genfp"), "w") as f:
            f.write(wh_fp)
        os.rename(tmp, wh)
    return wh


def _ensure_warehouse() -> str:
    def _phase(p):
        STATE["phase"] = p

    return ensure_warehouse(
        SF,
        datagen_timeout=lambda: max(60.0, min(_remaining() - 300.0,
                                              900.0)),
        transcode_timeout=lambda: max(60.0, _remaining() - 240.0),
        quiet=True, on_phase=_phase)


def _corpus_fingerprint(wh: str, queries) -> str:
    """Identity of (warehouse data, rendered query corpus, interpreter
    sources): the CPU baseline is a pure function of these, so cache it
    by this key."""
    import hashlib
    h = hashlib.sha256()
    h.update(_src_fingerprint(_CPU_SRCS).encode())
    for name, sql in queries:
        h.update(name.encode())
        h.update(hashlib.sha256(sql.encode()).digest())
    for root, dirs, files in sorted(os.walk(wh)):
        dirs.sort()
        for fn in sorted(files):
            st = os.stat(os.path.join(root, fn))
            h.update(f"{os.path.relpath(os.path.join(root, fn), wh)}:"
                     f"{st.st_size}".encode())
    return h.hexdigest()


def _load_cpu_cache(path: str, fp: str):
    try:
        with open(path) as f:
            d = json.load(f)
        if d.get("fingerprint") == fp:
            return d["cpu_times"], d["cpu_failed"]
    except (OSError, ValueError, KeyError):
        pass
    return None


def _save_cpu_cache(path: str, fp: str, times: dict, failed: list):
    try:
        with open(path, "w") as f:
            json.dump({"fingerprint": fp, "cpu_times": times,
                       "cpu_failed": failed}, f)
    except OSError:
        pass


_BACKEND_DEAD = ("UNAVAILABLE", "worker process crashed", "DATA_LOSS")
# a wedged remote-compile RPC blocks forever (observed: query39 at SF1);
# abandon the query in its daemon thread and keep the stream moving
QUERY_TIMEOUT_S = float(os.environ.get("NDSTPU_BENCH_QUERY_TIMEOUT_S",
                                       "900"))


def _run_one(sess, sql: str, slot: dict) -> None:
    try:
        out = sess.sql(sql)
        out.to_rows()  # materialize like collect() (nds_power.py:124-134)
        slot["ok"] = True
    except Exception as e:  # noqa: BLE001
        slot["err"] = e


def _power_run(sess, queries, times: dict, failed: list,
               stop_at: float, rebuild=None, watchdog=None,
               per_query_timeout=None, progress: bool = False,
               hang_abort: int = 3, reasons=None) -> bool:
    """Run the stream serially; returns True iff every query ran.
    ``rebuild()`` returns a FRESH session after a hang, so the
    abandoned zombie thread keeps only the old session's state and
    cannot race the rest of the stream.  ``watchdog`` defaults to on
    for accelerator runs; pass True to also bound CPU queries (SF10+
    interpreter passes, where one pathological numpy query could
    otherwise blow through the whole budget).  ``hang_abort`` bounds
    consecutive-run hang tolerance: N hangs mean a wedged backend on
    accelerators, but independent slow queries on a CPU interpreter —
    pass 0 to never abort (each hang still costs at most the per-query
    timeout).  ``reasons`` (dict) collects a per-query failure reason
    alongside the bare names in ``failed``."""
    import threading
    accel = sess.backend != "cpu"
    qto = per_query_timeout if per_query_timeout else QUERY_TIMEOUT_S
    if watchdog is None:
        watchdog = accel
    hangs = 0
    for name, sql in queries:
        if time.time() >= stop_at:
            return False
        t0 = time.time()
        slot: dict = {}
        if watchdog:
            th = threading.Thread(target=_run_one, args=(sess, sql, slot),
                                  daemon=True)
            th.start()
            waited = min(qto, max(30.0, stop_at - time.time()))
            th.join(waited)
            if th.is_alive():
                if waited < qto:
                    # deadline cut an ordinary query, not a hang
                    return False
                print(f"BENCH-ERROR {name}: hang (> "
                      f"{qto:.0f}s), abandoned",
                      file=sys.stderr, flush=True)
                failed.append(name)
                if reasons is not None:
                    reasons[name] = f"hang>{qto:.0f}s"
                hangs += 1
                if hang_abort and hangs >= hang_abort:
                    # backend wedged, not one bad program
                    print("BENCH-WARNING: repeated hangs, aborting run",
                          file=sys.stderr, flush=True)
                    return False
                if rebuild is not None:
                    # the zombie thread stays blocked inside its jax
                    # call — on the OLD session; a fresh one isolates
                    # the remaining stream from any late completion
                    try:
                        sess = rebuild()
                    except Exception as e:  # noqa: BLE001
                        print(f"BENCH-WARNING: session rebuild failed "
                              f"({e}); continuing on shared session",
                              file=sys.stderr, flush=True)
                continue
        else:
            _run_one(sess, sql, slot)
        if slot.get("ok"):
            times[name] = round(time.time() - t0, 4)
            if progress:
                print(f"{name}: {times[name]:.3f}s", flush=True)
            continue
        e = slot.get("err")
        # a failed query must not zero the whole 99-query benchmark
        print(f"BENCH-ERROR {name}: {type(e).__name__}: {e}",
              file=sys.stderr, flush=True)
        failed.append(name)
        if reasons is not None:
            reasons[name] = str(e)
        if accel and any(tok in str(e) for tok in _BACKEND_DEAD):
            # the TPU worker died: every further query would fail the
            # same way — abort this run so the report stays scoped to
            # what actually executed
            print("BENCH-WARNING: backend unavailable, aborting run",
                  file=sys.stderr, flush=True)
            return False
    return True


def main() -> None:
    global SF, DEADLINE
    if "--quick" in sys.argv:
        SF = min(SF, 0.01)
        STATE["sf"] = SF
    _install_handlers()
    sys.path.insert(0, REPO)
    import jax  # noqa: F401  (pre-imported by sitecustomize; config below)
    _setup_xla_cache()

    wh = _ensure_warehouse()

    STATE["phase"] = "stream-render"
    from ndstpu.engine.session import Session
    from ndstpu.io import loader
    from ndstpu.queries import streamgen

    queries = streamgen.render_power_corpus()
    STATE["n_queries"] = len(queries)

    STATE["phase"] = "load-catalog"
    catalog = loader.load_catalog(wh)

    # CPU baseline first: it is bounded (~minutes at SF1) while a
    # cold-cache TPU pass may not finish inside the budget — the
    # vs_baseline denominator must exist even when the TPU pass is cut.
    # The measured times are CACHED keyed by (SF, corpus fingerprint):
    # re-measuring 341 s of numpy every invocation ate 36% of the
    # realized budget in r03.  NDSTPU_BENCH_CPU=0 skips it entirely.
    STATE["phase"] = "cpu-baseline"
    if os.environ.get("NDSTPU_BENCH_CPU", "1") != "0":
        corpus_fp = _corpus_fingerprint(wh, queries)
        cpu_cache = os.path.join(CACHE, f"cpu_times_sf{SF:g}.json")
        cached = _load_cpu_cache(cpu_cache, corpus_fp)
        if cached is not None:
            STATE["cpu_times"], STATE["cpu_failed"] = cached
        else:
            cpu_sess = Session(catalog, backend="cpu")
            cpu_stop = time.time() + max(60.0, _remaining() * 0.45)
            complete = _power_run(cpu_sess, queries, STATE["cpu_times"],
                                  STATE["cpu_failed"], cpu_stop)
            # never cache a deadline-cut run NOR one with failures — a
            # transient failure would otherwise be replayed forever
            if complete and not STATE["cpu_failed"]:
                _save_cpu_cache(cpu_cache, corpus_fp,
                                STATE["cpu_times"], STATE["cpu_failed"])
    if STATE["cpu_failed"]:
        print(f"BENCH-WARNING: {len(STATE['cpu_failed'])} baseline "
              f"queries failed: {sorted(STATE['cpu_failed'])}",
              file=sys.stderr, flush=True)

    STATE["phase"] = "tpu-runs"
    rec_path = os.path.join(CACHE, f"plans_sf{SF:g}.pkl")

    def make_tpu_sess():
        s = Session(catalog, backend="tpu")
        try:  # persisted size-plan records: skip eager discovery
            s.preload_compiled(rec_path)
        except Exception:
            pass  # stale/corrupt records: discovery path still works
        return s

    holder = {"s": make_tpu_sess()}

    def rebuild():
        holder["s"] = make_tpu_sess()
        return holder["s"]

    n_runs = int(os.environ.get("NDSTPU_BENCH_RUNS", "3"))
    # run1 = discovery/compile (+persistent-cache replay), later runs =
    # compiled replay — the steady-state number.  Every run honors the
    # global deadline; a cut run is recorded as incomplete.
    for ri in range(n_runs):
        if _remaining() < 120.0:
            break
        run = {"times": {}, "failed": [], "complete": False}
        STATE["tpu_runs"].append(run)
        run["complete"] = _power_run(
            holder["s"], queries, run["times"], run["failed"],
            DEADLINE - 60.0, rebuild=rebuild)
        try:  # persist incrementally: a later crash must not lose them
            holder["s"].save_compiled(rec_path)
        except Exception:
            pass
        if not run["complete"]:
            break
        # stop early if another full run cannot fit
        est = sum(run["times"].values())
        if ri + 1 < n_runs and _remaining() - 60.0 < est:
            break

    # a deadline-cut first run mixes compile time into its per-query
    # numbers; if no complete run exists but some queries compiled,
    # spend whatever budget is left on a steady-state pass over that
    # subset so the headline measures execution, not compilation
    runs = STATE["tpu_runs"]
    if runs and not any(r["complete"] and not r["failed"] for r in runs):
        done = [(n, s) for n, s in queries
                if n in runs[-1]["times"] and
                n not in runs[-1]["failed"]]
        if done and _remaining() > 60.0:
            STATE["phase"] = "tpu-steady-subset"
            run = {"times": {}, "failed": [], "complete": False}
            STATE["tpu_runs"].append(run)
            _power_run(holder["s"], done, run["times"], run["failed"],
                       DEADLINE - 20.0, rebuild=rebuild)

    STATE["phase"] = "done"
    _emit()


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # noqa: BLE001
        import traceback
        traceback.print_exc()
        _emit(f"exception in phase {STATE['phase']}: "
              f"{type(e).__name__}: {e}")
