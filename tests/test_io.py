"""IO layer tests: CSV ingest, transcode, loader round-trip, ACID tables."""

import os
import subprocess

import numpy as np
import pyarrow as pa
import pytest

from ndstpu import schema
from ndstpu.check import check_build
from ndstpu.engine import columnar
from ndstpu.io import acid, csvio, loader


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    """Tiny generated dataset shared across IO tests."""
    out = tmp_path_factory.mktemp("data")
    tool = str(check_build())
    subprocess.run([tool, "-scale", "0.001", "-dir", str(out)], check=True)
    # driver layout: per-table dirs
    for t in schema.SOURCE_TABLE_NAMES:
        d = out / t
        d.mkdir()
        f = out / f"{t}_1_1.dat"
        if f.exists():
            f.rename(d / f.name)
    return out


@pytest.fixture(scope="module")
def warehouse(dataset, tmp_path_factory):
    out = tmp_path_factory.mktemp("wh")
    env = dict(os.environ, PYTHONPATH=os.getcwd())
    subprocess.run(
        ["python", "-m", "ndstpu.io.transcode",
         "--input_prefix", str(dataset),
         "--output_prefix", str(out),
         "--report_file", str(out / "load_report.txt")],
        check=True, env=env)
    return out


def test_csv_read_schema(dataset):
    s = schema.get_schemas()["store_sales"]
    at = csvio.read_table_dir(str(dataset), "store_sales", s)
    assert at.column_names == s.column_names
    assert at.num_rows > 0
    assert pa.types.is_decimal(at.schema.field("ss_net_paid").type)
    assert pa.types.is_int64(at.schema.field("ss_ticket_number").type)


def test_csv_nulls(dataset):
    s = schema.get_schemas()["store_sales"]
    at = csvio.read_table_dir(str(dataset), "store_sales", s)
    # ~2% of sold_date_sk are NULL by generator construction
    nulls = at.column("ss_sold_date_sk").null_count
    assert nulls > 0


def test_transcode_report(warehouse):
    text = (warehouse / "load_report.txt").read_text()
    assert "Load Test Time:" in text
    assert "RNGSEED used:" in text
    assert "Time to convert 'store_sales'" in text


def test_fact_partitioned_layout(warehouse):
    root = warehouse / "store_sales"
    parts = [p for p in os.listdir(root) if p.startswith("ss_sold_date_sk=")]
    assert len(parts) > 1
    # NULL sold dates (~2% by generator construction) land in the hive
    # default partition and must survive the round trip
    assert "ss_sold_date_sk=__HIVE_DEFAULT_PARTITION__" in parts


def test_loader_round_trip(dataset, warehouse):
    s = schema.get_schemas()["store_sales"]
    raw = csvio.read_table_dir(str(dataset), "store_sales", s)
    cat = loader.load_catalog(str(warehouse), ["store_sales", "date_dim"])
    t = cat.get("store_sales")
    assert t.num_rows == raw.num_rows
    assert t.column_names == s.column_names
    # decimal column is scaled int64
    c = t.column("ss_net_paid")
    assert c.ctype.kind == "decimal" and c.data.dtype == np.int64
    # sum of net_paid matches raw decimal sum
    raw_sum = sum(x.as_py() for x in raw.column("ss_net_paid") if x.is_valid)
    eng_sum = int(c.data[c.validity()].sum())
    assert float(raw_sum) == pytest.approx(eng_sum / 100, abs=0.01)


def test_dense_key_detection(warehouse):
    cat = loader.load_catalog(str(warehouse),
                              ["date_dim", "item", "customer"])
    assert cat.meta["item"].dense_key == "i_item_sk"
    assert cat.meta["item"].dense_min == 1
    assert cat.meta["date_dim"].dense_key == "d_date_sk"
    assert cat.meta["date_dim"].dense_min == 2415022


def test_string_dictionary_sorted(warehouse):
    cat = loader.load_catalog(str(warehouse), ["item"])
    d = cat.get("item").column("i_category").dictionary
    assert list(d) == sorted(d)


def test_avro_round_trip():
    import decimal as pydec

    from ndstpu.io import avroio
    at = pa.table({
        "i": pa.array([1, None, 3], type=pa.int32()),
        "l": pa.array([2 ** 60, None, -5], type=pa.int64()),
        "f": pa.array([1.5, None, float("nan")], type=pa.float64()),
        "s": pa.array(["a", None, "日本"], type=pa.string()),
        "d": pa.array([10957, None, 0], type=pa.int32()).cast(
            pa.date32()),
        "m": pa.array([pydec.Decimal("123.45"), None,
                       pydec.Decimal("-0.01")],
                      type=pa.decimal128(7, 2)),
    })
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        p = os.path.join(td, "t.avro")
        avroio.write_table(at, p)
        got = avroio.read_table(p)
    assert got.schema.names == at.schema.names
    for name in at.schema.names:
        a = at.column(name).to_pylist()
        b = got.column(name).to_pylist()
        for va, vb in zip(a, b):
            if isinstance(va, float) and va != va:
                assert vb != vb
            else:
                assert va == vb, (name, va, vb)


def test_avro_warehouse_round_trip(dataset, tmp_path):
    """transcode --output_format avro and load the warehouse back."""
    env = dict(os.environ, PYTHONPATH=os.getcwd())
    out = tmp_path / "wh_avro"
    subprocess.run(
        ["python", "-m", "ndstpu.io.transcode",
         "--input_prefix", str(dataset), "--output_prefix", str(out),
         "--report_file", str(out / "load.txt"),
         "--output_format", "avro", "--tables", "item,store"],
        check=True, env=env, stdout=subprocess.DEVNULL)
    cat = loader.load_catalog(str(out), tables=["item", "store"])
    t = cat.get("item")
    assert t.num_rows > 0
    assert "i_item_sk" in t.column_names
    # agrees with the parquet path
    cat2_dir = tmp_path / "wh_pq"
    subprocess.run(
        ["python", "-m", "ndstpu.io.transcode",
         "--input_prefix", str(dataset), "--output_prefix", str(cat2_dir),
         "--report_file", str(cat2_dir / "load.txt"),
         "--tables", "item,store"],
        check=True, env=env, stdout=subprocess.DEVNULL)
    cat2 = loader.load_catalog(str(cat2_dir), tables=["item", "store"])
    assert sorted(map(str, cat.get("item").to_rows())) == \
        sorted(map(str, cat2.get("item").to_rows()))


def test_acid_create_append_delete_rollback(tmp_path):
    at = pa.table({"k": pa.array([1, 2, 3, 4], pa.int32()),
                   "v": pa.array([10.0, 20.0, 30.0, 40.0])})
    root = str(tmp_path / "t")
    acid.create_table(root, at)
    assert acid.read(root).num_rows == 4
    v0 = acid.current_version(root)

    acid.append(root, pa.table({"k": pa.array([5], pa.int32()),
                                "v": pa.array([50.0])}))
    assert acid.read(root).num_rows == 5

    ts_before_delete = acid.load_snapshot(root).timestamp
    n = acid.delete_rows(
        root, lambda t: np.asarray(t.column("k").to_numpy() % 2 == 0))
    assert n == 2
    assert sorted(acid.read(root).column("k").to_pylist()) == [1, 3, 5]

    # time travel: read the pre-delete version
    assert acid.read(root, version=v0).num_rows == 4
    acid.rollback_to_timestamp(root, ts_before_delete)
    assert acid.read(root).num_rows == 5


def test_columnar_concat_string_merge():
    a = columnar.Table({"s": columnar.Column.from_strings(["b", "a", None])})
    b = columnar.Table({"s": columnar.Column.from_strings(["c", "a"])})
    m = columnar.Table.concat([a, b])
    assert m.column("s").to_pylist() == ["b", "a", None, "c", "a"]
    assert list(m.column("s").dictionary) == ["a", "b", "c"]


@pytest.mark.parametrize("fmt", ["ndslake", "ndsdelta"])
def test_lake_formats_create_append_delete_rollback(tmp_path, fmt):
    """Both ACID formats satisfy the same contract through the lake
    facade (reference benchmarks Iceberg AND Delta: nds_power.py:107-121)."""
    from ndstpu.io import lake
    mod = lake.module_for(fmt)
    at = pa.table({"k": pa.array([1, 2, 3, 4], pa.int32()),
                   "v": pa.array([10.0, 20.0, 30.0, 40.0])})
    root = str(tmp_path / "t")
    lake.create_table(fmt, root, at)
    assert lake.detect(root) is mod
    assert lake.read(root).num_rows == 4
    v0 = mod.current_version(root)

    lake.append(root, pa.table({"k": pa.array([5], pa.int32()),
                                "v": pa.array([50.0])}))
    assert lake.read(root).num_rows == 5
    import time as _time
    ts_before_delete = _time.time()

    n = lake.delete_rows(
        root, lambda t: np.asarray(t.column("k").to_numpy() % 2 == 0))
    assert n == 2
    assert sorted(lake.read(root).column("k").to_pylist()) == [1, 3, 5]

    # time travel + rollback
    assert lake.read(root, version=v0).num_rows == 4
    lake.rollback_to_timestamp(root, ts_before_delete)
    assert lake.read(root).num_rows == 5
    # rollback is itself a new commit: rolling forward again still works
    lake.rollback_to_version(root, v0)
    assert lake.read(root).num_rows == 4


def test_ndsdelta_checkpoint_replay(tmp_path):
    """Enough commits to cross a checkpoint: state must replay from the
    checkpoint, and time travel before it must still work."""
    from ndstpu.io import deltalog
    root = str(tmp_path / "t")
    deltalog.create_table(root, pa.table({"k": pa.array([0], pa.int32())}))
    for i in range(1, 14):
        deltalog.append(root, pa.table({"k": pa.array([i], pa.int32())}))
    assert deltalog.current_version(root) == 13
    cp = os.path.join(root, "_delta_log", "_last_checkpoint")
    assert os.path.exists(cp)
    assert deltalog.read(root).num_rows == 14
    # time travel to a pre-checkpoint version
    assert deltalog.read(root, version=3).num_rows == 4
    n = deltalog.delete_rows(
        root, lambda t: np.asarray(t.column("k").to_numpy() < 5))
    assert n == 5 and deltalog.read(root).num_rows == 9


def _sample_arrow():
    import decimal as _dec
    return pa.table({
        "k": pa.array([1, 2, 3, 4], pa.int64()),
        "d": pa.array([_dec.Decimal("1.50"), _dec.Decimal("2.25"),
                       None, _dec.Decimal("-9.99")],
                      pa.decimal128(7, 2)),
        "s": pa.array(["a", "b", None, "d"], pa.string()),
    })


@pytest.mark.parametrize("fmt", ["ndslake", "ndsdelta"])
def test_delta_export_standard_protocol(tmp_path, fmt):
    """Exported tables carry a protocol-correct Delta log: protocol +
    metaData (Spark schemaString) + one add per file with real sizes,
    and the data round-trips row-for-row — including after a DELETE
    (ndslake's merge-on-read deletion vectors must materialize)."""
    import json as _json
    from ndstpu.io import delta_export, deltalog
    at = _sample_arrow()
    src = tmp_path / "t"
    if fmt == "ndslake":
        acid.create_table(str(src), at)
        acid.delete_rows(str(src), lambda t: np.asarray(
            [v == 2 for v in t.column("k").to_pylist()]))
    else:
        deltalog.create_table(str(src), at)
        deltalog.delete_rows(str(src), lambda t: np.asarray(
            [v == 2 for v in t.column("k").to_pylist()]))
    out = tmp_path / "delta"
    info = delta_export.export(str(src), str(out))
    assert info["rows"] == 3
    log = out / "_delta_log" / f"{0:020d}.json"
    actions = [_json.loads(ln) for ln in log.read_text().splitlines()]
    kinds = [next(iter(a)) for a in actions]
    assert kinds[0] == "commitInfo"
    assert "protocol" in kinds and "metaData" in kinds
    proto = next(a["protocol"] for a in actions if "protocol" in a)
    assert proto == {"minReaderVersion": 1, "minWriterVersion": 2}
    meta = next(a["metaData"] for a in actions if "metaData" in a)
    sch = _json.loads(meta["schemaString"])
    assert sch["type"] == "struct"
    assert {f["name"]: f["type"] for f in sch["fields"]} == {
        "k": "long", "d": "decimal(7,2)", "s": "string"}
    adds = [a["add"] for a in actions if "add" in a]
    assert adds, "no add actions"
    total = 0
    for a in adds:
        fp = out / a["path"]
        assert fp.exists() and a["size"] == os.path.getsize(fp)
        assert a["partitionValues"] == {}
        total += pa.parquet.read_metadata(fp).num_rows  # noqa: F401
    # read back via the add list exactly as a Delta reader would
    import pyarrow.parquet as pq
    got = pa.concat_tables([pq.read_table(out / a["path"]) for a in adds])
    assert got.num_rows == 3
    assert sorted(got.column("k").to_pylist()) == [1, 3, 4]


# ---- crash-consistent commit protocol (io/commit.py) -----------------------


@pytest.mark.parametrize("fmt", ["ndslake", "ndsdelta"])
def test_lake_two_interleaved_writers_conflict(tmp_path, fmt):
    """Two writers based on the same snapshot: the first commit wins,
    the second raises a typed retryable CommitConflict instead of
    silently last-writer-wins clobbering."""
    from ndstpu.faults import taxonomy
    from ndstpu.io import lake
    at = pa.table({"k": pa.array([1, 2, 3], pa.int64())})
    root = str(tmp_path / "t")
    lake.create_table(fmt, root, at)
    v0 = lake.current_version(root)

    # writer A commits against v0 and wins
    lake.append(root, pa.table({"k": pa.array([4], pa.int64())}),
                expected_version=v0)
    # writer B also based its write on v0 — stale, must conflict
    with pytest.raises(lake.CommitConflict) as ei:
        lake.append(root, pa.table({"k": pa.array([5], pa.int64())}),
                    expected_version=v0)
    assert ei.value.expected == v0
    # conflicts are transient in the fault taxonomy: reload + retry
    assert taxonomy.classify(ei.value) == "transient"
    # writer A's commit survived intact, B's never landed
    assert sorted(lake.read(root).column("k").to_pylist()) == [1, 2, 3, 4]
    # the retry pattern: rebase on the current version and re-commit
    lake.append(root, pa.table({"k": pa.array([5], pa.int64())}),
                expected_version=lake.current_version(root))
    assert sorted(lake.read(root).column("k").to_pylist()) == \
        [1, 2, 3, 4, 5]


@pytest.mark.parametrize("fmt", ["ndslake", "ndsdelta"])
def test_lake_delete_conflict_on_stale_expected(tmp_path, fmt):
    from ndstpu.io import lake
    at = pa.table({"k": pa.array([1, 2, 3, 4], pa.int64())})
    root = str(tmp_path / "t")
    lake.create_table(fmt, root, at)
    v0 = lake.current_version(root)
    lake.append(root, pa.table({"k": pa.array([9], pa.int64())}))
    with pytest.raises(lake.CommitConflict):
        lake.delete_rows(
            root,
            lambda t: np.asarray(t.column("k").to_numpy() % 2 == 0),
            expected_version=v0)
    # nothing was deleted by the conflicted writer
    assert sorted(lake.read(root).column("k").to_pylist()) == \
        [1, 2, 3, 4, 9]


@pytest.mark.parametrize("fmt", ["ndslake", "ndsdelta"])
def test_lake_pinned_read_during_append_and_delete(tmp_path, fmt):
    """A reader pinned to its admission-time version sees exactly that
    snapshot's rows while appends AND deletes commit underneath it."""
    from ndstpu.io import lake
    at = pa.table({"k": pa.array(list(range(10)), pa.int64())})
    root = str(tmp_path / "t")
    lake.create_table(fmt, root, at)
    pin = lake.current_version(root)

    lake.append(root, pa.table({"k": pa.array([100, 101], pa.int64())}))
    lake.delete_rows(
        root, lambda t: np.asarray(t.column("k").to_numpy() % 3 == 0))

    live = sorted(lake.read(root).column("k").to_pylist())
    assert live != list(range(10))  # the live view moved
    pinned = sorted(lake.read(root, version=pin).column("k").to_pylist())
    assert pinned == list(range(10)), \
        "pinned read leaked post-pin appends or deletes"


@pytest.mark.parametrize("fmt", ["ndslake", "ndsdelta"])
def test_lake_pinned_historical_read_after_many_commits(tmp_path, fmt):
    """Every historical version stays resolvable after N commits."""
    from ndstpu.io import lake
    root = str(tmp_path / "t")
    lake.create_table(
        fmt, root, pa.table({"k": pa.array([0], pa.int64())}))
    versions = [lake.current_version(root)]
    for i in range(1, 13):  # crosses the ndsdelta checkpoint at v10
        lake.append(root, pa.table({"k": pa.array([i], pa.int64())}))
        versions.append(lake.current_version(root))
    for n, v in enumerate(versions, start=1):
        got = sorted(lake.read(root, version=v).column("k").to_pylist())
        assert got == list(range(n)), f"version {v} unresolvable"


@pytest.mark.parametrize("fmt", ["ndslake", "ndsdelta"])
def test_lake_abort_to_version_retracts_history(tmp_path, fmt):
    """Crash-recovery retraction: versions above the target disappear
    and the next commit reuses the retracted numbering — unlike
    rollback_to_version, which publishes a NEW snapshot."""
    from ndstpu.io import lake
    at = pa.table({"k": pa.array([1, 2], pa.int64())})
    root = str(tmp_path / "t")
    lake.create_table(fmt, root, at)
    v0 = lake.current_version(root)
    lake.append(root, pa.table({"k": pa.array([3], pa.int64())}))
    lake.append(root, pa.table({"k": pa.array([4], pa.int64())}))
    v2 = lake.current_version(root)
    assert v2 > v0

    lake.abort_to_version(root, v0)
    assert lake.current_version(root) == v0
    assert sorted(lake.read(root).column("k").to_pylist()) == [1, 2]
    # retracted versions are gone, and numbering restarts where the
    # first aborted commit had been — the clean-run trajectory
    lake.append(root, pa.table({"k": pa.array([7], pa.int64())}))
    assert lake.current_version(root) == v0 + 1
    assert sorted(lake.read(root).column("k").to_pylist()) == [1, 2, 7]


def test_ndslake_gc_orphan_manifests(tmp_path):
    """A manifest written but never published to CURRENT (crash or
    injected fault mid-commit) is GC-able, restoring _next_version."""
    import json as _json

    root = str(tmp_path / "t")
    acid.create_table(root, pa.table({"k": pa.array([1], pa.int64())}))
    cur = acid.current_version(root)
    orphan = acid._snap_path(root, cur + 3)
    with open(orphan, "w") as f:
        _json.dump({"version": cur + 3, "timestamp": 0.0, "files": [],
                    "partition_col": None, "operation": "torn"}, f)
    assert acid._next_version(root) == cur + 4  # skewed by the orphan
    assert acid.gc_orphan_manifests(root) == [cur + 3]
    assert not os.path.exists(orphan)
    assert acid._next_version(root) == cur + 1
    # CURRENT was never touched
    assert acid.current_version(root) == cur


@pytest.mark.parametrize("fmt", ["ndslake", "ndsdelta"])
def test_lake_chunk_source_windows_and_deletes(tmp_path, fmt):
    """LakeChunkSource reads a pinned version across multi-file windows
    with deletion masks applied, ignoring post-pin commits."""
    from ndstpu.io import lake
    from ndstpu.io.loader import LakeChunkSource
    root = str(tmp_path / "t")
    lake.create_table(
        fmt, root,
        pa.table({"k": pa.array(list(range(6)), pa.int64()),
                  "v": pa.array([float(i) for i in range(6)])}))
    lake.append(root, pa.table({"k": pa.array([6, 7], pa.int64()),
                                "v": pa.array([6.0, 7.0])}))
    lake.delete_rows(
        root, lambda t: np.asarray(t.column("k").to_numpy() == 1))
    pin = lake.current_version(root)

    src = LakeChunkSource(root, columns=["k", "v"], version=pin)
    assert src.num_rows == 7  # 8 rows minus the deleted k=1
    ks = []
    for start in range(0, src.num_rows, 3):  # windows cross file edges
        payload = src.read(start, min(3, src.num_rows - start))
        vals, valid = payload["k"]
        assert valid.all()
        ks.extend(vals.tolist())
    # windows tile the pinned rows exactly once; global file order is
    # format-specific (ndsdelta's COW delete rewrites file lists)
    assert sorted(ks) == [0, 2, 3, 4, 5, 6, 7]

    # post-pin commits are invisible to the pinned source
    lake.append(root, pa.table({"k": pa.array([99], pa.int64()),
                                "v": pa.array([99.0])}))
    assert LakeChunkSource(root, columns=["k"],
                           version=pin).num_rows == 7
    fresh = LakeChunkSource(root, columns=["k"])
    assert fresh.num_rows == 8
    vals, _ = fresh.read(0, 8)["k"]
    assert sorted(vals.tolist()) == [0, 2, 3, 4, 5, 6, 7, 99]


# ---- global dictionary sidecars (io/gdict.py) ------------------------------


def test_transcode_builds_gdict_sidecars(warehouse):
    """Transcode writes a _GLOBAL_DICTS.json sidecar per string-bearing
    table; the loader encodes resident columns against it, so resident
    codes ARE the warehouse-wide code space."""
    from ndstpu.io import gdict
    assert gdict.has_sidecar(str(warehouse / "item"))
    gds = gdict.table_dicts(str(warehouse / "item"), "item")
    cat = loader.load_catalog(str(warehouse), ["item"])
    c = cat.get("item").column("i_category")
    assert c.gdict is not None
    assert list(c.dictionary) == list(gds["i_category"].values)
    d = gds["i_category"]
    assert list(d.values) == sorted(d.values)
    assert d.hash == gdict.content_hash(d.values)
    assert d.nbytes == sum(len(str(v).encode()) for v in d.values)


def test_gdict_kill_switch_disables_layer(warehouse, monkeypatch):
    from ndstpu.io import gdict
    monkeypatch.setenv("NDSTPU_GLOBAL_DICTS", "0")
    assert not gdict.enabled()
    assert gdict.table_dicts(str(warehouse / "item"), "item") == {}
    cat = loader.load_catalog(str(warehouse), ["item"])
    assert cat.get("item").column("i_category").gdict is None


def test_gdict_update_sidecar_append_only(tmp_path):
    """Growth produces a NEW sorted version; the value set only grows;
    re-running with the same values writes nothing new; pinned
    selection returns the version matching the pin."""
    import numpy as np

    from ndstpu.io import gdict
    td = str(tmp_path / "t")
    gdict.update_sidecar(td, "t", {"s": np.asarray(
        ["birch", "ash"], object)}, table_version=0)
    d0 = gdict.table_dicts(td, "t")["s"]
    assert list(d0.values) == ["ash", "birch"] and d0.version == 0

    # idempotent: same value set -> no new version
    gdict.update_sidecar(td, "t", {"s": np.asarray(
        ["ash", "birch"], object)}, table_version=1)
    assert gdict.table_dicts(td, "t")["s"].version == 0

    # growth: union, re-sorted, new version stamped with the commit
    gdict.update_sidecar(td, "t", {"s": np.asarray(
        ["cedar", "ash"], object)}, table_version=2)
    d2 = gdict.table_dicts(td, "t")["s"]
    assert list(d2.values) == ["ash", "birch", "cedar"]
    assert d2.version == 1 and d2.table_version == 2
    # snapshot-pinned readers keep their matching version
    dp = gdict.table_dicts(td, "t", pin_table_version=1)["s"]
    assert list(dp.values) == ["ash", "birch"] and dp.version == 0


def test_parquet_chunk_source_streams_strings(warehouse):
    """String tables stream chunk-wise: every chunk decodes against the
    frozen sidecar dictionary, so chunk codes agree with the resident
    load (the invariant that unlocked out-of-core string tables)."""
    import numpy as np

    cat = loader.load_catalog(str(warehouse), ["item"])
    resident = cat.get("item")
    src = loader.ParquetChunkSource(
        str(warehouse), "item", ["i_item_sk", "i_category"])
    assert src.num_rows == resident.num_rows
    meta = src.column_meta()
    assert list(meta["i_category"][2]) == \
        list(resident.column("i_category").dictionary)
    codes = []
    for start in range(0, src.num_rows, 7):
        vals, _ = src.read(start, min(7, src.num_rows - start))[
            "i_category"]
        codes.extend(vals.tolist())
    assert np.array_equal(
        np.asarray(codes), resident.column("i_category").data)


def test_parquet_chunk_source_rejects_strings_without_dicts(
        warehouse, monkeypatch):
    monkeypatch.setenv("NDSTPU_GLOBAL_DICTS", "0")
    with pytest.raises(loader.StreamUnsupported) as ei:
        loader.ParquetChunkSource(str(warehouse), "item",
                                  ["i_item_sk", "i_category"])
    assert "NDSTPU_GLOBAL_DICTS" in str(ei.value)
