"""Canonical plan-shape analyzer: fingerprint stability, slot lifting,
and the shape-keyed compile cache it feeds.

Static half (zero-row schema catalog, no warehouse, no jax): the
canonicalizer's fingerprint must be a pure function of plan STRUCTURE —
renderings of one template that differ only in literals share it, and
the value-dependent artifacts the optimizer leaves behind (generated
``__ssa`` column names, ``UnaryOp('neg')`` wrappers) must not leak in.

Runtime half (tiny generated warehouse): canonical keying must be
invisible to results (differential vs the text-keyed path under
NDSTPU_CANON=0), must make re-renderings compile ZERO new programs, and
must give a discover-process and a preload-process identical compile
cache keys.
"""

import math
import os
import subprocess

import pytest

from ndstpu import analysis, obs
from ndstpu.engine.session import Session
from ndstpu.io import loader
from ndstpu.queries import streamgen

SEED_A = "07291122510"   # pinned bench seed
SEED_B = "19980713042"

# corpus sample for the runtime property tests: star joins + grouped
# aggregates, all verified to collapse to ONE cache key across seeds
# (scripts/canon_audit.py) — re-renderings must be compile-free
SAMPLE = ["query3", "query42", "query52", "query55", "query96"]


def render(name, seed, stream=0):
    parts = streamgen.render_template_parts(
        str(streamgen.TEMPLATE_DIR / f"{name}.tpl"), seed, stream)
    return [(p, sql) for p, sql in parts]


# -- static: fingerprint + slot semantics ------------------------------------


@pytest.fixture(scope="module")
def ssess():
    return Session(analysis.schema_catalog())


@pytest.fixture(scope="module")
def tables():
    return analysis.schema_tables()


def canon_of(ssess, tables, sql, query="q"):
    plan, _cols = ssess.plan(sql)
    return analysis.canonicalize(plan, tables=tables, query=query)


def test_fingerprint_stable_across_renderings(ssess, tables):
    """Different literal draws of one template -> one fingerprint;
    the drawn values travel in the binding, not the structure."""
    for name in ("query7", "query52"):
        fps, bindings = set(), []
        for seed in (SEED_A, SEED_B):
            for pname, sql in render(name, seed):
                res = canon_of(ssess, tables, sql, pname)
                fps.add(res.fingerprint)
                bindings.append(tuple(res.binding.values))
        assert len(fps) == 1, f"{name}: structure varied with literals"
        assert len(set(bindings)) > 1, \
            f"{name}: seeds drew identical literals (bad sample)"


def test_slots_are_per_occurrence_not_value_deduped(ssess, tables):
    """Two predicates that coincidentally render the SAME literal ('M'
    is a gender AND a marital status) must lift into two slots —
    value-based dedup would make structure depend on the draw."""
    res = canon_of(ssess, tables,
                   "select count(*) as n from customer_demographics "
                   "where cd_gender = 'M' and cd_marital_status = 'M'")
    cols = sorted(s.column for s in res.slots if s.column)
    assert cols == [("customer_demographics", "cd_gender"),
                    ("customer_demographics", "cd_marital_status")]
    # and the collision rendering shares its fingerprint with a
    # collision-free one
    res2 = canon_of(ssess, tables,
                    "select count(*) as n from customer_demographics "
                    "where cd_gender = 'F' and cd_marital_status = 'S'")
    assert res.fingerprint == res2.fingerprint


def test_negated_literal_folds_into_binding(ssess, tables):
    """`= -6` parses as UnaryOp('neg', 6); the sign must fold into the
    bound value so negative and positive draws share one structure."""
    neg = canon_of(ssess, tables,
                   "select count(*) as n from customer_address "
                   "where ca_gmt_offset = -6")
    pos = canon_of(ssess, tables,
                   "select count(*) as n from customer_address "
                   "where ca_gmt_offset = 7")
    assert neg.fingerprint == pos.fingerprint
    assert -6 in [s.value for s in neg.slots]
    assert ("customer_address", "ca_gmt_offset") in \
        [s.column for s in neg.slots]


def test_generated_ssa_names_normalized(ssess, tables):
    """The sibling-aggregate fusion names internal columns with an md5
    of the conjuncts — literal-dependent.  Canonicalization renumbers
    generated names so the q28 idiom collapses across draws."""
    def q28ish(b):
        return ("select * from "
                f"(select avg(ss_list_price) a1 from store_sales "
                f" where ss_quantity between {b[0]} and {b[1]}) x1, "
                f"(select avg(ss_list_price) a2 from store_sales "
                f" where ss_quantity between {b[2]} and {b[3]}) x2")
    r1 = canon_of(ssess, tables, q28ish((0, 5, 6, 10)))
    r2 = canon_of(ssess, tables, q28ish((11, 15, 16, 20)))
    assert r1.fingerprint == r2.fingerprint


def _plan_exprs(plan):
    import dataclasses

    from ndstpu.engine import expr as ex
    for node in plan.walk():
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            for it in (v if isinstance(v, (list, tuple)) else (v,)):
                if isinstance(it, tuple) and it and \
                        isinstance(it[0], ex.Expr):
                    it = it[0]
                if isinstance(it, ex.Expr):
                    yield from it.walk()


def test_exec_plan_param_sites_match_slot_classes(ssess, tables):
    """exec_plan (what the runtime compiles) keeps a Param at every
    BINDABLE site — that is the whole point of the shape key — while
    every shape-affecting value is substituted back as a literal so
    array extents stay concrete at trace time."""
    from ndstpu.engine import expr as ex
    for _p, sql in render("query7", SEED_A):
        res = canon_of(ssess, tables, sql)
        slots_seen = sorted(
            e.slot for e in _plan_exprs(res.exec_plan)
            if isinstance(e, (ex.Param, ex.InParam)))
        assert slots_seen == sorted(s.slot for s in res.bindable)
        from ndstpu.engine import plan as lp
        lits = [e.value for e in _plan_exprs(res.exec_plan)
                if isinstance(e, ex.Literal)]
        lits += [n.n for n in res.exec_plan.walk()
                 if isinstance(n, lp.Limit)]   # LIMIT count is shape
        for s in res.shape_affecting:
            vals = s.value if isinstance(s.value, tuple) else (s.value,)
            for v in vals:
                assert any(v == x or (isinstance(x, float) and
                           isinstance(v, (int, float)) and
                           math.isclose(float(v), x)) for x in lits), \
                    f"shape slot value {v!r} missing from exec_plan"
        # the bound values line up slot-for-slot with the lift
        assert res.binding.values == res.values
        # string binds never appear in the scalar spec (they reach the
        # device as dictionary hit tables, not broadcast scalars)
        assert all(ct.kind != "string" for _s, ct in res.binding.scalars)


def test_canonical_key_session_helper(ssess):
    """Session.canonical_key: two renderings -> same key; unparseable
    text degrades to the normalized-text key instead of raising."""
    from ndstpu.engine.sql import normalize_sql_key
    (_, sql_a), = render("query52", SEED_A)
    (_, sql_b), = render("query52", SEED_B)
    assert sql_a != sql_b
    key = ssess.canonical_key(sql_a)
    assert key.startswith("c:")
    assert key == ssess.canonical_key(sql_b)
    junk = "not sql at all"
    assert ssess.canonical_key(junk) == normalize_sql_key(junk)


# -- runtime: differential + cache-counter properties -------------------------


@pytest.fixture(scope="module")
def warehouse(tmp_path_factory):
    data = tmp_path_factory.mktemp("rawc")
    wh = tmp_path_factory.mktemp("whc")
    env = dict(os.environ, PYTHONPATH=os.getcwd())
    subprocess.run(["python", "-m", "ndstpu.datagen.driver", "local",
                    "0.002", "2", str(data)], check=True, env=env)
    subprocess.run(["python", "-m", "ndstpu.io.transcode",
                    "--input_prefix", str(data), "--output_prefix",
                    str(wh), "--report_file", str(wh / "load.txt")],
                   check=True, env=env, stdout=subprocess.DEVNULL)
    return wh


@pytest.fixture(scope="module")
def catalog(warehouse):
    return loader.load_catalog(str(warehouse))


def _rows(t):
    out = []
    for r in t.to_rows():
        row = []
        for v in r:
            if isinstance(v, float):
                row.append(round(v, 4))
            else:
                row.append(v)
        out.append(tuple(row))
    return sorted(out, key=repr)


def test_canonical_results_match_text_keyed(catalog, monkeypatch):
    """Property: for every sample rendering, the canonical (param-bound)
    execution equals the text-keyed execution of the SAME sql."""
    canon_sess = Session(catalog, backend="tpu")
    monkeypatch.setenv("NDSTPU_CANON", "0")
    text_sess = Session(catalog, backend="tpu")
    for name in SAMPLE:
        for seed in (SEED_A, SEED_B):
            for pname, sql in render(name, seed):
                monkeypatch.setenv("NDSTPU_CANON", "1")
                got = _rows(canon_sess.sql(sql))
                monkeypatch.setenv("NDSTPU_CANON", "0")
                want = _rows(text_sess.sql(sql))
                assert got == want, f"{pname} seed={seed}"


def test_second_seed_compiles_zero_new_programs(catalog):
    """The acceptance property: seed A's sweep misses the compile cache
    exactly once per distinct fingerprint; seed B's re-rendered sweep
    compiles NOTHING new — every part replays seed A's programs."""
    sess = Session(catalog, backend="tpu")
    fps = set()
    for name in SAMPLE:
        for _p, sql in render(name, SEED_A):
            fps.add(sess.canonical_key(sql))
    before = obs.counters_snapshot()
    for name in SAMPLE:
        for _p, sql in render(name, SEED_A):
            sess.sql(sql).to_rows()
    cold = obs.counter_delta(before)
    assert cold.get("engine.cache.compiled.miss", 0) == len(fps)

    before = obs.counters_snapshot()
    for name in SAMPLE:
        for _p, sql in render(name, SEED_B):
            sess.sql(sql).to_rows()
    warm = obs.counter_delta(before)
    assert warm.get("engine.cache.compiled.miss", 0) == 0, \
        "re-rendered corpus sample recompiled under canonical keying"
    assert warm.get("engine.cache.compiled.hit", 0) >= len(SAMPLE)


def test_discover_and_preload_agree_on_cache_keys(catalog, tmp_path):
    """A records-preloaded process must register every record under the
    SAME canonical key a fresh discover-process computes — otherwise the
    preload is dead weight and the first power query re-discovers."""
    sql = ("select i_category, count(*) as n, sum(ss_net_paid) as s "
           "from store_sales join item on ss_item_sk = i_item_sk "
           "group by i_category order by i_category")
    s1 = Session(catalog, backend="tpu")
    want = _rows(s1.sql(sql))
    path = str(tmp_path / "plans.pkl")
    assert s1.save_compiled(path) >= 1
    keys1 = set(s1._jax_executor()._compiled)

    s2 = Session(catalog, backend="tpu")
    assert s2.preload_compiled(path) >= 1
    keys2 = set(s2._jax_executor()._compiled)
    assert keys1 == keys2, \
        f"discover/preload key mismatch: {keys1 ^ keys2}"
    # the canonical key is what execution probes — and it is a
    # fingerprint key, not a text key
    ck = f"{s2._views_epoch}|{s2.canonical_key(sql)}"
    assert ck in keys2
    assert s2.canonical_key(sql).startswith("c:")
    # execution replays the preloaded record: no new cache entries,
    # identical rows
    before = obs.counters_snapshot()
    got = _rows(s2.sql(sql))
    assert got == want
    assert set(s2._jax_executor()._compiled) == keys2
    delta = obs.counter_delta(before)
    assert delta.get("engine.cache.compiled.miss", 0) == 0
