"""Data generator tests: determinism, chunking, schema conformance, refresh sets."""

import os
import subprocess

import pytest

from ndstpu import schema
from ndstpu.check import check_build


@pytest.fixture(scope="module")
def tool():
    return str(check_build())


def run_gen(tool, outdir, *extra):
    outdir.mkdir(parents=True, exist_ok=True)
    subprocess.run([tool, "-scale", "0.01", "-dir", str(outdir), *extra],
                   check=True)


def test_all_tables_generated(tool, tmp_path):
    run_gen(tool, tmp_path)
    for t in schema.SOURCE_TABLE_NAMES:
        assert (tmp_path / f"{t}_1_1.dat").exists(), t


def test_field_counts_match_schema(tool, tmp_path):
    run_gen(tool, tmp_path)
    schemas = schema.get_schemas()
    for t, s in schemas.items():
        path = tmp_path / f"{t}_1_1.dat"
        with open(path) as f:
            line = f.readline().rstrip("\n")
        # dsdgen convention: trailing '|' terminator -> n fields + empty tail
        fields = line.split("|")
        assert fields[-1] == "", f"{t}: missing trailing pipe"
        assert len(fields) - 1 == len(s), (
            f"{t}: {len(fields) - 1} fields vs {len(s)} schema columns")


def test_chunking_is_deterministic(tool, tmp_path):
    one = tmp_path / "one"
    four = tmp_path / "four"
    run_gen(tool, one, "-table", "customer")
    for c in "1234":
        run_gen(tool, four, "-parallel", "4", "-child", c, "-table", "customer")
    whole = (one / "customer_1_1.dat").read_text()
    parts = "".join(
        (four / f"customer_{c}_4.dat").read_text() for c in "1234")
    assert whole == parts


def test_seed_changes_content(tool, tmp_path):
    a = tmp_path / "a"
    b = tmp_path / "b"
    run_gen(tool, a, "-table", "item")
    run_gen(tool, b, "-table", "item", "-seed", "42")
    assert (a / "item_1_1.dat").read_text() != (b / "item_1_1.dat").read_text()


def test_referential_integrity_returns(tool, tmp_path):
    """store_returns rows must reference (ticket, item) pairs that exist in
    store_sales — the generator re-derives parent sale values."""
    run_gen(tool, tmp_path, "-table", "store_sales")
    run_gen(tool, tmp_path, "-table", "store_returns")
    sales = set()
    for line in (tmp_path / "store_sales_1_1.dat").read_text().splitlines():
        f = line.split("|")
        sales.add((f[9], f[2]))  # (ss_ticket_number, ss_item_sk)
    n = 0
    for line in (tmp_path / "store_returns_1_1.dat").read_text().splitlines():
        f = line.split("|")
        assert (f[9], f[2]) in sales  # (sr_ticket_number, sr_item_sk)
        n += 1
    assert n > 0


def test_date_dim_calendar(tool, tmp_path):
    run_gen(tool, tmp_path, "-table", "date_dim")
    lines = (tmp_path / "date_dim_1_1.dat").read_text().splitlines()
    assert len(lines) == 73049
    first = lines[0].split("|")
    assert first[0] == "2415022" and first[2] == "1900-01-02"
    assert first[14] == "Tuesday"
    # spot-check a known date: 2000-01-01 was a Saturday
    by_date = {l.split("|")[2]: l.split("|") for l in lines[36000:37500]}
    row = by_date["2000-01-01"]
    assert row[14] == "Saturday" and row[6] == "2000"


def test_update_set(tool, tmp_path):
    run_gen(tool, tmp_path, "-update", "1")
    for t in schema.MAINTENANCE_TABLE_NAMES:
        assert (tmp_path / f"{t}_1_1.dat").exists(), t
    # delete tables: 3 date ranges each, date1 <= date2
    for t in ("delete", "inventory_delete"):
        lines = (tmp_path / f"{t}_1_1.dat").read_text().splitlines()
        assert len(lines) == 3
        for line in lines:
            d1, d2, _ = line.split("|")
            assert d1 <= d2


def test_driver_cli(tool, tmp_path):
    out = tmp_path / "data"
    env = dict(os.environ, PYTHONPATH=os.getcwd())
    subprocess.run(
        ["python", "-m", "ndstpu.datagen.driver", "local", "0.01", "2",
         str(out)],
        check=True, env=env)
    # per-table dirs with chunk files inside
    assert (out / "store_sales" / "store_sales_1_2.dat").exists()
    assert (out / "store_sales" / "store_sales_2_2.dat").exists()
    assert (out / "date_dim" / "date_dim_1_2.dat").exists()
    # small tables may produce fewer chunks but the dir must exist
    assert (out / "warehouse").is_dir()


def test_driver_range_merge(tool, tmp_path):
    out = tmp_path / "data"
    env = dict(os.environ, PYTHONPATH=os.getcwd())
    for rng in ("1,2", "3,4"):
        subprocess.run(
            ["python", "-m", "ndstpu.datagen.driver", "local", "0.01", "4",
             str(out), "--range", rng],
            check=True, env=env)
    files = sorted(os.listdir(out / "customer"))
    assert files == [f"customer_{i}_4.dat" for i in (1, 2, 3, 4)]
    assert not (out / "_temp_").exists()
