"""Data generator tests: determinism, chunking, schema conformance, refresh sets."""

import os
import subprocess

import pytest

from ndstpu import schema
from ndstpu.check import check_build


@pytest.fixture(scope="module")
def tool():
    return str(check_build())


def run_gen(tool, outdir, *extra):
    outdir.mkdir(parents=True, exist_ok=True)
    subprocess.run([tool, "-scale", "0.01", "-dir", str(outdir), *extra],
                   check=True)


def test_all_tables_generated(tool, tmp_path):
    run_gen(tool, tmp_path)
    for t in schema.SOURCE_TABLE_NAMES:
        assert (tmp_path / f"{t}_1_1.dat").exists(), t


def test_field_counts_match_schema(tool, tmp_path):
    run_gen(tool, tmp_path)
    schemas = schema.get_schemas()
    for t, s in schemas.items():
        path = tmp_path / f"{t}_1_1.dat"
        with open(path) as f:
            line = f.readline().rstrip("\n")
        # dsdgen convention: trailing '|' terminator -> n fields + empty tail
        fields = line.split("|")
        assert fields[-1] == "", f"{t}: missing trailing pipe"
        assert len(fields) - 1 == len(s), (
            f"{t}: {len(fields) - 1} fields vs {len(s)} schema columns")


def test_chunking_is_deterministic(tool, tmp_path):
    one = tmp_path / "one"
    four = tmp_path / "four"
    run_gen(tool, one, "-table", "customer")
    for c in "1234":
        run_gen(tool, four, "-parallel", "4", "-child", c, "-table", "customer")
    whole = (one / "customer_1_1.dat").read_text()
    parts = "".join(
        (four / f"customer_{c}_4.dat").read_text() for c in "1234")
    assert whole == parts


def test_seed_changes_content(tool, tmp_path):
    a = tmp_path / "a"
    b = tmp_path / "b"
    run_gen(tool, a, "-table", "item")
    run_gen(tool, b, "-table", "item", "-seed", "42")
    assert (a / "item_1_1.dat").read_text() != (b / "item_1_1.dat").read_text()


def test_referential_integrity_returns(tool, tmp_path):
    """store_returns rows must reference (ticket, item) pairs that exist in
    store_sales — the generator re-derives parent sale values."""
    run_gen(tool, tmp_path, "-table", "store_sales")
    run_gen(tool, tmp_path, "-table", "store_returns")
    sales = set()
    for line in (tmp_path / "store_sales_1_1.dat").read_text().splitlines():
        f = line.split("|")
        sales.add((f[9], f[2]))  # (ss_ticket_number, ss_item_sk)
    n = 0
    for line in (tmp_path / "store_returns_1_1.dat").read_text().splitlines():
        f = line.split("|")
        assert (f[9], f[2]) in sales  # (sr_ticket_number, sr_item_sk)
        n += 1
    assert n > 0


def test_date_dim_calendar(tool, tmp_path):
    run_gen(tool, tmp_path, "-table", "date_dim")
    lines = (tmp_path / "date_dim_1_1.dat").read_text().splitlines()
    assert len(lines) == 73049
    first = lines[0].split("|")
    assert first[0] == "2415022" and first[2] == "1900-01-02"
    assert first[14] == "Tuesday"
    # spot-check a known date: 2000-01-01 was a Saturday
    by_date = {l.split("|")[2]: l.split("|") for l in lines[36000:37500]}
    row = by_date["2000-01-01"]
    assert row[14] == "Saturday" and row[6] == "2000"


def test_update_set(tool, tmp_path):
    run_gen(tool, tmp_path, "-update", "1")
    for t in schema.MAINTENANCE_TABLE_NAMES:
        assert (tmp_path / f"{t}_1_1.dat").exists(), t
    # delete tables: 3 date ranges each, date1 <= date2
    for t in ("delete", "inventory_delete"):
        lines = (tmp_path / f"{t}_1_1.dat").read_text().splitlines()
        assert len(lines) == 3
        for line in lines:
            d1, d2, _ = line.split("|")
            assert d1 <= d2


def test_driver_cli(tool, tmp_path):
    out = tmp_path / "data"
    env = dict(os.environ, PYTHONPATH=os.getcwd())
    subprocess.run(
        ["python", "-m", "ndstpu.datagen.driver", "local", "0.01", "2",
         str(out)],
        check=True, env=env)
    # per-table dirs with chunk files inside
    assert (out / "store_sales" / "store_sales_1_2.dat").exists()
    assert (out / "store_sales" / "store_sales_2_2.dat").exists()
    assert (out / "date_dim" / "date_dim_1_2.dat").exists()
    # small tables may produce fewer chunks but the dir must exist
    assert (out / "warehouse").is_dir()


def test_driver_range_merge(tool, tmp_path):
    out = tmp_path / "data"
    env = dict(os.environ, PYTHONPATH=os.getcwd())
    for rng in ("1,2", "3,4"):
        subprocess.run(
            ["python", "-m", "ndstpu.datagen.driver", "local", "0.01", "4",
             str(out), "--range", rng],
            check=True, env=env)
    files = sorted(os.listdir(out / "customer"))
    assert files == [f"customer_{i}_4.dat" for i in (1, 2, 3, 4)]
    assert not [d for d in os.listdir(out) if d.startswith("_temp_")]


def test_pod_mode_byte_identical_to_local(tmp_path):
    """`pod` mode (host-list fan-out, GenTable.java analog) over a
    shared directory must produce byte-identical output to a local run
    with the same scale/parallel: chunks are position-deterministic, so
    the host assignment cannot matter. Uses `--launcher 'bash -c'` so
    both 'hosts' are this machine."""
    import filecmp

    env = dict(os.environ, PYTHONPATH=os.getcwd())
    local = tmp_path / "local"
    pod = tmp_path / "pod"
    subprocess.run(["python", "-m", "ndstpu.datagen.driver", "local",
                    "0.002", "4", str(local)], check=True, env=env,
                   stdout=subprocess.DEVNULL)
    subprocess.run(["python", "-m", "ndstpu.datagen.driver", "pod",
                    "0.002", "4", str(pod),
                    "--hosts", "hostA,hostB",
                    "--launcher", "bash -c"],
                   check=True, env=env, stdout=subprocess.DEVNULL)
    tables = sorted(os.listdir(local))
    assert sorted(os.listdir(pod)) == tables
    for table in tables:
        lfiles = sorted(os.listdir(local / table))
        pfiles = sorted(os.listdir(pod / table))
        assert pfiles == lfiles, table
        for f in lfiles:
            assert filecmp.cmp(local / table / f, pod / table / f,
                               shallow=False), f"{table}/{f} differs"


def test_pod_mode_failure_reports_slices(tmp_path):
    env = dict(os.environ, PYTHONPATH=os.getcwd())
    r = subprocess.run(
        ["python", "-m", "ndstpu.datagen.driver", "pod", "0.002", "4",
         str(tmp_path / "x"), "--hosts", "h1",
         "--launcher", "false"],  # launcher that always fails
        env=env, capture_output=True, text=True)
    assert r.returncode != 0
    assert "re-run those slices" in r.stderr


def _sizes(tool, sf):
    out = subprocess.run([tool, "-sizes", str(sf)], capture_output=True,
                         text=True, check=True).stdout
    return {ln.split("|")[0]: int(ln.split("|")[1])
            for ln in out.strip().splitlines()}


def test_spec_step_table_cardinalities(tool):
    """Row counts follow the published TPC-DS step table (spec Table
    3-2) at SF 1/10/100 — dsdgen -scale semantics, wrapped by the
    reference at tpcds-gen/.../GenTable.java:49-167.  A lin/sqrt
    heuristic diverges from the NDS workload above SF1 (item must JUMP
    to 102,000 at SF10, not scale to ~57k)."""
    sf1 = _sizes(tool, 1)
    assert sf1["store_sales"] == 2880404
    assert sf1["store_returns"] == 287514
    assert sf1["catalog_sales"] == 1441548
    assert sf1["catalog_returns"] == 144067
    assert sf1["web_sales"] == 719384
    assert sf1["web_returns"] == 71763
    assert sf1["inventory"] == 11745000
    assert sf1["item"] == 18000
    assert sf1["customer"] == 100000
    assert sf1["customer_address"] == 50000
    assert sf1["store"] == 12
    assert sf1["warehouse"] == 5
    assert sf1["web_site"] == 30
    assert sf1["web_page"] == 60
    assert sf1["promotion"] == 300
    assert sf1["call_center"] == 6
    assert sf1["catalog_page"] == 11718
    assert sf1["reason"] == 35

    sf10 = _sizes(tool, 10)
    assert sf10["store_sales"] == 28800991
    assert sf10["store_returns"] == 2875432
    assert sf10["catalog_sales"] == 14401261
    assert sf10["catalog_returns"] == 1439749
    assert sf10["web_sales"] == 7197566
    assert sf10["web_returns"] == 719217
    assert sf10["inventory"] == 133110000
    assert sf10["item"] == 102000
    assert sf10["customer"] == 500000
    assert sf10["customer_address"] == 250000
    assert sf10["store"] == 102
    assert sf10["warehouse"] == 10
    assert sf10["web_site"] == 42
    assert sf10["web_page"] == 200
    assert sf10["promotion"] == 500
    assert sf10["call_center"] == 24
    assert sf10["catalog_page"] == 12000
    assert sf10["reason"] == 45

    sf100 = _sizes(tool, 100)
    assert sf100["store_sales"] == 287997024
    assert sf100["store_returns"] == 28795080
    assert sf100["catalog_sales"] == 143997065
    assert sf100["catalog_returns"] == 14404374
    assert sf100["web_sales"] == 72001237
    assert sf100["web_returns"] == 7197670
    assert sf100["inventory"] == 399330000
    assert sf100["item"] == 204000
    assert sf100["customer"] == 2000000
    assert sf100["customer_address"] == 1000000
    assert sf100["store"] == 402
    assert sf100["warehouse"] == 15
    # web_site is non-monotonic in the spec table: 42 at SF10, 24 at
    # SF100 — the canary that the model is table-driven, not a curve
    assert sf100["web_site"] == 24
    assert sf100["web_page"] == 2040
    assert sf100["promotion"] == 1000
    assert sf100["call_center"] == 30
    assert sf100["catalog_page"] == 20400
    assert sf100["reason"] == 55

    # fixed-size tables at every SF
    for z in (sf1, sf10, sf100):
        assert z["customer_demographics"] == 1920800
        assert z["date_dim"] == 73049
        assert z["time_dim"] == 86400
        assert z["household_demographics"] == 7200
        assert z["income_band"] == 20
        assert z["ship_mode"] == 20


def test_sub_sf1_scaling_keeps_proportions(tool):
    """Below SF1 (test datasets) facts shrink linearly and dims keep a
    damped fraction — generation at SF0.02 must stay tiny."""
    z = _sizes(tool, 0.02)
    assert z["store_sales"] == round(2880404 * 0.02)
    assert z["customer_demographics"] == 1920800  # fixed regardless
    assert 1 <= z["store"] <= 12
    assert z["item"] < 18000
