"""Targeted tests for the round-3 engine machinery: the
searchsorted-free join probe kernels (LUT + combined-sort paths),
segmented-compilation cache lifecycle (eviction -> rediscovery,
preloaded-record drift -> self-heal), lazy-view composition through
join chains, and the replay guard on recorded size plans.

These paths were previously covered only incidentally by the corpus
differential suite (VERDICT r3 weak #5).
"""

import os
import subprocess
import warnings

import jax.numpy as jnp
import numpy as np
import pytest

from ndstpu.engine import jaxexec
from ndstpu.engine.session import Session
from ndstpu.io import loader


@pytest.fixture(scope="module")
def warehouse(tmp_path_factory):
    data = tmp_path_factory.mktemp("raw3")
    wh = tmp_path_factory.mktemp("wh3")
    env = dict(os.environ, PYTHONPATH=os.getcwd())
    subprocess.run(["python", "-m", "ndstpu.datagen.driver", "local",
                    "0.002", "2", str(data)], check=True, env=env)
    subprocess.run(["python", "-m", "ndstpu.io.transcode",
                    "--input_prefix", str(data), "--output_prefix",
                    str(wh), "--report_file", str(wh / "load.txt")],
                   check=True, env=env, stdout=subprocess.DEVNULL)
    return wh


@pytest.fixture(scope="module")
def catalog(warehouse):
    return loader.load_catalog(str(warehouse))


@pytest.fixture()
def exe(catalog):
    return jaxexec.JaxExecutor(catalog)


# ---------------------------------------------------------------------------
# _probe_counts edge cases (both the LUT and the combined-sort paths)
# ---------------------------------------------------------------------------


def _check_probe(exe, pkey, bkey, bound, lut: bool):
    """Validate (lo, counts, order) against a brute-force reference:
    order[lo[i] .. lo[i]+counts[i]-1] must be exactly the build rows
    whose key equals probe key i (for valid keys)."""
    exe.join_lut_cap = (1 << 25) if lut else 0
    pk = jnp.asarray(np.asarray(pkey, np.int64))
    bk = jnp.asarray(np.asarray(bkey, np.int64))
    lo, counts, order = exe._probe_counts(pk, bk, bound)
    lo, counts, order = (np.asarray(lo), np.asarray(counts),
                         np.asarray(order))
    bkey = np.asarray(bkey)
    for i, k in enumerate(np.asarray(pkey)):
        want = sorted(np.nonzero(bkey == k)[0]) if k >= 0 else []
        got = sorted(order[lo[i]:lo[i] + counts[i]]) if counts[i] else []
        assert counts[i] == len(want), \
            f"probe {i} (key {k}): count {counts[i]} != {len(want)}"
        assert got == want, f"probe {i} (key {k}): rows {got} != {want}"


@pytest.mark.parametrize("lut", [True, False], ids=["lut", "sort"])
def test_probe_counts_basic(exe, lut):
    _check_probe(exe, [0, 1, 2, 5, 3], [1, 1, 3, 0, 2, 2, 2], 6, lut)


@pytest.mark.parametrize("lut", [True, False], ids=["lut", "sort"])
def test_probe_counts_all_dead_build(exe, lut):
    # every build row is a sentinel: no probe may match
    _check_probe(exe, [0, 1, 2], [-1, -1, -1, -1], 3, lut)


@pytest.mark.parametrize("lut", [True, False], ids=["lut", "sort"])
def test_probe_counts_bound_one(exe, lut):
    # single-slot key domain: all valid rows collide on key 0
    _check_probe(exe, [0, 0, -1], [0, -1, 0, 0], 1, lut)


@pytest.mark.parametrize("lut", [True, False], ids=["lut", "sort"])
def test_probe_counts_negative_sentinels(exe, lut):
    # negative keys on both sides: dead probes match nothing, dead
    # builds occupy order slots but never join
    _check_probe(exe, [-1, 2, -5, 0], [2, -3, 0, 2, -1, 0], 3, lut)


@pytest.mark.parametrize("lut", [True, False], ids=["lut", "sort"])
def test_probe_counts_empty_probe_matches(exe, lut):
    # probe keys entirely absent from the build side
    _check_probe(exe, [7, 8, 9], [0, 1, 2, 3], 10, lut)


def test_probe_counts_lut_sort_agree(exe):
    """The LUT and combined-sort paths must produce identical results
    at the boundary domain."""
    rng = np.random.default_rng(7)
    bkey = rng.integers(-2, 50, size=200)
    pkey = rng.integers(-2, 50, size=300)
    for lut in (True, False):
        _check_probe(exe, pkey, bkey, 50, lut)


# ---------------------------------------------------------------------------
# segmented-compilation cache lifecycle
# ---------------------------------------------------------------------------

_SEG_SQL = ("select i_category, count(*) as n, sum(ss_net_paid) as s, "
            "avg(ss_quantity) as q from store_sales "
            "join item on ss_item_sk = i_item_sk "
            "join date_dim on ss_sold_date_sk = d_date_sk "
            "where d_year >= 1998 group by i_category "
            "order by i_category")


def _fresh_tpu_session(catalog):
    return Session(catalog, backend="tpu")


def test_segment_eviction_rediscovers(catalog):
    """Evicting a shared segment must trigger rediscovery (with a
    warning), not a KeyError or a wrong result."""
    sess = _fresh_tpu_session(catalog)
    want = sess.sql(_SEG_SQL).to_rows()
    exe = sess._jax_executor()
    cp = sess.compiled_plan(_SEG_SQL)
    assert cp is not None
    if not cp.seg_fps:
        pytest.skip("plan too small to segment at this SF")
    evicted = cp.seg_fps[0]
    exe._seg_compiled.pop(evicted)
    disc = exe.n_discoveries
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        got = sess.sql(_SEG_SQL).to_rows()
    assert got == want
    assert exe.n_discoveries > disc, "eviction did not rediscover"
    assert any("rediscover" in str(w.message) for w in caught)


def test_preloaded_record_drift_self_heals(catalog, tmp_path):
    """A preloaded size-plan record whose recorded capacities no longer
    fit the data must fail its replay guard and self-heal by
    rediscovery, producing the correct result."""
    s1 = _fresh_tpu_session(catalog)
    want = s1.sql(_SEG_SQL).to_rows()
    path = str(tmp_path / "plans.pkl")
    assert s1.save_compiled(path) >= 1
    s2 = _fresh_tpu_session(catalog)
    assert s2.preload_compiled(path) >= 1
    exe2 = s2._jax_executor()
    # compiled_plan probes the canonical (fingerprint) key first, the
    # normalized-text key as fallback — same lookup _execute performs
    cp = s2.compiled_plan(_SEG_SQL)
    assert cp is not None and cp.preloaded
    # simulate drift: shrink every recorded capacity so the size-class
    # guards cannot hold at execution time
    cp.record = [(tag, (max(1, v // 16) if tag == "cap"
                        and isinstance(v, int) else v))
                 for tag, v in cp.record]
    got = s2.sql(_SEG_SQL).to_rows()
    assert got == want
    assert exe2.n_discoveries > 0, "drifted record did not self-heal"


def test_eager_demotion_warns(catalog, monkeypatch):
    """A query demoted to eager execution after repeated replay
    failures must surface a warning (the task-failure listener
    analog), not just print."""
    sess = _fresh_tpu_session(catalog)
    sql = "select count(*) as n from store_sales where ss_quantity > 3"
    want = sess.sql(sql).to_rows()
    cp = sess.compiled_plan(sql)
    assert cp is not None and cp.compilable

    import jax as _jax

    def boom(*a, **k):
        raise _jax.errors.JaxRuntimeError("injected compile failure")

    exe = sess._jax_executor()
    cp.fn_validated = False
    monkeypatch.setattr(exe, "_replay_query", boom)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        got = sess.sql(sql).to_rows()
    assert got == want
    assert not cp.compilable, "double failure did not demote"
    assert any("demoted to eager" in str(w.message) for w in caught)


# ---------------------------------------------------------------------------
# lazy-view composition through join chains
# ---------------------------------------------------------------------------


def test_lazy_views_multi_join_chain(catalog):
    """Columns gathered through inner->left join chains compose lazy
    views; results must match the numpy interpreter exactly (NULL
    pattern included)."""
    sql = ("select i_item_id, d_year, sr_return_quantity, ss_quantity "
           "from store_sales "
           "join item on ss_item_sk = i_item_sk "
           "join date_dim on ss_sold_date_sk = d_date_sk "
           "left join store_returns on ss_ticket_number = sr_ticket_number "
           "and ss_item_sk = sr_item_sk "
           "where d_moy = 12 "
           "order by i_item_id, d_year, ss_quantity, sr_return_quantity "
           "limit 500")
    cpu = Session(catalog, backend="cpu").sql(sql).to_rows()
    tpu = _fresh_tpu_session(catalog).sql(sql).to_rows()
    assert cpu == tpu


def test_select_cols_validity_base_mismatch_no_collapse():
    """_select_cols must NOT collapse to one lazy view when the two
    columns share a data buffer but carry different validity (the
    cast-with-extra-invalid shape) — collapsing would resurrect rows
    picked from side b with side a's validity."""
    data = jnp.arange(6, dtype=jnp.int32)
    va = jnp.asarray([True] * 6)
    vb = jnp.asarray([True, False, True, False, True, False])
    from ndstpu.schema import INT32
    a = jaxexec.DCol(data, va, INT32)
    b = jaxexec.DCol(data, vb, INT32)   # same buffer, stricter validity
    idx = jnp.asarray([0, 1, 2, 3, 4, 5], jnp.int32)
    pick_a = jnp.asarray([True, False, True, False, True, False])
    out = jaxexec._select_cols({"x": a}, {"x": b}, idx, idx, pick_a)
    got_valid = np.asarray(out["x"].valid)
    want_valid = np.where(np.asarray(pick_a), np.asarray(va),
                          np.asarray(vb))
    assert (got_valid == want_valid).all()


def test_stream_and_direct_sql_share_compiled_plan(catalog):
    """The SAME query must hit one compiled record whether it arrives
    as direct template text or as stream-file text carrying the
    `-- start/end` markers and trailing semicolon (the power CLI
    previously missed every persisted record and silently re-ran
    eager discovery per query)."""
    sess = _fresh_tpu_session(catalog)
    direct = ("select count(*) as n from store_sales "
              "where ss_quantity between 1 and 20")
    streamed = ("-- start query 1 in stream 0 using template queryX.tpl\n"
                + direct +
                "\n;\n-- end query 1 in stream 0 using template queryX.tpl\n")
    want = sess.sql(direct).to_rows()
    exe = sess._jax_executor()
    disc = exe.n_discoveries
    got = sess.sql(streamed).to_rows()
    assert got == want
    assert exe.n_discoveries == disc, \
        "stream-marker text missed the compiled-plan cache"
    assert sess.compiled_plan(direct) is sess.compiled_plan(streamed)


def test_stale_out_meta_self_heals(catalog, tmp_path):
    """An engine typing change can retype an output column without
    changing the plan tree, leaving a preloaded record's out_meta
    stale; assembling under the stale meta silently corrupted values
    (r04: scaled decimal data written as x100 floats).  The replay
    trace must detect the ctype drift and rediscover."""
    from ndstpu.schema import FLOAT64
    s1 = _fresh_tpu_session(catalog)
    sql = ("select i_category, sum(ss_net_paid) as s from store_sales "
           "join item on ss_item_sk = i_item_sk group by i_category "
           "order by i_category")
    want = s1.sql(sql).to_rows()
    path = str(tmp_path / "plans.pkl")
    assert s1.save_compiled(path) >= 1
    s2 = _fresh_tpu_session(catalog)
    assert s2.preload_compiled(path) >= 1
    exe2 = s2._jax_executor()
    cp = s2.compiled_plan(sql)
    assert cp is not None and cp.preloaded
    # simulate a typing change since the record was saved: claim the
    # decimal sum column was float64
    cp.out_meta = [(n, (FLOAT64 if n == "s" else ct), d, b)
                   for n, ct, d, b in cp.out_meta]
    for fp in (cp.seg_fps or ()):
        scp = exe2._seg_compiled[fp]
        scp.out_meta = [(n, (FLOAT64 if n == "s" else ct), d, b)
                        for n, ct, d, b in scp.out_meta]
    got = s2.sql(sql).to_rows()
    assert got == want, "stale out_meta produced corrupted values"
    assert exe2.n_discoveries > 0, "drifted meta did not self-heal"
