"""Test configuration: run JAX on a virtual 8-device CPU mesh.

jax may already be imported by the interpreter's sitecustomize (axon
PJRT), so env vars alone are too late — set XLA_FLAGS for the host
platform and switch the platform via jax.config before any backend is
initialized (pytest loads conftest before test modules).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# replay warm-up (compile at discovery) would add minutes of XLA:CPU
# compiles across the suite; tests that exercise it opt in explicitly
os.environ.setdefault("NDSTPU_WARM_REPLAY", "0")
# keep test power runs (and their subprocesses, which inherit env) out
# of the developer's real .bench_cache/ledger.jsonl — tests that need a
# ledger pass --ledger explicitly, which wins over this default
os.environ.setdefault("NDSTPU_LEDGER", "none")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pathlib
import sys

import pytest

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))


@pytest.fixture(scope="session")
def tiny_sf():
    """Scale factor used for in-process fixture datasets."""
    return 0.01
