"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Must set env vars before jax is imported anywhere, so this executes at
conftest import time (pytest loads conftest before test modules).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pathlib
import sys

import pytest

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))


@pytest.fixture(scope="session")
def tiny_sf():
    """Scale factor used for in-process fixture datasets."""
    return 0.01
