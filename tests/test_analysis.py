"""Static plan analyzer tests (ndstpu/analysis/): per-operator schema
inference, diagnostic emission (NDS1xx/2xx/3xx), golden diagnostics for
corpus queries, baseline gating, the plan_lint CLI, and the power-run
--static_check gate.  Everything here runs on a ZERO-ROW schema catalog
— no warehouse, no data execution."""

import json
import os
import re
import subprocess
import sys

import pytest

from ndstpu import analysis, obs
from ndstpu.analysis import diagnostics as diag_mod
from ndstpu.analysis.diagnostics import Diagnostic
from ndstpu.engine import plan as lp
from ndstpu.engine.columnar import FLOAT64, INT64
from ndstpu.engine.planner import PlanError
from ndstpu.engine.session import Session
from ndstpu.queries import streamgen

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def sess():
    return Session(analysis.schema_catalog())


@pytest.fixture(scope="module")
def tables():
    return analysis.schema_tables()


def analyze(sess, tables, sql, **kw):
    return analysis.analyze_sql(sess, "q", sql, tables=tables, **kw)


def codes(res):
    return [d.code for d in res.diagnostics]


# -- schema inference ------------------------------------------------------

def test_project_expression_types(sess, tables):
    res = analyze(sess, tables,
                  "select ss_item_sk, ss_quantity / 2 as r, "
                  "ss_ext_sales_price * ss_ext_sales_price as m, "
                  "ss_item_sk is null as b from store_sales")
    cols = dict(res.schema.cols)
    assert cols["ss_item_sk"].kind == "int32"
    # SQL division is float64 regardless of operand types
    assert cols["r"].ctype == FLOAT64
    # decimal * decimal widens to precision 38, scale ls+rs
    m = cols["m"].ctype
    assert (m.kind, m.precision, m.scale) == ("decimal", 38, 4)
    assert cols["b"].kind == "bool" and not cols["b"].nullable


def test_aggregate_result_types(sess, tables):
    res = analyze(sess, tables,
                  "select count(*) as c, sum(ss_quantity) as s, "
                  "avg(ss_ext_sales_price) as a, min(i_item_id) as m "
                  "from store_sales join item on ss_item_sk = i_item_sk "
                  "group by i_category")
    cols = dict(res.schema.cols)
    assert cols["c"].ctype == INT64 and not cols["c"].nullable
    assert cols["s"].ctype == INT64 and cols["s"].nullable
    assert cols["a"].ctype == FLOAT64
    assert cols["m"].kind == "string"   # min keeps char(16), not bare STRING
    assert res.verdict == "device"


def test_outer_join_nullability(sess, tables):
    res = analyze(sess, tables,
                  "select ss_item_sk, sr_return_quantity from store_sales "
                  "left join store_returns on ss_ticket_number = "
                  "sr_ticket_number and ss_item_sk = sr_item_sk")
    cols = dict(res.schema.cols)
    # the preserved side keeps its nullability; the other side becomes
    # nullable through the outer join
    assert cols["sr_return_quantity"].nullable


# -- NDS1xx typing diagnostics ---------------------------------------------

def test_lossy_cast_flagged(sess, tables):
    res = analyze(sess, tables,
                  "select cast(ss_ext_sales_price as int) as v "
                  "from store_sales")
    assert "NDS102" in codes(res)
    d = next(d for d in res.diagnostics if d.code == "NDS102")
    assert d.severity == "warning" and d.path  # anchored to a plan node
    assert res.verdict == "device"             # warnings never gate


def test_join_key_type_mismatch_flagged(sess, tables):
    res = analyze(sess, tables,
                  "select ss_item_sk from store_sales "
                  "join item on ss_item_sk = i_item_id")
    assert "NDS101" in codes(res)
    d = next(d for d in res.diagnostics if d.code == "NDS101")
    assert "/keys[" in d.path


def test_setop_mismatch_flagged(sess, tables):
    res = analyze(sess, tables,
                  "select ss_item_sk from store_sales "
                  "union all select i_item_id from item")
    assert "NDS104" in codes(res)


def test_underspecified_sort_flagged(sess, tables):
    res = analyze(sess, tables,
                  "select ss_item_sk, ss_quantity from store_sales "
                  "order by ss_item_sk limit 5")
    assert "NDS105" in codes(res)
    # a fully keyed sort is quiet
    res2 = analyze(sess, tables,
                   "select ss_item_sk, ss_quantity from store_sales "
                   "order by ss_item_sk, ss_quantity limit 5")
    assert "NDS105" not in codes(res2)


def test_int32_overflow_scales_with_sf(sess, tables):
    sql = "select sum(ss_item_sk) as s from store_sales"
    assert "NDS103" not in codes(analyze(sess, tables, sql,
                                         scale_factor=1.0))
    res = analyze(sess, tables, sql, scale_factor=2000.0)
    assert "NDS103" in codes(res)


# -- NDS2xx lowering audit -------------------------------------------------

def test_unsupported_function_gates_verdict(sess, tables):
    res = analyze(sess, tables,
                  "select upper(ss_item_sk) as u from store_sales")
    assert res.verdict == "fallback"
    assert "NDS206" in res.fallback_codes


def test_keyless_outer_join_gates_verdict(tables):
    plan = lp.Join(lp.Scan("store_sales", "store_sales"),
                   lp.Scan("store_returns", "store_returns"),
                   "full", [])
    res = analysis.analyze_plan(plan, tables=tables, query="q")
    assert res.verdict == "fallback"
    assert "NDS210" in res.fallback_codes


def test_subquery_fallback_does_not_gate(sess, tables):
    # jaxexec isolates _used_fallback across subquery resolution, so an
    # unsupported expression INSIDE a subquery must not flip the main
    # plan's verdict
    res = analyze(sess, tables,
                  "select ss_item_sk from store_sales where ss_quantity "
                  "> (select max(sr_return_quantity) from store_returns "
                  "   where upper(sr_item_sk) = 'X')")
    assert any(d.code == "NDS206" and "/subquery[" in d.path
               for d in res.diagnostics)
    assert res.verdict == "device"


# -- golden corpus diagnostics ---------------------------------------------

def corpus_part(name):
    tpl = name.split("_part")[0] + ".tpl"
    for n, sql in streamgen.render_template_parts(
            str(streamgen.TEMPLATE_DIR / tpl), "07291122510", 0):
        if n == name:
            return sql
    raise AssertionError(f"no corpus part {name}")


def test_golden_query41_no_fact_scan(sess, tables):
    res = analyze(sess, tables, corpus_part("query41"))
    # NDS401: the LIMIT count is a shape-affecting canon slot
    assert codes(res) == ["NDS301", "NDS401"]
    assert res.verdict == "device"   # NDS3xx/4xx are advisory only


def test_golden_query61_diagnostics(sess, tables):
    res = analyze(sess, tables, corpus_part("query61"))
    assert sorted(codes(res)) == \
        ["NDS102", "NDS102", "NDS105", "NDS305", "NDS401"]


# -- NDS305 cost-model placement -------------------------------------------

_NDS305_RE = re.compile(
    r"predicted exchange placement over (\w+): (\d+) broadcast "
    r"join\(s\) \(~(\d+) est build B\), (\d+) shuffle \(all_to_all\) "
    r"join\(s\), (\d+) build-reduce join\(s\)")


def test_nds305_reports_placement_and_bytes(sess, tables):
    sql = ("select d_year, count(*) as n from store_sales, date_dim "
           "where ss_sold_date_sk = d_date_sk group by d_year")
    res = analyze(sess, tables, sql)
    msgs = [d.message for d in res.diagnostics if d.code == "NDS305"]
    assert msgs, "spine query must carry the placement prediction"
    m = _NDS305_RE.fullmatch(msgs[0])
    assert m and m.group(1) == "store_sales"
    assert int(m.group(2)) == 1          # date_dim build broadcasts
    assert int(m.group(3)) > 0           # with a real byte estimate


def test_nds305_agrees_with_cost_audit_on_corpus(sess, tables):
    """Corpus agreement: the NDS305 placement mix (lowering's static
    audit) must match the cost audit's per-join placements — both go
    through the same choose_strategy the runtime dplan advisor uses,
    so a divergence here means the static prediction and the runtime
    decision rule have drifted apart."""
    from ndstpu.analysis import cost

    for part in ("query3", "query7", "query25", "query52", "query96"):
        sql = corpus_part(part)
        res = analyze(sess, tables, sql)
        msgs = [d.message for d in res.diagnostics
                if d.code == "NDS305"]
        assert len(msgs) == 1, part
        m = _NDS305_RE.fullmatch(msgs[0])
        assert m, msgs[0]
        plan, _cols = sess.plan(sql)
        rep = cost.audit_cost(plan, tables, query=part,
                              scale_factor=1.0, n_dev=8)
        counts = rep.placement_counts()
        assert (int(m.group(2)), int(m.group(4)), int(m.group(5))) == \
            (counts["broadcast"], counts["shuffle"],
             counts["build-reduce"]), part


# -- diagnostics plumbing --------------------------------------------------

def test_baseline_roundtrip():
    diags = [Diagnostic("NDS102", "m1", "Project", query="qa"),
             Diagnostic("NDS210", "m2", "Join", query="qb")]
    accepted = diag_mod.baseline_load(diag_mod.baseline_dump(diags))
    assert diag_mod.new_against_baseline(diags, accepted) == []
    extra = Diagnostic("NDS205", "m3", "Project", query="qa")
    new = diag_mod.new_against_baseline(diags + [extra], accepted)
    assert [d.code for d in new] == ["NDS205"]


def test_json_and_markdown_emitters():
    diags = [Diagnostic("NDS102", "lossy", "Project", query="qa")]
    obj = json.loads(diag_mod.to_json(diags, {"parts": 1}))
    assert obj["summary"]["by_code"] == {"NDS102": 1}
    md = diag_mod.to_markdown(diags, {"parts": 1})
    assert "NDS102" in md and "| qa |" in md


def test_unknown_code_rejected():
    with pytest.raises(ValueError):
        Diagnostic("NDS999", "nope", "Project")


# -- plan_lint CLI ---------------------------------------------------------

def run_plan_lint(tmp_path, *extra):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "plan_lint.py"),
         "--sub_queries", "query41,query61",
         "--json", str(tmp_path / "PL.json"),
         "--md", str(tmp_path / "PL.md"), *extra],
        capture_output=True, text=True, env=env)


def test_plan_lint_clean_against_committed_baseline(tmp_path):
    r = run_plan_lint(tmp_path, "--baseline")
    assert r.returncode == 0, r.stdout + r.stderr
    obj = json.loads((tmp_path / "PL.json").read_text())
    assert obj["meta"]["parts"] == 2
    assert (tmp_path / "PL.md").exists()


def test_plan_lint_missing_baseline_exits_2(tmp_path):
    r = run_plan_lint(tmp_path, "--baseline", str(tmp_path / "nope.json"))
    assert r.returncode == 2


def test_plan_lint_new_diagnostic_exits_1(tmp_path):
    empty = tmp_path / "empty.json"
    empty.write_text(diag_mod.baseline_dump([]))
    r = run_plan_lint(tmp_path, "--baseline", str(empty))
    assert r.returncode == 1
    assert "NDS" in r.stderr


def test_committed_artifacts_current(sess, tables):
    """The committed PLAN_LINT.json must match what the analyzer says
    today for the queries it covers (spot-checked, not a full sweep —
    CI's plan-lint step does the full gate)."""
    obj = json.loads(open(os.path.join(REPO, "PLAN_LINT.json")).read())
    # NDS5xx spine diagnostics are corpus-level (emitted by the
    # cross-query index over the whole sweep, analysis/spines.py) — a
    # single-query analysis cannot reproduce them, so scope the spot
    # check to the per-query families
    want = sorted(d["code"] for d in obj["diagnostics"]
                  if d["query"] == "query61"
                  and not d["code"].startswith("NDS5"))
    res = analyze(sess, tables, corpus_part("query61"))
    assert sorted(codes(res)) == want


# -- power.py --static_check gate ------------------------------------------

def test_static_check_gate(sess):
    from ndstpu.harness import power
    qd = {
        "q_good": "select ss_item_sk, count(*) from store_sales "
                  "group by ss_item_sk",
        "q_planfail": "select ss_item_sk from store_sales full join "
                      "store_returns on ss_ticket_number <> "
                      "sr_ticket_number",
        "q_lowerfail": "select upper(ss_item_sk) as u from store_sales",
    }
    off = power.static_check(sess, qd, "tpu")
    assert off == ["q_planfail", "q_lowerfail"]
    # the cpu interpreter executes everything: nothing gates
    assert power.static_check(sess, qd, "cpu") == []


# -- planner near-miss suggestions -----------------------------------------

def test_unresolved_column_suggests_near_misses(sess):
    with pytest.raises(PlanError, match="ss_item_sk"):
        sess.plan("select ss_itm_sk from store_sales")
    with pytest.raises(PlanError, match="ss_quantity"):
        sess.plan("select s.ss_quantty from store_sales s")
    # suggestions see the whole scope chain, including outer scopes
    with pytest.raises(PlanError, match="did you mean"):
        sess.plan("select ss_item_sk from store_sales where exists "
                  "(select 1 from store_returns "
                  " where sr_item_sk = ss_item_skk)")


# -- obs annotation --------------------------------------------------------

def test_annotate_reaches_query_summary():
    tr = obs.tracer()
    with tr.span("q_ann", cat="query", collect=True):
        tr.annotate(fallback_codes="NDS206:Project")
    qs = [q for q in tr.query_summaries() if q["query"] == "q_ann"]
    assert qs and qs[-1]["attrs"]["fallback_codes"] == "NDS206:Project"
