"""Static cost-model tests (ndstpu/analysis/cost.py): SF-scaled base
cardinalities, selectivity heuristics, NDS601 budget demotion,
ledger calibration, and the runtime differential — the dplan cost
advisor must pick only among semantically equivalent strategies, so
results are bit-identical (rows AND row order) with NDSTPU_COST=0."""

import json

import numpy as np
import pytest

from ndstpu import analysis, obs
from ndstpu.analysis import cost
from ndstpu.analysis.spines import SF1_ROWS
from ndstpu.engine import memplan, plan as lp
from ndstpu.engine import expr as ex
from ndstpu.engine.columnar import INT64, Column, Table
from ndstpu.engine.session import Session
from ndstpu.io.loader import Catalog


@pytest.fixture(scope="module")
def tables():
    return analysis.schema_tables()


@pytest.fixture(scope="module")
def sess():
    return Session(analysis.schema_catalog())


# -- base cardinalities -----------------------------------------------------


def test_base_rows_scales_facts_not_dims(tables):
    m1 = cost.CostModel(tables, scale_factor=1.0)
    m10 = cost.CostModel(tables, scale_factor=10.0)
    # facts (and the customer cluster) scale linearly with SF
    assert m1.base_rows("store_sales") == SF1_ROWS["store_sales"]
    assert m10.base_rows("store_sales") == \
        pytest.approx(10 * SF1_ROWS["store_sales"])
    # dimensions stay constant
    assert m10.base_rows("date_dim") == SF1_ROWS["date_dim"]
    assert m10.base_rows("not_a_table") is None


def test_base_rows_row_counts_override(tables):
    m = cost.CostModel(tables, scale_factor=100.0,
                       row_counts={"store_sales": 4096})
    assert m.base_rows("store_sales") == 4096.0     # override wins over SF
    assert m.base_rows("item") == SF1_ROWS["item"]  # others unaffected


# -- selectivity ------------------------------------------------------------


def _scan(table):
    return lp.Scan(table, table)


def test_selectivity_and_is_monotone(tables):
    m = cost.CostModel(tables)
    scans = [_scan("store_sales"), _scan("date_dim")]
    p1 = ex.BinOp("=", ex.ColumnRef("d_year"), ex.Literal(2000))
    p2 = ex.BinOp(">", ex.ColumnRef("ss_quantity"), ex.Literal(50))
    s1 = m.selectivity(p1, scans)
    s2 = m.selectivity(p2, scans)
    both = m.selectivity(ex.BinOp("and", p1, p2), scans)
    assert 0.0 < both <= min(s1, s2)            # AND never keeps more
    either = m.selectivity(ex.BinOp("or", p1, p2), scans)
    assert max(s1, s2) <= either <= min(s1 + s2, 1.0)
    # complement
    sn = m.selectivity(ex.UnaryOp("not", p1), scans)
    assert sn == pytest.approx(1.0 - s1)


def test_selectivity_inlist_grows_with_values(tables):
    m = cost.CostModel(tables)
    scans = [_scan("date_dim")]
    few = ex.InList(ex.ColumnRef("d_year"), (1999, 2000))
    many = ex.InList(ex.ColumnRef("d_year"), tuple(range(1990, 2000)))
    assert m.selectivity(few, scans) < m.selectivity(many, scans)
    neg = ex.InList(ex.ColumnRef("d_year"), (1999, 2000), negated=True)
    assert m.selectivity(neg, scans) == \
        pytest.approx(1.0 - m.selectivity(few, scans))


def test_filter_estimate_shrinks(sess, tables):
    plan, _ = sess.plan(
        "select ss_item_sk from store_sales where ss_quantity > 50")
    m = cost.CostModel(tables, scale_factor=1.0)
    est = m.estimate(plan)
    assert 0 < est.rows < SF1_ROWS["store_sales"]


def test_band_widens_with_depth_and_caps(sess, tables):
    shallow, _ = sess.plan("select ss_item_sk from store_sales")
    deep, _ = sess.plan(
        "select d_year, count(*) as n from store_sales, date_dim, item "
        "where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk "
        "and ss_quantity > 50 and d_year = 2000 group by d_year")
    m = cost.CostModel(tables, scale_factor=1.0)
    e_shallow = m.estimate_query(shallow)
    e_deep = m.estimate_query(deep)
    assert e_shallow.hi < e_deep.hi
    assert e_deep.hi <= 2.0 ** cost.MAX_BAND_STEPS
    assert e_deep.lo == pytest.approx(1.0 / e_deep.hi)


# -- NDS601: broadcast build over the replication budget --------------------


def test_nds601_wide_build_demoted(sess, tables):
    sql = ("select d_year, count(*) as n from store_sales, date_dim "
           "where ss_sold_date_sk = d_date_sk group by d_year")
    plan, _ = sess.plan(sql)
    # generous budget: dimension build broadcasts, no diagnostics
    r_ok = cost.audit_cost(plan, tables, query="q", scale_factor=1.0,
                           budget_bytes=1 << 30, n_dev=8)
    assert r_ok.placement_counts()["broadcast"] >= 1
    assert not [d for d in r_ok.diagnostics if d.code == "NDS601"]
    # starved budget: same build is over the replication fraction ->
    # NDS601 + demotion to the shuffle path
    r_tight = cost.audit_cost(plan, tables, query="q", scale_factor=1.0,
                              budget_bytes=100_000, n_dev=8)
    assert r_tight.placement_counts()["shuffle"] >= 1
    d601 = [d for d in r_tight.diagnostics if d.code == "NDS601"]
    assert d601 and "replication budget" in d601[0].message
    demoted = [p for p in r_tight.placements
               if p.decision.strategy == "shuffle"
               and p.decision.structural == "broadcast"]
    assert demoted and demoted[0].decision.overrode


def test_nds602_spill_risk_on_starved_budget(sess, tables):
    plan, _ = sess.plan(
        "select ss_item_sk, ss_quantity from store_sales")
    r = cost.audit_cost(plan, tables, query="q", scale_factor=1.0,
                        budget_bytes=50_000, n_dev=2)
    assert any(d.code == "NDS602" for d in r.diagnostics)
    assert r.working_set_bytes is not None
    assert r.working_set_bytes > 50_000


def test_nds6xx_registered():
    from ndstpu.analysis import diagnostics
    assert diagnostics.CODES["NDS601"][0] == "warning"
    assert diagnostics.CODES["NDS602"][0] == "warning"
    assert diagnostics.CODES["NDS603"][0] == "info"
    assert diagnostics.CODES["NDS604"][0] == "info"


# -- calibration ------------------------------------------------------------


def _fake_ledger(path, rows_by_query):
    with open(path, "w") as f:
        for q, n in rows_by_query.items():
            f.write(json.dumps({
                "query": q, "stream": 0, "status": "ok",
                "extra": {"result_rows": n}}) + "\n")
    return str(path)


def test_calibration_recenters_estimate(sess, tables, tmp_path):
    sql = "select d_year, count(*) as n from date_dim group by d_year"
    plan, _ = sess.plan(sql)
    raw = cost.CostModel(tables, query="qx").estimate_query(plan)
    ledger = _fake_ledger(tmp_path / "ledger.jsonl",
                          {"qx": raw.rows * 3.0, "qy": 10})
    observed = cost.observed_rows_from_ledger(ledger)
    assert observed["qx"] == pytest.approx(raw.rows * 3.0)
    calib = cost.Calibration.from_ledger(ledger, {"qx": raw.rows})
    assert calib.ratios["qx"] == pytest.approx(3.0)
    m = cost.CostModel(tables, query="qx", calibration=calib)
    est = m.estimate_query(plan)
    # recentered on the observed ratio, band from the calibration
    # dispersion (replaces the per-step doubling band)
    assert est.rows == pytest.approx(raw.rows * 3.0)
    assert est.hi == pytest.approx(calib.dispersion)
    assert est.lo == pytest.approx(1.0 / calib.dispersion)
    # uncalibrated query keeps the heuristic band
    other = cost.CostModel(tables, query="unseen",
                           calibration=calib).estimate_query(plan)
    assert other.rows == pytest.approx(raw.rows)


def test_misestimate_nds604(tmp_path):
    estimated = {"qa": cost.CostEstimate(rows=100.0),
                 "qb": cost.CostEstimate(rows=100.0),
                 "qc": cost.CostEstimate(rows=100.0)}
    observed = {"qa": 100.0 * (cost.MISESTIMATE_RATIO + 1),  # over
                "qb": 100.0 / (cost.MISESTIMATE_RATIO + 1),  # under
                "qc": 120.0}                                 # in band
    diags = cost.misestimate_diags(estimated, observed)
    assert sorted(d.query for d in diags) == ["qa", "qb"]
    assert all(d.code == "NDS604" for d in diags)


def test_cost_budget_sources(monkeypatch):
    monkeypatch.setenv("NDSTPU_COST_BUDGET_BYTES", "777")
    assert cost.cost_budget_bytes() == (777, "env")
    monkeypatch.delenv("NDSTPU_COST_BUDGET_BYTES")
    monkeypatch.setenv("NDSTPU_HBM_BYTES", "100000")
    assert cost.cost_budget_bytes() == \
        (int(100000 * memplan.SAFETY), "hbm")
    monkeypatch.delenv("NDSTPU_HBM_BYTES")
    budget, src = cost.cost_budget_bytes()
    assert budget > 0 and src == "default"


def test_memplan_resident_carveout_shrinks_chunks():
    """Broadcast-build bytes predicted resident by the advisor come out
    of the streaming budget: same fact, smaller (or equal) chunks."""
    base = memplan.plan_stream(1_000_000, 100, 2, budget_bytes=8 << 20)
    carved = memplan.plan_stream(1_000_000, 100, 2, budget_bytes=8 << 20,
                                 resident_bytes=2 << 20)
    assert base.chunk_rows is not None and carved.chunk_rows is not None
    assert carved.chunk_rows < base.chunk_rows
    # a resident footprint never flips a resident-fit plan to chunked
    # unless it actually eats the headroom
    tiny = memplan.plan_stream(1000, 100, 2, budget_bytes=2 << 30,
                               resident_bytes=1 << 20)
    assert tiny.chunk_rows is None


# -- choose_strategy / advisor ----------------------------------------------


def test_choose_strategy_demote_only():
    kw = dict(broadcast_limit_rows=1000, budget_bytes=100_000)
    # small build under both limits: broadcast, no override
    d = cost.choose_strategy(10, 500, **kw)
    assert d.strategy == "broadcast" and not d.overrode
    # byte-heavy build under the row limit: demoted (the override)
    d = cost.choose_strategy(10, 90_000, **kw)
    assert (d.strategy, d.structural) == ("shuffle", "broadcast")
    assert d.overrode
    # over the row limit: structural shuffle either way — the model
    # never promotes shuffle -> broadcast (forced-shuffle tests keep
    # their meaning)
    d = cost.choose_strategy(5000, 500, **kw)
    assert (d.strategy, d.structural) == ("shuffle", "shuffle")
    # reducible existence build wins outright
    d = cost.choose_strategy(5000, 500, reducible=True, **kw)
    assert d.strategy == "build-reduce"


def test_advisor_suppresses_unsafe_overrides():
    adv = cost.CostAdvisor(broadcast_limit_rows=1000,
                           budget_bytes=100_000)
    base = dict(build_rows=10, build_bytes=90_000, kind="inner")
    # row-order-sensitive spine: the demotion is suppressed
    d = adv.decide_join(dup_max=0, order_safe=False, **base)
    assert d.strategy == "broadcast" and not d.overrode
    # expanding inner join (dup_max > 0 = non-unique build keys)
    # cannot take the shuffle path
    d = adv.decide_join(dup_max=3, order_safe=True, **base)
    assert d.strategy == "broadcast" and not d.overrode
    # aggregate spine + unique build keys: demotion goes through
    d = adv.decide_join(dup_max=0, order_safe=True, **base)
    assert (d.strategy, d.structural) == ("shuffle", "broadcast")


# -- runtime differential: cost-driven dplan vs NDSTPU_COST=0 ---------------

N_FACT = 4096
N_DIM = 512


def _wide_catalog():
    """fact (sharded) joining a byte-heavy dim: 512 rows x 10 int64
    cols ~ 41 KB build — under any row limit, over a starved byte
    budget's replication fraction."""
    rng = np.random.RandomState(7)
    fact = Table({
        "f_key": Column(rng.randint(0, N_DIM, N_FACT).astype(np.int64),
                        INT64),
        "f_qty": Column(rng.randint(0, 100, N_FACT).astype(np.int64),
                        INT64),
    })
    cols = {"d_key": Column(np.arange(N_DIM, dtype=np.int64), INT64),
            "d_grp": Column((np.arange(N_DIM, dtype=np.int64) % 16),
                            INT64)}
    for i in range(8):   # pad the build side wide
        cols[f"d_pad{i}"] = Column(
            rng.randint(0, 1000, N_DIM).astype(np.int64), INT64)
    dim = Table(cols)
    cat = Catalog()
    cat.register("fact", fact)
    cat.register("dim", dim)
    return cat

# every pad column is aggregated so the optimizer cannot prune the
# build side narrow — the runtime build really is ~41 KB; all-integer
# aggregates keep the differential exact (no float reassociation)
Q_DIFF = ("select d_grp, count(*) as n, sum(f_qty) as s, "
          "min(f_qty) as lo, max(f_qty) as hi, "
          + ", ".join(f"sum(d_pad{i}) as p{i}" for i in range(8))
          + " from fact, dim where f_key = d_key "
          "group by d_grp order by d_grp")


def _table_rows(t):
    return list(map(str, t.to_rows()))


def test_dplan_cost_demotion_recorded():
    """Direct executor: the starved advisor demotes the wide build to
    the shuffle path, records the decision, and still matches the
    oracle exactly."""
    from ndstpu.engine import physical
    from ndstpu.parallel import dplan, mesh as pmesh

    cat = _wide_catalog()
    plan, _ = Session(cat, backend="cpu").plan(Q_DIFF)
    oracle = _table_rows(physical.execute(plan, cat))

    adv = cost.CostAdvisor(broadcast_limit_rows=50_000,
                           budget_bytes=50_000)
    before = obs.counters_snapshot()
    exe = dplan.DistributedPlanExecutor(
        cat, pmesh.make_mesh(8), shard_threshold_rows=1000,
        broadcast_limit_rows=50_000, cost_advisor=adv)
    got = _table_rows(exe.execute_plan(plan))
    assert got == oracle
    assert any(d["overrode"] and d["strategy"] == "shuffle"
               for d in exe.cost_decisions)
    d = obs.counter_delta(before)
    assert d.get("engine.cost.decisions", 0) >= 1
    assert d.get("engine.cost.overrides", 0) >= 1

    # control: advisor off = structural rule = broadcast, same rows
    exe0 = dplan.DistributedPlanExecutor(
        cat, pmesh.make_mesh(8), shard_threshold_rows=1000,
        broadcast_limit_rows=50_000, cost_advisor=None)
    got0 = _table_rows(exe0.execute_plan(plan))
    assert got0 == oracle == got        # bit-identical, order included
    assert exe0.cost_decisions == []


@pytest.mark.parametrize("backend", ["tpu", "tpu-spmd"])
def test_session_cost_differential_bit_identical(backend, monkeypatch):
    """Session path on a starved device budget: NDSTPU_COST on vs off
    must be bit-identical — rows AND row order (the aggregate uses
    exact integer arithmetic, so any divergence is a placement bug,
    not float reassociation)."""
    monkeypatch.setenv("NDSTPU_HBM_BYTES", "100000")
    cat = _wide_catalog()

    monkeypatch.setenv("NDSTPU_COST", "0")
    assert not cost.enabled()
    off = Session(cat, backend=backend, spmd_threshold=1000).sql(Q_DIFF)

    monkeypatch.setenv("NDSTPU_COST", "1")
    assert cost.enabled()
    sess_on = Session(cat, backend=backend, spmd_threshold=1000)
    on = sess_on.sql(Q_DIFF)

    assert _table_rows(on) == _table_rows(off)
    if backend == "tpu-spmd":
        # the starved budget really did engage the advisor
        assert sess_on._cost_advisor() is not None
        assert sess_on._cost_advisor().budget_bytes == \
            int(100000 * memplan.SAFETY)


def test_session_cost_kill_switch_disables_advisor(monkeypatch):
    monkeypatch.setenv("NDSTPU_COST", "0")
    sess = Session(_wide_catalog(), backend="tpu-spmd")
    assert sess._cost_advisor() is None


# -- static vs runtime agreement --------------------------------------------


def test_static_placement_agrees_with_runtime():
    """The lint-side choose_strategy over estimated rows/bytes and the
    runtime advisor over actual rows/bytes agree on the synthetic
    catalog when the static model is handed the true row counts."""
    from ndstpu.parallel import dplan, mesh as pmesh

    from ndstpu import schema as nds_schema

    cat = _wide_catalog()
    # audit_cost wants TableSchemas; derive them from the live tables
    tables = {
        name: nds_schema.TableSchema(name, tuple(
            nds_schema.ColumnSpec(cn, t.column(cn).ctype)
            for cn in t.column_names))
        for name, t in cat.tables.items()}
    plan, _ = Session(cat, backend="cpu").plan(Q_DIFF)
    counts = {n: t.num_rows for n, t in cat.tables.items()}
    rep = cost.audit_cost(
        plan, tables, query="qdiff", budget_bytes=50_000,
        n_dev=8, broadcast_limit_rows=50_000,
        shard_threshold_rows=1000, row_counts=counts)
    static = [p.decision.strategy for p in rep.placements]

    adv = cost.CostAdvisor(broadcast_limit_rows=50_000,
                           budget_bytes=50_000)
    exe = dplan.DistributedPlanExecutor(
        cat, pmesh.make_mesh(8), shard_threshold_rows=1000,
        broadcast_limit_rows=50_000, cost_advisor=adv)
    exe.execute_plan(plan)
    runtime = [d["strategy"] for d in exe.cost_decisions]
    assert static == runtime == ["shuffle"]
