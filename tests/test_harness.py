"""Harness tests: power run, reports, validation, maintenance, throughput."""

import json
import os
import subprocess

import numpy as np
import pytest

from ndstpu.harness import bench as bench_mod
from ndstpu.harness.power import ensure_valid_column_names, gen_sql_from_stream


@pytest.fixture(scope="module")
def env():
    return dict(os.environ, PYTHONPATH=os.getcwd())


@pytest.fixture(scope="module")
def dataset(tmp_path_factory, env):
    root = tmp_path_factory.mktemp("nds")
    subprocess.run(["python", "-m", "ndstpu.datagen.driver", "local",
                    "0.002", "2", str(root / "raw")], check=True, env=env)
    subprocess.run(["python", "-m", "ndstpu.datagen.driver", "local",
                    "0.002", "2", str(root / "raw_1"), "--update", "1"],
                   check=True, env=env)
    subprocess.run(["python", "-m", "ndstpu.io.transcode",
                    "--input_prefix", str(root / "raw"),
                    "--output_prefix", str(root / "wh"),
                    "--report_file", str(root / "load.txt"),
                    "--output_format", "ndslake"],
                   check=True, env=env, stdout=subprocess.DEVNULL)
    subprocess.run(["python", "-m", "ndstpu.queries.streamgen",
                    "--output_dir", str(root / "streams"),
                    "--rngseed", "07291122510", "--streams", "3"],
                   check=True, env=env, stdout=subprocess.DEVNULL)
    return root


def test_power_run_single_query(dataset, env, tmp_path):
    time_log = tmp_path / "time.csv"
    jdir = tmp_path / "json"
    subprocess.run(
        ["python", "-m", "ndstpu.harness.power",
         str(dataset / "streams" / "query_0.sql"),
         str(dataset / "wh"), str(time_log),
         "--input_format", "ndslake",
         "--sub_queries", "query3,query42",
         "--json_summary_folder", str(jdir),
         "--output_prefix", str(tmp_path / "out")],
        check=True, env=env)
    text = time_log.read_text()
    assert "application_id,query,time/milliseconds" in text
    assert "query3" in text and "Power Test Time" in text
    # JSON summary contract
    summaries = list(jdir.glob("*-query3-*.json"))
    assert len(summaries) == 1
    s = json.loads(summaries[0].read_text())
    assert s["queryStatus"] == ["Completed"]
    assert s["query"] == "query3"
    assert s["env"]["engineVersion"]
    assert not any("PASSWORD" in k for k in s["env"]["envVars"])
    # output written for validation
    assert (tmp_path / "out" / "query3").is_dir()


def test_power_failure_is_recorded(dataset, env, tmp_path):
    stream = tmp_path / "bad.sql"
    stream.write_text(
        "-- start query 1 in stream 0 using template query1.tpl\n"
        "select nonexistent_column from item\n;\n"
        "-- end query 1 in stream 0 using template query1.tpl\n")
    jdir = tmp_path / "json"
    subprocess.run(
        ["python", "-m", "ndstpu.harness.power", str(stream),
         str(dataset / "wh"), str(tmp_path / "t.csv"),
         "--json_summary_folder", str(jdir)],
        check=True, env=env)
    s = json.loads(next(jdir.glob("*-query1-*.json")).read_text())
    assert s["queryStatus"] == ["Failed"]
    assert s["exceptions"]


def test_validate_pass_and_fail(dataset, env, tmp_path):
    # run the same queries twice -> Pass; corrupt one output -> Fail
    for tag in ("a", "b"):
        subprocess.run(
            ["python", "-m", "ndstpu.harness.power",
             str(dataset / "streams" / "query_0.sql"),
             str(dataset / "wh"), str(tmp_path / f"t_{tag}.csv"),
             "--sub_queries", "query3,query55",
             "--output_prefix", str(tmp_path / tag)],
            check=True, env=env)
    r = subprocess.run(
        ["python", "-m", "ndstpu.harness.validate",
         str(tmp_path / "a"), str(tmp_path / "b"),
         str(dataset / "streams" / "query_0.sql"),
         "--sub_queries", "query3,query55"],
        env=env, capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "All queries match." in r.stdout

    # corrupt: truncate one NON-EMPTY parquet output by dropping a row
    # (query3 can legitimately match 0 rows on tiny skewed data — an
    # empty output cannot be corrupted by truncation)
    import pyarrow.parquet as pq
    for qdir in ("query3", "query55"):
        f = next((tmp_path / "b" / qdir).glob("*.parquet"))
        t = pq.read_table(f)
        if t.num_rows > 0:
            pq.write_table(t.slice(0, t.num_rows - 1), f)
            break
    else:
        pytest.skip("both test queries returned 0 rows at this SF")
    r2 = subprocess.run(
        ["python", "-m", "ndstpu.harness.validate",
         str(tmp_path / "a"), str(tmp_path / "b"),
         str(dataset / "streams" / "query_0.sql"),
         "--sub_queries", "query3,query55"],
        env=env, capture_output=True, text=True)
    assert r2.returncode == 1
    assert "mismatch" in r2.stdout


def test_throughput_concurrent_streams(dataset, env, tmp_path):
    overlap = tmp_path / "overlap.json"
    r = subprocess.run(
        ["python", "-m", "ndstpu.harness.throughput", "1,2",
         "--overlap_report", str(overlap), "--",
         "python", "-m", "ndstpu.harness.power",
         str(dataset / "streams") + "/query_{}.sql",
         str(dataset / "wh"),
         str(tmp_path) + "/time_{}.csv",
         "--sub_queries", "query3,query96"],
        check=True, env=env)
    assert r.returncode == 0
    for i in (1, 2):
        assert (tmp_path / f"time_{i}.csv").exists()
    # throughput elapsed derivable from the stream logs
    tt = bench_mod.get_throughput_time(str(tmp_path / "time"), 5, 1)
    assert tt >= 0  # 1s timestamp resolution: tiny runs can be 0
    # overlap evidence artifact: both streams recorded with true
    # start/end epochs; two unbounded streams on one host overlap
    ov = json.loads(overlap.read_text())
    assert ov["format"] == "ndstpu-throughput-overlap-v1"
    assert {s["stream"] for s in ov["streams"]} == {"1", "2"}
    assert ov["max_concurrent"] == 2
    assert ov["pairwise_overlap_s"]["1&2"] > 0
    for s in ov["streams"]:
        assert s["end_epoch_s"] >= s["start_epoch_s"]
        assert s["returncode"] == 0


def test_concurrency_timeline():
    from ndstpu.harness.throughput import concurrency_timeline
    recs = [
        {"stream": "1", "start_epoch_s": 0.0, "end_epoch_s": 10.0},
        {"stream": "2", "start_epoch_s": 5.0, "end_epoch_s": 15.0},
        {"stream": "3", "start_epoch_s": 14.0, "end_epoch_s": 20.0},
    ]
    tl = concurrency_timeline(recs)
    assert tl["max_concurrent"] == 2
    assert tl["pairwise_overlap_s"] == {"1&2": 5.0, "2&3": 1.0,
                                        "1&3": 0.0}
    assert tl["total_pairwise_overlap_s"] == 6.0


def test_power_budget_degradation(dataset, env, tmp_path):
    """A power run whose ledger priors project past the budget must
    degrade explicitly: cheapest-first reorder, per-query
    partial_reason in the sidecar (never a bare partial flag), and
    greppable heartbeat/budget lines (docs/OBSERVABILITY.md)."""
    from ndstpu.obs import ledger as ledger_mod

    ledger_path = tmp_path / "ledger.jsonl"
    led = ledger_mod.Ledger(str(ledger_path))
    # priors: two sub-second queries, two that can never fit a 30s
    # budget -> deterministic reorder + cut whatever the host speed
    for q, wall in (("query42", 0.02), ("query3", 0.05),
                    ("query96", 500.0), ("query55", 600.0)):
        led.append(ledger_mod.make_entry(
            q, wall, execute_s=wall, engine="cpu",
            scale_factor="unknown", seed="unknown", warmth="warm",
            source="seed"))
    time_log = tmp_path / "time.csv"
    r = subprocess.run(
        ["python", "-m", "ndstpu.harness.power",
         str(dataset / "streams" / "query_0.sql"),
         str(dataset / "wh"), str(time_log),
         "--sub_queries", "query96,query3,query55,query42",
         "--budget_s", "30", "--ledger", str(ledger_path)],
        check=True, env=env, capture_output=True, text=True)
    assert "[heartbeat] power" in r.stdout
    assert "cheapest-first" in r.stdout
    csv_queries = [line.split(",")[1]
                   for line in time_log.read_text().splitlines()[1:]
                   if line.split(",")[1:2] and
                   line.split(",")[1].startswith("query")]
    # cheapest-first: query42 (0.02s prior) ran before query3 (0.05s);
    # the 500/600s-prior queries were cut and wrote NO time-log row
    assert csv_queries == ["query42", "query3"]
    sidecar = json.loads(
        (tmp_path / "time.csv.metrics.json").read_text())
    assert sidecar["partial"] is True
    assert set(sidecar["partial_reasons"]) == {"query96", "query55"}
    for q, reason in sidecar["partial_reasons"].items():
        assert "budget" in reason and "30" in reason, (q, reason)
    # the executed queries were appended to the ledger
    led2 = ledger_mod.Ledger(str(ledger_path))
    appended = [e for e in led2.entries if e["source"] == "time.csv"]
    assert {e["query"] for e in appended} == {"query42", "query3"}


def test_power_ledger_sentinel_two_runs(dataset, env, tmp_path):
    """Acceptance loop: run the same stream twice against a fresh
    ledger.  Run 1 seeds baselines (verdict `new`); run 2 is judged
    against them with no cold-compile false positives, and every
    executed query has a ledger entry + sentinel verdict."""
    from ndstpu.obs import ledger as ledger_mod

    ledger_path = tmp_path / "ledger.jsonl"
    sub = "query3,query42,query55,query96,query52"
    sidecars = []
    for tag in ("r1", "r2"):
        time_log = tmp_path / f"{tag}.csv"
        subprocess.run(
            ["python", "-m", "ndstpu.harness.power",
             str(dataset / "streams" / "query_0.sql"),
             str(dataset / "wh"), str(time_log),
             "--sub_queries", sub, "--ledger", str(ledger_path)],
            check=True, env=env)
        sidecars.append(json.loads(
            (tmp_path / f"{tag}.csv.metrics.json").read_text()))
    names = set(sub.split(","))
    led = ledger_mod.Ledger(str(ledger_path))
    for tag, sc in zip(("r1", "r2"), sidecars):
        verdicts = {v["query"]: v for v in sc["sentinel"]["verdicts"]}
        assert set(verdicts) == names, tag
        entries = {e["query"] for e in led.entries
                   if e["source"] == f"{tag}.csv"}
        assert entries == names, tag
    # run 1 had no baselines; the cpu interpreter never compiles, so
    # every verdict is `new`, and run 2 must be judged against run 1's
    # entries (baseline present, never cold-compile)
    assert sidecars[0]["sentinel"]["counts"] == {"new": len(names)}
    for v in sidecars[1]["sentinel"]["verdicts"]:
        assert v["verdict"] != "cold-compile"
        assert v["verdict"] != "new"
        assert v["baseline_warm_s"] is not None
    assert sidecars[1]["ledger"]["appended"] == len(names)


def test_maintenance_insert_delete_and_rollback(dataset, env, tmp_path):
    from ndstpu.io import acid, loader

    wh = str(dataset / "wh")
    before = acid.read(os.path.join(wh, "store_sales")).num_rows
    import time as _time
    ts_before = _time.time()
    subprocess.run(
        ["python", "-m", "ndstpu.harness.maintenance", wh,
         str(dataset / "raw_1"), str(tmp_path / "dm.csv"),
         "--dm_funcs", "LF_SS,DF_SS"],
        check=True, env=env)
    text = (tmp_path / "dm.csv").read_text()
    assert "LF_SS" in text and "DF_SS" in text
    assert "Data Maintenance Time" in text
    after = acid.read(os.path.join(wh, "store_sales")).num_rows
    assert after != before  # inserts and deletes happened
    # ACID time travel: roll back and recover the original row count
    subprocess.run(
        ["python", "-m", "ndstpu.harness.rollback", wh, str(ts_before),
         "--tables", "store_sales,store_returns"],
        check=True, env=env)
    restored = acid.read(os.path.join(wh, "store_sales")).num_rows
    assert restored == before


def test_submit_template_layer(dataset, env, tmp_path):
    """ndstpu-submit sources a template and launches the phase CLI with
    the template's engine args (analog: spark-submit-template)."""
    time_log = tmp_path / "time.csv"
    subprocess.run(
        ["./ndstpu/harness/ndstpu-submit", "power_run_cpu.template",
         str(dataset / "streams" / "query_0.sql"),
         str(dataset / "wh"), str(time_log),
         "--input_format", "ndslake",
         "--sub_queries", "query42",
         "--json_summary_folder", str(tmp_path / "json")],
        check=True, env=env)
    assert "query42" in time_log.read_text()
    # the template's property file lands in the JSON summary engine conf
    summary = json.loads(
        next((tmp_path / "json").glob("cpu-query42-*.json")).read_text())
    assert summary["env"]["engineConf"]["engine.interpreter"] == "numpy"


def test_report_degradation_marks_task_failures():
    """Any engine degradation surfaced as a warning (eager demotion,
    size-class rediscovery, distributed fallback) must mark the query
    CompletedWithTaskFailures in the JSON summary — the reference's
    task-failure listener contract (PysparkBenchReport.py:89-92)."""
    import warnings

    from ndstpu.harness.report import BenchReport

    def degraded_query():
        warnings.warn("whole-query compile failed twice, demoted to "
                      "eager per-op execution: injected")

    rep = BenchReport()
    summary = rep.report_on(degraded_query)
    assert summary["queryStatus"] == ["CompletedWithTaskFailures"]
    assert any("demoted to eager" in f for f in summary["taskFailures"])

    rep2 = BenchReport()
    s2 = rep2.report_on(lambda: None)
    assert s2["queryStatus"] == ["Completed"]


def test_apply_engine_properties_jax_keys():
    from ndstpu.harness.power import apply_engine_properties
    import jax
    old = jax.config.jax_persistent_cache_min_compile_time_secs
    try:
        apply_engine_properties({
            "jax.persistent_cache_min_compile_time_secs": "0.5",
            "jax.unknown_knob_xyz": "1",   # warns, must not raise
            "engine.interpreter": "numpy",
        })
        assert jax.config.jax_persistent_cache_min_compile_time_secs == 0.5
    finally:
        jax.config.update("jax_persistent_cache_min_compile_time_secs", old)


def test_gen_sql_from_stream_contract(tmp_path):
    stream = tmp_path / "s.sql"
    stream.write_text(
        "-- start query 1 in stream 0 using template query96.tpl\n"
        "select 1 x from item\n;\n"
        "-- end query 1 in stream 0 using template query96.tpl\n\n"
        "-- start query 2 in stream 0 using template query14.tpl\n"
        "select 2 y from item\n;\n"
        "select 3 z from item\n;\n"
        "-- end query 2 in stream 0 using template query14.tpl\n")
    q = gen_sql_from_stream(str(stream))
    assert list(q) == ["query96", "query14_part1", "query14_part2"]


def test_gen_sql_from_stream_keeps_sql_and_markers(tmp_path):
    stream = tmp_path / "s.sql"
    stream.write_text(
        "-- start query 1 in stream 3 using template query5.tpl\n"
        "select a, b -- trailing comment with ; nothing\n"
        "from store_sales\n;\n"
        "-- end query 1 in stream 3 using template query5.tpl\n")
    q = gen_sql_from_stream(str(stream))
    assert list(q) == ["query5"]
    # single-statement blocks keep the full text, markers included
    assert q["query5"].startswith("-- start query 1 in stream 3")
    assert "from store_sales" in q["query5"]


def test_locate_unstable_cols_positional():
    from ndstpu.harness.validate import locate_unstable_cols
    sql = ("with x as (select 1 from t) select ss_customer_sk,\n"
           "round(ss_qty/(coalesce(ws_qty,0)+coalesce(cs_qty,0)),2) ratio,\n"
           "ss_qty store_qty\nfrom x")
    assert locate_unstable_cols("query78", sql) == [1]
    # a different layout moves the detected position with it
    sql2 = ("select a, b, c, round(x/(y+z),2) ratio from t")
    assert locate_unstable_cols("query78", sql2) == [3]
    # non-carve-out queries never get unstable columns
    assert locate_unstable_cols("query5", sql2) is None
    # missing ratio column in a q78 stream is an error, not a silent skip
    import pytest as _pytest
    with _pytest.raises(ValueError):
        locate_unstable_cols("query78", "select a, b from t")


def test_locate_unstable_cols_on_real_template(tmp_path):
    from ndstpu.harness.validate import locate_unstable_cols
    from ndstpu.queries import streamgen
    sql = streamgen.render_template(
        str(streamgen.TEMPLATE_DIR / "query78.tpl"), "42", 0)
    assert locate_unstable_cols("query78", sql) == [1]


def test_distlist_with_replacement_and_distinct():
    import random as _random
    from ndstpu.queries.streamgen import _dist_pick
    rng = _random.Random(7)
    # with replacement: hot values repeat across a long draw
    picks = _dist_pick(rng, "fips_county", 40)
    assert len(picks) == 40
    assert len(set(picks)) < 40  # duplicates present (distmember analog)
    # distinct mode: no repeats, capped at pool size
    rng = _random.Random(7)
    upicks = _dist_pick(rng, "fips_county", 8, distinct=True)
    assert len(upicks) == 8 and len(set(upicks)) == 8


def test_ensure_valid_column_names():
    from ndstpu.engine.columnar import INT32, Column, Table
    t = Table({"ok_name": Column(np.zeros(1, np.int32), INT32),
               "sum(x)": Column(np.zeros(1, np.int32), INT32)})
    out = ensure_valid_column_names(t)
    assert out.column_names == ["ok_name", "column_1"]


def test_full_bench_end_to_end(tmp_path, env):
    """The nds_bench analog runs all five phases from YAML and emits the
    composite metric (reference: nds/nds_bench.py:367-497)."""
    root = tmp_path
    # small template corpus keeps the 3-stream run fast
    import shutil as _sh

    from ndstpu.queries import streamgen
    tpl_dir = root / "tpl"
    tpl_dir.mkdir()
    for t in ["query3.tpl", "query7.tpl", "query42.tpl", "query52.tpl",
              "query96.tpl"]:
        _sh.copy(streamgen.TEMPLATE_DIR / t, tpl_dir / t)
    cfg = {
        "data_gen": {"scale_factor": 0.002, "parallel": 2,
                     "data_path": str(root / "raw"), "skip": False},
        "load_test": {"warehouse_path": str(root / "wh"),
                      "warehouse_format": "ndslake",
                      "report_file": str(root / "load.txt"),
                      "skip": False},
        "generate_query_stream": {
            "num_streams": 3, "template_dir": str(tpl_dir),
            "stream_output_path": str(root / "streams"), "skip": False},
        "power_test": {"engine": "cpu",
                       "report_file": str(root / "power.csv"),
                       "json_summary_folder": str(root / "json"),
                       "output_prefix": "", "skip": False},
        "throughput_test": {"report_base": str(root / "tt"),
                            "skip": False},
        "maintenance_test": {"report_base": str(root / "dm"),
                             "skip": False},
        "metrics": {"metrics_report": str(root / "metrics.csv")},
    }
    import yaml as _yaml
    cfg_path = root / "bench.yml"
    cfg_path.write_text(_yaml.safe_dump(cfg))
    subprocess.run(["python", "-m", "ndstpu.harness.bench",
                    str(cfg_path)], check=True, env=env,
                   stdout=subprocess.DEVNULL, timeout=3000)
    metrics = dict(line.split(",", 1) for line in
                   (root / "metrics.csv").read_text().splitlines())
    assert int(metrics["metric"]) > 0
    assert float(metrics["Tpower(s)"]) >= 0
    # all phase artifacts exist
    assert (root / "power.csv").exists()
    assert (root / "tt_1.csv").exists() and (root / "tt_2.csv").exists()
    assert (root / "dm_1.csv").exists() and (root / "dm_2.csv").exists()
    assert list((root / "json").glob("*-query3-*.json"))


def test_stream_parse_keys_match_rendered_corpus(tmp_path):
    """A stream file rendered with the bench seed must parse back into
    queries whose compile-record keys equal the directly-rendered
    corpus keys for ALL 103 parts — the pinned-rngseed hardware run
    (bench_hw_sf1.yml) replays warmed programs only if the two render
    paths agree after normalize_sql_key (markers, part splits,
    trailing semicolons)."""
    from ndstpu.engine.sql import normalize_sql_key
    from ndstpu.harness.power import gen_sql_from_stream
    from ndstpu.queries import streamgen

    streamgen.generate_query_streams(
        None, streamgen.BENCH_RNGSEED, str(tmp_path), 1)
    parsed = gen_sql_from_stream(str(tmp_path / "query_0.sql"))
    corpus = dict(streamgen.render_power_corpus())
    pk = {n: normalize_sql_key(s) for n, s in parsed.items()}
    ck = {n: normalize_sql_key(s) for n, s in corpus.items()}
    assert set(pk) == set(ck)
    assert not [n for n in pk if pk[n] != ck[n]]


def test_resolve_stream_rngseed(tmp_path):
    """An explicit `rngseed:` pin wins; otherwise the seed chains from
    the load report end timestamp (reference nds_bench.py:249-261; the
    pin mirrors nds_gen_query_stream.py's explicit --rngseed)."""
    report = tmp_path / "load.txt"
    report.write_text("Load Test Time: 12 seconds\n"
                      "RNGSEED used: 08021530120\n")
    assert bench_mod.resolve_stream_rngseed(
        {}, str(report)) == "08021530120"
    assert bench_mod.resolve_stream_rngseed(
        {"rngseed": "01151230000"}, str(report)) == "01151230000"
    # the sentinel resolves to the single warmed-corpus seed constant
    from ndstpu.queries.streamgen import BENCH_RNGSEED
    assert bench_mod.resolve_stream_rngseed(
        {"rngseed": "bench"}, str(report)) == BENCH_RNGSEED
    # unquoted yaml seeds parse as ints (octal for 0-prefixed Jan-Jul
    # timestamps) and silently pin the wrong corpus — refused outright
    import pytest as _pytest
    with _pytest.raises(ValueError):
        bench_mod.resolve_stream_rngseed({"rngseed": 0}, str(report))
    with _pytest.raises(ValueError):
        bench_mod.resolve_stream_rngseed(
            {"rngseed": 161820672}, str(report))


def test_metric_formula():
    m = bench_mod.get_perf_metric("100", 2, 99, 1000.0, 500.0, 300.0,
                                  310.0, 60.0, 65.0)
    # hand-computed reference formula
    Q = 2 * 99
    Tpt = 500.0 * 2 / 3600
    Ttt = 610.0 / 3600
    Tdm = 125.0 / 3600
    Tld = 0.01 * 2 * 1000.0 / 3600
    assert m == int(100 * Q / (Tpt * Ttt * Tdm * Tld) ** 0.25)
    assert bench_mod.round_up_to_nearest_10_percent(1.01) == 1.1
    assert bench_mod.get_stream_range(9, 1) == [1, 2, 3, 4]
    assert bench_mod.get_stream_range(9, 2) == [5, 6, 7, 8]


# --------------------------------------- robustness (docs/ROBUSTNESS.md)

def test_power_resume_skips_journaled_queries(dataset, env, tmp_path):
    """Crash-safe power resume: the per-query progress journal lets a
    second run of the same fingerprint skip every finished query and
    carry its time-log rows over."""
    time_log = tmp_path / "time.csv"
    cmd = ["python", "-m", "ndstpu.harness.power",
           str(dataset / "streams" / "query_0.sql"),
           str(dataset / "wh"), str(time_log),
           "--input_format", "ndslake",
           "--sub_queries", "query3,query42"]
    subprocess.run(cmd, check=True, env=env)
    journal = tmp_path / "time.csv.progress.jsonl"
    recs = [json.loads(line) for line
            in journal.read_text().splitlines()]
    assert [r["query"] for r in recs] == ["query3", "query42"]
    assert len({r["fp"] for r in recs}) == 1

    r = subprocess.run(cmd + ["--resume"], check=True, env=env,
                       capture_output=True, text=True)
    assert "Skip query3 (resume: already completed)" in r.stdout
    assert "Skip query42 (resume: already completed)" in r.stdout
    # carried-over rows keep the time-log contract intact
    text = time_log.read_text()
    assert "query3" in text and "Power Test Time" in text
    sidecar = json.loads(
        (tmp_path / "time.csv.metrics.json").read_text())
    assert sidecar["resumed"] == ["query3", "query42"]


def test_power_resume_ignores_other_fingerprint(dataset, env, tmp_path):
    """A journal written under different run parameters (here: another
    query subset) must never satisfy a resume."""
    time_log = tmp_path / "time.csv"
    base = ["python", "-m", "ndstpu.harness.power",
            str(dataset / "streams" / "query_0.sql"),
            str(dataset / "wh"), str(time_log),
            "--input_format", "ndslake"]
    subprocess.run(base + ["--sub_queries", "query3"],
                   check=True, env=env)
    r = subprocess.run(
        base + ["--sub_queries", "query42", "--resume"],
        check=True, env=env, capture_output=True, text=True)
    assert "Skip" not in r.stdout  # fingerprint mismatch: full rerun
    sidecar = json.loads(
        (tmp_path / "time.csv.metrics.json").read_text())
    assert sidecar["resumed"] is None


def test_power_watchdog_abandons_hung_query_and_reports_zombie(
        dataset, env, tmp_path):
    """A wedged execute on an accel engine is abandoned by the
    per-query watchdog (TimeoutError -> transient taxonomy), the stream
    swaps in a fresh session, and the abandoned thread surfaces as
    `zombieQueries` in the NEXT query's summary after its one grace
    join (docs/ROBUSTNESS.md)."""
    jdir = tmp_path / "json"
    time_log = tmp_path / "time.csv"
    # 15s watchdog: an order of magnitude above this stream's real
    # per-query cost (~4s compile+run at this SF) so only the injected
    # 120s hang trips it; the hang outlives the 10s zombie grace join
    hang_env = dict(
        env,
        NDSTPU_FAULTS="execute:hang:1.0:seedZ:times=1:hang=120",
        NDSTPU_POWER_QUERY_TIMEOUT_S="15",
        NDSTPU_RETRY_MAX="1")
    subprocess.run(
        ["python", "-m", "ndstpu.harness.power",
         str(dataset / "streams" / "query_0.sql"),
         str(dataset / "wh"), str(time_log),
         "--input_format", "ndslake",
         "--engine", "tpu",
         "--sub_queries", "query3,query42",
         "--json_summary_folder", str(jdir)],
        check=True, env=hang_env)
    s3 = json.loads(next(jdir.glob("*-query3-*.json")).read_text())
    assert s3["queryStatus"] == ["Failed"]
    assert any("abandoned" in e or "TimeoutError" in e
               for e in s3["exceptions"]), s3["exceptions"]
    s42 = json.loads(next(jdir.glob("*-query42-*.json")).read_text())
    assert s42["queryStatus"] == ["Completed"]
    assert s42["zombieQueries"] == ["query3"]
    sidecar = json.loads(
        (tmp_path / "time.csv.metrics.json").read_text())
    assert sidecar["faultTaxonomy"]["counts"] == {"transient": 1}
    assert sidecar["faultTaxonomy"]["queries"]["query3"] == "transient"


def test_transcode_resume_markers(dataset, env, tmp_path):
    """_SUCCESS markers: resume skips completed tables and rebuilds a
    torn (marker-less) table dir from scratch."""
    out = tmp_path / "wh"
    cmd = ["python", "-m", "ndstpu.io.transcode",
           "--input_prefix", str(dataset / "raw"),
           "--output_prefix", str(out),
           "--report_file", str(tmp_path / "load.txt"),
           "--output_format", "ndslake"]
    subprocess.run(cmd, check=True, env=env,
                   stdout=subprocess.DEVNULL)
    markers = list(out.glob("*/_SUCCESS"))
    assert markers  # every table dir is marked complete
    # simulate a crash mid-write on one table: kill its marker
    torn = markers[0].parent
    markers[0].unlink()
    r = subprocess.run(cmd + ["--resume"], check=True, env=env,
                       capture_output=True, text=True)
    assert f"[resume] {torn.name}: incomplete output" in r.stdout
    assert r.stdout.count("_SUCCESS marker present — skipping") == \
        len(markers) - 1
    assert (torn / "_SUCCESS").exists()  # rebuilt and re-marked
