"""Differential tests: JAX backend vs numpy reference interpreter.

Mirrors the reference's differential-validation strategy (CPU Spark vs GPU
rapids, nds/nds_validate.py) inside the test suite: every query template in
the corpus runs on both backends and must agree row-by-row under the
validator's epsilon/NULL/Decimal semantics, ignoring row order.
"""

import math
import os
import subprocess

import numpy as np
import pytest

from ndstpu.engine.session import Session
from ndstpu.io import loader
from ndstpu.queries import streamgen


@pytest.fixture(scope="module")
def warehouse(tmp_path_factory):
    data = tmp_path_factory.mktemp("raw")
    wh = tmp_path_factory.mktemp("wh")
    env = dict(os.environ, PYTHONPATH=os.getcwd())
    subprocess.run(["python", "-m", "ndstpu.datagen.driver", "local", "0.002",
                    "2", str(data)], check=True, env=env)
    subprocess.run(["python", "-m", "ndstpu.io.transcode",
                    "--input_prefix", str(data),
                    "--output_prefix", str(wh),
                    "--report_file", str(wh / "load.txt")],
                   check=True, env=env, stdout=subprocess.DEVNULL)
    return wh


@pytest.fixture(scope="module")
def catalog(warehouse):
    return loader.load_catalog(str(warehouse))


@pytest.fixture(scope="module")
def cpu_sess(catalog):
    return Session(catalog, backend="cpu")


@pytest.fixture(scope="module")
def tpu_sess(catalog):
    return Session(catalog, backend="tpu")


def _canon(v):
    if v is None:
        return None
    if isinstance(v, float):
        return v
    return v


def _rows_equal(a, b, eps=1e-5):
    if len(a) != len(b):
        return False
    for x, y in zip(a, b):
        if x is None or y is None:
            if not (x is None and y is None):
                return False
            continue
        if isinstance(x, float) or isinstance(y, float):
            fx, fy = float(x), float(y)
            if math.isnan(fx) or math.isnan(fy):
                if not (math.isnan(fx) and math.isnan(fy)):
                    return False
                continue
            tol = max(abs(fx), abs(fy)) * eps + 1e-9
            if abs(fx - fy) > tol:
                return False
        elif x != y:
            return False
    return True


def _sort_key(row):
    out = []
    for v in row:
        if v is None:
            out.append((0, ""))
        elif isinstance(v, float):
            out.append((1, f"{v:.4f}"))
        else:
            out.append((1, str(v)))
    return out


def assert_tables_match(t_cpu, t_tpu, ordered=False):
    rows_a = t_cpu.to_rows()
    rows_b = t_tpu.to_rows()
    assert len(rows_a) == len(rows_b), \
        f"row count {len(rows_a)} vs {len(rows_b)}"
    if not ordered:
        rows_a = sorted(rows_a, key=_sort_key)
        rows_b = sorted(rows_b, key=_sort_key)
    for i, (ra, rb) in enumerate(zip(rows_a, rows_b)):
        assert _rows_equal(ra, rb), f"row {i}: {ra} != {rb}"


@pytest.mark.parametrize("tpl", streamgen.list_templates())
def test_template_differential(cpu_sess, tpu_sess, tpl):
    for _name, sql in streamgen.render_template_parts(
            str(streamgen.TEMPLATE_DIR / tpl), "07291122510", 0):
        out_cpu = cpu_sess.sql(sql)
        out_tpu = tpu_sess.sql(sql)
        assert out_cpu.column_names == out_tpu.column_names
        assert_tables_match(out_cpu, out_tpu)


def _both(cpu_sess, tpu_sess, sql, ordered=False):
    a = cpu_sess.sql(sql)
    b = tpu_sess.sql(sql)
    assert a.column_names == b.column_names
    assert_tables_match(a, b, ordered=ordered)
    return b


def test_filter_project(cpu_sess, tpu_sess):
    _both(cpu_sess, tpu_sess,
          "select ss_item_sk, ss_quantity * 2 as q2, ss_sales_price "
          "from store_sales where ss_quantity > 10 and ss_sales_price > 50")


def test_join_groupby_sort(cpu_sess, tpu_sess):
    _both(cpu_sess, tpu_sess,
          "select i_category, count(*) as cnt, sum(ss_ext_sales_price) as s "
          "from store_sales, item where ss_item_sk = i_item_sk "
          "group by i_category order by i_category", ordered=True)


def test_left_join_nulls(cpu_sess, tpu_sess):
    _both(cpu_sess, tpu_sess,
          "select sr_item_sk, sr_ticket_number, ss_ticket_number "
          "from store_returns left join store_sales on "
          "sr_item_sk = ss_item_sk and sr_ticket_number = ss_ticket_number")


def test_decimal_agg_exact(cpu_sess, tpu_sess):
    out = _both(cpu_sess, tpu_sess,
                "select sum(ss_net_paid) as total, avg(ss_net_paid) as a, "
                "min(ss_net_paid) as lo, max(ss_net_paid) as hi "
                "from store_sales")
    assert out.num_rows == 1


def test_case_and_strings(cpu_sess, tpu_sess):
    _both(cpu_sess, tpu_sess,
          "select i_item_id, case when i_category = 'Music' then 'M' "
          "else 'other' end as tag, upper(i_brand) as ub "
          "from item where i_brand like '%max%' or i_category in "
          "('Music', 'Books')")


def test_distinct_and_dates(cpu_sess, tpu_sess):
    _both(cpu_sess, tpu_sess,
          "select distinct d_year, d_moy from date_dim "
          "where d_year between 1999 and 2001")


def test_scalar_subquery(cpu_sess, tpu_sess):
    _both(cpu_sess, tpu_sess,
          "select ss_item_sk, ss_sales_price from store_sales "
          "where ss_sales_price > (select avg(ss_sales_price) "
          "from store_sales)")


def test_limit_after_sort(cpu_sess, tpu_sess):
    _both(cpu_sess, tpu_sess,
          "select ss_item_sk, ss_net_paid from store_sales "
          "order by ss_net_paid desc, ss_item_sk limit 10", ordered=True)


def test_semi_anti_via_in(cpu_sess, tpu_sess):
    _both(cpu_sess, tpu_sess,
          "select count(*) as n from store_sales where ss_item_sk in "
          "(select i_item_sk from item where i_category = 'Music')")
    _both(cpu_sess, tpu_sess,
          "select count(*) as n from store_sales where ss_item_sk not in "
          "(select i_item_sk from item where i_category = 'Music')")


def test_in_list_untyped_date_literals(cpu_sess, tpu_sess):
    # plain string literals against a DATE column must coerce on BOTH
    # backends (query83 shape); result is non-empty so a silent
    # no-match bug can't hide
    out = _both(cpu_sess, tpu_sess,
                "select d_date, d_year from date_dim where d_date in "
                "('2000-06-30', '2000-09-27', '2000-11-17')")
    assert len(out.to_rows()) == 3
    # an uncoercible literal casts to NULL and never matches
    _both(cpu_sess, tpu_sess,
          "select count(*) as n from date_dim where d_date in "
          "('2000-06-30', 'not-a-date')")
    # NOT IN with a NULL-casting literal is never TRUE (NULL semantics)
    out = _both(cpu_sess, tpu_sess,
                "select count(*) as n from date_dim where d_date not in "
                "('2000-06-30', 'not-a-date')")
    assert out.to_rows()[0][0] == 0


def test_empty_result(cpu_sess, tpu_sess):
    _both(cpu_sess, tpu_sess,
          "select ss_item_sk, ss_quantity from store_sales "
          "where ss_quantity > 1000000")


def test_window_functions(cpu_sess, tpu_sess):
    _both(cpu_sess, tpu_sess,
          "select * from (select i_category, i_item_id, "
          "rank() over (partition by i_category "
          "order by i_current_price desc) as r from item) t where r <= 3")
    _both(cpu_sess, tpu_sess,
          "select ss_store_sk, ss_item_sk, "
          "sum(ss_net_paid) over (partition by ss_store_sk) as tot, "
          "row_number() over (partition by ss_store_sk "
          "order by ss_item_sk, ss_ticket_number) as rn "
          "from store_sales where ss_quantity > 40")


def test_rollup_on_device(cpu_sess, tpu_sess):
    _both(cpu_sess, tpu_sess,
          "select i_category, i_class, sum(ss_ext_sales_price) as s "
          "from store_sales, item where ss_item_sk = i_item_sk "
          "group by rollup(i_category, i_class) "
          "order by i_category, i_class", ordered=False)


def test_setops_on_device(cpu_sess, tpu_sess):
    _both(cpu_sess, tpu_sess,
          "select d_year from date_dim where d_moy = 11 intersect "
          "select d_year from date_dim where d_moy = 12")
    _both(cpu_sess, tpu_sess,
          "select i_category from item except "
          "select i_category from item where i_current_price > 50")
    _both(cpu_sess, tpu_sess,
          "select d_year from date_dim where d_year > 2000 union "
          "select d_year from date_dim where d_year < 1995")


def test_full_and_right_joins(cpu_sess, tpu_sess):
    _both(cpu_sess, tpu_sess,
          "select sr_item_sk, sr_ticket_number, ss_quantity from "
          "store_returns right join store_sales on "
          "sr_item_sk = ss_item_sk and sr_ticket_number = "
          "ss_ticket_number where ss_quantity > 45")


def test_distinct_aggregates_on_device(cpu_sess, tpu_sess):
    _both(cpu_sess, tpu_sess,
          "select ss_store_sk, count(distinct ss_item_sk) as di, "
          "sum(distinct ss_quantity) as sq, "
          "avg(distinct ss_wholesale_cost) as aw, "
          "count(ss_item_sk) as ci "
          "from store_sales group by ss_store_sk")


def test_distinct_aggregate_float_no_truncation(cpu_sess, tpu_sess):
    # distinct dedup must key on exact float values (bit pattern), not an
    # int cast; 1.5-scaling makes truncation merge distinct values
    out = _both(cpu_sess, tpu_sess,
                "select ss_store_sk, "
                "sum(distinct ss_wholesale_cost * 1.5) as s, "
                "count(distinct ss_wholesale_cost * 1.5) as c "
                "from store_sales group by ss_store_sk")
    rows = out.to_rows()
    assert any(r[1] is not None and r[1] != int(r[1]) for r in rows
               if r[1] is not None), "expected non-integer distinct sums"


def test_string_concat_on_device(cpu_sess, tpu_sess):
    # literal || column (q5/q80 shape)
    _both(cpu_sess, tpu_sess,
          "select 'store' || s_store_id as id from store")
    # column || literal || column (q84 shape) + concat() function
    _both(cpu_sess, tpu_sess,
          "select coalesce(c_last_name, '') || ', ' || "
          "coalesce(c_first_name, '') as customername, "
          "concat('id:', c_customer_id) as cid from customer")


def test_running_window_range_vs_rows(cpu_sess, tpu_sess):
    # RANGE (default): peer rows share the run value; ROWS: per-row
    _both(cpu_sess, tpu_sess,
          "select ss_store_sk, ss_sold_date_sk, "
          "sum(ss_quantity) over (partition by ss_store_sk "
          "order by ss_sold_date_sk) as run_range, "
          "sum(ss_quantity) over (partition by ss_store_sk "
          "order by ss_sold_date_sk rows between unbounded preceding "
          "and current row) as run_rows, "
          "max(ss_quantity) over (partition by ss_store_sk "
          "order by ss_sold_date_sk) as run_max "
          "from store_sales where ss_store_sk is not null "
          "and ss_sold_date_sk is not null")


def test_multi_key_join_no_radix_overflow(cpu_sess, tpu_sess):
    # 4-key equi-join exercises the composite-key re-densify path
    _both(cpu_sess, tpu_sess,
          "select count(*) as n from store_sales ss join store_returns sr "
          "on ss.ss_item_sk = sr.sr_item_sk "
          "and ss.ss_ticket_number = sr.sr_ticket_number "
          "and ss.ss_customer_sk = sr.sr_customer_sk "
          "and ss.ss_store_sk = sr.sr_store_sk")


def test_exists_under_or_mark_join(cpu_sess, tpu_sess):
    # q10/q35 shape: EXISTS subqueries under OR -> mark join on device
    _both(cpu_sess, tpu_sess,
          "select c_customer_sk from customer c where "
          "exists (select * from store_sales where ss_customer_sk = "
          "c.c_customer_sk and ss_quantity > 10) or "
          "exists (select * from web_sales where ws_bill_customer_sk = "
          "c.c_customer_sk)")


def test_compile_record_persistence(catalog, cpu_sess, tmp_path):
    """Saved size-plan records let a fresh session skip discovery and go
    straight to jitted replay, with identical results."""
    from ndstpu.engine.session import Session
    sql = ("select i_category, count(*) as n, sum(ss_net_paid) as s "
           "from store_sales join item on ss_item_sk = i_item_sk "
           "group by i_category order by i_category")
    s1 = Session(catalog, backend="tpu")
    want = s1.sql(sql).to_rows()
    path = str(tmp_path / "plans.pkl")
    assert s1.save_compiled(path) >= 1
    s2 = Session(catalog, backend="tpu")
    assert s2.preload_compiled(path) >= 1
    got = s2.sql(sql).to_rows()
    assert sorted(map(str, got)) == sorted(map(str, want))
    # the preloaded entry went straight to replay: the executor never ran
    # discovery for this SQL (its compiled record has a jitted fn now)
    cp = s2.compiled_plan(sql)
    assert cp is not None and cp.compilable and cp.fn is not None
    assert sorted(map(str, cpu_sess.sql(sql).to_rows())) == \
        sorted(map(str, got))


def test_corpus_compile_coverage(catalog):
    """Most corpus templates must compile to single XLA programs (no
    numpy fallback) — fallbacks are allowed but should be the minority.
    The static analyzer's per-part verdict must agree with the runtime
    outcome: its entire value is predicting device-vs-fallback without
    executing anything."""
    from ndstpu import analysis
    from ndstpu.engine.session import Session
    sess = Session(catalog, backend="tpu")
    tables = analysis.schema_tables()
    compiled, fallback, disagree = [], [], []
    for tpl in streamgen.list_templates():
        for name, sql in streamgen.render_template_parts(
                str(streamgen.TEMPLATE_DIR / tpl), "07291122510", 0):
            sess.sql(sql)
            cp = sess.compiled_plan(sql)
            ran_on_device = cp is not None and cp.compilable
            (compiled if ran_on_device else fallback).append(name)
            res = analysis.analyze_sql(sess, name, sql, tables=tables)
            predicted_device = res.verdict == "device"
            if predicted_device != ran_on_device:
                disagree.append(
                    (name, res.verdict,
                     "device" if ran_on_device else "fallback",
                     res.fallback_codes,
                     getattr(cp, "fallback_codes", ())))
    assert not fallback, \
        f"corpus queries falling back to numpy: {fallback}"
    assert not disagree, \
        f"static verdict vs runtime (query, static, runtime, " \
        f"static codes, runtime codes): {disagree}"


def test_compiled_replay_path(catalog, cpu_sess):
    """Second execution of a query must run the jitted whole-query
    program (replay) and agree with both the first run and the CPU
    interpreter."""
    from ndstpu.engine.session import Session
    sess = Session(catalog, backend="tpu")
    sql = ("select i_category, count(*) as cnt, "
           "sum(ss_ext_sales_price) as s "
           "from store_sales join item on ss_item_sk = i_item_sk "
           "where ss_quantity > 5 "
           "group by i_category order by i_category")
    first = sess.sql(sql)
    cp = sess.compiled_plan(sql)
    assert cp is not None
    assert cp.compilable and cp.fn is not None
    second = sess.sql(sql)   # replay path
    assert_tables_match(first, second, ordered=True)
    assert_tables_match(cpu_sess.sql(sql), second, ordered=True)


def test_steady_state_no_retrace(catalog, cpu_sess, monkeypatch):
    """With replay warm-up on (the bench configuration), the FIRST
    execute_cached pays discovery + jit compile; every later execution
    must dispatch only already-compiled programs — no discovery, no new
    jit builds, no retrace.  Guards the r03 regression where query1's
    'steady-state' second run took 59.4 s recompiling its replay."""
    monkeypatch.setenv("NDSTPU_WARM_REPLAY", "1")
    from ndstpu.engine.session import Session
    sess = Session(catalog, backend="tpu")
    sql = ("select i_category, count(*) as n, sum(ss_net_paid) as s "
           "from store_sales join item on ss_item_sk = i_item_sk "
           "where ss_quantity > 2 group by i_category "
           "order by i_category")
    first = sess.sql(sql)
    exe = sess._jax_executor()
    assert exe.warm_replay
    cp = sess.compiled_plan(sql)
    assert cp is not None and cp.compilable and cp.fn is not None
    # warm-up already validated the jitted program during discovery
    assert cp.fn_validated
    disc, builds = exe.n_discoveries, exe.n_jit_builds
    caches = [cp.fn] + [exe._seg_compiled[fp].fn
                        for fp in (cp.seg_fps or ())]
    sizes = [f._cache_size() for f in caches if f is not None]
    for _ in range(2):
        got = sess.sql(sql)
        assert_tables_match(first, got, ordered=True)
    assert exe.n_discoveries == disc, "steady-state run re-discovered"
    assert exe.n_jit_builds == builds, "steady-state run re-built a jit"
    assert [f._cache_size() for f in caches
            if f is not None] == sizes, "steady-state run re-traced"
    assert_tables_match(cpu_sess.sql(sql), got, ordered=True)


def test_compiled_invalidation_on_dml(catalog):
    """Catalog version changes must invalidate compiled plans (stale
    baked subquery literals / table uploads)."""
    from ndstpu.engine.session import Session
    sess = Session(catalog, backend="tpu")
    sql = "select count(*) as n from item"
    before = sess.sql(sql).to_rows()[0][0]
    item = catalog.get("item")
    import numpy as np
    keep = np.ones(item.num_rows, dtype=bool)
    if item.num_rows:
        keep[0] = False
    catalog.register("item", item.filter(keep))
    after = sess.sql(sql).to_rows()[0][0]
    assert after == before - (1 if before else 0)
    # restore for other tests
    catalog.register("item", item)


# -- group-by strategies (sort / direct small-domain / pallas MXU) ----------

_GB_QUERIES = [
    # int key with static bounds + decimal sum (pallas-eligible)
    "select ss_store_sk, sum(ss_ext_sales_price) as s, count(*) as n "
    "from store_sales group by ss_store_sk",
    # dictionary-coded string key + avg + min/max
    "select i_category, avg(i_current_price) as p, min(i_brand_id) as lo, "
    "max(i_brand_id) as hi from item group by i_category",
    # composite string x int domain; NULL keys from outer join misses
    "select i_category, ss_store_sk, sum(ss_quantity) as q, "
    "count(ss_item_sk) as n from store_sales "
    "left join item on ss_item_sk = i_item_sk "
    "group by i_category, ss_store_sk",
    # float aggregate: exercises the lazy-order compensated path
    "select d_year, stddev_samp(ss_sales_price) as sd, "
    "avg(ss_net_profit) as m from store_sales "
    "join date_dim on ss_sold_date_sk = d_date_sk group by d_year",
    # huge int domain (ticket numbers): must fall back to the sort path
    "select ss_ticket_number, count(*) as n from store_sales "
    "group by ss_ticket_number",
    # rollup keeps working under every mode
    "select i_category, i_class, count(*) as n from item "
    "group by rollup(i_category, i_class)",
]


@pytest.mark.parametrize("mode", ["sort", "auto", "pallas"])
def test_groupby_modes_differential(catalog, cpu_sess, monkeypatch, mode):
    monkeypatch.setenv("NDSTPU_GROUPBY", mode)
    sess = Session(catalog, backend="tpu")
    for sql in _GB_QUERIES:
        assert_tables_match(cpu_sess.sql(sql), sess.sql(sql))


def test_groupby_direct_path_engages(catalog, monkeypatch):
    """The small-domain linearized-gid path must actually be taken for a
    bounded int key (not silently fall back to the sort path)."""
    monkeypatch.setenv("NDSTPU_GROUPBY", "pallas")
    sess = Session(catalog, backend="tpu")
    exe = sess._jax_executor()
    from ndstpu.engine import jaxexec
    dt = jaxexec.to_device(catalog.get("store_sales"))
    key = dt.columns["ss_store_sk"]
    assert key.bounds is not None
    direct = exe._direct_group_ids([("k", key)], dt.alive)
    assert direct is not None
    gid, ngseg, out_alive, out_cols, order = direct
    lo, hi = key.bounds
    assert ngseg == (hi - lo + 1 + 1) + 1  # +NULL slot, +trash slot
    # pallas eligibility for the decimal measure column
    assert exe._pallas_sum_ok(dt.columns["ss_ext_sales_price"], ngseg)


def test_cast_preserves_bounds(catalog):
    """Value-preserving casts must carry column bounds through, so a
    CASE whose common type is decimal (or with one int64 branch) stays
    on the dense/bitmap group-by paths instead of falling to the sort
    path (r5 roadmap: bounds-through-cast)."""
    from ndstpu.engine import jaxexec
    from ndstpu.schema import DType

    dt = jaxexec.to_device(catalog.get("store_sales"))
    ev = jaxexec.JEval(dt)
    key = dt.columns["ss_store_sk"]
    assert key.bounds is not None
    lo, hi = key.bounds

    # int32 -> int64 widening preserves bounds exactly
    wide = ev.cast(key, DType("int64"))
    assert wide.bounds == (lo, hi)
    # int -> decimal scales bounds by 10^scale
    dec = ev.cast(key, DType("decimal", precision=12, scale=2))
    assert dec.bounds == (lo * 100, hi * 100)
    # decimal identity (same scale, wider precision) keeps bounds
    dec2 = ev.cast(dec, DType("decimal", precision=18, scale=2))
    assert dec2.bounds == (lo * 100, hi * 100)
    # decimal scale-up multiplies; scale-down divides monotonically
    up = ev.cast(dec, DType("decimal", precision=18, scale=4))
    assert up.bounds == (lo * 10000, hi * 10000)
    down = ev.cast(up, DType("decimal", precision=18, scale=2))
    assert down.bounds == (lo * 100, hi * 100)
    # decimal -> int truncates toward zero
    back = ev.cast(dec, DType("int32"))
    assert back.bounds == (lo, hi)


def test_case_of_decimal_literals_keeps_dense_groupby(catalog, cpu_sess):
    """A CASE key whose common type is decimal must still reach the
    small-domain direct group-by path (pre-fix: cast() dropped the
    branch bounds and the plan fell to the full sort path)."""
    from ndstpu.engine import jaxexec

    sql = ("select case when ss_quantity < 10 then 0.5 "
           "when ss_quantity < 50 then 1.5 else 2.5 end as bucket, "
           "count(*) as n, sum(ss_ext_sales_price) as s "
           "from store_sales group by bucket")
    sess = Session(catalog, backend="tpu")
    assert_tables_match(cpu_sess.sql(sql), sess.sql(sql))
    # the key expression itself must carry bounds through the decimal
    # casts the CASE inserts
    dt = jaxexec.to_device(catalog.get("store_sales"))
    ev = jaxexec.JEval(dt)
    from ndstpu.engine import expr as ex
    from ndstpu.schema import DType
    dt10 = DType("decimal", precision=3, scale=1)
    case = ex.Case(
        ((ex.BinOp("<", ex.ColumnRef("ss_quantity"), ex.Literal(10)),
          ex.Literal(0.5, dt10)),
         (ex.BinOp("<", ex.ColumnRef("ss_quantity"), ex.Literal(50)),
          ex.Literal(1.5, dt10))),
        ex.Literal(2.5, dt10))
    out = ev.eval(case)
    assert out.ctype.kind == "decimal"
    assert out.bounds == (5, 25)


def test_coalesce_decimal_literal_stays_decimal(cpu_sess, tpu_sess):
    """Spark types `0.0` as DECIMAL(1,1), so coalesce(decimal, 0.0)
    must stay DECIMAL (exact scaled-int math on TPU) instead of
    promoting to emulated f64 — q75's UNION-distinct drifted on real
    hardware when the money column went through float."""
    sql = ("select ss_item_sk, "
           "ss_ext_sales_price - coalesce(ss_ext_discount_amt, 0.0) as x "
           "from store_sales order by ss_item_sk, x limit 50")
    a = cpu_sess.sql(sql)
    b = tpu_sess.sql(sql)
    from ndstpu.schema import DType  # noqa: F401
    assert a.columns["x"].ctype.kind == "decimal"
    assert b.columns["x"].ctype.kind == "decimal"
    assert a.to_rows() == b.to_rows()


def test_distinct_bitmap_path_matches_sort_path(catalog, cpu_sess, tpu_sess):
    """Small-domain int/decimal distinct aggregates take the presence-
    bitmap path (no sort); results must equal the CPU interpreter and
    the sort path (forced by shrinking the slot budget)."""
    sql = ("select ss_store_sk, count(distinct ss_quantity) cd, "
           "sum(distinct ss_quantity) sd, avg(distinct ss_quantity) ad, "
           "count(distinct ss_list_price) cdp "
           "from store_sales group by ss_store_sk order by ss_store_sk")
    want = cpu_sess.sql(sql).to_rows()
    got = tpu_sess.sql(sql).to_rows()
    assert _rows_equal(got, want)
    # force the sort path and compare (same session would reuse the
    # compiled plan, so use a fresh one with a tiny slot budget)
    from ndstpu.engine import jaxexec
    sort_sess = Session(catalog, backend="tpu")
    exe = sort_sess._jax_executor()
    exe._DISTINCT_BITMAP_SLOTS = 0
    got_sort = sort_sess.sql(sql).to_rows()
    assert _rows_equal(got_sort, want)


def test_pivot_rewrite_fires_and_matches(catalog, cpu_sess, tpu_sess):
    """The masked-sum pivot rewrite (optimizer.pivot_case_aggregates)
    must fire on a q2-style aggregate and produce identical results."""
    sql = ("select d_week_seq, "
           "sum(case when d_day_name='Sunday' then ss_net_paid else null end) s1, "
           "sum(case when d_day_name='Monday' then ss_net_paid else null end) s2, "
           "sum(case when d_day_name='Tuesday' then ss_net_paid else null end) s3, "
           "count(*) n "
           "from store_sales join date_dim on ss_sold_date_sk = d_date_sk "
           "group by d_week_seq order by d_week_seq limit 50")
    p, _cols = cpu_sess.plan(sql)
    from ndstpu.engine import plan as lp

    def has_pivot(node):
        if isinstance(node, lp.Aggregate) and \
                any(n == "__pv_s" for n, _ in node.group_by):
            return True
        return any(has_pivot(c) for c in node.children())

    assert has_pivot(p), "pivot rewrite did not fire"
    want = cpu_sess.sql(sql).to_rows()
    got = tpu_sess.sql(sql).to_rows()
    assert _rows_equal(got, want)


def test_null_filter_left_join_becomes_anti(catalog, cpu_sess, tpu_sess):
    """q78's refresh-exclusion idiom must plan as an ANTI join, and the
    right key must still resolve (as NULL) when selected."""
    sql = ("select ss_ticket_number, sr_ticket_number "
           "from store_sales left join store_returns "
           "on sr_ticket_number = ss_ticket_number "
           "and ss_item_sk = sr_item_sk "
           "where sr_ticket_number is null "
           "order by ss_ticket_number limit 20")
    from ndstpu.engine import plan as lp
    p, _cols = cpu_sess.plan(sql)
    kinds = []

    def walk(n):
        if isinstance(n, lp.Join):
            kinds.append(n.kind)
        for c in n.children():
            walk(c)

    walk(p)
    assert "anti" in kinds, kinds
    want = cpu_sess.sql(sql).to_rows()
    got = tpu_sess.sql(sql).to_rows()
    assert len(want) == 20 and all(r[1] is None for r in want)
    assert _rows_equal(got, want)


def test_anti_rewrite_blocked_when_parent_selects_right_column(
        catalog, cpu_sess, tpu_sess):
    """Selecting a NON-key right column (legal, all-NULL) must not be
    broken by the anti-join conversion."""
    sql = ("select ss_ticket_number, sr_returned_date_sk "
           "from store_sales left join store_returns "
           "on sr_ticket_number = ss_ticket_number "
           "and ss_item_sk = sr_item_sk "
           "where sr_ticket_number is null "
           "order by ss_ticket_number limit 10")
    want = cpu_sess.sql(sql).to_rows()
    assert len(want) == 10 and all(r[1] is None for r in want)
    got = tpu_sess.sql(sql).to_rows()
    assert _rows_equal(got, want)


def test_pivot_keyless_count_on_empty_input(cpu_sess, tpu_sess):
    """A keyless pivoted aggregate over zero rows must keep count()=0
    (sum-of-partials over no rows is NULL; the rewrite coalesces)."""
    sql = ("select sum(case when d_day_name='Sunday' then d_year end) a, "
           "sum(case when d_day_name='Monday' then d_year end) b, "
           "sum(case when d_day_name='Tuesday' then d_year end) c, "
           "count(*) n "
           "from date_dim where d_year = -5")
    want = cpu_sess.sql(sql).to_rows()
    got = tpu_sess.sql(sql).to_rows()
    assert want == [(None, None, None, 0)]
    assert _rows_equal(got, want)


def test_compile_records_merge_not_truncate(catalog, tmp_path):
    """A subset session saving records must MERGE with the on-disk file
    (a 12-query validation run must never truncate a full-corpus warm),
    and the write must be atomic."""
    rec = str(tmp_path / "plans.pkl")
    s1 = Session(catalog, backend="tpu")
    s1.sql("select ss_store_sk, sum(ss_quantity) q from store_sales "
           "group by ss_store_sk").to_rows()
    s1.sql("select i_category, count(*) n from item "
           "group by i_category").to_rows()
    n1 = s1.save_compiled(rec)
    assert n1 >= 2
    s2 = Session(catalog, backend="tpu")
    s2.sql("select d_year, count(*) n from date_dim "
           "group by d_year").to_rows()
    n2 = s2.save_compiled(rec)
    assert n2 >= n1 + 1, "merge lost prior records"
    s3 = Session(catalog, backend="tpu")
    assert s3.preload_compiled(rec) >= n1 + 1


def test_sibling_scalar_agg_fusion_fires_and_matches(catalog, cpu_sess,
                                                     tpu_sess):
    """The q28 idiom (cross-joined keyless aggregates over the same
    table with disjoint-interval filters) must fuse into ONE scan +
    one grouped aggregate, and produce identical results on both
    backends — including the count(distinct) columns."""
    sql = ("select * from "
           "(select avg(ss_list_price) a1, count(ss_list_price) c1, "
           " count(distinct ss_list_price) d1 from store_sales "
           " where ss_quantity between 0 and 5) b1, "
           "(select avg(ss_list_price) a2, count(ss_list_price) c2, "
           " count(distinct ss_list_price) d2 from store_sales "
           " where ss_quantity between 6 and 10) b2, "
           "(select avg(ss_list_price) a3, count(ss_list_price) c3, "
           " count(distinct ss_list_price) d3 from store_sales "
           " where ss_quantity between 11 and 15) b3")
    from ndstpu.engine import plan as lp
    p, _cols = cpu_sess.plan(sql)
    scans = [n for n in p.walk() if isinstance(n, lp.Scan)]
    assert len(scans) == 1, "fusion did not collapse the sibling scans"
    grouped = [n for n in p.walk() if isinstance(n, lp.Aggregate)
               and any(name.endswith("_b") for name, _ in n.group_by)]
    assert grouped, "no bucket-grouped aggregate in the fused plan"
    want = cpu_sess.sql(sql).to_rows()
    got = tpu_sess.sql(sql).to_rows()
    assert len(want) == 1
    assert _rows_equal(got, want)
    # ground truth from a session with the pass disabled — both
    # backends above share the optimizer, so a systematic soundness
    # bug (e.g. buckets swapped between branches) would match itself
    from ndstpu.engine import optimizer as opt
    orig = opt.fuse_sibling_scalar_aggregates
    opt.fuse_sibling_scalar_aggregates = lambda p, _used=None: p
    try:
        unfused_sess = Session(cpu_sess.catalog, backend="cpu")
        unfused = unfused_sess.sql(sql).to_rows()
    finally:
        opt.fuse_sibling_scalar_aggregates = orig
    assert _rows_equal(want, unfused)


def test_sibling_scalar_agg_fusion_empty_bucket(catalog, cpu_sess,
                                                tpu_sess):
    """A branch whose interval matches no rows must keep scalar-
    aggregate semantics through the fusion: avg NULL, counts 0."""
    sql = ("select * from "
           "(select avg(ss_list_price) a1, count(ss_list_price) c1, "
           " count(distinct ss_list_price) d1 from store_sales "
           " where ss_quantity between 0 and 5) b1, "
           "(select avg(ss_list_price) a2, count(ss_list_price) c2, "
           " count(distinct ss_list_price) d2 from store_sales "
           " where ss_quantity between 1000000 and 1000005) b2")
    want = cpu_sess.sql(sql).to_rows()
    got = tpu_sess.sql(sql).to_rows()
    assert len(want) == 1
    assert want[0][3] is None and want[0][4] == 0 and want[0][5] == 0
    assert _rows_equal(got, want)


def test_sibling_scalar_agg_fusion_rejects_overlap(catalog, cpu_sess):
    """Overlapping intervals must NOT fuse (a row could belong to two
    branches) — and the un-fused plan must still answer correctly."""
    sql = ("select * from "
           "(select count(ss_list_price) c1 from store_sales "
           " where ss_quantity between 0 and 10) b1, "
           "(select count(ss_list_price) c2 from store_sales "
           " where ss_quantity between 5 and 15) b2")
    from ndstpu.engine import plan as lp
    p, _cols = cpu_sess.plan(sql)
    scans = [n for n in p.walk() if isinstance(n, lp.Scan)]
    assert len(scans) == 2, "overlapping intervals must not fuse"
