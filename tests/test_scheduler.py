"""In-process multi-stream throughput scheduler tests.

The tentpole claims (ndstpu/harness/scheduler.py): N streams over ONE
shared session produce per-query results identical to a serial run;
each distinct query text plans/compiles ONCE (proven by the obs cache
counters, not by timing); the admission gate bounds device-level
concurrency at ``slots`` while stream walls still overlap; and one
stream's failing query neither poisons the shared caches nor the other
streams.
"""

import json
import os
import subprocess
import threading
import time

import pytest

from ndstpu import obs
from ndstpu.engine.latch import KeyedLatch
from ndstpu.engine.sql import normalize_sql_key
from ndstpu.harness import bench as bench_mod
from ndstpu.harness.admission import InprocAdmission
from ndstpu.harness.scheduler import StreamScheduler, run_streams_inproc


@pytest.fixture(scope="module")
def env():
    return dict(os.environ, PYTHONPATH=os.getcwd())


@pytest.fixture(scope="module")
def dataset(tmp_path_factory, env):
    root = tmp_path_factory.mktemp("nds_sched")
    subprocess.run(["python", "-m", "ndstpu.datagen.driver", "local",
                    "0.002", "2", str(root / "raw")], check=True, env=env)
    subprocess.run(["python", "-m", "ndstpu.io.transcode",
                    "--input_prefix", str(root / "raw"),
                    "--output_prefix", str(root / "wh"),
                    "--report_file", str(root / "load.txt"),
                    "--output_format", "ndslake"],
                   check=True, env=env, stdout=subprocess.DEVNULL)
    return root


TINY_STREAM = (
    "-- start query 1 in stream 0 using template query1.tpl\n"
    "select i_item_sk, i_current_price from item\n"
    "where i_item_sk < 100 order by i_item_sk\n;\n"
    "-- end query 1 in stream 0 using template query1.tpl\n"
    "-- start query 2 in stream 0 using template query2.tpl\n"
    "select count(*) as cnt from store_sales\n;\n"
    "-- end query 2 in stream 0 using template query2.tpl\n")


# -- unit: the locking/admission/scheduling primitives -----------------------


def test_keyed_latch_exclusive_per_key_and_cleanup():
    latch = KeyedLatch()
    order = []
    inside = threading.Event()
    release = threading.Event()

    def holder():
        with latch.holding("k"):
            order.append("first-in")
            inside.set()
            release.wait(5)
            order.append("first-out")

    def waiter():
        inside.wait(5)
        with latch.holding("k"):
            order.append("second-in")

    t1 = threading.Thread(target=holder)
    t2 = threading.Thread(target=waiter)
    t1.start()
    t2.start()
    inside.wait(5)
    assert len(latch) == 1  # key registered while held/contended
    release.set()
    t1.join(5)
    t2.join(5)
    assert order == ["first-in", "first-out", "second-in"]
    assert len(latch) == 0  # refcount cleanup: no per-key leak


def test_keyed_latch_releases_on_exception():
    latch = KeyedLatch()
    with pytest.raises(RuntimeError):
        with latch.holding("k"):
            raise RuntimeError("boom")
    # a crashed holder must not deadlock the next arrival
    with latch.holding("k"):
        pass
    assert len(latch) == 0


def test_inproc_admission_caps_concurrency():
    gate = InprocAdmission(2)
    n_threads = 5

    def work():
        with gate.slot():
            time.sleep(0.03)

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10)
    tl = gate.device_timeline()
    assert tl["slots"] == 2
    assert 1 <= tl["max_concurrent"] <= 2
    assert tl["gated_queries"] == n_threads
    assert tl["busy_s_total"] > 0
    with pytest.raises(ValueError):
        InprocAdmission(0)


def test_stream_scheduler_cold_cheapest_first_and_sharing():
    texts = {"a": "select 1", "b": "select 2", "c": "select 3"}
    cold = {"a": 10.0, "b": 2.0, "c": 5.0}
    sched = StreamScheduler({"1": dict(texts), "2": dict(texts)},
                            est_cold=lambda n: cold[n],
                            est_warm=lambda n: 1.0)
    v1, v2 = sched.view("1"), sched.view("2")
    assert v1.next(0) == "b"  # cheapest cold prior first
    # b is in flight on stream 1 -> stream 2 starts a DIFFERENT compile
    assert v2.next(0) == "c"
    v1.done("b")
    # cold-before-warm: a (cold, 10s) outranks the published b (warm)
    # so compiles keep front-loading
    assert v2.next(0) == "a"
    assert v2.next(0) == "b"
    for n in ("c", "a", "b"):
        v2.done(n)
    # everything stream 1 still holds is compiled now: cheapest-warm
    # order with original-index tiebreak
    assert v1.next(0) == "a"
    assert v1.next(0) == "c"
    assert v1.next(0) is None
    assert not v1.skipped and not v2.skipped


def test_stream_scheduler_failed_query_not_published():
    sched = StreamScheduler({"1": {"a": "select 1"},
                             "2": {"a": "select 1"}})
    v1 = sched.view("1")
    assert v1.next(0) == "a"
    v1.done("a", failed=True)
    key = normalize_sql_key("select 1")
    assert key not in sched.compiled  # others keep their cold estimate
    assert key not in sched.inflight


def test_stream_scheduler_budget_degrades_explicitly(capsys):
    sched = StreamScheduler({"1": {"a": "select 1", "b": "select 2"}},
                            budget_s=10.0,
                            est_cold=lambda n: {"a": 4.0, "b": 20.0}[n],
                            est_warm=lambda n: 1.0)
    v = sched.view("1")
    assert v.next(0) == "a"
    v.done("a")
    # remaining 5s cannot fit b's 20s prior: explicit per-query reason
    assert v.next(5.0) is None
    assert "exceeds remaining" in v.skipped["b"]
    out = capsys.readouterr().out
    assert "[budget]" in out and "cheapest-first" in out

    sched2 = StreamScheduler({"1": {"a": "select 1"}}, budget_s=5.0)
    v2 = sched2.view("1")
    assert v2.next(6.0) is None  # already past the deadline
    assert "budget exhausted" in v2.skipped["a"]


# -- end to end: shared-session streams over a real warehouse ----------------


def _inproc_cmd(dataset, tmp_path, stream_file, *extra):
    return ["python", "-m", "ndstpu.harness.power", str(stream_file),
            str(dataset / "wh"), str(tmp_path) + "/time_{}.csv",
            "--input_format", "ndslake", *extra]


def test_inproc_parity_compile_once_and_overlap(dataset, tmp_path):
    """2 streams x same texts on one shared session: results match a
    serial run bit-for-bit, each distinct text plans once (hit counters
    >= (N-1) x distinct), and the overlap report carries both the
    device-gate peak (<= slots) and nonzero stream overlap."""
    stream_file = tmp_path / "query_0.sql"
    stream_file.write_text(TINY_STREAM)
    overlap = tmp_path / "overlap.json"
    obs.reset()
    before = obs.counters_snapshot()
    res = run_streams_inproc(
        ["1", "2"],
        _inproc_cmd(dataset, tmp_path, stream_file,
                    "--output_prefix", str(tmp_path) + "/out_{}"),
        concurrent=2, overlap_report=str(overlap))
    assert res.rc == 0 and not res.errors
    delta = obs.counter_delta(before)

    # compile-once evidence: 2 distinct texts, 4 executions -> exactly
    # 2 plan misses and >= (streams-1) x distinct = 2 plan hits
    assert delta.get("engine.cache.plan.miss") == 2
    assert delta.get("engine.cache.plan.hit", 0) >= 2

    # overlap evidence: device peak bounded by slots, stream walls
    # genuinely concurrent (two threads started together, >= 2 queries
    # each), process-compatible format plus the inproc extras
    ov = json.loads(overlap.read_text())
    assert ov["format"] == "ndstpu-throughput-overlap-v1"
    assert ov["mode"] == "inproc"
    assert ov["max_concurrent"] <= 2
    assert ov["device_timeline"]["max_concurrent"] <= 2
    assert ov["stream_max_concurrent"] == 2
    assert ov["pairwise_overlap_s"]["1&2"] > 0
    assert res.gate.device_timeline()["gated_queries"] == 4

    # per-stream results: every query ran in both streams (order is
    # the scheduler's to choose — in-flight texts defer to cold ones)
    for sid in ("1", "2"):
        assert set(res.results[sid]["executed"]) == {"query1", "query2"}
        assert res.results[sid]["failures"] == 0

    # time-log contract: bench's throughput-elapsed math parses both
    for sid in ("1", "2"):
        text = (tmp_path / f"time_{sid}.csv").read_text()
        assert "Power Start Time" in text and "Power End Time" in text
    assert bench_mod.get_throughput_time(
        str(tmp_path / "time"), 2, 1) >= 0

    # parity: stream outputs identical to each other AND to a serial
    # session over a fresh catalog
    import pyarrow.parquet as pq

    from ndstpu.engine.session import Session
    from ndstpu.harness.power import gen_sql_from_stream, run_one_query
    from ndstpu.io import loader
    serial = Session(loader.load_catalog(str(dataset / "wh")))
    for name, sql in gen_sql_from_stream(str(stream_file)).items():
        run_one_query(serial, sql, name,
                      str(tmp_path / "out_serial"), "parquet")
    for name in ("query1", "query2"):
        tables = [pq.read_table(
            tmp_path / f"out_{tag}" / name / "part-0.parquet")
            for tag in ("1", "2", "serial")]
        assert tables[0].equals(tables[1])
        assert tables[0].equals(tables[2])

    # one trace + one sidecar for the whole phase, streams tagged
    sidecar = json.loads(
        (tmp_path / "overlap.json.metrics.json").read_text())
    assert sidecar["mode"] == "inproc"
    assert {r["stream"] for r in sidecar["streams"]} == {"1", "2"}
    tagged = [q for q in obs.tracer().query_summaries()
              if (q.get("attrs") or {}).get("stream_id")]
    assert {(q["attrs"]["stream_id"], q["query"]) for q in tagged} == {
        (sid, q) for sid in ("1", "2") for q in ("query1", "query2")}


def test_inproc_shares_compiled_executor_cache(dataset, tmp_path):
    """On the accel engine the shared executor compiles each distinct
    text once: exactly ``distinct`` compiled-cache misses and
    >= (streams-1) x distinct hits across 2 streams."""
    stream_file = tmp_path / "query_0.sql"
    stream_file.write_text(TINY_STREAM)
    obs.reset()
    before = obs.counters_snapshot()
    res = run_streams_inproc(
        ["1", "2"],
        _inproc_cmd(dataset, tmp_path, stream_file, "--engine", "tpu"),
        concurrent=2)
    assert res.rc == 0 and not res.errors
    for sid in ("1", "2"):
        assert res.results[sid]["failures"] == 0
    delta = obs.counter_delta(before)
    assert delta.get("engine.cache.compiled.miss") == 2
    assert delta.get("engine.cache.compiled.hit", 0) >= 2
    assert delta.get("engine.cache.plan.miss") == 2
    assert delta.get("engine.cache.plan.hit", 0) >= 2


def test_inproc_failure_isolated_from_shared_cache(dataset, tmp_path):
    """A failing query in one stream must not poison the shared plan
    cache, mark its text compiled, or disturb the other stream."""
    bad_sql = "select nonexistent_column from item"
    (tmp_path / "query_A.sql").write_text(
        "-- start query 1 in stream 0 using template query1.tpl\n"
        f"{bad_sql}\n;\n"
        "-- end query 1 in stream 0 using template query1.tpl\n")
    (tmp_path / "query_B.sql").write_text(
        "-- start query 1 in stream 0 using template query1.tpl\n"
        "select count(*) as cnt from item\n;\n"
        "-- end query 1 in stream 0 using template query1.tpl\n")
    obs.reset()
    res = run_streams_inproc(
        ["A", "B"],
        _inproc_cmd(dataset, tmp_path, str(tmp_path) + "/query_{}.sql"),
        concurrent=2)
    # a Failed query is a recorded benchmark outcome, not a crash
    assert res.rc == 0 and not res.errors
    assert res.results["A"]["failures"] == 1
    assert res.results["B"]["failures"] == 0
    assert res.results["B"]["executed"] == ["query1"]
    bad_key = normalize_sql_key(bad_sql)
    assert bad_key not in res.session._plan_cache  # no poisoning
    assert bad_key not in res.scheduler.compiled
    assert not res.scheduler.inflight  # nothing stranded in flight


def test_inproc_rejects_divergent_stream_templates(dataset, tmp_path):
    """Streams resolving to different warehouses cannot share one
    session — explicit refusal, not a silent wrong answer."""
    stream_file = tmp_path / "query_0.sql"
    stream_file.write_text(TINY_STREAM)
    os.makedirs(tmp_path / "wh_1", exist_ok=True)
    os.makedirs(tmp_path / "wh_2", exist_ok=True)
    cmd = ["python", "-m", "ndstpu.harness.power", str(stream_file),
           str(tmp_path) + "/wh_{}", str(tmp_path) + "/time_{}.csv"]
    with pytest.raises(ValueError, match="share one input_prefix"):
        run_streams_inproc(["1", "2"], cmd)
    with pytest.raises(ValueError, match="ndstpu.harness.power"):
        run_streams_inproc(["1"], ["python", "-m", "something.else"])
