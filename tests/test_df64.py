"""Compensated float accumulation (ndstpu.engine.df64).

TPU computes float64 at f32 precision; these tests run on CPU where the
f32 ops behave identically, so the drift comparison below is an honest
simulation of the on-chip behavior (docs/STATUS.md gap 1)."""

import math

import numpy as np

import jax.numpy as jnp

from ndstpu.engine import df64


def test_two_sum_exact():
    a = jnp.float32(1e8)
    b = jnp.float32(1.5)
    s, e = df64.two_sum(a, b)
    # s + e must carry the exact sum the f32 add dropped
    assert float(s) + float(e) == 1e8 + 1.5


def test_segment_sum_matches_fsum():
    rng = np.random.RandomState(3)
    n, nseg = 4096, 37
    gid = np.sort(rng.randint(0, nseg, n)).astype(np.int64)
    x = rng.uniform(-1e6, 1e6, n)
    hi, lo = df64.segment_sum_ds(jnp.asarray(x), jnp.asarray(gid), nseg)
    got = np.asarray(hi, np.float64) + np.asarray(lo, np.float64)
    for s in range(nseg):
        want = math.fsum(x[gid == s])
        assert abs(got[s] - want) <= 2e-8 * max(1.0, abs(want)) + 1e-3, \
            (s, got[s], want)


def test_compensated_beats_naive_f32_drift():
    """Adversarial accumulation: many small values riding on a large
    one.  Naive f32 accumulation loses them entirely; the double-single
    pair keeps ~48 bits."""
    n = 100_000
    x = np.full(n, 0.001, np.float64)
    x[0] = 1e8
    want = math.fsum(np.float64(np.float32(x)))  # f32-quantized inputs
    gid = np.zeros(n, np.int64)
    hi, lo = df64.segment_sum_ds(jnp.asarray(x), jnp.asarray(gid), 1)
    got = float(np.asarray(hi, np.float64)[0] +
                np.asarray(lo, np.float64)[0])
    # sequential f32 accumulation (what a naive running sum does on
    # chip) absorbs every 0.001 into 1e8 and loses the whole stream
    naive = float(np.add.accumulate(np.float32(x))[-1])
    assert abs(naive - want) > 50.0
    assert abs(got - want) < 1.0            # pair keeps it


def test_segment_sum_empty_and_single():
    z_hi, z_lo = df64.segment_sum_ds(jnp.zeros(0), jnp.zeros(0, jnp.int64), 4)
    assert np.allclose(np.asarray(z_hi), 0)
    hi, lo = df64.segment_sum_ds(jnp.asarray([2.5]),
                                 jnp.asarray([2], dtype=jnp.int64), 4)
    out = np.asarray(hi, np.float64) + np.asarray(lo, np.float64)
    assert out[2] == 2.5 and out[0] == 0


def test_compensated_segment_sum_wrapper():
    rng = np.random.RandomState(9)
    n, nseg = 512, 5
    gid = rng.randint(0, nseg, n).astype(np.int64)
    x = rng.uniform(-100, 100, n)
    order = np.argsort(gid, kind="stable")
    got = np.asarray(df64.segment_sum_compensated(
        jnp.asarray(x), jnp.asarray(gid), nseg, jnp.asarray(order)))
    for s in range(nseg):
        want = math.fsum(x[gid == s])
        assert abs(got[s] - want) <= 1e-4, (s, got[s], want)
