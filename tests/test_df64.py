"""Compensated float accumulation (ndstpu.engine.df64).

TPU computes float64 at f32 precision; these tests run on CPU where the
f32 ops behave identically, so the drift comparison below is an honest
simulation of the on-chip behavior (docs/STATUS.md gap 1)."""

import math

import numpy as np

import jax.numpy as jnp

from ndstpu.engine import df64


def test_two_sum_exact():
    a = jnp.float32(1e8)
    b = jnp.float32(1.5)
    s, e = df64.two_sum(a, b)
    # s + e must carry the exact sum the f32 add dropped
    assert float(s) + float(e) == 1e8 + 1.5


def test_segment_sum_matches_fsum():
    rng = np.random.RandomState(3)
    n, nseg = 4096, 37
    gid = np.sort(rng.randint(0, nseg, n)).astype(np.int64)
    x = rng.uniform(-1e6, 1e6, n)
    hi, lo = df64.segment_sum_ds(jnp.asarray(x), jnp.asarray(gid), nseg)
    got = np.asarray(hi, np.float64) + np.asarray(lo, np.float64)
    for s in range(nseg):
        want = math.fsum(x[gid == s])
        assert abs(got[s] - want) <= 2e-8 * max(1.0, abs(want)) + 1e-3, \
            (s, got[s], want)


def test_compensated_beats_naive_f32_drift():
    """Adversarial accumulation: many small values riding on a large
    one.  Naive f32 accumulation loses them entirely; the double-single
    pair keeps ~48 bits."""
    n = 100_000
    x = np.full(n, 0.001, np.float64)
    x[0] = 1e8
    want = math.fsum(np.float64(np.float32(x)))  # f32-quantized inputs
    gid = np.zeros(n, np.int64)
    hi, lo = df64.segment_sum_ds(jnp.asarray(x), jnp.asarray(gid), 1)
    got = float(np.asarray(hi, np.float64)[0] +
                np.asarray(lo, np.float64)[0])
    # sequential f32 accumulation (what a naive running sum does on
    # chip) absorbs every 0.001 into 1e8 and loses the whole stream
    naive = float(np.add.accumulate(np.float32(x))[-1])
    assert abs(naive - want) > 50.0
    assert abs(got - want) < 1.0            # pair keeps it


def test_segment_sum_empty_and_single():
    z_hi, z_lo = df64.segment_sum_ds(jnp.zeros(0), jnp.zeros(0, jnp.int64), 4)
    assert np.allclose(np.asarray(z_hi), 0)
    hi, lo = df64.segment_sum_ds(jnp.asarray([2.5]),
                                 jnp.asarray([2], dtype=jnp.int64), 4)
    out = np.asarray(hi, np.float64) + np.asarray(lo, np.float64)
    assert out[2] == 2.5 and out[0] == 0


def test_compensated_segment_sum_wrapper():
    rng = np.random.RandomState(9)
    n, nseg = 512, 5
    gid = rng.randint(0, nseg, n).astype(np.int64)
    x = rng.uniform(-100, 100, n)
    order = np.argsort(gid, kind="stable")
    got = np.asarray(df64.segment_sum_compensated(
        jnp.asarray(x), jnp.asarray(gid), nseg, jnp.asarray(order)))
    for s in range(nseg):
        want = math.fsum(x[gid == s])
        assert abs(got[s] - want) <= 1e-4, (s, got[s], want)


def test_stddev_no_cancellation_all_backends():
    """mean/stddev ratio ~1e6: the raw-moment formula E[x^2]-E[x]^2
    loses ~12 digits here and fails the validator's 1e-5 epsilon
    (nds_validate.py:194-215 analog); the shifted two-pass / Chan
    combine must hold it on every backend."""
    from ndstpu.engine.columnar import Column, FLOAT64, INT32, Table
    from ndstpu.engine.session import Session
    from ndstpu.io.loader import Catalog

    rng = np.random.RandomState(7)
    n = 8192
    g = rng.randint(0, 4, n).astype(np.int32)
    x = 1e6 + rng.standard_normal(n)          # mean ~1e6, stddev ~1
    cat = Catalog()
    cat.register("t", Table({"g": Column(g, INT32),
                             "x": Column(x, FLOAT64)}))
    want = {}
    for gg in range(4):
        want[gg] = float(np.std(x[g == gg], ddof=1))
    sql = "select g, stddev_samp(x) as s, var_samp(x) as v " \
          "from t group by g order by g"
    for backend in ("cpu", "tpu", "tpu-spmd"):
        sess = Session(cat, backend=backend, spmd_threshold=1)
        rows = sess.sql(sql).to_rows()
        assert len(rows) == 4, (backend, rows)
        for gg, s, v in rows:
            rel = abs(s - want[gg]) / want[gg]
            assert rel < 1e-5, (backend, gg, s, want[gg], rel)
            assert abs(v - want[gg] ** 2) / want[gg] ** 2 < 1e-5
        if backend == "tpu-spmd":
            assert not getattr(sess, "_spmd_errors", None), \
                sess._spmd_errors
            assert getattr(sess, "_spmd_used", False)
