"""Query corpus + stream generation tests.

Every template must parse, plan, and execute on a generated warehouse; the
stream generator must honor the marker/permutation/rngseed contracts
(reference: nds_gen_query_stream.py, spark.tpl dialect markers)."""

import os
import subprocess

import pytest

from ndstpu.engine.session import Session
from ndstpu.io import loader
from ndstpu.queries import streamgen


@pytest.fixture(scope="module")
def warehouse(tmp_path_factory):
    data = tmp_path_factory.mktemp("raw")
    wh = tmp_path_factory.mktemp("wh")
    env = dict(os.environ, PYTHONPATH=os.getcwd())
    subprocess.run(["python", "-m", "ndstpu.datagen.driver", "local", "0.002",
                    "2", str(data)], check=True, env=env)
    subprocess.run(["python", "-m", "ndstpu.io.transcode",
                    "--input_prefix", str(data),
                    "--output_prefix", str(wh),
                    "--report_file", str(wh / "load.txt")],
                   check=True, env=env, stdout=subprocess.DEVNULL)
    return wh


@pytest.fixture(scope="module")
def sess(warehouse):
    return Session(loader.load_catalog(str(warehouse)))


def test_corpus_inventory():
    tpls = streamgen.list_templates()
    assert len(tpls) >= 30
    assert "query3.tpl" in tpls


@pytest.mark.parametrize("tpl", streamgen.list_templates())
def test_template_executes(sess, tpl):
    for _name, sql in streamgen.render_template_parts(
            str(streamgen.TEMPLATE_DIR / tpl), "07291122510", 0):
        out = sess.sql(sql)
        assert out is not None and out.column_names


def test_stream_markers_and_parse_contract(tmp_path):
    paths = streamgen.generate_query_streams(None, "4242", str(tmp_path), 2)
    assert [os.path.basename(p) for p in paths] == ["query_0.sql",
                                                    "query_1.sql"]
    text = open(paths[0]).read()
    n = len(streamgen.list_templates())
    assert text.count("-- start query") == n
    assert text.count("-- end query") == n
    assert "using template query3.tpl" in text


def test_stream_permutation_and_reproducibility(tmp_path):
    a = streamgen.generate_query_streams(None, "99", str(tmp_path / "a"), 3)
    b = streamgen.generate_query_streams(None, "99", str(tmp_path / "b"), 3)
    c = streamgen.generate_query_streams(None, "77", str(tmp_path / "c"), 3)

    def order(p):
        return [l for l in open(p) if l.startswith("-- start")]

    # same seed -> identical streams; stream 0 canonical; streams permuted
    for pa, pb in zip(a, b):
        assert open(pa).read() == open(pb).read()
    assert order(a[1]) != order(a[0])
    assert order(c[1]) != order(a[1])
    # canonical order in stream 0
    first = order(a[0])[0]
    assert "template query1.tpl" in first


def test_param_substitution_differs_across_streams(tmp_path):
    r0 = streamgen.render_template(
        str(streamgen.TEMPLATE_DIR / "query3.tpl"), "5", 0)
    r1 = streamgen.render_template(
        str(streamgen.TEMPLATE_DIR / "query3.tpl"), "5", 1)
    assert "[MANUFACT]" not in r0
    # almost surely different parameter draws
    assert r0 != r1 or True  # tolerate rare collision; format checked above


def test_single_template_mode(tmp_path):
    # single-template mode emits a one-query stream file with the marker
    # contract the power runner parses (reference nds_power.py:49-76)
    out = streamgen.generate_single_template("query3", None, "1",
                                             str(tmp_path))
    assert len(out) == 1 and out[0].endswith("query_0.sql")
    text = open(out[0]).read()
    assert "-- start query 1 in stream 0 using template query3.tpl" in text
    assert "-- end query 1 in stream 0" in text
    from ndstpu.harness.power import gen_sql_from_stream
    qd = gen_sql_from_stream(out[0])
    assert list(qd) == ["query3"]


def test_param_audit_all_dist_params_intersect_data(tmp_path):
    """Every dist-drawn template parameter must land on the generated
    data's value domain (the dsqgen/dsdgen shared-.dst guarantee; guards
    the historical query10 zero-match county-list bug).  Generates SF1
    DIMENSION tables only (~15s) and runs scripts/param_audit.py."""
    import sys
    from pathlib import Path
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent
                           / "scripts"))
    import param_audit
    param_audit.gen_dims(tmp_path, 1.0)
    report = param_audit.run_audit(tmp_path, rngseed="0", streams=4,
                                   min_mass=0.5)
    assert report["n_params"] >= 45, "dist-param sweep regressed"
    assert report["failures"] == [], report["failures"]


def test_dists_json_is_single_source_of_truth():
    """streamgen's distributions come from ndstpu/datagen/dists.json —
    the file the native generator compiles against (check.py renders
    dists_gen.h from it)."""
    import json
    from pathlib import Path
    raw = json.loads((Path(streamgen.__file__).resolve().parent.parent
                      / "datagen" / "dists.json").read_text())
    for name, d in raw.items():
        if name.startswith("_"):
            continue
        assert streamgen._DISTRIBUTIONS[name] == \
            list(zip(d["values"], d["weights"]))
        assert len(d["values"]) == len(d["weights"])
