"""Run ledger, regression sentinel, and budget-queue unit tests
(ndstpu/obs/ledger.py, ndstpu/obs/sentinel.py,
ndstpu/harness/progress.py — docs/OBSERVABILITY.md)."""

import json
import os

import pytest

from ndstpu.harness import progress
from ndstpu.obs import ledger as ledger_mod
from ndstpu.obs import sentinel

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------- ledger

def test_make_entry_derives_warmth_from_split():
    cold = ledger_mod.make_entry("q1", 10.0, compile_s=8.0,
                                 execute_s=2.0)
    assert cold["warmth"] == "cold"
    warm = ledger_mod.make_entry("q1", 10.0, compile_s=0.0,
                                 execute_s=9.9)
    assert warm["warmth"] == "warm"
    # explicit warmth (legacy artifacts) wins over the split
    forced = ledger_mod.make_entry("q1", 10.0, compile_s=8.0,
                                   warmth="warm")
    assert forced["warmth"] == "warm"


def test_fingerprint_distinguishes_configs():
    fps = {ledger_mod.make_entry("q1", 1.0, engine=e, scale_factor=sf,
                                 seed=sd)["fingerprint"]
           for e in ("cpu", "tpu") for sf in ("1", "10")
           for sd in ("bench", "777")}
    assert len(fps) == 8


def test_append_reload_roundtrip_and_corrupt_tolerance(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    led = ledger_mod.Ledger(path)
    led.record_query("query1", 2.0, 0.0, 1.9, engine="cpu",
                     scale_factor="1", seed="s", source="t1")
    led.record_query("query2", 3.0, 2.9, 0.1, engine="cpu",
                     scale_factor="1", seed="s", source="t1")
    # interrupted append / junk must not poison the history
    with open(path, "a") as f:
        f.write("{truncated json\n")
        f.write("[1,2,3]\n")
    led2 = ledger_mod.Ledger(path)
    assert len(led2) == 2
    assert led2.corrupt_lines == 2
    assert led2.best_warm("query1", engine="cpu",
                          scale_factor="1") == 2.0


def test_best_warm_uses_cold_execute_split_as_proxy():
    led = ledger_mod.Ledger(path=None)
    # first-ever run is cold: 60s wall, 55 compile, 5 execute
    led.record_query("query4", 60.0, 55.0, 5.0, engine="tpu",
                     scale_factor="1")
    # the split seeds the baseline — a second warm run at 5.2s is flat,
    # not "regressed vs nothing" and not judged against the 60s wall
    assert led.best_warm("query4", engine="tpu",
                         scale_factor="1") == 5.0
    v = sentinel.classify_query("query4", 5.2, 0.0, 5.2, 5.0)
    assert v["verdict"] == "flat"


def test_prior_scope_strict_but_estimate_relaxes():
    led = ledger_mod.Ledger(path=None)
    led.record_query("query5", 1.5, 0.0, 1.5, engine="cpu",
                     scale_factor="1")
    assert led.best_warm("query5", engine="tpu",
                         scale_factor="1") is None
    assert led.best_warm("query5", engine="cpu",
                         scale_factor="10") is None
    # the ETA estimator relaxes scope: any history beats no history
    assert led.estimate("query5", engine="tpu",
                        scale_factor="10") == 1.5
    assert led.estimate("missing", engine="cpu", default=7.0) == 7.0


def test_expected_cold_is_median():
    led = ledger_mod.Ledger(path=None)
    for wall in (10.0, 30.0, 20.0):
        led.record_query("query6", wall, compile_s=wall * 0.9,
                         execute_s=wall * 0.1, engine="tpu",
                         scale_factor="1")
    assert led.expected_cold("query6", engine="tpu",
                             scale_factor="1") == 20.0


def test_ingest_legacy_shapes(tmp_path):
    warm = tmp_path / "WARM.json"
    warm.write_text(json.dumps({
        "discover": {"query1": 12.0}, "steady": {"query1": 0.4},
        "failed": [], "note": "x"}))
    bench = tmp_path / "BENCH_r99.json"
    bench.write_text(json.dumps({
        "n": 99, "cmd": "python x", "rc": 0,
        "parsed": {"metric": "m", "value": 1.0, "elapsed_s": 100.0}}))
    sidecar = tmp_path / "t.csv.metrics.json"
    sidecar.write_text(json.dumps({
        "engine": "cpu",
        "queries": [{"query": "query2", "wall_s": 1.0,
                     "compile_s": 0.0, "execute_s": 0.98,
                     "mode": "warm"}],
        "totals": {}}))
    led = ledger_mod.Ledger(path=None)
    assert led.ingest_file(str(warm), engine="tpu",
                           scale_factor="1") == 2
    assert led.ingest_file(str(bench)) == 1
    assert led.ingest_file(str(sidecar), scale_factor="1") == 1
    # warmth came through: discover=cold, steady=warm
    assert led.best_warm("query1", engine="tpu",
                         scale_factor="1") == 0.4
    assert led.expected_cold("query1", engine="tpu",
                             scale_factor="1") == 12.0
    assert led.best_warm("query2", engine="cpu",
                         scale_factor="1") == 1.0
    # re-ingest is a no-op (dedupe)
    assert led.ingest_file(str(warm), engine="tpu",
                           scale_factor="1") == 0


def test_ingest_committed_history():
    led = ledger_mod.Ledger(path=None)
    counts = led.ingest_history(REPO)
    # the committed warm-corpus artifact alone carries >100 queries
    assert sum(counts.values()) > 100
    assert led.best_warm("query1", engine="tpu",
                         scale_factor="1") is not None


# -------------------------------------------------------------- sentinel

def test_cold_compile_is_never_a_regression():
    # 60s wall vs a 1s baseline would be a 60x "regression" — but the
    # split says it was compile work, so the verdict is cold-compile
    v = sentinel.classify_query("q", 60.0, 55.0, 5.0, 1.0)
    assert v["verdict"] == "cold-compile"


@pytest.mark.parametrize("wall,base,verdict", [
    (2.0, 1.0, "regressed"),       # +1s, 2x: beyond both guards
    (1.2, 1.0, "flat"),            # +0.2s: under the 0.25s floor
    (1.3, 1.1, "flat"),            # +18%: under the 25% relative tol
    (0.5, 1.0, "improved"),
    (0.9, 1.0, "flat"),
    (1.0, None, "new"),
])
def test_warm_verdict_table(wall, base, verdict):
    v = sentinel.classify_query("q", wall, 0.0, wall, base)
    assert v["verdict"] == verdict, v


def test_classify_run_counts_and_failed():
    led = ledger_mod.Ledger(path=None)
    led.record_query("query1", 1.0, 0.0, 1.0, engine="cpu",
                     scale_factor="1")
    qsums = [
        {"query": "query1", "wall_s": 1.02, "compile_s": 0.0,
         "execute_s": 1.02},
        {"query": "query2", "wall_s": 9.0, "compile_s": 8.5,
         "execute_s": 0.5},
        {"query": "query3", "wall_s": 0.1, "compile_s": 0.0,
         "execute_s": 0.1, "attrs": {"error": "boom"}},
    ]
    res = sentinel.classify_run(qsums, led, engine="cpu",
                                scale_factor="1")
    assert res["counts"] == {"flat": 1, "cold-compile": 1, "failed": 1}
    assert res["regressions"] == []
    md = sentinel.markdown_table(res)
    assert "| query1 |" in md and "cold-compile" in md


def test_regression_exits_reports(tmp_path):
    led = ledger_mod.Ledger(path=None)
    led.record_query("query1", 1.0, 0.0, 1.0, engine="cpu",
                     scale_factor="1")
    res = sentinel.classify_run(
        [{"query": "query1", "wall_s": 3.0, "compile_s": 0.0,
          "execute_s": 3.0}], led, engine="cpu", scale_factor="1")
    assert res["regressions"] == ["query1"]
    paths = sentinel.write_reports(res,
                                   str(tmp_path / "REGRESSIONS.json"),
                                   str(tmp_path / "REGRESSIONS.md"))
    with open(paths["json"]) as f:
        assert json.load(f)["regressions"] == ["query1"]


# -------------------------------------------------------- budget / queue

def test_budgeted_queue_fifo_without_budget():
    q = progress.BudgetedQueue(["a", "b", "c"], None, None)
    assert [q.next(0), q.next(0), q.next(0), q.next(0)] == \
        ["a", "b", "c", None]
    assert q.skipped == {}


def test_budgeted_queue_reorders_cheapest_first_then_cuts():
    est = {"a": 1.0, "b": 100.0, "c": 2.0}.get
    events = []
    q = progress.BudgetedQueue(["b", "a", "c"], 10.0, est, phase="p",
                               on_event=events.append)
    order, elapsed = [], 0.0
    while True:
        n = q.next(elapsed)
        if n is None:
            break
        order.append(n)
        elapsed += est(n)
    assert order == ["a", "c"]
    assert set(q.skipped) == {"b"}
    assert "prior" in q.skipped["b"] and "budget" in q.skipped["b"]
    assert any("cheapest-first" in e for e in events)


def test_budgeted_queue_cuts_everything_when_exhausted():
    q = progress.BudgetedQueue(["a", "b"], 5.0, lambda n: 1.0,
                               on_event=lambda s: None)
    assert q.next(6.0) is None
    assert sorted(q.skipped) == ["a", "b"]
    for reason in q.skipped.values():
        assert "exhausted" in reason


def test_heartbeat_line_grammar():
    lines = []
    hb = progress.Heartbeat("power", total=9, budget_s=100.0,
                            out=lines.append)
    hb.beat(3, "query7", 12.5, eta_s=40.0)
    assert lines == ["[heartbeat] power 3/9 query7 elapsed=12.5s "
                     "eta=40.0s budget=100s remaining=87.5s"]


def test_ledger_estimator_feeds_queue():
    led = ledger_mod.Ledger(path=None)
    led.record_query("query1", 2.5, 0.0, 2.5, engine="cpu",
                     scale_factor="1")
    est = progress.ledger_estimator(led, engine="cpu",
                                    scale_factor="1")
    q = progress.BudgetedQueue(["query1", "queryX"], 100.0, est)
    assert q.cost("query1") == 2.5
    assert q.cost("queryX") == progress.DEFAULT_COST_S
    assert progress.ledger_estimator(None)("query1") is None


# ------------------------------------------- snapshot-epoch awareness


def _epoch_ledger():
    """Warm baselines under epoch eAAA plus one unstamped legacy row."""
    led = ledger_mod.Ledger(path=None)
    led.record_query("query1", 2.0, 0.0, 1.9, engine="cpu",
                     scale_factor="1", extra={"snapshot_epoch": "eAAA"})
    led.record_query("query2", 3.0, 0.0, 2.9, engine="cpu",
                     scale_factor="1", extra={"snapshot_epoch": "eAAA"})
    led.record_query("query3", 4.0, 0.0, 3.9, engine="cpu",
                     scale_factor="1")  # legacy: no epoch stamp
    return led


def test_best_warm_scopes_to_snapshot_epoch():
    led = _epoch_ledger()
    # same epoch: baseline applies
    assert led.best_warm("query1", engine="cpu", scale_factor="1",
                         snapshot_epoch="eAAA") == 2.0
    # other epoch: the data changed — the eAAA wall must not be used
    assert led.best_warm("query1", engine="cpu", scale_factor="1",
                         snapshot_epoch="eBBB") is None
    # no epoch given (legacy caller): everything stays comparable
    assert led.best_warm("query1", engine="cpu",
                         scale_factor="1") == 2.0
    # unstamped legacy entries qualify under ANY epoch
    assert led.best_warm("query3", engine="cpu", scale_factor="1",
                         snapshot_epoch="eBBB") == 4.0


def test_warm_epochs_lists_stamped_epochs():
    led = _epoch_ledger()
    led.record_query("query1", 2.5, 0.0, 2.4, engine="cpu",
                     scale_factor="1", extra={"snapshot_epoch": "eCCC"})
    assert led.warm_epochs("query1", engine="cpu",
                           scale_factor="1") == {"eAAA", "eCCC"}
    # legacy unstamped entries contribute no epoch
    assert led.warm_epochs("query3", engine="cpu",
                           scale_factor="1") == set()


def test_sentinel_data_changed_not_regressed_across_epochs():
    """A warm wall 10x the baseline under a DIFFERENT snapshot epoch
    is the data changing, not the engine regressing."""
    led = _epoch_ledger()
    run = [{"query": "query1", "wall_s": 20.0, "compile_s": 0.0,
            "execute_s": 19.9}]
    res = sentinel.classify_run(run, led, engine="cpu",
                                scale_factor="1",
                                snapshot_epoch="eBBB")
    v = res["verdicts"][0]
    assert v["verdict"] == "data-changed"
    assert "eAAA" in v["reason"]
    assert res["regressions"] == []
    # the SAME wall under the SAME epoch is a genuine regression
    res2 = sentinel.classify_run(run, led, engine="cpu",
                                 scale_factor="1",
                                 snapshot_epoch="eAAA")
    assert res2["verdicts"][0]["verdict"] == "regressed"


def test_sentinel_epoch_unstamped_stays_comparable():
    """Legacy ledgers (no epoch stamps) keep classifying normally under
    an epoch-stamped run — no data-changed false positives."""
    led = ledger_mod.Ledger(path=None)
    led.record_query("query1", 2.0, 0.0, 1.9, engine="cpu",
                     scale_factor="1")
    run = [{"query": "query1", "wall_s": 2.1, "compile_s": 0.0,
            "execute_s": 2.0}]
    res = sentinel.classify_run(run, led, engine="cpu",
                                scale_factor="1",
                                snapshot_epoch="eNEW")
    assert res["verdicts"][0]["verdict"] == "flat"


def test_sentinel_genuinely_new_query_stays_new_under_epoch():
    led = _epoch_ledger()
    run = [{"query": "query9", "wall_s": 1.0, "compile_s": 0.0,
            "execute_s": 0.9}]
    res = sentinel.classify_run(run, led, engine="cpu",
                                scale_factor="1",
                                snapshot_epoch="eBBB")
    assert res["verdicts"][0]["verdict"] == "new"
