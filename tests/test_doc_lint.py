"""Doc/artifact citation lint tests (ndstpu/obs/artifact_lint.py,
scripts/doc_lint.py) — the committed tree must never cite a ghost
artifact, and stale perf artifacts must say so."""

import json
import os
import subprocess
import sys

from ndstpu.obs import artifact_lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_missing_citation_fails(tmp_path):
    (tmp_path / "docs").mkdir()
    text = "See `docs/GHOST_BENCH.json` for the numbers.\n"
    findings = artifact_lint.lint_text(text, str(tmp_path), doc="d.md")
    assert len(findings) == 1
    assert "docs/GHOST_BENCH.json" in findings[0]


def test_present_artifact_and_pending_marker_pass(tmp_path):
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "REAL.json").write_text("{}")
    text = ("cites `docs/REAL.json` (committed)\n"
            "and `docs/FUTURE.json` is pending a hardware run\n"
            "plus an uncommitted `BENCH_r99.json` snapshot\n")
    assert artifact_lint.lint_text(text, str(tmp_path)) == []


def test_bench_root_citations_checked(tmp_path):
    text = "headline in `BENCH_r42.json`\n"
    assert artifact_lint.lint_text(text, str(tmp_path)) != []
    (tmp_path / "BENCH_r42.json").write_text("{}")
    assert artifact_lint.lint_text(text, str(tmp_path)) == []


def test_plan_lint_root_citations_checked(tmp_path):
    text = "static verdicts in `PLAN_LINT.json` and `PLAN_LINT.md`\n"
    findings = artifact_lint.lint_text(text, str(tmp_path))
    assert len(findings) == 2
    (tmp_path / "PLAN_LINT.json").write_text("{}")
    (tmp_path / "PLAN_LINT.md").write_text("# lint\n")
    assert artifact_lint.lint_text(text, str(tmp_path)) == []


def test_canon_audit_root_citations_checked(tmp_path):
    text = "collapse sweep in `CANON_AUDIT.json` and `CANON_AUDIT.md`\n"
    findings = artifact_lint.lint_text(text, str(tmp_path))
    assert len(findings) == 2
    (tmp_path / "CANON_AUDIT.json").write_text("{}")
    (tmp_path / "CANON_AUDIT.md").write_text("# canon\n")
    assert artifact_lint.lint_text(text, str(tmp_path)) == []


def test_cost_lint_root_citations_checked(tmp_path):
    text = "cost sweep in `COST_LINT.json` and `COST_LINT.md`\n"
    findings = artifact_lint.lint_text(text, str(tmp_path))
    assert len(findings) == 2
    (tmp_path / "COST_LINT.json").write_text("{}")
    (tmp_path / "COST_LINT.md").write_text("# cost\n")
    assert artifact_lint.lint_text(text, str(tmp_path)) == []


def test_config_mismatch_flagged_unless_stale(tmp_path):
    docs = tmp_path / "docs"
    docs.mkdir()
    current = {"NDSTPU_GROUPBY": "pallas"}
    art = {"engine_defaults": {"NDSTPU_GROUPBY": "auto"}, "data": {}}
    (docs / "A.json").write_text(json.dumps(art))
    findings = artifact_lint.artifact_config_mismatches(
        str(tmp_path), current=current)
    assert len(findings) == 1 and "NDSTPU_GROUPBY" in findings[0]
    # the stale stamp is the escape hatch: artifact admits its age
    art["stale"] = True
    (docs / "A.json").write_text(json.dumps(art))
    assert artifact_lint.artifact_config_mismatches(
        str(tmp_path), current=current) == []


def test_current_defaults_parsed_from_source():
    cur = artifact_lint.current_engine_defaults(REPO)
    assert cur.get("NDSTPU_GROUPBY") in ("pallas", "auto", "sort")


def test_committed_tree_is_clean():
    assert artifact_lint.lint_repo(REPO) == []


def test_cli_exit_codes(tmp_path):
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "doc_lint.py")],
        capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    # a tree citing a ghost artifact fails
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "bad.md").write_text(
        "numbers in `docs/NOT_THERE.json`\n")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "doc_lint.py"),
         "--root", str(tmp_path)],
        capture_output=True, text=True, env=env)
    assert r.returncode == 1
    assert "NOT_THERE" in r.stdout


def test_run_state_citation_is_recognized_but_runtime_exempt(tmp_path):
    """`RUN_STATE.json` is a per-run resume journal
    (docs/ROBUSTNESS.md): citing it must never demand a committed
    file — while ghost doc artifacts in the same text still flag."""
    text = ("the bench driver journals phases to `RUN_STATE.json`\n"
            "and cites `docs/GHOST.json` for numbers\n")
    (tmp_path / "docs").mkdir()
    findings = artifact_lint.lint_text(text, str(tmp_path), doc="d.md")
    assert len(findings) == 1
    assert "GHOST" in findings[0]
    assert not any("RUN_STATE" in f for f in findings)


def test_ingest_diff_citation_is_recognized_but_runtime_exempt(tmp_path):
    """`INGEST_DIFF.json` is the ingest differential's per-run artifact
    (scripts/ingest_smoke.py): recognized as a citation, exempt from
    the committed-file existence check."""
    text = ("the ingest smoke writes `INGEST_DIFF.json` per run\n"
            "and cites `docs/GHOST.json` for numbers\n")
    (tmp_path / "docs").mkdir()
    findings = artifact_lint.lint_text(text, str(tmp_path), doc="d.md")
    assert len(findings) == 1 and "GHOST" in findings[0]
    assert not any("INGEST_DIFF" in f for f in findings)
    assert any("INGEST_DIFF.json" in m.group(0)
               for m in artifact_lint.CITED_RE.finditer(text))


def test_fleet_health_citation_is_recognized_but_runtime_exempt(tmp_path):
    """`FLEET_HEALTH.json` is the fleet supervisor's per-run artifact
    (serve/fleet.py): recognized as a citation, exempt from the
    committed-file existence check."""
    text = ("the supervisor writes `FLEET_HEALTH.json` per monitor pass\n"
            "and cites `docs/GHOST.json` for numbers\n")
    (tmp_path / "docs").mkdir()
    findings = artifact_lint.lint_text(text, str(tmp_path), doc="d.md")
    assert len(findings) == 1 and "GHOST" in findings[0]
    assert not any("FLEET_HEALTH" in f for f in findings)
    assert any("FLEET_HEALTH.json" in m.group(0)
               for m in artifact_lint.CITED_RE.finditer(text))
