"""Observability: span tracer, cost attribution, exports, instruments.

Covers the tentpole contracts (docs/OBSERVABILITY.md): span
nesting/ordering invariants, bucket self-time accounting (buckets sum
to collector wall within tolerance), cache hit/miss counters across a
scripted cold-then-warm session, Chrome-trace validity (matched B/E
pairs), the NDSTPU_TRACE=0 no-op path leaving query output
byte-identical, the BenchReport ``metrics`` block, and the >=90%
per-query attribution acceptance bar over a multi-query power-style
stream.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from ndstpu import obs
from ndstpu.engine import columnar
from ndstpu.engine.columnar import INT32, Column
from ndstpu.engine.session import Session
from ndstpu.io.loader import Catalog


@pytest.fixture(autouse=True)
def fresh_tracer():
    """Each test gets its own enabled tracer; the global is restored to
    env-default afterwards so other test modules are unaffected."""
    obs.reset(enabled=True)
    yield obs.tracer()
    obs.reset()


def col_i32(vals):
    return Column(np.array(vals, dtype=np.int32), INT32, None)


def tiny_catalog() -> Catalog:
    cat = Catalog()
    cat.register("item", columnar.Table({
        "i_item_sk": col_i32(list(range(1, 21))),
        "i_brand_id": col_i32([i % 3 for i in range(20)]),
    }))
    cat.register("sales", columnar.Table({
        "s_item_sk": col_i32([i % 20 + 1 for i in range(60)]),
        "s_qty": col_i32([i % 7 for i in range(60)]),
        "s_price": col_i32([100 + i for i in range(60)]),
    }))
    return cat


FIVE_QUERIES = [
    "select s_item_sk, sum(s_qty) as q from sales group by s_item_sk "
    "order by q desc limit 5",
    "select i_brand_id, count(*) as n from item group by i_brand_id",
    "select sum(s_price) as total from sales where s_qty > 2",
    "select i_brand_id, sum(s_qty) as q from sales, item "
    "where s_item_sk = i_item_sk group by i_brand_id order by i_brand_id",
    "select avg(s_price) as p, max(s_qty) as m from sales",
]


# -- span model ---------------------------------------------------------------


def test_span_nesting_and_ordering(fresh_tracer):
    t = fresh_tracer
    with t.span("outer", cat="query", collect=True):
        with t.span("mid", cat="plan-node"):
            with t.span("inner", cat="plan-node"):
                pass
        with t.span("sibling", cat="plan-node"):
            pass
    names = [e["name"] for e in t.events]
    # events append in END order: children before parents, siblings in
    # completion order
    assert names == ["inner", "mid", "sibling", "outer"]
    depth = {e["name"]: e["depth"] for e in t.events}
    assert depth == {"outer": 0, "mid": 1, "inner": 2, "sibling": 1}
    seq = {e["name"]: e["seq"] for e in t.events}
    # seq is assigned at OPEN: parents before their children
    assert seq["outer"] < seq["mid"] < seq["inner"] < seq["sibling"]
    # timestamps nest: children start no earlier, end no later
    ev = {e["name"]: e for e in t.events}
    for child, parent in (("mid", "outer"), ("inner", "mid"),
                          ("sibling", "outer")):
        c, p = ev[child], ev[parent]
        assert c["ts_epoch_s"] >= p["ts_epoch_s"] - 1e-6
        assert (c["ts_epoch_s"] + c["wall_s"]
                <= p["ts_epoch_s"] + p["wall_s"] + 1e-6)


def test_buckets_sum_to_collector_wall(fresh_tracer):
    """Self-time accounting: nested bucketed spans never double count,
    and a fully-bucketed tree's totals equal the collector wall."""
    import time
    t = fresh_tracer
    with t.span("q", cat="query", collect=True) as q:
        with t.span("stmt", cat="plan-node", bucket="execute_s"):
            with t.span("discover", cat="plan-node", bucket="compile_s"):
                time.sleep(0.02)
            with t.span("build", cat="plan-node", bucket="compile_s"):
                time.sleep(0.01)
            time.sleep(0.02)
    total = sum(q.buckets.values())
    assert q.buckets["compile_s"] >= 0.03 - 1e-3
    assert q.buckets["execute_s"] >= 0.02 - 1e-3
    # buckets cover the whole wall here (everything inside is bucketed)
    assert total <= q.wall_s + 1e-6
    assert total >= 0.95 * q.wall_s


def test_transparent_span_propagates_bucketed_time(fresh_tracer):
    """A non-bucketed span between two bucketed ones must still
    subtract its bucketed children from the outer span's self time."""
    import time
    t = fresh_tracer
    with t.span("q", cat="query", collect=True) as q:
        with t.span("outer", cat="plan-node", bucket="execute_s"):
            with t.span("transparent", cat="plan-node"):
                with t.span("inner", cat="plan-node",
                            bucket="compile_s"):
                    time.sleep(0.02)
    # compile time is NOT also counted as execute self time
    assert q.buckets["compile_s"] >= 0.02 - 1e-3
    assert q.buckets.get("execute_s", 0.0) < 0.02
    assert sum(q.buckets.values()) <= q.wall_s + 1e-6


def test_collector_rollup_to_stream(fresh_tracer):
    t = fresh_tracer
    with t.span("stream", cat="stream", collect=True) as st:
        for qn in ("q1", "q2"):
            with t.span(qn, cat="query", collect=True):
                with t.span("work", cat="plan-node",
                            bucket="execute_s"):
                    pass
    assert st.buckets.get("execute_s", 0.0) > 0.0
    assert len(t.query_summaries()) == 2


def test_cross_thread_fallback_collector(fresh_tracer):
    """A span opened on a worker thread with an empty stack attributes
    to the process's open collector (the power watchdog pattern)."""
    import threading
    t = fresh_tracer
    with t.span("q", cat="query", collect=True) as q:
        def work():
            with t.span("engine_work", cat="plan-node",
                        bucket="execute_s"):
                pass
        th = threading.Thread(target=work)
        th.start()
        th.join()
    assert q.buckets.get("execute_s", 0.0) > 0.0


def test_disabled_tracer_is_noop(monkeypatch):
    from ndstpu.obs.trace import env_enabled
    monkeypatch.setenv("NDSTPU_TRACE", "0")
    assert not env_enabled()
    monkeypatch.setenv("NDSTPU_TRACE", "false")
    assert not env_enabled()
    monkeypatch.delenv("NDSTPU_TRACE")
    assert env_enabled()
    t = obs.reset(enabled=False)
    with obs.span("x", cat="query", collect=True) as sp:
        obs.inc("some.counter")
        obs.set_gauge("some.gauge", 3)
    assert sp is obs.NULL_SPAN
    assert t.events == [] and t.counters == {} and t.gauges == {}


# -- engine integration -------------------------------------------------------


def test_cache_counters_cold_then_warm(fresh_tracer):
    """A scripted cold-then-replay session: the first run misses every
    cache and discovers; the replay hits the compiled-plan cache and
    classifies warm with ~zero compile seconds."""
    sess = Session(tiny_catalog(), backend="tpu")
    sql = FIVE_QUERIES[0]

    with obs.span("cold", cat="query", collect=True):
        sess.sql(sql).to_rows()
    cold = obs.counters_snapshot()
    assert cold.get("engine.cache.compiled.miss", 0) == 1
    assert cold.get("engine.discoveries", 0) == 1
    assert cold.get("engine.cache.compiled.hit", 0) == 0

    with obs.span("warm", cat="query", collect=True):
        sess.sql(sql).to_rows()
    delta = obs.counter_delta(cold)
    assert delta.get("engine.cache.compiled.hit", 0) == 1
    assert "engine.discoveries" not in delta

    summaries = obs.tracer().query_summaries()
    assert [s["query"] for s in summaries] == ["cold", "warm"]
    assert summaries[0]["mode"] == "cold"
    assert summaries[1]["mode"] == "warm"
    assert summaries[1]["compile_s"] <= 0.05 * summaries[1]["wall_s"] + 1e-4


def test_trace_off_query_output_identical(fresh_tracer):
    """NDSTPU_TRACE=0 must not perturb results: bytes out are identical
    with tracing on and off."""
    sql = FIVE_QUERIES[3]
    sess_on = Session(tiny_catalog(), backend="tpu")
    obs.reset(enabled=True)
    rows_on = sess_on.sql(sql).to_rows()
    assert obs.tracer().counters  # tracing actually observed the run

    obs.reset(enabled=False)
    sess_off = Session(tiny_catalog(), backend="tpu")
    rows_off = sess_off.sql(sql).to_rows()
    assert not obs.tracer().counters
    assert repr(rows_on) == repr(rows_off)
    assert json.dumps(rows_on, default=str) == \
        json.dumps(rows_off, default=str)


def test_power_style_attribution_five_queries(fresh_tracer):
    """Acceptance bar: per-query compile_s + execute_s accounts for
    >=90% of measured wall over a 5-query stream, cold and warm."""
    sess = Session(tiny_catalog(), backend="tpu")
    for rnd in ("cold", "warm"):
        for i, sql in enumerate(FIVE_QUERIES):
            with obs.span(f"query{i}_{rnd}", cat="query", collect=True):
                r = sess.sql(sql)
                if r is not None:
                    r.to_rows()
    summaries = obs.tracer().query_summaries()
    assert len(summaries) == 10
    for s in summaries:
        assert s["attributed_frac"] >= 0.9, s
    cold = [s for s in summaries if s["query"].endswith("_cold")]
    warm = [s for s in summaries if s["query"].endswith("_warm")]
    assert all(s["mode"] == "cold" for s in cold)
    assert all(s["mode"] == "warm" for s in warm)
    # cache counters separate the rounds: every query discovered once
    c = obs.counters_snapshot()
    assert c["engine.discoveries"] == len(FIVE_QUERIES)
    assert c["engine.cache.compiled.hit"] >= len(FIVE_QUERIES)


# -- exports ------------------------------------------------------------------


def _populated_tracer():
    t = obs.tracer()
    with t.span("stream", cat="stream", collect=True):
        with t.span("q1", cat="query", collect=True):
            with t.span("work", cat="plan-node", bucket="execute_s"):
                pass
    t.inc("engine.cache.compiled.miss")
    t.set_gauge("xla.persistent_cache.files", 4)
    t.record("stream_2", "stream", t.t0_epoch, 0.5, returncode=0)
    return t


def test_jsonl_export_roundtrip(tmp_path, fresh_tracer):
    _populated_tracer()
    path = obs.export_jsonl(str(tmp_path / "run.trace.jsonl"))
    lines = [json.loads(ln) for ln in
             open(path).read().splitlines()]
    assert lines[0]["type"] == "meta"
    assert lines[0]["format"] == "ndstpu-trace-v1"
    spans = [ln for ln in lines if ln["type"] == "span"]
    assert {"work", "q1", "stream", "stream_2"} <= \
        {s["name"] for s in spans}
    q1 = next(s for s in spans if s["name"] == "q1")
    assert q1["collect"] and "execute_s" in q1["buckets"]
    counters = next(ln for ln in lines if ln["type"] == "counters")
    assert counters["counters"]["engine.cache.compiled.miss"] == 1
    gauges = next(ln for ln in lines if ln["type"] == "gauges")
    assert gauges["gauges"]["xla.persistent_cache.files"] == 4


def test_chrome_trace_valid_and_balanced(tmp_path, fresh_tracer):
    _populated_tracer()
    path = obs.export_chrome(str(tmp_path / "run.trace.json"))
    doc = json.load(open(path))  # must be valid JSON
    evs = doc["traceEvents"]
    by_name: dict = {}
    for e in evs:
        assert e["ph"] in ("B", "E")
        by_name.setdefault(e["name"], []).append(e["ph"])
    for name, phs in by_name.items():
        assert phs.count("B") == phs.count("E"), name
    # timestamps are non-decreasing (Perfetto requirement per track)
    ts = [e["ts"] for e in evs]
    assert ts == sorted(ts)
    # nesting survives: q1 opens after stream opens, closes before
    opens = {e["name"]: e["ts"] for e in evs if e["ph"] == "B"}
    closes = {e["name"]: e["ts"] for e in evs if e["ph"] == "E"}
    assert opens["stream"] <= opens["q1"] <= closes["q1"] \
        <= closes["stream"]


def test_run_metrics_and_export_run(tmp_path, fresh_tracer):
    _populated_tracer()
    m = obs.run_metrics({"app_id": "x"})
    assert m["totals"]["n_queries"] == 1
    assert m["app_id"] == "x"
    assert m["counters"]["engine.cache.compiled.miss"] == 1
    paths = obs.export_run(str(tmp_path), "power_time.csv")
    assert paths["jsonl"].endswith("power_time.csv.trace.jsonl")
    assert paths["chrome"].endswith("power_time.csv.trace.json")
    for p in paths.values():
        assert json is not None and open(p).read()


# -- harness integration ------------------------------------------------------


def test_bench_report_metrics_block(fresh_tracer):
    from ndstpu.harness.report import BenchReport
    sess = Session(tiny_catalog(), backend="tpu")
    sql = FIVE_QUERIES[2]

    def run(q):
        sess.sql(q).to_rows()

    rep = BenchReport({"engine": "tpu"})
    summary = rep.report_on(run, sql, query_name="query42")
    assert summary["queryStatus"] == ["Completed"]
    blk = summary["metrics"][0]
    assert blk["query"] == "query42"
    assert blk["mode"] == "cold"
    assert blk["attributed_frac"] >= 0.9
    assert blk["counters"].get("engine.cache.compiled.miss") == 1
    assert blk["wall_s"] >= blk["compile_s"] + blk["execute_s"] - 1e-6

    rep2 = BenchReport({"engine": "tpu"})
    s2 = rep2.report_on(run, sql, query_name="query42")
    assert s2["metrics"][0]["mode"] == "warm"
    assert s2["metrics"][0]["counters"].get(
        "engine.cache.compiled.hit") == 1


def test_bench_report_metrics_on_failure(fresh_tracer):
    from ndstpu.harness.report import BenchReport

    def boom():
        raise RuntimeError("no")

    rep = BenchReport({})
    summary = rep.report_on(boom, query_name="qx")
    assert summary["queryStatus"] == ["Failed"]
    # the metrics block still exists and the span recorded the error
    assert summary["metrics"][0]["query"] == "qx"
    ev = [e for e in obs.tracer().events if e["name"] == "qx"]
    assert ev and ev[0]["args"].get("error") == "RuntimeError"


def test_report_disabled_tracer_no_metrics_block():
    from ndstpu.harness.report import BenchReport
    obs.reset(enabled=False)
    try:
        rep = BenchReport({})
        summary = rep.report_on(lambda: None, query_name="q")
        assert "metrics" not in summary
    finally:
        obs.reset()


def test_hw_metrics_artifact(tmp_path, fresh_tracer):
    from ndstpu.harness.bench import write_hw_metrics
    sidecar_data = {"totals": {"n_queries": 2, "cold_queries": 0}}
    report_file = tmp_path / "power.csv"
    (tmp_path / "power.csv.metrics.json").write_text(
        json.dumps(sidecar_data))
    params = {
        "data_gen": {"scale_factor": 1},
        "generate_query_stream": {"num_streams": 5},
        "power_test": {"engine": "tpu",
                       "report_file": str(report_file)},
        "metrics": {"metrics_report": str(tmp_path / "metrics.csv"),
                    "hw_metrics": str(tmp_path / "hw.json")},
    }
    path = write_hw_metrics(params, {"metric": 123},
                            {"power_test": 1.5})
    hw = json.load(open(path))
    assert hw["format"] == "ndstpu-hw-metrics-v1"
    assert hw["phases"]["power_test"] == 1.5
    assert hw["summary"]["metric"] == 123
    assert hw["power"]["totals"]["cold_queries"] == 0


def test_hw_metrics_default_path(tmp_path, fresh_tracer):
    from ndstpu.harness.bench import write_hw_metrics
    params = {
        "data_gen": {"scale_factor": 1},
        "generate_query_stream": {"num_streams": 3},
        "power_test": {"report_file": str(tmp_path / "p.csv")},
        "metrics": {"metrics_report": str(tmp_path / "metrics.csv")},
    }
    path = write_hw_metrics(params, {}, {})
    assert path == str(tmp_path / "hw_metrics.json")
    assert json.load(open(path))["power"] is None


def test_power_run_emits_traces_and_sidecar(tmp_path, monkeypatch,
                                            fresh_tracer):
    """Acceptance shape: a power run over 5 queries produces the JSONL
    trace, the Chrome trace, and the metrics sidecar whose per-query
    compile_s + execute_s accounts for >=90% of wall, with cache
    counters distinguishing the cold run."""
    import argparse

    from ndstpu.harness import power
    from ndstpu.io import loader

    stream = tmp_path / "query_0.sql"
    stream.write_text("".join(
        f"-- start query {i + 1} in stream 0 using template "
        f"query{i + 1}.tpl\n{sql};\n"
        for i, sql in enumerate(FIVE_QUERIES)))
    monkeypatch.setattr(loader, "load_catalog",
                        lambda prefix, use_decimal=True: tiny_catalog())
    xla_dir = tmp_path / "xla"
    xla_dir.mkdir()
    (xla_dir / "seeded_entry").write_text("x")
    args = argparse.Namespace(
        query_stream_file=str(stream), input_prefix=str(tmp_path),
        time_log=str(tmp_path / "power_time.csv"),
        input_format="parquet", engine="tpu", output_prefix=None,
        output_format="parquet", property_file=None,
        json_summary_folder=str(tmp_path / "json"), sub_queries=None,
        extra_time_log=None, xla_cache_dir=str(xla_dir),
        compile_records=None, floats=True)
    power.run_query_stream(args)

    sidecar = json.load(open(str(tmp_path / "power_time.csv.metrics.json")))
    assert sidecar["totals"]["n_queries"] == len(FIVE_QUERIES)
    assert sidecar["totals"]["attributed_frac"] >= 0.9
    assert sidecar["totals"]["cold_queries"] == len(FIVE_QUERIES)
    for q in sidecar["queries"]:
        assert q["attributed_frac"] >= 0.9, q
    c = sidecar["counters"]
    assert c["engine.cache.compiled.miss"] == len(FIVE_QUERIES)
    assert sidecar["gauges"]["xla.persistent_cache.files"] == 1

    jsonl = (tmp_path / "power_time.csv.trace.jsonl").read_text()
    spans = [json.loads(ln) for ln in jsonl.splitlines()
             if json.loads(ln)["type"] == "span"]
    assert sum(1 for s in spans if s["cat"] == "query") == \
        len(FIVE_QUERIES)
    assert any(s["cat"] == "stream" for s in spans)

    chrome = json.load(open(str(tmp_path / "power_time.csv.trace.json")))
    phs = [e["ph"] for e in chrome["traceEvents"]]
    assert phs.count("B") == phs.count("E") > 0

    # BenchReport summaries carry the per-query metrics block; the
    # filename contract is unchanged
    summaries = list((tmp_path / "json").glob("-query1-*.json"))
    assert len(summaries) == 1
    s = json.load(open(str(summaries[0])))
    assert s["metrics"][0]["mode"] == "cold"
    assert s["metrics"][0]["xla_cache_files"] == {"before": 1, "after": 1}


# -- exchange instruments -----------------------------------------------------


def test_exchange_collective_counters(fresh_tracer):
    """Counters tick at trace time with static byte estimates (the
    documented per-compiled-program semantics)."""
    import jax
    import jax.numpy as jnp
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ndstpu.parallel import exchange
    from ndstpu.parallel.mesh import SHARD_AXIS, make_mesh

    n_dev = jax.device_count()
    if n_dev < 2:
        pytest.skip("needs a multi-device mesh")
    mesh = make_mesh(n_dev)

    def body(x):
        return exchange.broadcast_gather(x)

    try:  # replication-check kwarg was renamed across jax versions
        fn = shard_map(body, mesh=mesh, in_specs=(P(SHARD_AXIS),),
                       out_specs=P(), check_vma=False)
    except TypeError:
        fn = shard_map(body, mesh=mesh, in_specs=(P(SHARD_AXIS),),
                       out_specs=P(), check_rep=False)
    x = jnp.arange(n_dev * 4, dtype=jnp.int32)
    before = obs.counters_snapshot()
    jax.jit(fn)(x)
    delta = obs.counter_delta(before)
    assert delta.get("exchange.all_gather.calls") == 1
    # global wire bytes from static PER-SHARD shapes: every device
    # sends its local shard (size/n_dev elements) to n_dev-1 peers
    local = x.size // n_dev
    assert delta.get("exchange.shuffle_bytes") == \
        local * 4 * n_dev * (n_dev - 1)
