"""Common-spine sharing tests (analysis + runtime MQO).

Analysis half (ndstpu/analysis/spines.py): every plan subtree gets a
canonical fingerprint that is STABLE across corpus renderings — the
same template under different seeds/streams maps to the same
per-subtree fingerprints (literals are slot-lifted per subtree), which
is what makes the cross-corpus spine index meaningful.

Runtime half (ndstpu/engine/spine.py + Session._splice_spines): a
query whose flagged spine is already cached splices the materialized
table instead of recomputing — and the spliced run must be
bit-identical to the recomputed run, row order included, on both the
single-device and the SPMD backend.  The LRU cache never holds more
than its byte budget, and NDSTPU_SPINES=0 disables sharing entirely.
"""

import os
import subprocess

import numpy as np
import pytest

from ndstpu import analysis, obs
from ndstpu.analysis import spines as an_spines
from ndstpu.engine import columnar
from ndstpu.engine import spine as rt_spine
from ndstpu.engine.session import Session
from ndstpu.io import loader
from ndstpu.queries import streamgen

SEEDS = ("07291122510", "19980713042")
STREAMS = (0, 1)
PARTS = ("query3", "query7", "query52")


def _render(rngseed, stream, wanted):
    out = {}
    for name, sql in streamgen.render_power_corpus(rngseed=rngseed,
                                                   stream=stream):
        if name in wanted:
            out[name] = sql
    return out


# -- analysis: subtree fingerprints + the shared-spine index -----------------


@pytest.fixture(scope="module")
def schema_session():
    return Session(analysis.schema_catalog())


def test_subtree_fingerprints_stable_across_renderings(schema_session):
    """Each part's {subtree path -> fingerprint} map is identical under
    every seed x stream rendering: per-subtree slot-lifting removes the
    literals, so only the template's structure is fingerprinted."""
    tables = analysis.schema_tables()
    maps = {}  # part -> {combo: {path: fingerprint}}
    for seed in SEEDS:
        for stream in STREAMS:
            rendered = _render(seed, stream, set(PARTS))
            assert set(rendered) == set(PARTS)
            for name, sql in rendered.items():
                plan, _ = schema_session.plan(sql)
                subs = analysis.canonicalize_subtrees(plan, tables=tables,
                                                      query=name)
                fp = {s.path: s.canon.fingerprint for s in subs
                      if s.canon is not None}
                assert fp, f"{name}: no canonicalizable subtrees"
                maps.setdefault(name, {})[(seed, stream)] = fp
    for name, by_combo in maps.items():
        combos = list(by_combo.values())
        for other in combos[1:]:
            assert other == combos[0], \
                f"{name}: subtree fingerprints vary across renderings"


def test_shared_spine_index_and_diagnostics(schema_session):
    """query1/query7 share a canonical subtree with different literal
    bindings: the index reports it shareable across both parts and the
    diagnostics carry NDS501 (+ NDS502 for the divergent params)."""
    from ndstpu.analysis import diagnostics as diag_mod
    for code in ("NDS501", "NDS502", "NDS503", "NDS504"):
        assert code in diag_mod.CODES  # registered, not ad-hoc
    tables = analysis.schema_tables()
    per_sites = {}
    for name, sql in _render(SEEDS[0], 0, {"query1", "query7"}).items():
        res = analysis.analyze_sql(schema_session, name, sql,
                                   tables=tables, spine_pass=True)
        per_sites[name] = res.spine_sites or []
        assert res.spine_sites, f"{name}: spine pass found no sites"
    index, diags = an_spines.build_index(per_sites)
    shared = [rec for rec in index.values()
              if len(rec["queries"]) >= 2 and rec["shareable"]]
    assert shared, "query1/query7 lost their shared spine"
    codes = {d.code for d in diags}
    assert "NDS501" in codes
    assert "NDS502" in codes  # different literals -> param-divergent
    doc = an_spines.index_to_doc(index)
    assert doc["summary"]["shared_spine_candidates"] >= 1
    # eligibility: outermost only — no selected site may contain another
    for name, sites in per_sites.items():
        chosen = an_spines.eligible_sites(sites)
        paths = [s.path for s in chosen]
        for p in paths:
            assert not any(q != p and q.startswith(p + "/")
                           for q in paths), \
                f"{name}: nested eligible sites {paths}"


# -- runtime: LRU byte budget ------------------------------------------------


def _table(n_rows: int) -> columnar.Table:
    return columnar.Table({"v": columnar.Column.from_numpy(
        np.arange(n_rows, dtype=np.int64), columnar.INT64)})


def test_spine_cache_eviction_never_exceeds_budget():
    one = rt_spine.table_bytes(_table(100))  # 800 B
    cache = rt_spine.SpineCache(budget_bytes=2 * one)
    assert cache.eligible("anything")  # flagged=None -> publish all
    state = ("epoch", ())
    for i in range(5):
        assert cache.put(f"vk{i}", state, _table(100))
        assert cache.total_bytes <= cache.budget_bytes
    assert len(cache) == 2
    assert cache.evictions == 3
    # LRU order: the two most recent survive
    assert cache.get("vk4", state) is not None
    assert cache.get("vk0", state) is None
    # a table bigger than the whole budget is refused, not force-fit
    assert not cache.put("huge", state, _table(1000))
    assert cache.total_bytes <= cache.budget_bytes
    # stale state drops the entry instead of serving it
    assert cache.get("vk4", ("epoch2", ())) is None
    assert "vk4" not in cache._entries


def test_replace_nodes_is_non_mutating(schema_session):
    plan, _ = schema_session.plan(
        "select i_item_sk from item where i_item_sk < 10")
    target = plan
    while getattr(target, "child", None) is not None:
        target = target.child
    inline = columnar.Table({"i_item_sk": columnar.Column.from_numpy(
        np.arange(3, dtype=np.int64), columnar.INT64)})
    from ndstpu.engine import plan as lp
    spliced = rt_spine.replace_nodes(
        plan, {id(target): lp.InlineTable(inline, name="spine:test")})
    assert spliced is not plan
    # the shared cached plan keeps its original node
    t = plan
    while getattr(t, "child", None) is not None:
        t = t.child
    assert not isinstance(t, lp.InlineTable)
    t = spliced
    while getattr(t, "child", None) is not None:
        t = t.child
    assert isinstance(t, lp.InlineTable)


# -- runtime: splice vs recompute over a real warehouse ----------------------


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    root = tmp_path_factory.mktemp("nds_spine")
    env = dict(os.environ, PYTHONPATH=os.getcwd())
    subprocess.run(["python", "-m", "ndstpu.datagen.driver", "local",
                    "0.002", "2", str(root / "raw")], check=True, env=env)
    subprocess.run(["python", "-m", "ndstpu.io.transcode",
                    "--input_prefix", str(root / "raw"),
                    "--output_prefix", str(root / "wh"),
                    "--report_file", str(root / "load.txt"),
                    "--output_format", "ndslake"],
                   check=True, env=env, stdout=subprocess.DEVNULL)
    return root


@pytest.fixture(scope="module")
def spine_queries():
    return _render(SEEDS[0], 0, {"query3", "query52"})


def _run_differential(dataset, spine_queries, backend):
    """Same queries on a plain session and on a spine-cached session
    (run twice: first populates, second must hit); all three result
    sets must be byte-identical including row order."""
    import pyarrow  # noqa: F401 — to_arrow comparison below

    catalog = loader.load_catalog(str(dataset / "wh"))
    plain = Session(catalog, backend=backend)
    shared = Session(catalog, backend=backend)
    shared.spine_cache = rt_spine.SpineCache(64 << 20)  # flag everything

    before = obs.counters_snapshot()
    for name, sql in spine_queries.items():
        baseline = plain.sql(sql)
        first = shared.sql(sql)
        second = shared.sql(sql)
        for tag, got in (("first", first), ("second", second)):
            a, b = columnar.to_arrow(baseline), columnar.to_arrow(got)
            assert a.equals(b), \
                f"{backend} {name}: {tag} spliced run differs"
    delta = obs.counter_delta(before)
    assert shared.spine_cache.hits >= len(spine_queries), \
        f"{backend}: repeated queries did not hit the spine cache"
    assert delta.get("engine.spine.hit", 0) >= len(spine_queries)
    assert delta.get("engine.spine.miss", 0) >= 1
    assert shared.spine_cache.total_bytes <= \
        shared.spine_cache.budget_bytes


def test_splice_vs_recompute_identical_single_device(dataset,
                                                     spine_queries):
    _run_differential(dataset, spine_queries, "tpu")


def test_splice_vs_recompute_identical_spmd(dataset, spine_queries):
    # conftest pins an 8-device virtual CPU mesh; tpu-spmd distributes
    # (or per-query falls back) over it — either way results must match
    _run_differential(dataset, spine_queries, "tpu-spmd")


def test_kill_switch_disables_sharing(dataset, spine_queries,
                                      monkeypatch):
    name, sql = next(iter(spine_queries.items()))
    catalog = loader.load_catalog(str(dataset / "wh"))
    on = Session(catalog, backend="cpu")
    on.spine_cache = rt_spine.SpineCache(64 << 20)
    expected = columnar.to_arrow(on.sql(sql))

    monkeypatch.setenv("NDSTPU_SPINES", "0")
    off = Session(catalog, backend="cpu")
    off.spine_cache = rt_spine.SpineCache(64 << 20)
    for _ in range(2):
        got = columnar.to_arrow(off.sql(sql))
        assert expected.equals(got)
    assert off.spine_cache.hits == 0
    assert off.spine_cache.misses == 0
    assert len(off.spine_cache) == 0  # nothing published either
    assert not rt_spine.enabled()
    monkeypatch.delenv("NDSTPU_SPINES")
    assert rt_spine.enabled()
