"""Pallas segment-sum kernels (ndstpu.ops.segsum) vs numpy oracle.

Runs the pallas interpreter on CPU; the real lowering targets the MXU
(one-hot matmul formulation of grouped aggregation)."""

import numpy as np
import pytest

import jax.numpy as jnp

from ndstpu.ops import segsum


@pytest.mark.parametrize("n,s", [(1000, 7), (4096, 300), (513, 1)])
def test_segment_sum_f32(n, s):
    rng = np.random.RandomState(5)
    vals = rng.uniform(-100, 100, n).astype(np.float32)
    gid = rng.randint(0, s, n).astype(np.int32)
    mask = rng.rand(n) < 0.8
    got = np.asarray(segsum.segment_sum_f32(
        jnp.asarray(vals), jnp.asarray(gid), jnp.asarray(mask), s,
        block_rows=256, block_segs=128, interpret=True))
    want = np.zeros(s, np.float64)
    np.add.at(want, gid[mask], vals[mask].astype(np.float64))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-2)


@pytest.mark.parametrize("n,s", [(2048, 11), (4096, 500)])
def test_segment_sum_decimal_exact(n, s):
    rng = np.random.RandomState(7)
    # signed cents incl. values far above f32's exact-integer range
    vals = rng.randint(-10**12, 10**12, n).astype(np.int64)
    gid = rng.randint(0, s, n).astype(np.int32)
    mask = rng.rand(n) < 0.9
    sums, counts = segsum.segment_sum_decimal(
        jnp.asarray(vals), jnp.asarray(gid), jnp.asarray(mask), s,
        block_rows=256, block_segs=128, interpret=True)
    want = np.zeros(s, np.int64)
    np.add.at(want, gid[mask], vals[mask])
    wantc = np.bincount(gid[mask], minlength=s).astype(np.int64)
    np.testing.assert_array_equal(np.asarray(sums), want)   # EXACT
    np.testing.assert_array_equal(np.asarray(counts), wantc)


def test_segment_sum_decimal_empty_mask():
    n, s = 512, 9
    vals = np.arange(n, dtype=np.int64)
    gid = (np.arange(n) % s).astype(np.int32)
    mask = np.zeros(n, bool)
    sums, counts = segsum.segment_sum_decimal(
        jnp.asarray(vals), jnp.asarray(gid), jnp.asarray(mask), s,
        block_rows=256, block_segs=128, interpret=True)
    assert np.asarray(sums).tolist() == [0] * s
    assert np.asarray(counts).tolist() == [0] * s
