"""SQL frontend → planner → executor end-to-end tests."""

import numpy as np
import pytest

from ndstpu.engine import columnar, expr as ex
from ndstpu.engine.columnar import INT32, Column, Table, decimal
from ndstpu.engine.session import Session
from ndstpu.io.loader import Catalog


def col_i32(vals):
    valid = np.array([v is not None for v in vals])
    data = np.array([0 if v is None else v for v in vals], dtype=np.int32)
    return Column(data, INT32, None if valid.all() else valid)


def col_dec(vals, scale=2):
    valid = np.array([v is not None for v in vals])
    data = np.array([0 if v is None else round(v * 10**scale) for v in vals],
                    dtype=np.int64)
    return Column(data, decimal(7, scale), None if valid.all() else valid)


@pytest.fixture
def sess():
    cat = Catalog()
    cat.register("sales", Table({
        "item_sk": col_i32([1, 2, 1, 3, 2, None]),
        "store_sk": col_i32([1, 1, 2, 2, 1, 1]),
        "qty": col_i32([10, 20, 30, 40, 50, 60]),
        "price": col_dec([1.50, 2.25, 1.00, None, 3.10, 4.00]),
    }))
    cat.register("item", Table({
        "i_item_sk": col_i32([1, 2, 3]),
        "i_name": Column.from_strings(["apple", "banana", "cherry"]),
        "i_cat": Column.from_strings(["fruit", "fruit", "berry"]),
    }))
    cat.register("store", Table({
        "st_sk": col_i32([1, 2]),
        "st_state": Column.from_strings(["CA", "TN"]),
    }))
    return Session(cat)


def rows(t):
    return t.to_rows()


def test_select_where(sess):
    t = sess.sql("select qty, price from sales where qty > 25")
    assert t.to_pydict()["qty"] == [30, 40, 50, 60]


def test_join_group_order(sess):
    t = sess.sql("""
        select i.i_name, sum(s.qty) total
        from sales s, item i
        where s.item_sk = i.i_item_sk
        group by i.i_name
        order by total desc
    """)
    assert rows(t) == [("banana", 70), ("apple", 40), ("cherry", 40)]


def test_explicit_join_syntax(sess):
    t = sess.sql("""
        select st.st_state, count(*) n
        from sales s join store st on s.store_sk = st.st_sk
        group by st.st_state order by n desc, st_state
    """)
    assert rows(t) == [("CA", 4), ("TN", 2)]


def test_left_join_sql(sess):
    t = sess.sql("""
        select s.qty, i.i_name
        from sales s left join item i on s.item_sk = i.i_item_sk
        where s.qty >= 40 order by s.qty
    """)
    assert rows(t) == [(40, "cherry"), (50, "banana"), (60, None)]


def test_having_and_alias_group(sess):
    t = sess.sql("""
        select item_sk, sum(qty) sq from sales
        group by item_sk having sum(qty) > 40 order by item_sk
    """)
    assert rows(t) == [(2, 70), (None, 60)][::-1] or True
    # Spark: NULL group sorts first ascending
    assert rows(t) == [(None, 60), (2, 70)]


def test_case_cast_between(sess):
    t = sess.sql("""
        select qty, case when qty between 20 and 40 then 'mid'
                         when qty < 20 then 'low' else 'high' end band
        from sales order by qty limit 3
    """)
    assert rows(t) == [(10, "low"), (20, "mid"), (30, "mid")]


def test_in_list_and_like(sess):
    t = sess.sql("""
        select i_name from item
        where i_cat in ('fruit') and i_name like '%an%'
    """)
    assert t.to_pydict()["i_name"] == ["banana"]


def test_uncorrelated_in_subquery(sess):
    t = sess.sql("""
        select qty from sales
        where item_sk in (select i_item_sk from item where i_cat = 'fruit')
        order by qty
    """)
    assert t.to_pydict()["qty"] == [10, 20, 30, 50]


def test_not_in_subquery(sess):
    t = sess.sql("""
        select qty from sales
        where item_sk not in (select i_item_sk from item
                              where i_cat = 'fruit')
        order by qty
    """)
    # Spark 3VL: the NULL item_sk row is excluded (NULL NOT IN (...) is NULL)
    assert t.to_pydict()["qty"] == [40]


def test_not_in_subquery_with_null_values(sess):
    # subquery side contains NULL -> NOT IN yields no rows at all
    t = sess.sql("""
        select qty from sales
        where qty not in (select item_sk from sales)
    """)
    assert t.num_rows == 0


def test_uncorrelated_scalar_subquery(sess):
    t = sess.sql("""
        select qty from sales
        where qty > (select avg(qty) from sales) order by qty
    """)
    assert t.to_pydict()["qty"] == [40, 50, 60]


def test_correlated_scalar_aggregate(sess):
    # q1-style: rows above their store's average
    t = sess.sql("""
        select s1.qty from sales s1
        where s1.qty > (select avg(s2.qty) * 1.2 from sales s2
                        where s2.store_sk = s1.store_sk)
        order by s1.qty
    """)
    # store 1 avg=35 *1.2=42 -> qty 50,60 ; store 2 avg=35 *1.2=42 -> none
    assert t.to_pydict()["qty"] == [50, 60]


def test_exists_correlated(sess):
    t = sess.sql("""
        select i_name from item i
        where exists (select 1 from sales s where s.item_sk = i.i_item_sk
                      and s.qty > 35)
        order by i_name
    """)
    assert t.to_pydict()["i_name"] == ["banana", "cherry"]


def test_cte_and_derived_table(sess):
    t = sess.sql("""
        with big as (select * from sales where qty >= 30)
        select x.item_sk, x.qty from (select item_sk, qty from big) x
        order by x.qty desc limit 2
    """)
    assert rows(t) == [(None, 60), (2, 50)]


def test_union_and_intersect(sess):
    t = sess.sql("""
        select item_sk from sales where qty > 40
        union select i_item_sk from item order by item_sk
    """)
    assert t.to_pydict()["item_sk"] == [None, 1, 2, 3]
    t2 = sess.sql("""
        select item_sk from sales intersect select i_item_sk from item
    """)
    assert sorted(x for x in t2.to_pydict()["item_sk"]) == [1, 2, 3]


def test_rollup_sql(sess):
    t = sess.sql("""
        select store_sk, sum(qty) s from sales
        where item_sk is not null
        group by rollup(store_sk) order by store_sk
    """)
    assert rows(t) == [(None, 150), (1, 80), (2, 70)]


def test_window_sql(sess):
    t = sess.sql("""
        select qty, rank() over (partition by store_sk order by qty desc) r
        from sales where item_sk is not null order by store_sk, r
    """)
    assert t.to_pydict()["r"] == [1, 2, 3, 1, 2]


def test_self_join_aliases(sess):
    t = sess.sql("""
        select a.qty, b.qty
        from sales a, sales b
        where a.item_sk = b.item_sk and a.qty < b.qty
        order by a.qty
    """)
    assert rows(t) == [(10, 30), (20, 50)]


def test_distinct(sess):
    t = sess.sql("select distinct store_sk from sales order by store_sk")
    assert t.to_pydict()["store_sk"] == [1, 2]


def test_date_literal_arithmetic(sess):
    cat = sess.catalog
    base = (np.datetime64("1999-02-22") - np.datetime64("1970-01-01")
            ).astype(int)
    cat.register("dates", Table({
        "d": Column(np.array([base - 10, base, base + 20, base + 40],
                             dtype=np.int32), columnar.DATE),
    }))
    t = sess.sql("""
        select count(*) n from dates
        where d between date '1999-02-22'
          and (date '1999-02-22' + interval 30 days)
    """)
    assert t.to_pydict()["n"] == [2]


def test_decimal_avg_precision(sess):
    t = sess.sql("select avg(price) a, sum(price) s from sales")
    d = t.to_pydict()
    assert d["a"] == [pytest.approx(11.85 / 5)]
    assert d["s"] == [pytest.approx(11.85)]


def test_count_distinct_sql(sess):
    t = sess.sql("select count(distinct store_sk) c from sales")
    assert t.to_pydict()["c"] == [2]


def test_q3_full_text(sess):
    """The real NDS q3 shape end-to-end on a synthetic catalog."""
    cat = Catalog()
    n = 300
    rng = np.random.RandomState(7)
    date_sks = rng.randint(2450816, 2450816 + 400, n).astype(np.int32)
    cat.register("store_sales", Table({
        "ss_sold_date_sk": Column(date_sks, INT32),
        "ss_item_sk": Column(rng.randint(1, 20, n).astype(np.int32), INT32),
        "ss_ext_sales_price": col_dec(list(
            np.round(rng.uniform(1, 100, n), 2))),
    }))
    djd = np.arange(2450816, 2450816 + 400, dtype=np.int32)
    years = 1998 + (djd - 2450816) // 365
    moys = ((djd - 2450816) // 30) % 12 + 1
    cat.register("date_dim", Table({
        "d_date_sk": Column(djd, INT32),
        "d_year": Column(years.astype(np.int64), columnar.INT64),
        "d_moy": Column(moys.astype(np.int64), columnar.INT64),
    }))
    cat.register("item", Table({
        "i_item_sk": Column(np.arange(1, 21, dtype=np.int32), INT32),
        "i_brand_id": Column((np.arange(20) % 5 + 1).astype(np.int64),
                             columnar.INT64),
        "i_brand": Column.from_strings([f"brand{k % 5 + 1}"
                                        for k in range(20)]),
        "i_manufact_id": Column((np.arange(20) % 3 + 100).astype(np.int64),
                                columnar.INT64),
    }))
    s = Session(cat)
    t = s.sql("""
        select dt.d_year, item.i_brand_id brand_id, item.i_brand brand,
               sum(ss_ext_sales_price) sum_agg
        from date_dim dt, store_sales, item
        where dt.d_date_sk = store_sales.ss_sold_date_sk
          and store_sales.ss_item_sk = item.i_item_sk
          and item.i_manufact_id = 100
          and dt.d_moy = 11
        group by dt.d_year, item.i_brand_id, item.i_brand
        order by dt.d_year, sum_agg desc, brand_id
        limit 100
    """)
    assert t.column_names == ["d_year", "brand_id", "brand", "sum_agg"]
    # cross-check with a straight numpy computation
    mask = np.isin(date_sks, djd[moys == 11])
    items = cat.get("store_sales").column("ss_item_sk").data
    manu = np.array([100 + k % 3 for k in range(20)])
    mask &= manu[items - 1] == 100
    expected_total = round(float(
        cat.get("store_sales").column("ss_ext_sales_price").data[mask].sum())
        / 100, 2)
    got_total = round(sum(t.to_pydict()["sum_agg"]), 2)
    assert got_total == pytest.approx(expected_total)
    # ordering contract: year asc, sum desc within year
    d = t.to_pydict()
    for i in range(1, t.num_rows):
        if d["d_year"][i] == d["d_year"][i - 1]:
            assert d["sum_agg"][i] <= d["sum_agg"][i - 1] + 1e-9


def test_sibling_fusion_two_table_groups(sess):
    """Every qualifying sibling group fuses — two groups over two
    different tables in one cross spine collapse to two scans, with
    hand-computed scalars (sales qty: 10,20,30,40,50,60 on rows whose
    price is 1.50,2.25,1.00,NULL,3.10,4.00; item_sk 1..3)."""
    sql = ("select * from "
           "(select count(price) c1, sum(qty) s1 from sales "
           " where qty >= 0 and qty <= 25) a1, "
           "(select count(price) c2, sum(qty) s2 from sales "
           " where qty >= 26 and qty <= 100) a2, "
           "(select count(*) c3 from item "
           " where i_item_sk >= 1 and i_item_sk <= 1) b1, "
           "(select count(*) c4 from item "
           " where i_item_sk >= 2 and i_item_sk <= 3) b2")
    from ndstpu.engine import plan as lp
    p, _cols = sess.plan(sql)
    scans = [n for n in p.walk() if isinstance(n, lp.Scan)]
    assert len(scans) == 2, "each table group must fuse to one scan"
    t = sess.sql(sql)
    assert t.to_rows() == [(2, 30, 3, 180, 1, 2)]
