"""Schema registry parity tests (vs reference nds_schema.py:49-716)."""

from ndstpu import schema


def test_source_table_count():
    s = schema.get_schemas(use_decimal=True)
    assert len(s) == 25


def test_maintenance_table_count():
    s = schema.get_maintenance_schemas(use_decimal=True)
    assert len(s) == 12


def test_column_counts():
    s = schema.get_schemas()
    expected = {
        "customer_address": 13, "customer_demographics": 9, "date_dim": 28,
        "warehouse": 14, "ship_mode": 6, "time_dim": 10, "reason": 3,
        "income_band": 3, "item": 22, "store": 29, "call_center": 31,
        "customer": 18, "web_site": 26, "store_returns": 20,
        "household_demographics": 5, "web_page": 14, "promotion": 19,
        "catalog_page": 9, "inventory": 4, "catalog_returns": 27,
        "web_returns": 24, "web_sales": 34, "catalog_sales": 34,
        "store_sales": 23, "dbgen_version": 4,
    }
    for t, n in expected.items():
        assert len(s[t]) == n, t


def test_decimal_switch():
    dec = schema.get_schemas(use_decimal=True)
    flt = schema.get_schemas(use_decimal=False)
    c = dec["store_sales"].column("ss_net_paid")
    assert c.dtype.kind == "decimal" and (c.dtype.precision, c.dtype.scale) == (7, 2)
    c2 = flt["store_sales"].column("ss_net_paid")
    assert c2.dtype.kind == "float64"


def test_identifier_width_policy():
    s = schema.get_schemas()
    # ticket numbers are 64-bit (reference rationale nds_schema.py:328-331)
    assert s["store_sales"].column("ss_ticket_number").dtype.kind == "int64"
    assert s["store_returns"].column("sr_ticket_number").dtype.kind == "int64"
    # plain surrogate keys are 32-bit
    assert s["store_sales"].column("ss_item_sk").dtype.kind == "int32"
    assert s["customer"].column("c_customer_sk").dtype.kind == "int32"


def test_nullability():
    s = schema.get_schemas()
    assert not s["store_sales"].column("ss_item_sk").nullable
    assert s["store_sales"].column("ss_sold_date_sk").nullable
    assert not s["date_dim"].column("d_date_sk").nullable


def test_special_decimals():
    s = schema.get_schemas()
    assert s["promotion"].column("p_cost").dtype.precision == 15
    assert s["customer_address"].column("ca_gmt_offset").dtype.precision == 5
    assert s["store"].column("s_tax_precentage").dtype.precision == 5


def test_partitioning_map():
    assert len(schema.TABLE_PARTITIONING) == 7
    assert schema.TABLE_PARTITIONING["store_sales"] == "ss_sold_date_sk"
    assert schema.TABLE_PARTITIONING["inventory"] == "inv_date_sk"


def test_maintenance_delete_tables():
    s = schema.get_maintenance_schemas()
    for t in ("delete", "inventory_delete"):
        assert s[t].column_names == ["date1", "date2"]
