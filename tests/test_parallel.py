"""Multi-chip sharding tests on the virtual 8-device CPU mesh.

Validates that the distributed query step (shard_map + collectives)
compiles and produces results identical to a numpy oracle, and that the
exchange primitives preserve rows."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from ndstpu.parallel import dquery, exchange, mesh as pmesh


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) >= 8, "conftest must force 8 CPU devices"
    return pmesh.make_mesh(8)


def test_q3_step_matches_oracle(mesh8):
    n_items, n_dates, d_base = 64, 64, 2450815
    args = dquery.example_inputs(n_rows=4096, n_items=n_items,
                                 n_dates=n_dates, d_base=d_base,
                                 n_dev=8)
    step = dquery.build_q3_step(mesh8, n_items, n_dates, d_base)
    sharding = pmesh.row_sharding(mesh8)
    sharded_args = [jax.device_put(a, sharding) for a in args[:3]] + \
        [jax.device_put(a, pmesh.replicated(mesh8)) for a in args[3:]]
    per_brand, n_rows, shuffled, dropped = step(*sharded_args)
    ref_brand, ref_n, ref_item = dquery.reference_result(
        *args, n_items=n_items, n_dates=n_dates, d_base=d_base)
    assert int(dropped) == 0
    np.testing.assert_array_equal(np.asarray(per_brand), ref_brand)
    assert int(n_rows) == ref_n
    np.testing.assert_array_equal(np.asarray(shuffled), ref_item)


def test_hash_repartition_preserves_rows(mesh8):
    """Every alive row lands on exactly one device, keyed consistently."""
    n_dev = 8
    n_local = 128
    bucket_cap = 64
    rng = np.random.RandomState(7)
    keys = rng.randint(0, 50, n_dev * n_local).astype(np.int64)
    vals = rng.randint(0, 1000, n_dev * n_local).astype(np.int64)
    alive = rng.rand(n_dev * n_local) < 0.9

    def body(k, v, a):
        cols, alive_out, dropped = exchange.hash_repartition(
            {"v": v, "k": k}, k, a, n_dev, bucket_cap)
        # per-key sums of received rows
        local = jax.ops.segment_sum(
            jnp.where(alive_out, cols["v"], 0),
            jnp.clip(cols["k"], 0, 49).astype(jnp.int32), num_segments=50)
        return jax.lax.psum(local, pmesh.SHARD_AXIS), dropped

    fn = jax.jit(shard_map(
        body, mesh=mesh8,
        in_specs=(P(pmesh.SHARD_AXIS),) * 3, out_specs=(P(), P()),
        check_vma=False))
    got, dropped = fn(
        jax.device_put(jnp.asarray(keys), pmesh.row_sharding(mesh8)),
        jax.device_put(jnp.asarray(vals), pmesh.row_sharding(mesh8)),
        jax.device_put(jnp.asarray(alive), pmesh.row_sharding(mesh8)))
    assert int(dropped) == 0
    ref = np.zeros(50, np.int64)
    np.add.at(ref, keys[alive], vals[alive])
    np.testing.assert_array_equal(np.asarray(got), ref)


def test_broadcast_gather(mesh8):
    n_dev, n_local = 8, 16
    x = np.arange(n_dev * n_local, dtype=np.int32)

    def body(v):
        return exchange.broadcast_gather(v)

    fn = jax.jit(shard_map(body, mesh=mesh8,
                           in_specs=P(pmesh.SHARD_AXIS),
                           out_specs=P(pmesh.SHARD_AXIS)))
    out = fn(jax.device_put(jnp.asarray(x), pmesh.row_sharding(mesh8)))
    # each shard gathered the full array; sharded output stacks them
    assert out.shape == (n_dev * n_dev * n_local,)
    np.testing.assert_array_equal(np.asarray(out)[:n_dev * n_local], x)


def test_mesh_construction():
    m = pmesh.make_mesh(8)
    assert m.devices.size == 8
    assert m.axis_names == (pmesh.SHARD_AXIS,)
    with pytest.raises(ValueError):
        pmesh.make_mesh(10**6)
