"""Multi-chip sharding tests on the virtual 8-device CPU mesh.

Validates that the distributed query step (shard_map + collectives)
compiles and produces results identical to a numpy oracle, and that the
exchange primitives preserve rows."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ndstpu.parallel import dquery, exchange, mesh as pmesh
from ndstpu.parallel.mesh import shard_map


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) >= 8, "conftest must force 8 CPU devices"
    return pmesh.make_mesh(8)


def test_q3_step_matches_oracle(mesh8):
    n_items, n_dates, d_base = 64, 64, 2450815
    args = dquery.example_inputs(n_rows=4096, n_items=n_items,
                                 n_dates=n_dates, d_base=d_base,
                                 n_dev=8)
    step = dquery.build_q3_step(mesh8, n_items, n_dates, d_base)
    sharding = pmesh.row_sharding(mesh8)
    sharded_args = [jax.device_put(a, sharding) for a in args[:3]] + \
        [jax.device_put(a, pmesh.replicated(mesh8)) for a in args[3:]]
    per_brand, n_rows, shuffled, dropped = step(*sharded_args)
    ref_brand, ref_n, ref_item = dquery.reference_result(
        *args, n_items=n_items, n_dates=n_dates, d_base=d_base)
    assert int(dropped) == 0
    np.testing.assert_array_equal(np.asarray(per_brand), ref_brand)
    assert int(n_rows) == ref_n
    np.testing.assert_array_equal(np.asarray(shuffled), ref_item)


def test_hash_repartition_preserves_rows(mesh8):
    """Every alive row lands on exactly one device, keyed consistently."""
    n_dev = 8
    n_local = 128
    bucket_cap = 64
    rng = np.random.RandomState(7)
    keys = rng.randint(0, 50, n_dev * n_local).astype(np.int64)
    vals = rng.randint(0, 1000, n_dev * n_local).astype(np.int64)
    alive = rng.rand(n_dev * n_local) < 0.9

    def body(k, v, a):
        cols, alive_out, dropped = exchange.hash_repartition(
            {"v": v, "k": k}, k, a, n_dev, bucket_cap)
        # per-key sums of received rows
        local = jax.ops.segment_sum(
            jnp.where(alive_out, cols["v"], 0),
            jnp.clip(cols["k"], 0, 49).astype(jnp.int32), num_segments=50)
        return jax.lax.psum(local, pmesh.SHARD_AXIS), dropped

    fn = jax.jit(shard_map(
        body, mesh=mesh8,
        in_specs=(P(pmesh.SHARD_AXIS),) * 3, out_specs=(P(), P()),
        check_vma=False))
    got, dropped = fn(
        jax.device_put(jnp.asarray(keys), pmesh.row_sharding(mesh8)),
        jax.device_put(jnp.asarray(vals), pmesh.row_sharding(mesh8)),
        jax.device_put(jnp.asarray(alive), pmesh.row_sharding(mesh8)))
    assert int(dropped) == 0
    ref = np.zeros(50, np.int64)
    np.add.at(ref, keys[alive], vals[alive])
    np.testing.assert_array_equal(np.asarray(got), ref)


def test_broadcast_gather(mesh8):
    n_dev, n_local = 8, 16
    x = np.arange(n_dev * n_local, dtype=np.int32)

    def body(v):
        return exchange.broadcast_gather(v)

    fn = jax.jit(shard_map(body, mesh=mesh8,
                           in_specs=P(pmesh.SHARD_AXIS),
                           out_specs=P(pmesh.SHARD_AXIS)))
    out = fn(jax.device_put(jnp.asarray(x), pmesh.row_sharding(mesh8)))
    # each shard gathered the full array; sharded output stacks them
    assert out.shape == (n_dev * n_dev * n_local,)
    np.testing.assert_array_equal(np.asarray(out)[:n_dev * n_local], x)


# -- distributed plan executor (SQL -> SPMD program) ------------------------


@pytest.fixture(scope="module")
def dist_catalog(tmp_path_factory):
    import os
    import subprocess

    from ndstpu.io import loader
    data = tmp_path_factory.mktemp("draw")
    wh = tmp_path_factory.mktemp("dwh")
    env = dict(os.environ, PYTHONPATH=os.getcwd())
    subprocess.run(["python", "-m", "ndstpu.datagen.driver", "local",
                    "0.002", "2", str(data)], check=True, env=env)
    subprocess.run(["python", "-m", "ndstpu.io.transcode",
                    "--input_prefix", str(data),
                    "--output_prefix", str(wh),
                    "--report_file", str(wh / "load.txt")],
                   check=True, env=env, stdout=subprocess.DEVNULL)
    return loader.load_catalog(str(wh))


def _dist_vs_cpu(catalog, mesh, sql, threshold=1000, broadcast_limit=None,
                 expect_shuffle=0):
    """Plan once; run distributed and on the numpy interpreter; compare."""
    from ndstpu.engine import physical
    from ndstpu.engine.session import Session
    from ndstpu.parallel import dplan

    sess = Session(catalog, backend="cpu")
    plan, _cols = sess.plan(sql)
    want = physical.execute(plan, catalog)
    kw = {}
    if broadcast_limit is not None:
        kw["broadcast_limit_rows"] = broadcast_limit
    exe = dplan.DistributedPlanExecutor(catalog, mesh,
                                        shard_threshold_rows=threshold, **kw)
    got = exe.execute_plan(plan)
    n_shuffle = sum(1 for j in exe.joins.values()
                    if isinstance(j, dplan._ShuffleJoin))
    assert n_shuffle >= expect_shuffle, \
        f"expected >= {expect_shuffle} shuffle joins, traced {n_shuffle}"
    assert want.column_names == got.column_names
    rows_w = sorted(want.to_rows(), key=lambda r: tuple(
        (v is None, str(v)) for v in r))
    rows_g = sorted(got.to_rows(), key=lambda r: tuple(
        (v is None, str(v)) for v in r))
    assert len(rows_w) == len(rows_g), \
        f"{len(rows_w)} vs {len(rows_g)} rows"
    for rw, rg in zip(rows_w, rows_g):
        for vw, vg in zip(rw, rg):
            if isinstance(vw, float) and isinstance(vg, float):
                assert vw == pytest.approx(vg, rel=1e-9, abs=1e-9)
            else:
                assert vw == vg, f"{rw} != {rg}"
    return got


def test_dist_filter_project(dist_catalog, mesh8):
    _dist_vs_cpu(dist_catalog, mesh8,
                 "select ss_item_sk, ss_quantity, ss_sales_price "
                 "from store_sales where ss_quantity > 40")


def test_dist_star_join_groupby(dist_catalog, mesh8):
    # the q3 shape: fact scan -> dim joins -> group-by -> (host) sort/limit
    _dist_vs_cpu(dist_catalog, mesh8,
                 "select d_year, i_brand_id, sum(ss_ext_sales_price) as s, "
                 "count(*) as n "
                 "from store_sales, date_dim, item "
                 "where ss_sold_date_sk = d_date_sk "
                 "and ss_item_sk = i_item_sk and i_manufact_id > 500 "
                 "group by d_year, i_brand_id "
                 "order by d_year, s desc limit 10")


def test_dist_global_aggregate(dist_catalog, mesh8):
    _dist_vs_cpu(dist_catalog, mesh8,
                 "select count(*) as n, sum(ss_net_paid) as s, "
                 "avg(ss_quantity) as a, min(ss_sales_price) as lo, "
                 "max(ss_sales_price) as hi from store_sales "
                 "where ss_store_sk is not null")


def test_dist_global_aggregate_empty(dist_catalog, mesh8):
    # SQL: a global aggregate over zero rows still returns one row
    # (count 0, NULL sums)
    _dist_vs_cpu(dist_catalog, mesh8,
                 "select count(*) as n, sum(ss_net_paid) as s "
                 "from store_sales where ss_quantity > 1000000")


def test_dist_semi_anti_join(dist_catalog, mesh8):
    _dist_vs_cpu(dist_catalog, mesh8,
                 "select count(*) as n from store_sales where ss_item_sk "
                 "in (select i_item_sk from item "
                 "where i_category = 'Music')")
    _dist_vs_cpu(dist_catalog, mesh8,
                 "select count(*) as n from store_sales where ss_item_sk "
                 "not in (select i_item_sk from item "
                 "where i_category = 'Music')")


def test_dist_agg_expression_outputs(dist_catalog, mesh8):
    _dist_vs_cpu(dist_catalog, mesh8,
                 "select ss_store_sk, "
                 "sum(ss_net_paid) / count(ss_net_paid) as ratio "
                 "from store_sales group by ss_store_sk")


def test_session_spmd_backend(dist_catalog):
    """backend='tpu-spmd' distributes supported queries and silently
    falls back on the rest; results must match the cpu interpreter."""
    from ndstpu.engine.session import Session

    cpu = Session(dist_catalog, backend="cpu")
    spmd = Session(dist_catalog, backend="tpu-spmd", spmd_threshold=1000)
    # distributable star aggregate — must take the distributed branch
    sql = ("select d_year, sum(ss_ext_sales_price) as s from store_sales, "
           "date_dim where ss_sold_date_sk = d_date_sk group by d_year "
           "order by d_year")
    a = cpu.sql(sql).to_rows()
    b = spmd.sql(sql).to_rows()
    assert sorted(map(str, a)) == sorted(map(str, b))
    assert getattr(spmd, "_spmd_used", False), \
        "distributed executor was never used"
    # a window over the sharded scan runs sharded too: rows colocate by
    # partition key (here: none -> one device) and rank on-device
    sql = ("select * from (select ss_item_sk, row_number() over "
           "(order by ss_net_paid desc, ss_item_sk) as rn from "
           "store_sales) t where rn <= 5")
    a = cpu.sql(sql).to_rows()
    b = spmd.sql(sql).to_rows()
    assert sorted(map(str, a)) == sorted(map(str, b))
    # repeat execution takes the cached-executor path (no re-trace) and
    # stays correct; the cache is keyed on the canonical plan
    # fingerprint (parameterized plans share one compiled program)
    from ndstpu import obs
    sql = ("select d_year, sum(ss_ext_sales_price) as s from store_sales, "
           "date_dim where ss_sold_date_sk = d_date_sk group by d_year "
           "order by d_year")
    first = spmd.sql(sql).to_rows()
    assert spmd._spmd_cache, "executor cache never populated"
    before = obs.counters_snapshot()
    again = spmd.sql(sql).to_rows()
    assert obs.counter_delta(before).get("engine.cache.spmd.hit", 0) >= 1
    assert first == again == cpu.sql(sql).to_rows()
    # not distributable (no sharded-size table) -> single-chip fallback
    spmd._spmd_used = False
    sql = "select s_store_sk, s_store_id from store order by s_store_sk"
    a = cpu.sql(sql).to_rows()
    b = spmd.sql(sql).to_rows()
    assert sorted(map(str, a)) == sorted(map(str, b))
    assert not spmd._spmd_used


def test_dist_shuffle_join_inner(dist_catalog, mesh8):
    # fact-fact join over the broadcast limit: all_to_all hash exchange
    _dist_vs_cpu(dist_catalog, mesh8,
                 "select count(*) as c, sum(ss_quantity) as q "
                 "from store_sales, store_returns "
                 "where ss_item_sk = sr_item_sk "
                 "and ss_ticket_number = sr_ticket_number",
                 broadcast_limit=50, expect_shuffle=1)


def test_dist_shuffle_join_left_groupby(dist_catalog, mesh8):
    _dist_vs_cpu(dist_catalog, mesh8,
                 "select i_item_id, count(sr_ticket_number) as r, "
                 "sum(ss_ext_sales_price) as s "
                 "from store_sales left join store_returns "
                 "on ss_item_sk = sr_item_sk "
                 "and ss_ticket_number = sr_ticket_number "
                 "join item on ss_item_sk = i_item_sk "
                 "group by i_item_id",
                 broadcast_limit=50, expect_shuffle=2)


def test_dist_shuffle_join_semi_rowmode(dist_catalog, mesh8):
    _dist_vs_cpu(dist_catalog, mesh8,
                 "select count(*) as c from store_sales where exists "
                 "(select 1 from store_returns where sr_item_sk = ss_item_sk "
                 "and sr_ticket_number = ss_ticket_number)",
                 broadcast_limit=50, expect_shuffle=1)
    # row-mode spine: joined rows come back sharded, no aggregate
    _dist_vs_cpu(dist_catalog, mesh8,
                 "select ss_item_sk, ss_ticket_number, sr_return_quantity "
                 "from store_sales, store_returns "
                 "where ss_item_sk = sr_item_sk "
                 "and ss_ticket_number = sr_ticket_number",
                 broadcast_limit=50, expect_shuffle=1)


def test_dist_shuffle_skew_retry(dist_catalog, mesh8):
    """A low-cardinality shuffle key (every probe row hashes to a handful
    of buckets) overflows the first receive-bucket size; the executor
    must retry with doubled slack up to the lossless bound, never drop."""
    from ndstpu.engine import physical
    from ndstpu.engine.session import Session
    from ndstpu.parallel import dplan

    sess = Session(dist_catalog, backend="cpu")
    sql = ("select s_store_id, count(*) as n from store_sales, store "
           "where ss_store_sk = s_store_sk group by s_store_id")
    plan, _ = sess.plan(sql)
    want = physical.execute(plan, dist_catalog)
    exe = dplan.DistributedPlanExecutor(dist_catalog, mesh8,
                                        shard_threshold_rows=1000,
                                        broadcast_limit_rows=0)
    got = exe.execute_plan(plan)
    assert exe.shuffle_slack > 2, "skew did not trigger a slack retry"
    assert exe._last_dropped == 0
    assert sorted(map(str, want.to_rows())) == sorted(map(str, got.to_rows()))


def test_dist_empty_build_side(dist_catalog, mesh8):
    # a dimension filter that matches nothing: the broadcast build side
    # is empty — joins must produce typed NULLs / empty results, not
    # crash in a zero-row gather
    _dist_vs_cpu(dist_catalog, mesh8,
                 "select count(*) as n, sum(ss_net_paid) as s "
                 "from store_sales, date_dim where ss_sold_date_sk = "
                 "d_date_sk and d_year = 1800")
    _dist_vs_cpu(dist_catalog, mesh8,
                 "select ss_item_sk, d_year from store_sales left join "
                 "date_dim on ss_sold_date_sk = d_date_sk and d_year = 1800")


def test_dist_deep_aggregate_split(dist_catalog, mesh8):
    # stacked aggregates: the DEEPEST one is the spine top; the outer
    # aggregate and sort run in the host tail over the small result
    _dist_vs_cpu(dist_catalog, mesh8,
                 "select avg(s) as a from (select ss_store_sk, "
                 "sum(ss_net_paid) as s from store_sales "
                 "group by ss_store_sk) t")


def test_dist_rollup_grouping_sets(dist_catalog, mesh8):
    # ROLLUP runs the spine at the finest grouping; each set re-combines
    # the decomposable partials on the host (q18/q22/q27/q36/q70 shape)
    _dist_vs_cpu(dist_catalog, mesh8,
                 "select i_category, i_class, "
                 "grouping(i_category) + grouping(i_class) as lochierarchy, "
                 "sum(ss_net_profit) as p, avg(ss_quantity) as aq, "
                 "count(*) as n, min(ss_sales_price) as lo, "
                 "max(ss_sales_price) as hi "
                 "from store_sales, item where ss_item_sk = i_item_sk "
                 "group by rollup(i_category, i_class)")
    _dist_vs_cpu(dist_catalog, mesh8,
                 "select d_year, stddev_samp(ss_quantity) as sd "
                 "from store_sales, date_dim "
                 "where ss_sold_date_sk = d_date_sk "
                 "group by rollup(d_year)")


def test_dist_distinct_aggregates(dist_catalog, mesh8):
    # DISTINCT colocates each group's rows on one device (all_to_all by
    # group-key hash), then dedups locally — globally exact
    _dist_vs_cpu(dist_catalog, mesh8,
                 "select ss_store_sk, count(distinct ss_ticket_number) "
                 "as t, count(*) as n, sum(ss_quantity) as q "
                 "from store_sales group by ss_store_sk")
    _dist_vs_cpu(dist_catalog, mesh8,
                 "select d_year, count(distinct ss_customer_sk) as c, "
                 "sum(distinct ss_sales_price) as sd "
                 "from store_sales, date_dim "
                 "where ss_sold_date_sk = d_date_sk group by d_year")
    _dist_vs_cpu(dist_catalog, mesh8,
                 "select count(distinct ss_item_sk) as u from store_sales")


def test_dist_union_all_aggregate(dist_catalog, mesh8):
    """Channel-union aggregates (q5/q33/q56/q60/q66/q71/q76 shape): each
    branch runs as its own sharded spine; the host combines decomposable
    partials across branches."""
    from ndstpu.engine import physical
    from ndstpu.engine.session import Session
    from ndstpu.parallel import dplan

    sess = Session(dist_catalog, backend="cpu")
    queries = [
        # union -> group by
        "select item_sk, sum(amt) as total, count(*) as n from ("
        "select ss_item_sk as item_sk, ss_ext_sales_price as amt "
        "from store_sales union all "
        "select cs_item_sk as item_sk, cs_ext_sales_price as amt "
        "from catalog_sales union all "
        "select ws_item_sk as item_sk, ws_ext_sales_price as amt "
        "from web_sales) t group by item_sk",
        # union -> rollup (q5 shape)
        "select chan, sk, sum(amt) as total from ("
        "select 'store' as chan, ss_store_sk as sk, ss_net_profit as amt "
        "from store_sales union all "
        "select 'web' as chan, ws_web_site_sk as sk, ws_net_profit as amt "
        "from web_sales) t group by rollup(chan, sk)",
        # union -> global aggregate; min/max fold across branches
        "select sum(amt) as total, min(amt) as lo, max(amt) as hi from ("
        "select ss_ext_sales_price as amt from store_sales union all "
        "select ws_ext_sales_price as amt from web_sales) t",
        # min/max over per-branch dictionary-encoded strings must
        # translate into the union dictionary before folding
        "select k, min(id) as lo, max(id) as hi from ("
        "select ss_store_sk as k, i_item_id as id from store_sales, item "
        "where ss_item_sk = i_item_sk union all "
        "select cs_call_center_sk as k, i_item_id as id from "
        "catalog_sales, item where cs_item_sk = i_item_sk) t group by k",
    ]
    for sql in queries:
        plan, _ = sess.plan(sql)
        want = physical.execute(plan, dist_catalog)
        exe = dplan.DistributedPlanExecutor(dist_catalog, mesh8,
                                            shard_threshold_rows=500)
        got = exe.execute_plan(plan)
        assert exe._union_ctx is not None, f"union path not taken: {sql}"
        assert any(e is not None for e in exe._union_ctx[2])
        rw = sorted(map(str, want.to_rows()))
        rg = sorted(map(str, got.to_rows()))
        assert want.column_names == got.column_names
        assert rw == rg
        # cached repeat execution
        assert sorted(map(str, exe.execute_again().to_rows())) == rg


def test_dist_string_join_keys(dist_catalog, mesh8):
    # string keys join in the build dictionary's code space; the traced
    # probe translates its codes through a static mapping (q56/q60 shape)
    _dist_vs_cpu(dist_catalog, mesh8,
                 "select count(*) as n, sum(ss_ext_sales_price) as s "
                 "from store_sales, item where ss_item_sk = i_item_sk "
                 "and i_item_id in (select i_item_id from item "
                 "where i_color in ('red', 'blue'))")


def test_dist_semi_anti_residual_runs(dist_catalog, mesh8):
    # duplicate build keys + correlated residual: the probe walks the
    # whole key run (q16/q94 EXISTS self-join shape), on both the
    # broadcast and the all_to_all shuffle paths
    sql_exists = (
        "select count(*) as c from web_sales ws1 where exists "
        "(select 1 from web_sales ws2 where ws1.ws_order_number = "
        "ws2.ws_order_number and ws1.ws_warehouse_sk <> "
        "ws2.ws_warehouse_sk)")
    sql_not = sql_exists.replace("where exists", "where not exists")
    for sql in (sql_exists, sql_not):
        _dist_vs_cpu(dist_catalog, mesh8, sql, threshold=500)
        _dist_vs_cpu(dist_catalog, mesh8, sql, threshold=500,
                     broadcast_limit=50, expect_shuffle=1)


def test_dist_multi_union_sites(dist_catalog, mesh8):
    # a q5-shaped plan: rollup over channels whose unions sit UNDER the
    # per-channel aggregates; every union site must distribute (the
    # executor recurses on the plan remainder)
    from ndstpu.engine import physical
    from ndstpu.engine.session import Session
    from ndstpu.parallel import dplan

    sess = Session(dist_catalog, backend="cpu")
    sql = (
        "select chan, sum(amt) as total from ("
        " select 'c1' as chan, sk, amt from ("
        "  select ss_store_sk as sk, ss_net_profit as amt from store_sales"
        "  union all select sr_store_sk as sk, (0 - sr_return_amt) as amt "
        "  from store_returns) a, store where sk = s_store_sk"
        " union all"
        " select 'c2' as chan, sk2, amt2 from ("
        "  select ws_web_site_sk as sk2, ws_net_profit as amt2 "
        "  from web_sales"
        "  union all select wr_web_page_sk as sk2, (0 - wr_return_amt) "
        "  as amt2 from web_returns) b"
        ") t group by rollup(chan)")
    plan, _ = sess.plan(sql)
    want = physical.execute(plan, dist_catalog)
    exe = dplan.DistributedPlanExecutor(dist_catalog, mesh8,
                                        shard_threshold_rows=500)
    got = exe.execute_plan(plan)
    assert exe._union_ctx is not None
    rw = sorted(map(str, want.to_rows()))
    assert sorted(map(str, got.to_rows())) == rw
    assert sorted(map(str, exe.execute_again().to_rows())) == rw


def test_dist_out_of_core_chunks(dist_catalog, mesh8):
    """chunk_rows streams the fact through the device chunk by chunk
    (one compiled program); per-chunk partials combine on the host like
    union branches, row-mode chunks concatenate."""
    from ndstpu.engine import physical
    from ndstpu.engine.session import Session
    from ndstpu.parallel import dplan

    sess = Session(dist_catalog, backend="cpu")
    queries = [
        "select d_year, i_brand_id, sum(ss_ext_sales_price) as s, "
        "count(*) as n from store_sales, date_dim, item "
        "where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk "
        "group by d_year, i_brand_id",
        "select i_category, sum(ss_net_profit) as p, "
        "min(ss_sales_price) as lo from store_sales, item "
        "where ss_item_sk = i_item_sk group by rollup(i_category)",
        "select ss_item_sk, ss_quantity from store_sales "
        "where ss_quantity > 90",
        "select count(*) as c, sum(ss_quantity) as q from store_sales, "
        "store_returns where ss_item_sk = sr_item_sk "
        "and ss_ticket_number = sr_ticket_number",
    ]
    for sql in queries:
        plan, _ = sess.plan(sql)
        want = physical.execute(plan, dist_catalog)
        exe = dplan.DistributedPlanExecutor(
            dist_catalog, mesh8, shard_threshold_rows=500,
            broadcast_limit_rows=50, chunk_rows=1000)
        got = exe.execute_plan(plan)
        assert exe._chunk_info[0], f"not chunked: {sql[:50]}"
        rw = sorted(map(str, want.to_rows()))
        assert sorted(map(str, got.to_rows())) == rw, sql[:60]
        assert sorted(map(str, exe.execute_again().to_rows())) == rw


def test_dist_dup_insensitive_semi_conversion(dist_catalog, mesh8):
    # q37/q82 shape: an expanding inner join (inventory's non-unique
    # item keys) feeding a pure GROUP BY dedup — demoted to a semi join
    _dist_vs_cpu(dist_catalog, mesh8,
                 "select i_item_id, i_current_price from item, inventory, "
                 "store_sales where i_item_sk = inv_item_sk "
                 "and i_item_sk = ss_item_sk "
                 "and inv_quantity_on_hand between 100 and 500 "
                 "group by i_item_id, i_current_price")


SPMD_CORPUS_TPLS = [
    "query2.tpl",    # CTE union reused twice (multi union sites)
    "query5.tpl",    # rollup over channels with nested unions
    "query10.tpl",   # EXISTS build sides that contain sharded facts
    "query16.tpl",   # semi/anti self-join with residual runs
    "query35.tpl",   # EXISTS-over-three-channels build reduction
    "query37.tpl",   # expanding inventory join -> semi conversion
    "query56.tpl",   # string join keys in union channels
    "query69.tpl",   # EXISTS + NOT EXISTS mixed build reduction
    "query75.tpl",   # multi-channel union with fact-fact joins
    "query82.tpl",   # expanding inventory join -> semi conversion
    "query94.tpl",   # EXISTS/NOT EXISTS self-join residual runs
]


@pytest.mark.slow
@pytest.mark.parametrize("tpl", SPMD_CORPUS_TPLS)
def test_spmd_corpus_differential(dist_catalog, mesh8, tpl):
    """The corpus queries that exercise the newest distributed paths
    must DISTRIBUTE (no fallback) and match the numpy oracle."""
    from ndstpu.engine import physical
    from ndstpu.engine.session import Session
    from ndstpu.parallel import dplan
    from ndstpu.queries import streamgen

    sess = Session(dist_catalog, backend="cpu")
    for _name, sql in streamgen.render_template_parts(
            str(streamgen.TEMPLATE_DIR / tpl), "07291122510", 0):
        plan, _ = sess.plan(sql)
        want = physical.execute(plan, dist_catalog)
        exe = dplan.DistributedPlanExecutor(dist_catalog, mesh8,
                                            shard_threshold_rows=500)
        got = exe.execute_plan(plan)   # DistUnsupported = regression
        rows_w = sorted(want.to_rows(), key=lambda r: tuple(
            (v is None, str(v)) for v in r))
        rows_g = sorted(got.to_rows(), key=lambda r: tuple(
            (v is None, str(v)) for v in r))
        assert want.column_names == got.column_names
        assert len(rows_w) == len(rows_g)
        for rw, rg in zip(rows_w, rows_g):
            for vw, vg in zip(rw, rg):
                if isinstance(vw, float) and isinstance(vg, float):
                    assert vw == pytest.approx(vg, rel=1e-7, abs=1e-7)
                else:
                    assert vw == vg, f"{rw} != {rg}"


def test_dist_unsupported_falls_out(dist_catalog, mesh8):
    from ndstpu.engine.session import Session
    from ndstpu.parallel import dplan

    sess = Session(dist_catalog, backend="cpu")
    # full outer join is outside the spine subset
    plan, _ = sess.plan(
        "select count(*) as n from store_sales full join store_returns "
        "on ss_ticket_number = sr_ticket_number "
        "and ss_item_sk = sr_item_sk")
    with pytest.raises(dplan.DistUnsupported):
        dplan.execute_distributed(dist_catalog, mesh8, plan,
                                  shard_threshold_rows=1000,
                                  broadcast_limit_rows=100)
    # no sharded-size table at all
    plan2, _ = sess.plan("select count(*) as n from item")
    with pytest.raises(dplan.DistUnsupported):
        dplan.execute_distributed(dist_catalog, mesh8, plan2,
                                  shard_threshold_rows=10**9)


def test_mesh_construction():
    m = pmesh.make_mesh(8)
    assert m.devices.size == 8
    assert m.axis_names == (pmesh.SHARD_AXIS,)
    with pytest.raises(ValueError):
        pmesh.make_mesh(10**6)


def test_single_chip_out_of_core(dist_catalog):
    """Session backend='tpu' + spmd_chunk_rows routes aggregates through
    the chunked executor over a 1-DEVICE mesh (SF >> HBM on one chip,
    VERDICT weak #7): differential vs the numpy interpreter at an
    artificially small chunk size, with chunking actually engaged."""
    from ndstpu.engine.session import Session

    cpu = Session(dist_catalog, backend="cpu")
    tpu = Session(dist_catalog, backend="tpu",
                  spmd_threshold=500, spmd_chunk_rows=1000)
    queries = [
        "select d_year, i_brand_id, sum(ss_ext_sales_price) as s, "
        "count(*) as n from store_sales, date_dim, item "
        "where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk "
        "group by d_year, i_brand_id",
        # row-mode spine (no aggregate): chunks concatenate
        "select ss_item_sk, ss_quantity from store_sales "
        "where ss_quantity > 90",
    ]
    for sql in queries:
        want = sorted(map(str, cpu.sql(sql).to_rows()))
        got = sorted(map(str, tpu.sql(sql).to_rows()))
        assert got == want, sql[:60]
    assert getattr(tpu, "_spmd_used", False)
    assert not getattr(tpu, "_spmd_errors", None)
    # the mesh really is single-device
    assert len(tpu._mesh().devices.ravel()) == 1
    # chunking engaged on the cached executors
    chunked = [ent[1]._chunk_info[0]
               for ent in tpu._spmd_cache.values()]
    assert any(chunked)
    # a shape the chunked executor can't take still answers (fallback)
    out = tpu.sql("select count(*) as n from item")
    assert out.to_rows()[0][0] == dist_catalog.get("item").num_rows


def test_dist_scalar_subquery_offload(dist_catalog, mesh8):
    """q9 shape: outer FROM is a tiny dim; the work lives in uncorrelated
    scalar subqueries over the fact. Each body runs distributed (child
    executors) and the scalars are inlined into the host outer plan."""
    from ndstpu.engine import physical
    from ndstpu.engine.session import Session
    from ndstpu.parallel import dplan
    from ndstpu.queries import streamgen

    sess = Session(dist_catalog, backend="cpu")
    _name, sql = streamgen.render_template_parts(
        str(streamgen.TEMPLATE_DIR / "query9.tpl"), "07291122510", 0)[0]
    plan, _ = sess.plan(sql)
    want = physical.execute(plan, dist_catalog)
    exe = dplan.DistributedPlanExecutor(dist_catalog, mesh8,
                                        shard_threshold_rows=500)
    got = exe.execute_plan(plan)
    assert getattr(exe, "_scalar_ctx", None) is not None
    assert len(exe._scalar_ctx[1]) == 15      # 5 buckets x (count,avg,avg)
    assert sorted(map(str, got.to_rows())) == \
        sorted(map(str, want.to_rows()))
    assert sorted(map(str, exe.execute_again().to_rows())) == \
        sorted(map(str, want.to_rows()))


def test_dist_expanding_inner_broadcast_join(dist_catalog, mesh8):
    """Non-unique build keys on an inner broadcast join expand the probe
    side by bounded duplication (q72's d1-d2 week_seq join: <=7 days per
    week), instead of falling back to the single-chip path."""
    from ndstpu.engine import physical
    from ndstpu.engine.session import Session
    from ndstpu.parallel import dplan

    sess = Session(dist_catalog, backend="cpu")
    # d2 joins the spine on inv_date_sk (unique), then d1 arrives via
    # the NON-unique d_week_seq edge and must expand (7 days/week), with
    # the quantity filter as a lifted residual
    sql = ("select d1.d_day_name, count(*) as n, "
           "sum(inv_quantity_on_hand) as q "
           "from inventory "
           "join date_dim d2 on inv_date_sk = d2.d_date_sk "
           "join date_dim d1 on d1.d_week_seq = d2.d_week_seq "
           "where inv_quantity_on_hand < 500 "
           "group by d1.d_day_name")
    plan, _ = sess.plan(sql)
    want = physical.execute(plan, dist_catalog)
    exe = dplan.DistributedPlanExecutor(dist_catalog, mesh8,
                                        shard_threshold_rows=500)
    got = exe.execute_plan(plan)
    assert any(isinstance(j, dplan._BroadcastJoin) and j.dup_max > 1
               and j.kind == "inner" for j in exe.joins.values()), \
        "expansion not engaged"
    assert sorted(map(str, got.to_rows())) == \
        sorted(map(str, want.to_rows()))
    assert sorted(map(str, exe.execute_again().to_rows())) == \
        sorted(map(str, want.to_rows()))


def test_dist_build_reduce_existence_join(dist_catalog, mesh8):
    """q10/q35/q69 shape: an EXISTS / NOT EXISTS build side contains a
    sharded-size fact.  Instead of executing the whole subtree on host
    numpy, a child spine reduces it to its distinct join-key tuples over
    the mesh (existence joins are insensitive to build multiplicity) and
    only those broadcast."""
    from ndstpu.engine import physical
    from ndstpu.engine.session import Session
    from ndstpu.parallel import dplan

    sess = Session(dist_catalog, backend="cpu")
    sql_exists = (
        "select count(*) as c from store_sales where exists "
        "(select 1 from web_sales where ws_item_sk = ss_item_sk)")
    for sql in (sql_exists,
                sql_exists.replace("where exists", "where not exists")):
        plan, _ = sess.plan(sql)
        want = physical.execute(plan, dist_catalog)
        exe = dplan.DistributedPlanExecutor(dist_catalog, mesh8,
                                            shard_threshold_rows=500)
        got = exe.execute_plan(plan)
        assert exe.build_reduced, f"build not reduced distributed: {sql}"
        kind, n_reduced = exe.build_reduced[0]
        assert kind in ("semi", "anti", "nullaware_anti", "mark")
        # the reduction really deduplicated (distinct item keys < rows)
        assert n_reduced < dist_catalog.get("web_sales").num_rows
        rw = sorted(map(str, want.to_rows()))
        assert sorted(map(str, got.to_rows())) == rw
        assert sorted(map(str, exe.execute_again().to_rows())) == rw


def test_dist_build_reduce_attempt_recovery(dist_catalog, mesh8):
    """When the LARGEST fact sits on the build side, its anchored
    candidate fails fast with NDS308 (recorded in attempt_codes), and
    the probe-anchored candidate distributes with the reduced build —
    the executor recovers instead of falling back to single-chip."""
    from ndstpu.engine import physical
    from ndstpu.engine.session import Session
    from ndstpu.parallel import dplan

    sess = Session(dist_catalog, backend="cpu")
    assert dist_catalog.get("store_sales").num_rows > \
        dist_catalog.get("web_sales").num_rows
    sql = ("select count(*) as c from web_sales where exists "
           "(select 1 from store_sales where ss_item_sk = ws_item_sk)")
    plan, _ = sess.plan(sql)
    want = physical.execute(plan, dist_catalog)
    exe = dplan.DistributedPlanExecutor(dist_catalog, mesh8,
                                        shard_threshold_rows=500)
    got = exe.execute_plan(plan)
    assert "NDS308" in exe.attempt_codes, \
        "fact-on-build-side candidate should have failed with NDS308"
    assert exe.build_reduced
    assert sorted(map(str, got.to_rows())) == \
        sorted(map(str, want.to_rows()))


def test_dist_sharded_window(dist_catalog, mesh8):
    """Ranking and whole-partition aggregate windows run sharded: rows
    colocate by partition-key hash (one all_to_all per distinct
    PARTITION BY list), ties replay the original row order."""
    # rank with a duplicate-heavy order key
    _dist_vs_cpu(dist_catalog, mesh8,
                 "select ss_store_sk, ss_item_sk, "
                 "rank() over (partition by ss_store_sk "
                 "order by ss_net_paid desc) as rnk "
                 "from store_sales where ss_net_paid > 90",
                 threshold=500)
    # two windows with DIFFERENT partition keys (two exchanges), plus
    # row_number ties broken by original row order on both paths
    _dist_vs_cpu(dist_catalog, mesh8,
                 "select ss_ticket_number, "
                 "row_number() over (partition by ss_store_sk "
                 "order by ss_sold_date_sk, ss_ticket_number) as rn, "
                 "dense_rank() over (partition by ss_item_sk "
                 "order by ss_quantity desc) as dr "
                 "from store_sales where ss_quantity > 80",
                 threshold=500)
    # whole-partition aggregates (no ORDER BY): order-independent
    _dist_vs_cpu(dist_catalog, mesh8,
                 "select ss_item_sk, ss_net_paid, "
                 "sum(ss_net_paid) over (partition by ss_item_sk) as tot, "
                 "count(*) over (partition by ss_item_sk) as n, "
                 "avg(ss_quantity) over (partition by ss_item_sk) as aq "
                 "from store_sales where ss_item_sk < 100",
                 threshold=500)


def test_dist_device_tail_topk(dist_catalog, mesh8):
    """Sort+LIMIT (or bare LIMIT) above a row spine finalizes on-device
    as a per-device top-k: only ~limit rows ever reach the host (the
    host_gather_bytes counter is the evidence), and the result must be
    bit-identical to the numpy interpreter INCLUDING row order."""
    from ndstpu import obs
    from ndstpu.engine import physical
    from ndstpu.engine.session import Session
    from ndstpu.parallel import dplan

    sess = Session(dist_catalog, backend="cpu")
    queries = [
        # ordered top-k; desc + tiebreak column
        "select ss_item_sk, ss_net_paid from store_sales "
        "where ss_quantity > 10 "
        "order by ss_net_paid desc, ss_item_sk limit 25",
        # NULLable leading key, mixed asc/desc
        "select ss_store_sk, ss_net_profit from store_sales "
        "order by ss_store_sk, ss_net_profit desc limit 17",
        # bare LIMIT: original row order, no sort keys at all
        "select ss_item_sk, ss_ticket_number from store_sales limit 40",
        # limit larger than the alive row count: dead-row padding in the
        # gather must be masked out, every alive row survives
        "select ss_item_sk from store_sales where ss_quantity > 99 "
        "order by ss_item_sk limit 1000",
    ]
    for sql in queries:
        plan, _ = sess.plan(sql)
        want = physical.execute(plan, dist_catalog)
        exe = dplan.DistributedPlanExecutor(dist_catalog, mesh8,
                                            shard_threshold_rows=500)
        before = obs.counters_snapshot()
        got = exe.execute_plan(plan)
        delta = obs.counter_delta(before)
        assert exe._tail is not None, f"tail not on-device: {sql[:50]}"
        assert want.column_names == got.column_names
        # ORDER-SENSITIVE comparison: the whole point of the tail
        assert [tuple(map(str, r)) for r in got.to_rows()] == \
            [tuple(map(str, r)) for r in want.to_rows()], sql[:60]
        assert delta.get("exchange.collective.calls", 0) >= 1
        gathered = delta.get("engine.spmd.host_gather_bytes", 0)
        assert gathered > 0
        rw = [tuple(map(str, r)) for r in want.to_rows()]
        assert [tuple(map(str, r))
                for r in exe.execute_again().to_rows()] == rw
    # evidence of the bytes DROP: the 25-row tail gathers orders of
    # magnitude less than the sharded relation it ranks (which the
    # pre-tail executor shipped to the host wholesale)
    plan, _ = sess.plan(queries[0])
    exe = dplan.DistributedPlanExecutor(dist_catalog, mesh8,
                                        shard_threshold_rows=500)
    before = obs.counters_snapshot()
    exe.execute_plan(plan)
    gathered = obs.counter_delta(before).get(
        "engine.spmd.host_gather_bytes", 0)
    n_fact = dist_catalog.get("store_sales").num_rows
    assert 0 < gathered < n_fact * 2 * 8, \
        f"tail gathered {gathered} bytes for {n_fact} fact rows"


def test_session_spmd_parameterized_plans(dist_catalog):
    """Parameterized (canonicalized) plans take the SPMD path: the
    executor cache keys on the canonical fingerprint plus the bound
    literal values (literals bake into the compiled program), where the
    old executor rejected any plan with parameters (NDS301)."""
    from ndstpu import obs
    from ndstpu.engine.session import Session

    cpu = Session(dist_catalog, backend="cpu")
    spmd = Session(dist_catalog, backend="tpu-spmd", spmd_threshold=500)
    tpl = ("select d_year, sum(ss_ext_sales_price) as s from store_sales"
           ", date_dim where ss_sold_date_sk = d_date_sk "
           "and ss_quantity > {} group by d_year order by d_year")
    a = spmd.sql(tpl.format(10)).to_rows()
    assert a == cpu.sql(tpl.format(10)).to_rows()
    assert getattr(spmd, "_spmd_used", False), "SPMD path not used"
    assert not getattr(spmd, "_spmd_errors", None)
    # a different literal binds a different value hash (new entry, still
    # distributed, still correct)
    b = spmd.sql(tpl.format(90)).to_rows()
    assert b == cpu.sql(tpl.format(90)).to_rows()
    # the same literal again is a cache hit (no re-trace)
    before = obs.counters_snapshot()
    again = spmd.sql(tpl.format(10)).to_rows()
    assert again == a
    assert obs.counter_delta(before).get("engine.cache.spmd.hit", 0) >= 1


@pytest.mark.slow
def test_dist_full_corpus_row_equal(dist_catalog, mesh8):
    """EVERY corpus query part must (a) execute under the distributed
    executor on the 8-device mesh and (b) produce rows equal to the
    numpy interpreter — the distributed analog of the reference's
    full-corpus differential validation (nds_validate.py:217-260).
    Previously only 8 templates were oracle-compared (VERDICT r3 #3)."""
    import importlib.util
    import pathlib

    spec = importlib.util.spec_from_file_location(
        "spmd_coverage",
        pathlib.Path(__file__).resolve().parent.parent / "scripts" /
        "spmd_coverage.py")
    cov = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cov)

    ok, mism, fell = cov.run_corpus(dist_catalog, mesh8,
                                    shard_threshold_rows=500,
                                    verbose=False)
    assert not fell, f"distributed fallbacks: {fell}"
    assert not mism, f"distributed row mismatches: {mism}"
    assert len(ok) >= 103
