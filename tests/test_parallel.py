"""Multi-chip sharding tests on the virtual 8-device CPU mesh.

Validates that the distributed query step (shard_map + collectives)
compiles and produces results identical to a numpy oracle, and that the
exchange primitives preserve rows."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from ndstpu.parallel import dquery, exchange, mesh as pmesh


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) >= 8, "conftest must force 8 CPU devices"
    return pmesh.make_mesh(8)


def test_q3_step_matches_oracle(mesh8):
    n_items, n_dates, d_base = 64, 64, 2450815
    args = dquery.example_inputs(n_rows=4096, n_items=n_items,
                                 n_dates=n_dates, d_base=d_base,
                                 n_dev=8)
    step = dquery.build_q3_step(mesh8, n_items, n_dates, d_base)
    sharding = pmesh.row_sharding(mesh8)
    sharded_args = [jax.device_put(a, sharding) for a in args[:3]] + \
        [jax.device_put(a, pmesh.replicated(mesh8)) for a in args[3:]]
    per_brand, n_rows, shuffled, dropped = step(*sharded_args)
    ref_brand, ref_n, ref_item = dquery.reference_result(
        *args, n_items=n_items, n_dates=n_dates, d_base=d_base)
    assert int(dropped) == 0
    np.testing.assert_array_equal(np.asarray(per_brand), ref_brand)
    assert int(n_rows) == ref_n
    np.testing.assert_array_equal(np.asarray(shuffled), ref_item)


def test_hash_repartition_preserves_rows(mesh8):
    """Every alive row lands on exactly one device, keyed consistently."""
    n_dev = 8
    n_local = 128
    bucket_cap = 64
    rng = np.random.RandomState(7)
    keys = rng.randint(0, 50, n_dev * n_local).astype(np.int64)
    vals = rng.randint(0, 1000, n_dev * n_local).astype(np.int64)
    alive = rng.rand(n_dev * n_local) < 0.9

    def body(k, v, a):
        cols, alive_out, dropped = exchange.hash_repartition(
            {"v": v, "k": k}, k, a, n_dev, bucket_cap)
        # per-key sums of received rows
        local = jax.ops.segment_sum(
            jnp.where(alive_out, cols["v"], 0),
            jnp.clip(cols["k"], 0, 49).astype(jnp.int32), num_segments=50)
        return jax.lax.psum(local, pmesh.SHARD_AXIS), dropped

    fn = jax.jit(shard_map(
        body, mesh=mesh8,
        in_specs=(P(pmesh.SHARD_AXIS),) * 3, out_specs=(P(), P()),
        check_vma=False))
    got, dropped = fn(
        jax.device_put(jnp.asarray(keys), pmesh.row_sharding(mesh8)),
        jax.device_put(jnp.asarray(vals), pmesh.row_sharding(mesh8)),
        jax.device_put(jnp.asarray(alive), pmesh.row_sharding(mesh8)))
    assert int(dropped) == 0
    ref = np.zeros(50, np.int64)
    np.add.at(ref, keys[alive], vals[alive])
    np.testing.assert_array_equal(np.asarray(got), ref)


def test_broadcast_gather(mesh8):
    n_dev, n_local = 8, 16
    x = np.arange(n_dev * n_local, dtype=np.int32)

    def body(v):
        return exchange.broadcast_gather(v)

    fn = jax.jit(shard_map(body, mesh=mesh8,
                           in_specs=P(pmesh.SHARD_AXIS),
                           out_specs=P(pmesh.SHARD_AXIS)))
    out = fn(jax.device_put(jnp.asarray(x), pmesh.row_sharding(mesh8)))
    # each shard gathered the full array; sharded output stacks them
    assert out.shape == (n_dev * n_dev * n_local,)
    np.testing.assert_array_equal(np.asarray(out)[:n_dev * n_local], x)


# -- distributed plan executor (SQL -> SPMD program) ------------------------


@pytest.fixture(scope="module")
def dist_catalog(tmp_path_factory):
    import os
    import subprocess

    from ndstpu.io import loader
    data = tmp_path_factory.mktemp("draw")
    wh = tmp_path_factory.mktemp("dwh")
    env = dict(os.environ, PYTHONPATH=os.getcwd())
    subprocess.run(["python", "-m", "ndstpu.datagen.driver", "local",
                    "0.002", "2", str(data)], check=True, env=env)
    subprocess.run(["python", "-m", "ndstpu.io.transcode",
                    "--input_prefix", str(data),
                    "--output_prefix", str(wh),
                    "--report_file", str(wh / "load.txt")],
                   check=True, env=env, stdout=subprocess.DEVNULL)
    return loader.load_catalog(str(wh))


def _dist_vs_cpu(catalog, mesh, sql, threshold=1000):
    """Plan once; run distributed and on the numpy interpreter; compare."""
    from ndstpu.engine import physical
    from ndstpu.engine.session import Session
    from ndstpu.parallel import dplan

    sess = Session(catalog, backend="cpu")
    plan, _cols = sess.plan(sql)
    want = physical.execute(plan, catalog)
    got = dplan.execute_distributed(catalog, mesh, plan,
                                    shard_threshold_rows=threshold)
    assert want.column_names == got.column_names
    rows_w = sorted(want.to_rows(), key=lambda r: tuple(
        (v is None, str(v)) for v in r))
    rows_g = sorted(got.to_rows(), key=lambda r: tuple(
        (v is None, str(v)) for v in r))
    assert len(rows_w) == len(rows_g), \
        f"{len(rows_w)} vs {len(rows_g)} rows"
    for rw, rg in zip(rows_w, rows_g):
        for vw, vg in zip(rw, rg):
            if isinstance(vw, float) and isinstance(vg, float):
                assert vw == pytest.approx(vg, rel=1e-9, abs=1e-9)
            else:
                assert vw == vg, f"{rw} != {rg}"
    return got


def test_dist_filter_project(dist_catalog, mesh8):
    _dist_vs_cpu(dist_catalog, mesh8,
                 "select ss_item_sk, ss_quantity, ss_sales_price "
                 "from store_sales where ss_quantity > 40")


def test_dist_star_join_groupby(dist_catalog, mesh8):
    # the q3 shape: fact scan -> dim joins -> group-by -> (host) sort/limit
    _dist_vs_cpu(dist_catalog, mesh8,
                 "select d_year, i_brand_id, sum(ss_ext_sales_price) as s, "
                 "count(*) as n "
                 "from store_sales, date_dim, item "
                 "where ss_sold_date_sk = d_date_sk "
                 "and ss_item_sk = i_item_sk and i_manufact_id > 500 "
                 "group by d_year, i_brand_id "
                 "order by d_year, s desc limit 10")


def test_dist_global_aggregate(dist_catalog, mesh8):
    _dist_vs_cpu(dist_catalog, mesh8,
                 "select count(*) as n, sum(ss_net_paid) as s, "
                 "avg(ss_quantity) as a, min(ss_sales_price) as lo, "
                 "max(ss_sales_price) as hi from store_sales "
                 "where ss_store_sk is not null")


def test_dist_global_aggregate_empty(dist_catalog, mesh8):
    # SQL: a global aggregate over zero rows still returns one row
    # (count 0, NULL sums)
    _dist_vs_cpu(dist_catalog, mesh8,
                 "select count(*) as n, sum(ss_net_paid) as s "
                 "from store_sales where ss_quantity > 1000000")


def test_dist_semi_anti_join(dist_catalog, mesh8):
    _dist_vs_cpu(dist_catalog, mesh8,
                 "select count(*) as n from store_sales where ss_item_sk "
                 "in (select i_item_sk from item "
                 "where i_category = 'Music')")
    _dist_vs_cpu(dist_catalog, mesh8,
                 "select count(*) as n from store_sales where ss_item_sk "
                 "not in (select i_item_sk from item "
                 "where i_category = 'Music')")


def test_dist_agg_expression_outputs(dist_catalog, mesh8):
    _dist_vs_cpu(dist_catalog, mesh8,
                 "select ss_store_sk, "
                 "sum(ss_net_paid) / count(ss_net_paid) as ratio "
                 "from store_sales group by ss_store_sk")


def test_session_spmd_backend(dist_catalog):
    """backend='tpu-spmd' distributes supported queries and silently
    falls back on the rest; results must match the cpu interpreter."""
    from ndstpu.engine.session import Session

    cpu = Session(dist_catalog, backend="cpu")
    spmd = Session(dist_catalog, backend="tpu-spmd", spmd_threshold=1000)
    # distributable star aggregate — must take the distributed branch
    sql = ("select d_year, sum(ss_ext_sales_price) as s from store_sales, "
           "date_dim where ss_sold_date_sk = d_date_sk group by d_year "
           "order by d_year")
    a = cpu.sql(sql).to_rows()
    b = spmd.sql(sql).to_rows()
    assert sorted(map(str, a)) == sorted(map(str, b))
    assert getattr(spmd, "_spmd_used", False), \
        "distributed executor was never used"
    # a window over the sharded scan distributes the scan and finishes
    # the window in the host tail
    sql = ("select * from (select ss_item_sk, row_number() over "
           "(order by ss_net_paid desc, ss_item_sk) as rn from "
           "store_sales) t where rn <= 5")
    a = cpu.sql(sql).to_rows()
    b = spmd.sql(sql).to_rows()
    assert sorted(map(str, a)) == sorted(map(str, b))
    # repeat execution takes the cached-executor path (no re-trace) and
    # stays correct
    sql = ("select d_year, sum(ss_ext_sales_price) as s from store_sales, "
           "date_dim where ss_sold_date_sk = d_date_sk group by d_year "
           "order by d_year")
    first = spmd.sql(sql).to_rows()
    assert sql in " ".join(k or "" for k in spmd._spmd_cache)
    again = spmd.sql(sql).to_rows()
    assert first == again == cpu.sql(sql).to_rows()
    # not distributable (no sharded-size table) -> single-chip fallback
    spmd._spmd_used = False
    sql = "select s_store_sk, s_store_id from store order by s_store_sk"
    a = cpu.sql(sql).to_rows()
    b = spmd.sql(sql).to_rows()
    assert sorted(map(str, a)) == sorted(map(str, b))
    assert not spmd._spmd_used


def test_dist_unsupported_falls_out(dist_catalog, mesh8):
    from ndstpu.engine.session import Session
    from ndstpu.parallel import dplan

    sess = Session(dist_catalog, backend="cpu")
    # fact-fact join: the second table exceeds the broadcast limit
    plan, _ = sess.plan(
        "select count(*) as n from store_sales, store_returns "
        "where ss_ticket_number = sr_ticket_number "
        "and ss_item_sk = sr_item_sk")
    with pytest.raises(dplan.DistUnsupported):
        dplan.execute_distributed(dist_catalog, mesh8, plan,
                                  shard_threshold_rows=1000,
                                  broadcast_limit_rows=100)
    # no sharded-size table at all
    plan2, _ = sess.plan("select count(*) as n from item")
    with pytest.raises(dplan.DistUnsupported):
        dplan.execute_distributed(dist_catalog, mesh8, plan2,
                                  shard_threshold_rows=10**9)


def test_mesh_construction():
    m = pmesh.make_mesh(8)
    assert m.devices.size == 8
    assert m.axis_names == (pmesh.SHARD_AXIS,)
    with pytest.raises(ValueError):
        pmesh.make_mesh(10**6)
