"""Device admission control (ndstpu.harness.admission): the
concurrentGpuTasks analog for concurrent streams on one chip."""

import multiprocessing as mp
import time

from ndstpu.harness.admission import DeviceAdmission, from_env


def _worker(lock_dir, slots, hold_s, out):
    gate = DeviceAdmission(slots, lock_dir)
    with gate.slot():
        out.put(("in", time.time()))
        time.sleep(hold_s)
        out.put(("out", time.time()))
    gate.close()


def test_semaphore_bounds_concurrency(tmp_path):
    """4 processes through a 2-slot gate: at most 2 inside at once.
    spawn, not fork: the pytest process has live JAX threads."""
    ctx = mp.get_context("spawn")
    slots, nproc, hold = 2, 4, 0.3
    q = ctx.Queue()
    procs = [ctx.Process(target=_worker,
                         args=(str(tmp_path), slots, hold, q))
             for _ in range(nproc)]
    for p in procs:
        p.start()
    events = []
    for _ in range(nproc * 2):
        # generous timeout: spawn re-imports the package per process,
        # which can take >30 s on a loaded machine (observed flaking
        # while a TPU warm run shared the host)
        events.append(q.get(timeout=180))
    for p in procs:
        p.join(timeout=60)
    events.sort(key=lambda e: e[1])
    inside = peak = 0
    for kind, _ in events:
        inside += 1 if kind == "in" else -1
        peak = max(peak, inside)
    assert peak <= slots, f"{peak} streams inside a {slots}-slot gate"
    assert peak >= 1


def test_same_process_reacquire(tmp_path):
    gate = DeviceAdmission(1, str(tmp_path))
    with gate.slot():
        pass
    with gate.slot():   # releasing must allow re-acquisition
        pass
    gate.close()


def test_from_env(tmp_path, monkeypatch):
    monkeypatch.delenv("NDSTPU_ADMISSION_SLOTS", raising=False)
    assert from_env() is None
    monkeypatch.setenv("NDSTPU_ADMISSION_SLOTS", "3")
    monkeypatch.setenv("NDSTPU_ADMISSION_DIR", str(tmp_path))
    gate = from_env()
    assert gate is not None and gate.slots == 3
    gate.close()
