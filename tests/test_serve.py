"""Serve layer: protocol, admission/overload edges, drain, warm restart.

The satellite coverage the issue names explicitly:

* drain with a hung in-flight query hits the watchdog path (abandon on
  a zombie thread + fresh-session swap) instead of blocking shutdown;
* a tenant at budget gets the typed ``Rejected`` while other tenants
  proceed;
* a tripped circuit breaker recovers after its cooldown (half-open
  probe) — tripped off the PR 5 quarantine list, per canonical key.

Plus the protocol/scheduler/lifecycle seams the server composes:
length-prefixed framing, continuous-feed StreamScheduler streams,
connection-fault taxonomy, journal replay, and the warm-restart
zero-new-compiles invariant the serve smoke proves cross-process.
"""

import os
import socket
import threading
import time

import numpy as np
import pytest

from ndstpu import faults, obs
from ndstpu.engine.columnar import INT32, Column, Table
from ndstpu.engine.session import Session
from ndstpu.faults import taxonomy
from ndstpu.harness.scheduler import StreamScheduler
from ndstpu.io import atomic
from ndstpu.io.loader import Catalog
from ndstpu.obs import artifact_lint
from ndstpu.serve import lifecycle, protocol
from ndstpu.serve.client import ServeClient
from ndstpu.serve.overload import (AdmissionQueue, CircuitBreaker,
                                   Overloaded, Rejected, TenantBudgets)
from ndstpu.serve.server import QueryServer, ServeConfig


def col_i32(vals):
    return Column(np.asarray(vals, dtype=np.int32), INT32, None)


def tiny_session(backend: str = "cpu") -> Session:
    cat = Catalog()
    cat.register("t", Table({
        "a": col_i32(list(range(10))),
        "b": col_i32([v % 3 for v in range(10)]),
    }))
    return Session(cat, backend=backend)


@pytest.fixture
def serve_env(tmp_path):
    """A started server over a tiny cpu session + one client; drains
    on teardown.  Yields a factory so tests can tune ServeConfig."""
    made = []

    def make(session=None, **cfg):
        defaults = dict(
            socket_path=str(tmp_path / f"s{len(made)}.sock"),
            engine="cpu",
            output_prefix=str(tmp_path / f"out{len(made)}"),
            journal_path=str(tmp_path / f"journal{len(made)}.jsonl"),
            slo_path=str(tmp_path / f"SLO{len(made)}.json"),
            ledger_path="none",
            query_timeout_s=30.0)
        defaults.update(cfg)
        srv = QueryServer(ServeConfig(**defaults),
                          session=session or tiny_session(
                              defaults["engine"]))
        srv.start()
        cli = ServeClient(defaults["socket_path"], retries=4,
                          connect_timeout_s=10.0)
        assert cli.wait_ready(10.0)
        made.append((srv, cli))
        return srv, cli

    yield make
    for srv, cli in made:
        cli.close()
        if not srv.draining:
            srv.drain(reason="teardown")


# -- protocol ----------------------------------------------------------------

def test_protocol_roundtrip_and_bounds():
    a, b = socket.socketpair()
    try:
        msg = {"op": "sql", "sql": "SELECT 1; -- '\n\x00 unicode ☃"}
        protocol.send_msg(a, msg)
        assert protocol.recv_msg(b) == msg
        a.close()
        assert protocol.recv_msg(b) is None  # clean EOF
    finally:
        b.close()
    c, d = socket.socketpair()
    try:
        c.sendall(b"\x7f\xff\xff\xff")  # absurd length prefix
        with pytest.raises(protocol.ProtocolError):
            protocol.recv_msg(d)
    finally:
        c.close()
        d.close()


def test_protocol_truncated_frame_is_error():
    a, b = socket.socketpair()
    try:
        a.sendall(b"\x00\x00\x00\x10partial")
        a.close()
        with pytest.raises(protocol.ProtocolError):
            protocol.recv_msg(b)
    finally:
        b.close()


# -- connection-fault taxonomy (satellite 1) ---------------------------------

def test_connection_faults_classify_transient():
    assert taxonomy.classify(socket.timeout("timed out")) == "transient"
    assert taxonomy.classify(
        ConnectionRefusedError("connection refused")) == "transient"
    assert taxonomy.classify(ConnectionResetError()) == "transient"
    assert taxonomy.classify(BrokenPipeError()) == "transient"
    # pre-3.10 socket.timeout pickles/paths carry the bare class name
    assert taxonomy.classify_name("timeout", "") == "transient"
    assert taxonomy.classify_name(
        "SomeWrapperError", "upstream: Connection refused") == "transient"
    assert taxonomy.classify_name(
        "SomeWrapperError", "Broken pipe on fd 7") == "transient"


# -- continuous-feed scheduler ----------------------------------------------

def test_scheduler_continuous_feed():
    sched = StreamScheduler({})
    view = sched.open_stream("c1")
    sched.feed("c1", "q1", "SELECT 1")
    sched.feed("c1", "q2", "SELECT 2")
    assert view.next(0.0) in ("q1", "q2")
    view.done("q1")
    got = []

    def drain_view():
        while True:
            n = view.next(0.0)
            if n is None:
                return
            got.append(n)
            view.done(n)

    th = threading.Thread(target=drain_view, daemon=True)
    th.start()
    time.sleep(0.1)
    sched.feed("c1", "q3", "SELECT 3")  # wakes the blocked next()
    time.sleep(0.2)
    sched.close("c1")
    th.join(5.0)
    assert not th.is_alive()
    assert set(got) == {"q2", "q3"}
    with pytest.raises(ValueError):
        sched.feed("c1", "q4", "SELECT 4")  # closed stream


def test_scheduler_feed_dedups_across_streams():
    sched = StreamScheduler(
        {}, key_fn=lambda s: " ".join(s.lower().split()))
    sched.open_stream("a")
    sched.open_stream("b")
    sched.feed("a", "qa", "SELECT * FROM t")
    sched.feed("b", "qb", "select  *  from  t")  # same normalized key
    va, vb = sched.view("a"), sched.view("b")
    assert va.next(0.0) == "qa"
    # b's identical text is classed in-flight-elsewhere, still runnable
    assert vb.next(0.0) == "qb"
    va.done("qa")
    assert sched._key[("a", "qa")] == sched._key[("b", "qb")]
    assert sched._key[("a", "qa")] in sched.compiled


# -- overload primitives -----------------------------------------------------

def test_tenant_budget_isolation():
    clock = [0.0]
    budgets = TenantBudgets(capacity=2, refill_per_s=1.0,
                            clock=lambda: clock[0])
    budgets.acquire("a")
    budgets.acquire("a")
    with pytest.raises(Rejected) as ei:
        budgets.acquire("a")
    assert ei.value.reason == "tenant-budget"
    budgets.acquire("b")  # other tenants unaffected
    clock[0] += 1.5  # refill restores tenant a
    budgets.acquire("a")


def test_admission_queue_overload_and_deadline_shed():
    q = AdmissionQueue(depth=2, est_wait_s=1.0)
    q.admit()
    q.admit(deadline_s=10.0)
    with pytest.raises(Overloaded) as ei:
        q.admit()
    assert ei.value.retry_after_s > 0
    q.release()
    with pytest.raises(Rejected) as ei:  # 1 ahead * 1s > 0.5s deadline
        q.admit(deadline_s=0.5)
    assert ei.value.reason == "deadline"
    q.admit(deadline_s=5.0)


def test_circuit_breaker_trips_and_recovers_after_cooldown():
    clock = [0.0]
    quarantine = faults.Quarantine(max_failures=1)
    cb = CircuitBreaker(quarantine, cooldown_s=10.0,
                        clock=lambda: clock[0])
    cb.check("fp1")  # closed: no-op
    quarantine.note_failure("fp1", "permanent")
    assert cb.note_failure("fp1") is True  # quarantined -> trips
    assert cb.state("fp1") == "open"
    with pytest.raises(Rejected) as ei:
        cb.check("fp1")
    assert ei.value.reason == "circuit-open"
    clock[0] += 11.0  # past cooldown: half-open, one probe admitted
    assert cb.state("fp1") == "half-open"
    cb.check("fp1")
    with pytest.raises(Rejected):
        cb.check("fp1")  # second concurrent probe rejected
    cb.note_success("fp1")  # probe succeeded -> closed
    assert cb.state("fp1") == "closed"
    cb.check("fp1")
    # and an unpoisoned failure never trips
    assert cb.note_failure("fp2") is False
    cb.check("fp2")


# -- server end-to-end -------------------------------------------------------

def test_sql_roundtrip_output_and_journal(serve_env):
    srv, cli = serve_env()
    r = cli.sql("SELECT a, b FROM t WHERE a < 4 ORDER BY a")
    assert r["rows"] == 4 and r["data"][0] == [0, 0]
    r2 = cli.sql("SELECT sum(a) AS s FROM t", name="q_out")
    assert r2["rows"] == 1
    assert os.path.exists(os.path.join(
        srv.config.output_prefix, "q_out", "part-0.csv"))
    events = [rec["event"] for rec in
              atomic.read_jsonl(srv.config.journal_path)]
    assert events[0] == lifecycle.JOURNAL_START
    assert events.count(lifecycle.JOURNAL_QUERY) == 2
    health = cli.health()
    assert health["ready"] and health["ok"] >= 2


def test_bad_sql_is_permanent_error(serve_env):
    _, cli = serve_env()
    from ndstpu.serve.client import ServeError
    with pytest.raises(ServeError) as ei:
        cli.sql("SELEKT nope")
    assert ei.value.taxonomy == "permanent"


def test_tenant_at_budget_rejected_while_others_proceed(serve_env):
    _, cli = serve_env(tenant_tokens=2, tenant_refill_per_s=0.001)
    cli.sql("SELECT count(*) AS c FROM t", tenant="greedy")
    cli.sql("SELECT count(*) AS c FROM t", tenant="greedy")
    with pytest.raises(Rejected) as ei:
        cli.sql("SELECT count(*) AS c FROM t", tenant="greedy")
    assert ei.value.reason == "tenant-budget"
    # the other tenant is untouched by greedy's exhaustion
    r = cli.sql("SELECT count(*) AS c FROM t", tenant="modest")
    assert r["status"] == "ok"


def test_dispatch_fault_is_client_visible_and_retried(serve_env):
    _, cli = serve_env()
    faults.install("serve.dispatch:transient:1:times=1")
    try:
        before = obs.counters_snapshot()
        r = cli.sql("SELECT max(a) AS m FROM t")
        assert r["status"] == "ok"
        delta = obs.counter_delta(before)
        assert delta.get(
            "faults.injected.serve.dispatch.transient") == 1
        # the CLIENT retried — the server deliberately does not absorb
        # dispatch faults (that is what distinguishes the site from
        # `execute`, which run_with_retry absorbs server-side)
        assert cli.retried >= 1
        assert delta.get("serve.errors") == 1
        assert delta.get("serve.ok") == 1
    finally:
        faults.uninstall()


def test_drain_with_hung_query_hits_watchdog(serve_env):
    """A wedged in-flight query must not block SIGTERM drain: the
    watchdog abandons it on a zombie thread, swaps a fresh session,
    and the retry completes the request — zero dropped queries."""
    srv, cli = serve_env(query_timeout_s=0.5)
    faults.install("execute:hang:1:times=1:hang=8")
    try:
        before = obs.counters_snapshot()
        got = {}

        def send():
            got["resp"] = cli.sql("SELECT min(a) AS m FROM t")

        th = threading.Thread(target=send, daemon=True)
        th.start()
        time.sleep(0.2)  # let the query wedge in the hang
        t0 = time.time()
        summary = srv.drain(reason="SIGTERM-test")
        drain_wall = time.time() - t0
        th.join(15.0)
        assert not th.is_alive()
        # the hung attempt was abandoned, the retry answered the client
        assert got["resp"]["status"] == "ok"
        assert got["resp"]["attempts"] >= 2
        delta = obs.counter_delta(before)
        assert delta.get("serve.watchdog.abandoned", 0) >= 1
        assert drain_wall < 8.0, \
            f"drain blocked {drain_wall:.1f}s behind a hung query"
        assert summary["reason"] == "SIGTERM-test"
        events = [rec["event"] for rec in
                  atomic.read_jsonl(srv.config.journal_path)]
        assert events[-1] == lifecycle.JOURNAL_CLEAN
    finally:
        faults.uninstall()


def test_draining_rejects_new_requests(serve_env):
    srv, cli = serve_env()
    cli.sql("SELECT 1 AS one FROM t")
    srv.draining = True  # admission stopped, socket still up
    from ndstpu.serve.client import ServerDraining
    with pytest.raises(ServerDraining):
        cli.sql("SELECT 2 AS two FROM t")
    srv.draining = False


# -- lifecycle: journal replay + warm restart --------------------------------

def test_journal_replay_state(tmp_path):
    j = lifecycle.ServeJournal(str(tmp_path / "j.jsonl"))
    assert j.replay_state() == {"sqls": [], "clean": True}
    j.mark_start()
    j.mark_query("q1", "SELECT 1", canon_key="k1")
    j.mark_query("q1", "SELECT 1")  # dedup
    j.mark_query("q2", "SELECT 2")
    state = lifecycle.ServeJournal(str(tmp_path / "j.jsonl")) \
        .replay_state()
    assert [r["sql"] for r in state["sqls"]] == ["SELECT 1", "SELECT 2"]
    assert state["clean"] is False  # started, never marked clean
    j.mark_clean_shutdown()
    state = lifecycle.ServeJournal(str(tmp_path / "j.jsonl")) \
        .replay_state()
    assert state["clean"] is True


def test_warm_restart_zero_new_compiles(tmp_path):
    """The serve_smoke leg-4 invariant, in-process: a restarted server
    answering a previously-seen plan shape compiles NOTHING new
    (engine.cache.compiled.miss stays flat)."""
    records = str(tmp_path / "records.json")
    journal = str(tmp_path / "j.jsonl")
    sql = "SELECT b, sum(a) AS s FROM t GROUP BY b ORDER BY b"
    cfg = dict(socket_path=str(tmp_path / "warm.sock"),
               engine="tpu", compile_records=records,
               journal_path=journal, ledger_path="none",
               query_timeout_s=60.0)

    srv1 = QueryServer(ServeConfig(**cfg), session=tiny_session("tpu"))
    srv1.start()
    cli = ServeClient(cfg["socket_path"])
    assert cli.wait_ready(10.0)
    r1 = cli.sql(sql)
    cli.close()
    # no clean drain: simulate the SIGKILL by never calling drain() —
    # the incremental persistence must already have saved the records
    assert os.path.exists(records)
    srv1._listener.close()

    cfg2 = dict(cfg, socket_path=str(tmp_path / "warm2.sock"))
    srv2 = QueryServer(ServeConfig(**cfg2),
                       session=tiny_session("tpu"))
    srv2.start()
    cli2 = ServeClient(cfg2["socket_path"])
    assert cli2.wait_ready(10.0)
    before = obs.counters_snapshot()
    r2 = cli2.sql(sql)
    delta = obs.counter_delta(before)
    cli2.close()
    srv2.drain(reason="test")
    assert r2["data"] == r1["data"]
    assert delta.get("engine.cache.compiled.miss", 0) == 0, \
        f"warm restart recompiled: {delta}"
    assert delta.get("engine.cache.compiled.hit", 0) >= 1


# -- SLO artifact ------------------------------------------------------------

def test_slo_tracker_percentiles_and_export(tmp_path):
    slo = lifecycle.SLOTracker()
    for ms in range(1, 101):
        slo.record("a", ms / 1000.0, "ok")
    slo.record("a", 0.0, "overloaded")
    slo.record("b", 0.005, "ok")
    doc = slo.export(str(tmp_path / "SLO.json"))
    assert doc["artifact"] == lifecycle.SLO_ARTIFACT
    a = doc["tenants"]["a"]
    assert a["count"] == 101 and a["overloaded"] == 1
    assert a["p50_ms"] == pytest.approx(50.0, abs=2.0)
    assert a["p95_ms"] == pytest.approx(95.0, abs=2.0)
    assert a["p99_ms"] == pytest.approx(99.0, abs=2.0)
    assert doc["tenants"]["b"]["p50_ms"] == pytest.approx(5.0, abs=1.0)


def test_artifact_lint_recognizes_slo_as_runtime():
    text = "the server exports `SLO.json` next to its journal"
    assert artifact_lint.lint_text(text, root="/nonexistent") == []
    assert any(p == "SLO.json" for _, p, _ in
               artifact_lint.cited_artifacts(text))
