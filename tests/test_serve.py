"""Serve layer: protocol, admission/overload edges, drain, warm restart.

The satellite coverage the issue names explicitly:

* drain with a hung in-flight query hits the watchdog path (abandon on
  a zombie thread + fresh-session swap) instead of blocking shutdown;
* a tenant at budget gets the typed ``Rejected`` while other tenants
  proceed;
* a tripped circuit breaker recovers after its cooldown (half-open
  probe) — tripped off the PR 5 quarantine list, per canonical key.

Plus the protocol/scheduler/lifecycle seams the server composes:
length-prefixed framing, continuous-feed StreamScheduler streams,
connection-fault taxonomy, journal replay, and the warm-restart
zero-new-compiles invariant the serve smoke proves cross-process.
"""

import json
import os
import socket
import threading
import time

import numpy as np
import pytest

from ndstpu import faults, obs
from ndstpu.engine.columnar import INT32, Column, Table
from ndstpu.engine.session import Session
from ndstpu.faults import taxonomy
from ndstpu.harness.scheduler import StreamScheduler
from ndstpu.io import atomic
from ndstpu.io.loader import Catalog
from ndstpu.obs import artifact_lint
from ndstpu.serve import lifecycle, protocol, transport
from ndstpu.serve.client import NoHealthyEndpoint, ServeClient
from ndstpu.serve.overload import (AdmissionQueue, CircuitBreaker,
                                   Overloaded, Rejected, TenantBudgets)
from ndstpu.serve.server import QueryServer, ServeConfig


def col_i32(vals):
    return Column(np.asarray(vals, dtype=np.int32), INT32, None)


def tiny_session(backend: str = "cpu") -> Session:
    cat = Catalog()
    cat.register("t", Table({
        "a": col_i32(list(range(10))),
        "b": col_i32([v % 3 for v in range(10)]),
    }))
    return Session(cat, backend=backend)


@pytest.fixture
def serve_env(tmp_path):
    """A started server over a tiny cpu session + one client; drains
    on teardown.  Yields a factory so tests can tune ServeConfig."""
    made = []

    def make(session=None, **cfg):
        defaults = dict(
            socket_path=str(tmp_path / f"s{len(made)}.sock"),
            engine="cpu",
            output_prefix=str(tmp_path / f"out{len(made)}"),
            journal_path=str(tmp_path / f"journal{len(made)}.jsonl"),
            slo_path=str(tmp_path / f"SLO{len(made)}.json"),
            ledger_path="none",
            query_timeout_s=30.0)
        defaults.update(cfg)
        srv = QueryServer(ServeConfig(**defaults),
                          session=session or tiny_session(
                              defaults["engine"]))
        srv.start()
        cli = ServeClient(defaults["socket_path"], retries=4,
                          connect_timeout_s=10.0)
        assert cli.wait_ready(10.0)
        made.append((srv, cli))
        return srv, cli

    yield make
    for srv, cli in made:
        cli.close()
        if not srv.draining:
            srv.drain(reason="teardown")


# -- protocol ----------------------------------------------------------------

def test_protocol_roundtrip_and_bounds():
    a, b = socket.socketpair()
    try:
        msg = {"op": "sql", "sql": "SELECT 1; -- '\n\x00 unicode ☃"}
        protocol.send_msg(a, msg)
        assert protocol.recv_msg(b) == msg
        a.close()
        assert protocol.recv_msg(b) is None  # clean EOF
    finally:
        b.close()
    c, d = socket.socketpair()
    try:
        c.sendall(b"\x7f\xff\xff\xff")  # absurd length prefix
        with pytest.raises(protocol.ProtocolError):
            protocol.recv_msg(d)
    finally:
        c.close()
        d.close()


def test_protocol_truncated_frame_is_error():
    a, b = socket.socketpair()
    try:
        a.sendall(b"\x00\x00\x00\x10partial")
        a.close()
        with pytest.raises(protocol.ProtocolError):
            protocol.recv_msg(b)
    finally:
        b.close()


# -- connection-fault taxonomy (satellite 1) ---------------------------------

def test_connection_faults_classify_transient():
    assert taxonomy.classify(socket.timeout("timed out")) == "transient"
    assert taxonomy.classify(
        ConnectionRefusedError("connection refused")) == "transient"
    assert taxonomy.classify(ConnectionResetError()) == "transient"
    assert taxonomy.classify(BrokenPipeError()) == "transient"
    # pre-3.10 socket.timeout pickles/paths carry the bare class name
    assert taxonomy.classify_name("timeout", "") == "transient"
    assert taxonomy.classify_name(
        "SomeWrapperError", "upstream: Connection refused") == "transient"
    assert taxonomy.classify_name(
        "SomeWrapperError", "Broken pipe on fd 7") == "transient"


# -- continuous-feed scheduler ----------------------------------------------

def test_scheduler_continuous_feed():
    sched = StreamScheduler({})
    view = sched.open_stream("c1")
    sched.feed("c1", "q1", "SELECT 1")
    sched.feed("c1", "q2", "SELECT 2")
    assert view.next(0.0) in ("q1", "q2")
    view.done("q1")
    got = []

    def drain_view():
        while True:
            n = view.next(0.0)
            if n is None:
                return
            got.append(n)
            view.done(n)

    th = threading.Thread(target=drain_view, daemon=True)
    th.start()
    time.sleep(0.1)
    sched.feed("c1", "q3", "SELECT 3")  # wakes the blocked next()
    time.sleep(0.2)
    sched.close("c1")
    th.join(5.0)
    assert not th.is_alive()
    assert set(got) == {"q2", "q3"}
    with pytest.raises(ValueError):
        sched.feed("c1", "q4", "SELECT 4")  # closed stream


def test_scheduler_feed_dedups_across_streams():
    sched = StreamScheduler(
        {}, key_fn=lambda s: " ".join(s.lower().split()))
    sched.open_stream("a")
    sched.open_stream("b")
    sched.feed("a", "qa", "SELECT * FROM t")
    sched.feed("b", "qb", "select  *  from  t")  # same normalized key
    va, vb = sched.view("a"), sched.view("b")
    assert va.next(0.0) == "qa"
    # b's identical text is classed in-flight-elsewhere, still runnable
    assert vb.next(0.0) == "qb"
    va.done("qa")
    assert sched._key[("a", "qa")] == sched._key[("b", "qb")]
    assert sched._key[("a", "qa")] in sched.compiled


# -- overload primitives -----------------------------------------------------

def test_tenant_budget_isolation():
    clock = [0.0]
    budgets = TenantBudgets(capacity=2, refill_per_s=1.0,
                            clock=lambda: clock[0])
    budgets.acquire("a")
    budgets.acquire("a")
    with pytest.raises(Rejected) as ei:
        budgets.acquire("a")
    assert ei.value.reason == "tenant-budget"
    budgets.acquire("b")  # other tenants unaffected
    clock[0] += 1.5  # refill restores tenant a
    budgets.acquire("a")


def test_admission_queue_overload_and_deadline_shed():
    q = AdmissionQueue(depth=2, est_wait_s=1.0)
    q.admit()
    q.admit(deadline_s=10.0)
    with pytest.raises(Overloaded) as ei:
        q.admit()
    assert ei.value.retry_after_s > 0
    q.release()
    with pytest.raises(Rejected) as ei:  # 1 ahead * 1s > 0.5s deadline
        q.admit(deadline_s=0.5)
    assert ei.value.reason == "deadline"
    q.admit(deadline_s=5.0)


def test_circuit_breaker_trips_and_recovers_after_cooldown():
    clock = [0.0]
    quarantine = faults.Quarantine(max_failures=1)
    cb = CircuitBreaker(quarantine, cooldown_s=10.0,
                        clock=lambda: clock[0])
    cb.check("fp1")  # closed: no-op
    quarantine.note_failure("fp1", "permanent")
    assert cb.note_failure("fp1") is True  # quarantined -> trips
    assert cb.state("fp1") == "open"
    with pytest.raises(Rejected) as ei:
        cb.check("fp1")
    assert ei.value.reason == "circuit-open"
    clock[0] += 11.0  # past cooldown: half-open, one probe admitted
    assert cb.state("fp1") == "half-open"
    cb.check("fp1")
    with pytest.raises(Rejected):
        cb.check("fp1")  # second concurrent probe rejected
    cb.note_success("fp1")  # probe succeeded -> closed
    assert cb.state("fp1") == "closed"
    cb.check("fp1")
    # and an unpoisoned failure never trips
    assert cb.note_failure("fp2") is False
    cb.check("fp2")


# -- server end-to-end -------------------------------------------------------

def test_sql_roundtrip_output_and_journal(serve_env):
    srv, cli = serve_env()
    r = cli.sql("SELECT a, b FROM t WHERE a < 4 ORDER BY a")
    assert r["rows"] == 4 and r["data"][0] == [0, 0]
    r2 = cli.sql("SELECT sum(a) AS s FROM t", name="q_out")
    assert r2["rows"] == 1
    assert os.path.exists(os.path.join(
        srv.config.output_prefix, "q_out", "part-0.csv"))
    events = [rec["event"] for rec in
              atomic.read_jsonl(srv.config.journal_path)]
    assert events[0] == lifecycle.JOURNAL_START
    assert events.count(lifecycle.JOURNAL_QUERY) == 2
    health = cli.health()
    assert health["ready"] and health["ok"] >= 2


def test_bad_sql_is_permanent_error(serve_env):
    _, cli = serve_env()
    from ndstpu.serve.client import ServeError
    with pytest.raises(ServeError) as ei:
        cli.sql("SELEKT nope")
    assert ei.value.taxonomy == "permanent"


def test_tenant_at_budget_rejected_while_others_proceed(serve_env):
    _, cli = serve_env(tenant_tokens=2, tenant_refill_per_s=0.001)
    cli.sql("SELECT count(*) AS c FROM t", tenant="greedy")
    cli.sql("SELECT count(*) AS c FROM t", tenant="greedy")
    with pytest.raises(Rejected) as ei:
        cli.sql("SELECT count(*) AS c FROM t", tenant="greedy")
    assert ei.value.reason == "tenant-budget"
    # the other tenant is untouched by greedy's exhaustion
    r = cli.sql("SELECT count(*) AS c FROM t", tenant="modest")
    assert r["status"] == "ok"


def test_dispatch_fault_is_client_visible_and_retried(serve_env):
    _, cli = serve_env()
    faults.install("serve.dispatch:transient:1:times=1")
    try:
        before = obs.counters_snapshot()
        r = cli.sql("SELECT max(a) AS m FROM t")
        assert r["status"] == "ok"
        delta = obs.counter_delta(before)
        assert delta.get(
            "faults.injected.serve.dispatch.transient") == 1
        # the CLIENT retried — the server deliberately does not absorb
        # dispatch faults (that is what distinguishes the site from
        # `execute`, which run_with_retry absorbs server-side)
        assert cli.retried >= 1
        assert delta.get("serve.errors") == 1
        assert delta.get("serve.ok") == 1
    finally:
        faults.uninstall()


def test_drain_with_hung_query_hits_watchdog(serve_env):
    """A wedged in-flight query must not block SIGTERM drain: the
    watchdog abandons it on a zombie thread, swaps a fresh session,
    and the retry completes the request — zero dropped queries."""
    srv, cli = serve_env(query_timeout_s=0.5)
    faults.install("execute:hang:1:times=1:hang=8")
    try:
        before = obs.counters_snapshot()
        got = {}

        def send():
            got["resp"] = cli.sql("SELECT min(a) AS m FROM t")

        th = threading.Thread(target=send, daemon=True)
        th.start()
        time.sleep(0.2)  # let the query wedge in the hang
        t0 = time.time()
        summary = srv.drain(reason="SIGTERM-test")
        drain_wall = time.time() - t0
        th.join(15.0)
        assert not th.is_alive()
        # the hung attempt was abandoned, the retry answered the client
        assert got["resp"]["status"] == "ok"
        assert got["resp"]["attempts"] >= 2
        delta = obs.counter_delta(before)
        assert delta.get("serve.watchdog.abandoned", 0) >= 1
        assert drain_wall < 8.0, \
            f"drain blocked {drain_wall:.1f}s behind a hung query"
        assert summary["reason"] == "SIGTERM-test"
        events = [rec["event"] for rec in
                  atomic.read_jsonl(srv.config.journal_path)]
        assert events[-1] == lifecycle.JOURNAL_CLEAN
    finally:
        faults.uninstall()


def test_draining_rejects_new_requests(serve_env):
    srv, cli = serve_env()
    cli.sql("SELECT 1 AS one FROM t")
    srv.draining = True  # admission stopped, socket still up
    from ndstpu.serve.client import ServerDraining
    with pytest.raises(ServerDraining):
        cli.sql("SELECT 2 AS two FROM t")
    srv.draining = False


# -- lifecycle: journal replay + warm restart --------------------------------

def test_journal_replay_state(tmp_path):
    j = lifecycle.ServeJournal(str(tmp_path / "j.jsonl"))
    assert j.replay_state() == {"sqls": [], "clean": True}
    j.mark_start()
    j.mark_query("q1", "SELECT 1", canon_key="k1")
    j.mark_query("q1", "SELECT 1")  # dedup
    j.mark_query("q2", "SELECT 2")
    state = lifecycle.ServeJournal(str(tmp_path / "j.jsonl")) \
        .replay_state()
    assert [r["sql"] for r in state["sqls"]] == ["SELECT 1", "SELECT 2"]
    assert state["clean"] is False  # started, never marked clean
    j.mark_clean_shutdown()
    state = lifecycle.ServeJournal(str(tmp_path / "j.jsonl")) \
        .replay_state()
    assert state["clean"] is True


def test_warm_restart_zero_new_compiles(tmp_path):
    """The serve_smoke leg-4 invariant, in-process: a restarted server
    answering a previously-seen plan shape compiles NOTHING new
    (engine.cache.compiled.miss stays flat)."""
    records = str(tmp_path / "records.json")
    journal = str(tmp_path / "j.jsonl")
    sql = "SELECT b, sum(a) AS s FROM t GROUP BY b ORDER BY b"
    cfg = dict(socket_path=str(tmp_path / "warm.sock"),
               engine="tpu", compile_records=records,
               journal_path=journal, ledger_path="none",
               query_timeout_s=60.0)

    srv1 = QueryServer(ServeConfig(**cfg), session=tiny_session("tpu"))
    srv1.start()
    cli = ServeClient(cfg["socket_path"])
    assert cli.wait_ready(10.0)
    r1 = cli.sql(sql)
    cli.close()
    # no clean drain: simulate the SIGKILL by never calling drain() —
    # the incremental persistence must already have saved the records
    assert os.path.exists(records)
    for ls in srv1._listeners:
        ls.close()

    cfg2 = dict(cfg, socket_path=str(tmp_path / "warm2.sock"))
    srv2 = QueryServer(ServeConfig(**cfg2),
                       session=tiny_session("tpu"))
    srv2.start()
    cli2 = ServeClient(cfg2["socket_path"])
    assert cli2.wait_ready(10.0)
    before = obs.counters_snapshot()
    r2 = cli2.sql(sql)
    delta = obs.counter_delta(before)
    cli2.close()
    srv2.drain(reason="test")
    assert r2["data"] == r1["data"]
    assert delta.get("engine.cache.compiled.miss", 0) == 0, \
        f"warm restart recompiled: {delta}"
    assert delta.get("engine.cache.compiled.hit", 0) >= 1


# -- SLO artifact ------------------------------------------------------------

def test_slo_tracker_percentiles_and_export(tmp_path):
    slo = lifecycle.SLOTracker()
    for ms in range(1, 101):
        slo.record("a", ms / 1000.0, "ok")
    slo.record("a", 0.0, "overloaded")
    slo.record("b", 0.005, "ok")
    doc = slo.export(str(tmp_path / "SLO.json"))
    assert doc["artifact"] == lifecycle.SLO_ARTIFACT
    a = doc["tenants"]["a"]
    assert a["count"] == 101 and a["overloaded"] == 1
    assert a["p50_ms"] == pytest.approx(50.0, abs=2.0)
    assert a["p95_ms"] == pytest.approx(95.0, abs=2.0)
    assert a["p99_ms"] == pytest.approx(99.0, abs=2.0)
    assert doc["tenants"]["b"]["p50_ms"] == pytest.approx(5.0, abs=1.0)


def test_artifact_lint_recognizes_slo_as_runtime():
    text = "the server exports `SLO.json` next to its journal"
    assert artifact_lint.lint_text(text, root="/nonexistent") == []
    assert any(p == "SLO.json" for _, p, _ in
               artifact_lint.cited_artifacts(text))


def test_artifact_lint_recognizes_fleet_health_as_runtime():
    text = "each tick rewrites `FLEET_HEALTH.json` in the run dir"
    assert artifact_lint.lint_text(text, root="/nonexistent") == []
    assert any(p == "FLEET_HEALTH.json" for _, p, _ in
               artifact_lint.cited_artifacts(text))


# -- fleet satellites: transports, failover, readiness, backpressure ---------

def test_tcp_unix_parity_same_request_same_response(serve_env):
    """Satellite 3: the SAME request sent over AF_UNIX and TCP gets
    the SAME response — shared framing, shared dispatch; only the
    volatile wall clock may differ."""
    srv, _cli = serve_env(tcp="127.0.0.1:0")
    specs = [ep.spec for ep in srv.endpoints]
    assert any(s.startswith("unix:") for s in specs), specs
    assert any(s.startswith("tcp:") for s in specs), specs

    def ask(spec, msg):
        s = transport.connect(spec, connect_timeout_s=10.0)
        try:
            protocol.send_msg(s, msg)
            return protocol.recv_msg(s)
        finally:
            s.close()

    for msg in (
            {"op": "ping", "id": "par-1"},
            {"op": "ready", "id": "par-2"},
            {"op": "sql", "id": "par-3", "tenant": "parity",
             "sql": "SELECT b, sum(a) AS s FROM t GROUP BY b "
                    "ORDER BY b"}):
        answers = []
        for spec in specs:
            resp = ask(spec, dict(msg))
            resp.pop("wall_s", None)
            answers.append(resp)
        assert answers[0] == answers[1], \
            f"transport-dependent response for {msg['op']}: {answers}"


def _tenant_for_index(idx: int, n: int) -> str:
    import zlib
    for i in range(1000):
        t = f"t{i}"
        if zlib.crc32(t.encode()) % n == idx:
            return t
    raise AssertionError("unreachable")


def test_client_fails_over_from_refused_endpoint(serve_env, tmp_path):
    """Satellite 3: first endpoint refuses -> the client silently
    moves to the next and counts the switch in ``failovers``."""
    srv, _cli = serve_env()
    live = srv.endpoints[0].spec
    dead = str(tmp_path / "nobody-listening.sock")
    cli = ServeClient(f"{dead},{live}",
                      tenant=_tenant_for_index(0, 2),
                      retries=4, connect_timeout_s=10.0)
    try:
        assert cli.endpoint.spec != live  # starts on the dead one
        assert cli.ping()["pong"] is True
        assert cli.failovers >= 1
        assert cli.endpoint.spec == live
        r = cli.sql("SELECT count(*) AS n FROM t")
        assert r["status"] == "ok" and r["data"] == [[10]]
    finally:
        cli.close()


def test_client_all_endpoints_down_raises_typed_transient(tmp_path):
    """Satellite 3: every endpoint down -> NoHealthyEndpoint naming
    the endpoints tried, classified transient for outer retry loops."""
    d1 = str(tmp_path / "d1.sock")
    d2 = str(tmp_path / "d2.sock")
    cli = ServeClient(f"{d1},{d2}", retries=0, connect_timeout_s=0.3,
                      backoff_s=0.01)
    with pytest.raises(NoHealthyEndpoint) as ei:
        cli.ping()
    assert sorted(ei.value.endpoints) == sorted(
        [f"unix:{d1}", f"unix:{d2}"])
    assert taxonomy.classify(ei.value) == "transient"
    # single endpoint keeps the PR 14 contract: the raw OSError
    solo = ServeClient(d1, retries=0, connect_timeout_s=0.3,
                       backoff_s=0.01)
    with pytest.raises(OSError) as ei2:
        solo.ping()
    assert not isinstance(ei2.value, NoHealthyEndpoint)


def test_bind_early_probe_answers_and_sql_sheds_until_ready(tmp_path):
    """Satellite 3 readiness gating: a bind_early replica answers the
    probe verb immediately, sheds sql as retryable ``overloaded``
    while warming, and flips ready only after the warm/AOT work is
    done."""
    gate = threading.Event()
    entered = threading.Event()

    class SlowBoot(QueryServer):
        def _aot_precompile(self):
            entered.set()
            assert gate.wait(30.0)
            super()._aot_precompile()

    sock = str(tmp_path / "warm_gate.sock")
    srv = SlowBoot(ServeConfig(socket_path=sock, engine="cpu",
                               journal_path=str(tmp_path / "j.jsonl"),
                               ledger_path="none", bind_early=True,
                               replica_id="r-gate"),
                   session=tiny_session())
    boot = threading.Thread(target=srv.start, daemon=True)
    boot.start()
    try:
        assert entered.wait(30.0)
        cli = ServeClient(sock, retries=0, connect_timeout_s=10.0)
        probe = cli.probe()   # probe answers while still warming
        assert probe["alive"] is True and probe["ready"] is False
        assert probe["replica_id"] == "r-gate"
        resp = cli._roundtrip({"op": "sql", "id": "w1",
                               "sql": "SELECT count(*) FROM t",
                               "tenant": "warm"})
        assert resp["status"] == "overloaded"  # retryable, NOT fatal
        assert resp["retry_after_s"] > 0
        before = obs.counters_snapshot()
        gate.set()
        boot.join(30.0)
        assert not boot.is_alive()
        assert cli.wait_ready(10.0)
        assert cli.probe()["ready"] is True
        r = cli.sql("SELECT count(*) AS n FROM t")
        assert r["data"] == [[10]]
        assert obs.counter_delta(before).get(
            "serve.warming_rejects", 0) == 0  # none after readiness
        cli.close()
    finally:
        gate.set()
        if not srv.draining:
            srv.drain(reason="test")


# -- EWMA retry hint (satellite 1) -------------------------------------------

def test_admission_queue_ewma_hint_grows_and_decays():
    q = AdmissionQueue(depth=2, est_wait_s=0.25, ewma_alpha=0.5)
    assert q.est_wait_s == pytest.approx(0.25)  # seed before data
    for _ in range(4):
        q.observe(2.0)  # slow queries: the hint must grow
    grown = q.est_wait_s
    assert grown > 1.0
    for _ in range(8):
        q.observe(0.01)  # fast again: the hint must decay back
    assert q.est_wait_s < 0.1 < grown
    snap = q.snapshot()
    assert snap["observed"] == 12
    assert snap["est_wait_s"] == pytest.approx(q.est_wait_s,
                                               abs=1e-5)


def test_admission_queue_shed_hint_tracks_ewma():
    q = AdmissionQueue(depth=1, est_wait_s=0.25, ewma_alpha=1.0)
    q.observe(3.0)  # alpha=1: est jumps straight to the observation
    q.admit()
    with pytest.raises(Overloaded) as ei:
        q.admit()
    assert ei.value.retry_after_s == pytest.approx(3.0)
    q.release()


# -- memplan admission budget (tentpole seam) --------------------------------

def test_memplan_admission_budget_clamps_and_env(monkeypatch):
    from ndstpu.engine import memplan

    doc = memplan.admission_budget(budget_bytes=8 << 30,
                                   bytes_per_query=64 << 20)
    assert doc["depth"] == (8 << 30) // 2 // (64 << 20)
    assert doc["budget_source"] == "caller"
    # starved budget clamps to the floor, never zero
    doc = memplan.admission_budget(budget_bytes=16 << 20,
                                   bytes_per_query=64 << 20)
    assert doc["depth"] == memplan.ADMISSION_MIN_DEPTH
    # huge budget clamps to the ceiling
    doc = memplan.admission_budget(budget_bytes=1 << 50,
                                   bytes_per_query=1)
    assert doc["depth"] == memplan.ADMISSION_MAX_DEPTH
    # NDSTPU_HBM_BYTES drives the budget (source: env), the serve
    # knob overrides the per-query working set
    monkeypatch.setenv("NDSTPU_HBM_BYTES", str(1 << 30))
    monkeypatch.setenv("NDSTPU_SERVE_QUERY_BYTES", str(128 << 20))
    doc = memplan.admission_budget()
    assert doc["budget_source"] == "env"
    assert doc["bytes_per_query"] == 128 << 20
    assert doc["depth"] == (1 << 30) // 2 // (128 << 20)


def test_server_auto_queue_depth_from_memplan(serve_env, monkeypatch):
    monkeypatch.setenv("NDSTPU_HBM_BYTES", str(192 << 20))
    srv, cli = serve_env(queue_depth=None)
    h = cli.health()
    assert h["admission_model"]["budget_source"] == "env"
    assert h["admission_model"]["depth"] == 1
    assert h["queue_depth"] == 1


# -- fleet supervisor units (injectable probe/launcher) ----------------------

class _FakeProc:
    def __init__(self, pid):
        self.pid = pid
        self.rc = None
        self.returncode = None

    def poll(self):
        self.returncode = self.rc
        return self.rc

    def kill(self):
        self.rc = -9

    def wait(self, timeout=None):
        self.returncode = self.rc
        return self.rc


def _fleet_cfg(tmp_path, **kw):
    from ndstpu.serve.fleet import FleetConfig
    defaults = dict(input_prefix=str(tmp_path / "wh"),
                    replicas=2, run_dir=str(tmp_path / "fleet"),
                    probe_interval_s=30.0, probe_fail_threshold=3,
                    restart_backoff_s=0.0, restart_backoff_max_s=0.0)
    defaults.update(kw)
    return FleetConfig(**defaults)


def test_fleet_adopts_live_replicas_instead_of_double_starting(
        tmp_path):
    from ndstpu.serve.fleet import FleetSupervisor
    launched = []

    def launcher(rep):
        p = _FakeProc(pid=1000 + len(launched))
        launched.append(rep.replica_id)
        return p

    def probe(rep):
        if rep.replica_id == "r0":  # r0 is already running out there
            return {"alive": True, "ready": True, "pid": 4242}
        raise ConnectionRefusedError("r1 not running")

    sup = FleetSupervisor(_fleet_cfg(tmp_path, probe_fail_threshold=99),
                          probe_fn=probe, launcher=launcher)
    sup.start()
    try:
        r0, r1 = sup.replicas
        assert r0.adopted and r0.pid == 4242 and r0.ready
        assert "r0" not in launched, "adopted replica was double-started"
        assert launched == ["r1"]
        doc = sup.health_doc()
        assert doc["artifact"] == "ndstpu-fleet-health-v1"
        assert doc["replicas"][0]["adopted"] is True
        assert os.path.exists(sup.health_path)
    finally:
        sup._stopped.set()


def test_fleet_restarts_dead_replica_and_fences_stale_lock(tmp_path):
    from ndstpu.io import commit as commit_mod
    from ndstpu.serve.fleet import FleetSupervisor
    wh = tmp_path / "wh" / "store_sales"
    wh.mkdir(parents=True)
    launched = []

    def launcher(rep):
        p = _FakeProc(pid=1000 + len(launched))
        launched.append(p)
        return p

    sup = FleetSupervisor(_fleet_cfg(tmp_path, replicas=1),
                          probe_fn=lambda rep: {"alive": True,
                                                "ready": True,
                                                "pid": None},
                          launcher=launcher)
    rep = sup.replicas[0]
    sup._start_replica(rep)
    assert len(launched) == 1 and rep.pid == 1000
    # the replica dies holding a CAS commit lease; a live stranger's
    # lease must survive the fence
    stale = wh / commit_mod.LOCK_BASENAME
    stale.write_text(json.dumps({"pid": rep.pid, "ts": 0}))
    live_dir = tmp_path / "wh" / "other"
    live_dir.mkdir()
    (live_dir / commit_mod.LOCK_BASENAME).write_text(
        json.dumps({"pid": os.getpid(), "ts": 0}))
    launched[0].rc = 9
    sup._check_one(rep)
    assert rep.restarts == 1
    assert len(launched) == 2, "death did not relaunch the replica"
    assert rep.pid == launched[1].pid, "pid not tracking the relaunch"
    assert not stale.exists(), "stale commit lease was not fenced"
    assert (live_dir / commit_mod.LOCK_BASENAME).exists(), \
        "fence broke a LIVE pid's lease"


def test_fleet_probe_failures_restart_only_at_threshold(tmp_path):
    from ndstpu.serve.fleet import FleetSupervisor
    launched = []

    def launcher(rep):
        p = _FakeProc(pid=2000 + len(launched))
        launched.append(p)
        return p

    def probe(rep):
        raise ConnectionRefusedError("injected probe failure")

    sup = FleetSupervisor(
        _fleet_cfg(tmp_path, replicas=1, probe_fail_threshold=3,
                   boot_grace_s=0.5),
        probe_fn=probe, launcher=launcher)
    rep = sup.replicas[0]
    sup._start_replica(rep)
    sup._check_one(rep)
    assert rep.consecutive_failures == 0, \
        "a probe failure during the boot grace window counted"
    rep.launched_at -= 1.0  # age the incarnation past the grace
    sup._check_one(rep)
    sup._check_one(rep)
    assert rep.restarts == 0, "restarted below the probe threshold"
    sup._check_one(rep)  # third consecutive failure crosses it
    assert rep.restarts == 1 and len(launched) == 2


def test_fleet_kill_switch_degenerates_to_one_replica(tmp_path,
                                                      monkeypatch):
    from ndstpu.serve import fleet as fleet_mod
    monkeypatch.setenv(fleet_mod.FLEET_ENV, "0")
    sup = fleet_mod.FleetSupervisor(
        _fleet_cfg(tmp_path, replicas=3),
        probe_fn=lambda rep: {"alive": True, "ready": True},
        launcher=lambda rep: _FakeProc(pid=1))
    assert len(sup.replicas) == 1
    assert "," not in sup.endpoints_spec()


def test_fleet_default_endpoints_stable_and_short(tmp_path):
    from ndstpu.serve.fleet import default_endpoints
    a = default_endpoints(str(tmp_path / "fleet"), 3)
    b = default_endpoints(str(tmp_path / "fleet"), 3)
    assert a == b, "re-adoption needs stable endpoint derivation"
    assert len(set(a)) == 3
    assert all(len(p) < 100 for p in a), "AF_UNIX ~108-byte path cap"
    assert default_endpoints(str(tmp_path / "other"), 3) != a
