"""Engine semantics tests: expressions, joins, aggregates, windows, set ops.

Hand-built logical plans over tiny in-memory tables; Spark-compatible
NULL/decimal/ordering semantics are the acceptance bar (they are what the
validator assumes, cf. reference nds_validate.py).
"""

import numpy as np
import pytest

from ndstpu.engine import columnar, expr as ex, physical, plan as lp
from ndstpu.engine.columnar import BOOL, FLOAT64, INT32, INT64, Column, Table, decimal
from ndstpu.io.loader import Catalog


def col_i32(vals):
    valid = np.array([v is not None for v in vals])
    data = np.array([0 if v is None else v for v in vals], dtype=np.int32)
    return Column(data, INT32, None if valid.all() else valid)


def col_dec(vals, scale=2):
    valid = np.array([v is not None for v in vals])
    data = np.array([0 if v is None else round(v * 10**scale) for v in vals],
                    dtype=np.int64)
    return Column(data, decimal(7, scale), None if valid.all() else valid)


def make_catalog(**tables) -> Catalog:
    cat = Catalog()
    for name, t in tables.items():
        cat.register(name, t)
    return cat


@pytest.fixture
def sales_cat():
    sales = Table({
        "s_item": col_i32([1, 2, 1, 3, 2, None]),
        "s_qty": col_i32([10, 20, 30, 40, 50, 60]),
        "s_price": col_dec([1.50, 2.25, 1.00, None, 3.10, 4.00]),
    })
    items = Table({
        "i_item": col_i32([1, 2, 3]),
        "i_name": Column.from_strings(["apple", "banana", "cherry"]),
    })
    return make_catalog(sales=sales, items=items)


def run(plan, cat):
    return physical.execute(plan, cat)


# -- expressions -------------------------------------------------------------

def test_three_valued_logic():
    t = Table({"a": col_i32([1, None, 0])})
    # a = 1 AND a IS NOT NULL etc.
    e = ex.BinOp("and",
                 ex.BinOp("=", ex.ColumnRef("a"), ex.Literal(1)),
                 ex.Literal(True))
    mask = ex.eval_predicate(t, e)
    assert list(mask) == [True, False, False]
    # NULL OR TRUE == TRUE
    e2 = ex.BinOp("or",
                  ex.BinOp("=", ex.ColumnRef("a"), ex.Literal(1)),
                  ex.Literal(True))
    c = ex.Evaluator(t).eval(e2)
    assert list(c.data & c.validity()) == [True, True, True]


def test_decimal_arithmetic():
    t = Table({"p": col_dec([1.50, 2.25]), "q": col_i32([2, 4])})
    c = ex.Evaluator(t).eval(
        ex.BinOp("*", ex.ColumnRef("p"), ex.ColumnRef("q")))
    assert c.ctype.kind == "decimal" and c.ctype.scale == 2
    assert list(c.data) == [300, 900]
    c2 = ex.Evaluator(t).eval(
        ex.BinOp("+", ex.ColumnRef("p"), ex.Literal(1)))
    assert list(c2.data) == [250, 325]


def test_division_null_on_zero():
    t = Table({"a": col_i32([6, 5]), "b": col_i32([2, 0])})
    c = ex.Evaluator(t).eval(
        ex.BinOp("/", ex.ColumnRef("a"), ex.ColumnRef("b")))
    assert c.to_pylist() == [3.0, None]


def test_like_and_substr():
    t = Table({"s": Column.from_strings(["apple pie", "banana", None])})
    c = ex.Evaluator(t).eval(
        ex.Func("like", (ex.ColumnRef("s"), ex.Literal("%pie%"))))
    assert c.to_pylist() == [True, False, None]
    c2 = ex.Evaluator(t).eval(
        ex.Func("substr", (ex.ColumnRef("s"), ex.Literal(1), ex.Literal(3))))
    assert c2.to_pylist() == ["app", "ban", None]


def test_case_expr():
    t = Table({"a": col_i32([1, 2, 3])})
    c = ex.Evaluator(t).eval(ex.Case(
        ((ex.BinOp("=", ex.ColumnRef("a"), ex.Literal(1)), ex.Literal(10)),
         (ex.BinOp("=", ex.ColumnRef("a"), ex.Literal(2)), ex.Literal(20))),
        ex.Literal(0)))
    assert c.to_pylist() == [10, 20, 0]


# -- plans -------------------------------------------------------------------

def test_filter_project(sales_cat):
    p = lp.Project(
        lp.Filter(lp.Scan("sales", "sales"),
                  ex.BinOp(">", ex.ColumnRef("s_qty"), ex.Literal(25))),
        [("q", ex.ColumnRef("s_qty"))])
    out = run(p, sales_cat)
    assert out.to_pydict()["q"] == [30, 40, 50, 60]


def test_inner_join_null_keys_dont_match(sales_cat):
    p = lp.Join(lp.Scan("sales", "sales"), lp.Scan("items", "items"),
                "inner", [(ex.ColumnRef("s_item"), ex.ColumnRef("i_item"))])
    out = run(p, sales_cat)
    assert out.num_rows == 5  # NULL item row dropped
    d = out.to_pydict()
    for it, nm in zip(d["s_item"], d["i_name"]):
        assert {1: "apple", 2: "banana", 3: "cherry"}[it] == nm


def test_left_join(sales_cat):
    p = lp.Join(lp.Scan("sales", "sales"), lp.Scan("items", "items"),
                "left", [(ex.ColumnRef("s_item"), ex.ColumnRef("i_item"))])
    out = run(p, sales_cat)
    assert out.num_rows == 6
    d = out.to_pydict()
    row = [i for i, v in enumerate(d["s_item"]) if v is None]
    assert len(row) == 1 and d["i_name"][row[0]] is None


def test_semi_anti_join(sales_cat):
    semi = run(lp.Join(lp.Scan("items", "items"), lp.Scan("sales", "sales"),
                       "semi",
                       [(ex.ColumnRef("i_item"), ex.ColumnRef("s_item"))]),
               sales_cat)
    assert semi.num_rows == 3
    anti = run(lp.Join(lp.Scan("items", "items"),
                       lp.Filter(lp.Scan("sales", "sales"),
                                 ex.BinOp("<", ex.ColumnRef("s_item"),
                                          ex.Literal(3))),
                       "anti",
                       [(ex.ColumnRef("i_item"), ex.ColumnRef("s_item"))]),
               sales_cat)
    assert anti.to_pydict()["i_item"] == [3]


def test_many_to_many_join():
    l = Table({"k": col_i32([1, 1, 2])})
    r = Table({"k2": col_i32([1, 1, 1, 2]), "v": col_i32([7, 8, 9, 5])})
    cat = make_catalog(l=l, r=r)
    out = run(lp.Join(lp.Scan("l", "l"), lp.Scan("r", "r"), "inner",
                      [(ex.ColumnRef("k"), ex.ColumnRef("k2"))]), cat)
    assert out.num_rows == 7  # 3 + 3 + 1


def test_group_by_aggregates(sales_cat):
    p = lp.Aggregate(
        lp.Scan("sales", "sales"),
        [("item", ex.ColumnRef("s_item"))],
        [("total_qty", ex.AggExpr("sum", ex.ColumnRef("s_qty"))),
         ("n", ex.AggExpr("count", ex.Star())),
         ("avg_price", ex.AggExpr("avg", ex.ColumnRef("s_price"))),
         ("max_q", ex.AggExpr("max", ex.ColumnRef("s_qty")))])
    out = run(lp.Sort(p, [(ex.ColumnRef("item"), True)]), sales_cat)
    d = out.to_pydict()
    # null group sorts first (Spark ASC NULLS FIRST)
    assert d["item"] == [None, 1, 2, 3]
    assert d["total_qty"] == [60, 40, 70, 40]
    assert d["n"] == [1, 2, 2, 1]
    assert d["avg_price"][1] == pytest.approx(1.25)
    assert d["avg_price"][3] is None  # only NULL prices in group 3
    assert d["max_q"] == [60, 30, 50, 40]


def test_sum_decimal_exact(sales_cat):
    p = lp.Aggregate(lp.Scan("sales", "sales"), [],
                     [("s", ex.AggExpr("sum", ex.ColumnRef("s_price")))])
    out = run(p, sales_cat)
    assert out.to_pydict()["s"] == [pytest.approx(11.85)]


def test_rollup(sales_cat):
    p = lp.Aggregate(
        lp.Filter(lp.Scan("sales", "sales"),
                  ex.UnaryOp("isnotnull", ex.ColumnRef("s_item"))),
        [("item", ex.ColumnRef("s_item"))],
        [("q", ex.AggExpr("sum", ex.ColumnRef("s_qty")))],
        grouping_sets=[[0], []])
    out = run(lp.Sort(p, [(ex.ColumnRef("item"), True)]), sales_cat)
    d = out.to_pydict()
    assert d["item"] == [None, 1, 2, 3]
    assert d["q"] == [150, 40, 70, 40]  # grand total row has NULL key


def test_count_distinct():
    t = Table({"g": col_i32([1, 1, 1, 2, 2]),
               "v": col_i32([5, 5, 7, 5, None])})
    cat = make_catalog(t=t)
    p = lp.Aggregate(lp.Scan("t", "t"), [("g", ex.ColumnRef("g"))],
                     [("cd", ex.AggExpr("count", ex.ColumnRef("v"),
                                        distinct=True))])
    out = run(lp.Sort(p, [(ex.ColumnRef("g"), True)]), cat)
    assert out.to_pydict()["cd"] == [2, 1]


def test_distinct_and_setops():
    a = Table({"x": col_i32([1, 2, 2, 3])})
    b = Table({"y": col_i32([2, 3, 4])})
    cat = make_catalog(a=a, b=b)
    d = run(lp.Distinct(lp.Scan("a", "a")), cat)
    assert sorted(d.to_pydict()["x"]) == [1, 2, 3]
    u = run(lp.SetOp("union", lp.Scan("a", "a"), lp.Scan("b", "b")), cat)
    assert sorted(u.to_pydict()["x"]) == [1, 2, 3, 4]
    i = run(lp.SetOp("intersect", lp.Scan("a", "a"), lp.Scan("b", "b")), cat)
    assert sorted(i.to_pydict()["x"]) == [2, 3]
    e = run(lp.SetOp("except", lp.Scan("a", "a"), lp.Scan("b", "b")), cat)
    assert sorted(e.to_pydict()["x"]) == [1]


def test_sort_order_nulls_and_desc(sales_cat):
    p = lp.Sort(lp.Scan("sales", "sales"),
                [(ex.ColumnRef("s_item"), True),
                 (ex.ColumnRef("s_qty"), False)])
    out = run(p, sales_cat)
    d = out.to_pydict()
    assert d["s_item"] == [None, 1, 1, 2, 2, 3]
    assert d["s_qty"][:3] == [60, 30, 10]  # qty desc within item


def test_limit(sales_cat):
    p = lp.Limit(lp.Sort(lp.Scan("sales", "sales"),
                         [(ex.ColumnRef("s_qty"), False)]), 2)
    out = run(p, sales_cat)
    assert out.to_pydict()["s_qty"] == [60, 50]


def test_window_rank():
    t = Table({"g": col_i32([1, 1, 1, 2, 2]),
               "v": col_i32([10, 20, 20, 5, 1])})
    cat = make_catalog(t=t)
    w = ex.WindowExpr("rank", None, (ex.ColumnRef("g"),),
                      ((ex.ColumnRef("v"), False),))
    out = run(lp.Window(lp.Scan("t", "t"), [("r", w)]), cat)
    d = out.to_pydict()
    assert d["r"] == [3, 1, 1, 1, 2]
    w2 = ex.WindowExpr("dense_rank", None, (ex.ColumnRef("g"),),
                       ((ex.ColumnRef("v"), False),))
    out2 = run(lp.Window(lp.Scan("t", "t"), [("r", w2)]), cat)
    assert out2.to_pydict()["r"] == [2, 1, 1, 1, 2]


def test_window_partition_sum():
    t = Table({"g": col_i32([1, 1, 2]), "v": col_dec([1.00, 2.00, 5.00])})
    cat = make_catalog(t=t)
    w = ex.WindowExpr("sum", ex.ColumnRef("v"), (ex.ColumnRef("g"),), ())
    out = run(lp.Window(lp.Scan("t", "t"), [("s", w)]), cat)
    assert out.to_pydict()["s"] == [3.0, 3.0, 5.0]


def test_full_join():
    l = Table({"k": col_i32([1, 2]), "a": col_i32([10, 20])})
    r = Table({"k2": col_i32([2, 3]), "b": col_i32([200, 300])})
    cat = make_catalog(l=l, r=r)
    out = run(lp.Join(lp.Scan("l", "l"), lp.Scan("r", "r"), "full",
                      [(ex.ColumnRef("k"), ex.ColumnRef("k2"))]), cat)
    rows = sorted(out.to_rows(), key=lambda x: (x[0] is None, x[0] or 0))
    assert len(rows) == 3
    assert rows[0] == (1, 10, None, None)
    assert rows[1] == (2, 20, 2, 200)
    assert rows[2] == (None, None, 3, 300)
