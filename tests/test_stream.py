"""Streaming out-of-core pipeline tests: sharded chunking composed with
the H2D prefetch ring, the parallel scan/decode pool, the spill-aware
memory planner, and the NDS311 fall-through diagnostic.

Correctness bar: distributed-chunked results are bit-identical — rows
AND row order — to the single-chip chunked path and the numpy oracle,
at every prefetch depth, under injected io.read / io.prefetch faults,
and across a mid-stream SIGKILL + --resume."""

import json
import os
import subprocess
import time

import numpy as np
import pytest

from ndstpu import faults, obs
from ndstpu.engine import memplan
from ndstpu.io import loader
from ndstpu.parallel import mesh as pmesh


@pytest.fixture(scope="module")
def env():
    return dict(os.environ, PYTHONPATH=os.getcwd())


@pytest.fixture(scope="module")
def stream_root(tmp_path_factory, env):
    """Tiny plain-parquet warehouse (ParquetChunkSource cannot stream
    ndslake ACID layouts) + one query stream for the power CLI."""
    root = tmp_path_factory.mktemp("stream")
    subprocess.run(["python", "-m", "ndstpu.datagen.driver", "local",
                    "0.002", "2", str(root / "raw")], check=True, env=env)
    subprocess.run(["python", "-m", "ndstpu.io.transcode",
                    "--input_prefix", str(root / "raw"),
                    "--output_prefix", str(root / "wh"),
                    "--report_file", str(root / "load.txt")],
                   check=True, env=env, stdout=subprocess.DEVNULL)
    subprocess.run(["python", "-m", "ndstpu.queries.streamgen",
                    "--output_dir", str(root / "streams"),
                    "--rngseed", "07291122510", "--streams", "1"],
                   check=True, env=env, stdout=subprocess.DEVNULL)
    return root


@pytest.fixture(scope="module")
def catalog(stream_root):
    return loader.load_catalog(str(stream_root / "wh"))


# exact-order queries: unique ORDER BY keys for the aggregate, original
# fact row order (__rowid__ restore) for the row-mode spine
Q_AGG = ("select d_year, i_brand_id, sum(ss_ext_sales_price) as s, "
         "count(*) as n from store_sales, date_dim, item "
         "where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk "
         "group by d_year, i_brand_id order by d_year, i_brand_id")
Q_ROWS = ("select ss_item_sk, ss_quantity from store_sales "
          "where ss_quantity > 90")


def _chunked_rows(catalog, n_dev, sql, depth, chunk_rows=1000):
    """Plan once on the cpu session, execute on an n_dev mesh with the
    chunked executor; return (exact row list, executor)."""
    from ndstpu.engine.session import Session
    from ndstpu.parallel import dplan

    plan, _ = Session(catalog, backend="cpu").plan(sql)
    exe = dplan.DistributedPlanExecutor(
        catalog, pmesh.make_mesh(n_dev), shard_threshold_rows=500,
        broadcast_limit_rows=50, chunk_rows=chunk_rows,
        prefetch_depth=depth)
    got = exe.execute_plan(plan)
    return list(map(str, got.to_rows())), exe


def _oracle_rows(catalog, sql):
    from ndstpu.engine import physical
    from ndstpu.engine.session import Session
    plan, _ = Session(catalog, backend="cpu").plan(sql)
    return list(map(str, physical.execute(plan, catalog).to_rows()))


# -- memory planner ---------------------------------------------------------


def test_memplan_resident_when_fact_fits():
    p = memplan.plan_stream(1000, 100, 2, budget_bytes=2 << 30)
    assert p.chunk_rows is None and p.prefetch_depth == 0
    assert "resident" in p.describe()


def test_memplan_chunked_pow2_and_depth():
    p = memplan.plan_stream(1_000_000, 100, 2, budget_bytes=8 << 20)
    assert p.chunk_rows == 8192 and p.prefetch_depth == 2
    assert p.chunk_rows & (p.chunk_rows - 1) == 0
    assert "chunk_rows=8192 depth=2" in p.describe()


def test_memplan_shallower_ring_buys_bigger_chunks():
    # budget too tight for MIN_CHUNK_ROWS at depth 2: the planner trades
    # ring depth for chunk size all the way down to synchronous
    p = memplan.plan_stream(1_000_000, 100, 2, budget_bytes=200_000)
    assert p.prefetch_depth == 0
    assert p.chunk_rows == 256          # pow2 floor, >= n_dev


def test_memplan_budget_sources(monkeypatch):
    monkeypatch.setenv("NDSTPU_HBM_BYTES", "12345")
    assert memplan.device_budget_bytes() == (12345, "env")
    monkeypatch.delenv("NDSTPU_HBM_BYTES")
    budget, source = memplan.device_budget_bytes()
    assert budget > 0 and source in ("memory_stats", "default")


def test_memplan_row_widths():
    assert memplan.row_bytes([8, 8]) == 19    # data + validity + alive
    from ndstpu import schema as nds_schema
    schema = nds_schema.get_schemas(True)["store_sales"]
    sub = memplan.schema_row_bytes(schema, ["ss_item_sk", "ss_quantity"])
    assert 0 < sub < memplan.schema_row_bytes(schema)


# -- scan/decode pool -------------------------------------------------------


def _payload(s, n=4):
    return {"x": (np.full(n, s, dtype=np.int64), np.ones(n, bool))}


def test_scan_pool_reads_ahead():
    reads = []

    def read_fn(s):
        reads.append(s)
        return _payload(s)

    before = obs.counters_snapshot()
    pool = loader.ChunkScanPool(read_fn, range(5), workers=2, depth=2)
    try:
        for s in range(5):
            got = pool.get(s)
            np.testing.assert_array_equal(got["x"][0],
                                          np.full(4, s, dtype=np.int64))
            time.sleep(0.05)     # let the ahead workers land
    finally:
        pool.close()
    assert sorted(reads) == [0, 1, 2, 3, 4]   # KeyedLatch: no re-decode
    d = obs.counter_delta(before)
    assert d.get("io.scan.ahead.hit", 0) >= 3
    assert "io.scan.wait_s" in d


def test_scan_pool_degrades_to_synchronous_on_failure():
    calls = {0: 0}

    def read_fn(s):
        if s == 0:
            calls[0] += 1
            if calls[0] == 1:
                raise RuntimeError("disk went away")
        return _payload(s)

    before = obs.counters_snapshot()
    pool = loader.ChunkScanPool(read_fn, range(3), workers=2, depth=2)
    try:
        for s in range(3):
            np.testing.assert_array_equal(pool.get(s)["x"][0],
                                          np.full(4, s, dtype=np.int64))
    finally:
        pool.close()
    d = obs.counter_delta(before)
    assert d.get("io.scan.degraded") == 1
    assert calls[0] == 2       # failed worker read + sync retry


# -- parquet chunk source ---------------------------------------------------


def test_parquet_chunk_source_windows_match_resident(stream_root, catalog):
    cols = ["ss_item_sk", "ss_quantity"]
    src = loader.ParquetChunkSource(str(stream_root / "wh"),
                                    "store_sales", columns=cols)
    resident = catalog.get("store_sales")
    assert src.num_rows == resident.num_rows
    n = src.num_rows
    for start, count in [(0, 100), (n - 57, 57), (n // 3, 1000),
                         (0, n), (n, 10)]:
        got = src.read(start, count)
        for c in cols:
            data, valid = got[c]
            ref = resident.column(c)
            np.testing.assert_array_equal(
                data, ref.data[start:start + count])
            np.testing.assert_array_equal(
                valid, ref.validity()[start:start + count])
    meta = src.column_meta()
    assert set(meta) == set(cols)


def test_parquet_chunk_source_rejects_string_columns(stream_root,
                                                     monkeypatch):
    """With the global-dict sidecar present string columns stream; with
    NDSTPU_GLOBAL_DICTS=0 the source refuses them as before."""
    src = loader.ParquetChunkSource(str(stream_root / "wh"), "item",
                                    columns=["i_item_sk", "i_category"])
    assert src.column_meta()["i_category"][2] is not None
    monkeypatch.setenv("NDSTPU_GLOBAL_DICTS", "0")
    with pytest.raises(loader.StreamUnsupported, match="string column"):
        loader.ParquetChunkSource(str(stream_root / "wh"), "item",
                                  columns=["i_item_sk", "i_category"])


def test_attach_stream_source_validates(stream_root, catalog):
    src = loader.ParquetChunkSource(str(stream_root / "wh"),
                                    "store_sales",
                                    columns=["ss_item_sk", "ss_quantity"])
    with pytest.raises(KeyError):
        loader.attach_stream_source(catalog, "nope", src)
    with pytest.raises(ValueError, match="rows"):
        loader.attach_stream_source(catalog, "store_returns", src)


def test_chunked_execute_streams_from_parquet(stream_root, catalog):
    """With a registered ParquetChunkSource the chunked executor pulls
    rows from disk (io.scan.bytes moves) and still matches the oracle
    bit-identically, row order included."""
    src = loader.ParquetChunkSource(str(stream_root / "wh"),
                                    "store_sales",
                                    columns=["ss_item_sk", "ss_quantity"])
    loader.attach_stream_source(catalog, "store_sales", src)
    before = obs.counters_snapshot()
    try:
        got, exe = _chunked_rows(catalog, 2, Q_ROWS, depth=2)
        assert exe._chunk_info[0]
        assert got == _oracle_rows(catalog, Q_ROWS)
    finally:
        catalog.streams.pop("store_sales", None)
    d = obs.counter_delta(before)
    assert d.get("io.scan.bytes", 0) > 0


# -- prefetch ring ----------------------------------------------------------


def test_prefetch_depths_bit_identical(catalog):
    """Depth 0/1/2 on a 2-device mesh and depth 2 on a 1-device mesh all
    produce the same bytes in the same order; the ring actually engages
    (hits at depth 2, none at depth 0) and streams >= 3 launches."""
    for sql in (Q_AGG, Q_ROWS):
        oracle = _oracle_rows(catalog, sql)
        single, exe1 = _chunked_rows(catalog, 1, sql, depth=2)
        assert exe1._chunk_info[0]
        assert single == oracle
        for depth in (0, 1, 2):
            before = obs.counters_snapshot()
            got, exe = _chunked_rows(catalog, 2, sql, depth=depth)
            chunked, n_launches = exe._chunk_info[0], exe._chunk_info[1]
            assert chunked and n_launches >= 3
            assert got == oracle, f"depth={depth}: {sql[:48]}"
            d = obs.counter_delta(before)
            if depth == 0:
                assert d.get("io.prefetch.hit", 0) == 0
            else:
                assert d.get("io.prefetch.hit", 0) > 0
            assert d.get("engine.h2d.bytes", 0) > 0
            assert d.get("engine.stream.execute_s", 0) > 0


def test_prefetch_fault_degrades_but_stays_correct(catalog):
    faults.install("io.prefetch:transient:1.0:seedF:times=1")
    before = obs.counters_snapshot()
    try:
        got, exe = _chunked_rows(catalog, 2, Q_ROWS, depth=2)
    finally:
        faults.uninstall()
    assert exe._chunk_info[0]
    assert got == _oracle_rows(catalog, Q_ROWS)
    d = obs.counter_delta(before)
    assert d.get("io.prefetch.degraded", 0) >= 1
    assert d.get("faults.injected.io.prefetch.transient", 0) == 1


def test_scan_fault_degrades_but_stays_correct(catalog):
    faults.install("io.read:transient:1.0:seedR:times=1")
    before = obs.counters_snapshot()
    try:
        got, exe = _chunked_rows(catalog, 2, Q_ROWS, depth=2)
    finally:
        faults.uninstall()
    assert exe._chunk_info[0]
    assert got == _oracle_rows(catalog, Q_ROWS)
    d = obs.counter_delta(before)
    assert d.get("io.scan.degraded", 0) >= 1
    assert d.get("faults.injected.io.read.transient", 0) == 1


# -- session wiring ---------------------------------------------------------


def test_session_auto_chunk_rows(catalog, monkeypatch):
    """spmd_chunk_rows='auto' sizes the stream from the (pinned) device
    budget and engages chunking when the fact exceeds it."""
    from ndstpu.engine.session import Session

    monkeypatch.setenv("NDSTPU_HBM_BYTES", "200000")
    cpu = Session(catalog, backend="cpu")
    tpu = Session(catalog, backend="tpu", spmd_threshold=500,
                  spmd_chunk_rows="auto")
    sql = Q_AGG
    assert sorted(map(str, tpu.sql(sql).to_rows())) == \
        sorted(map(str, cpu.sql(sql).to_rows()))
    assert getattr(tpu, "_spmd_used", False)
    assert not getattr(tpu, "_spmd_errors", None)
    assert any(ent[1]._chunk_info[0] for ent in tpu._spmd_cache.values())


def test_session_stream_config_validation(catalog):
    from ndstpu.engine.session import Session
    for bad in (0, -5, True, "bogus", 3.5):
        with pytest.raises(ValueError):
            Session(catalog, spmd_chunk_rows=bad)
    with pytest.raises(ValueError):
        Session(catalog, spmd_prefetch_depth=-1)
    Session(catalog, spmd_chunk_rows="auto", spmd_prefetch_depth=0)


def test_nds311_chunk_fallthrough_warns_and_strict_raises(
        catalog, monkeypatch):
    """Chunking configured on a multi-device mesh + a plan that falls
    back to the single-chip path is no longer silent: NDS311 warning,
    counter, and an error under NDSTPU_SPMD_STRICT."""
    from ndstpu.engine.session import ChunkFallthroughError, Session

    # default shard threshold: every table at this SF broadcasts, so the
    # distributed executor refuses the plan and the session falls back
    sql = "select count(*) as n from item"
    sess = Session(catalog, backend="tpu-spmd", spmd_chunk_rows=1000)
    before = obs.counters_snapshot()
    with pytest.warns(UserWarning, match="NDS311"):
        out = sess.sql(sql)
    assert out.to_rows()[0][0] == catalog.get("item").num_rows
    assert obs.counter_delta(before).get(
        "engine.spmd.fallback.NDS311") == 1

    monkeypatch.setenv("NDSTPU_SPMD_STRICT", "1")
    strict = Session(catalog, backend="tpu-spmd", spmd_chunk_rows=1000)
    with pytest.raises(ChunkFallthroughError, match="NDS311"):
        strict.sql(sql)


def test_nds311_registered():
    from ndstpu.analysis import diagnostics
    assert diagnostics.CODES["NDS311"][0] == "warning"


# -- crash safety -----------------------------------------------------------


def test_power_sigkill_midstream_then_resume(stream_root, env, tmp_path):
    """SIGKILL the power CLI while the chunked prefetching engine is
    mid-stream; --resume must skip the journaled query and complete the
    rest with the same fingerprint."""
    props = tmp_path / "stream.properties"
    props.write_text("spmd.threshold_rows=500\n"
                     "spmd.chunk_rows=1000\n"
                     "spmd.prefetch_depth=2\n")
    time_log = tmp_path / "time.csv"
    cmd = ["python", "-m", "ndstpu.harness.power",
           str(stream_root / "streams" / "query_0.sql"),
           str(stream_root / "wh"), str(time_log),
           "--engine", "tpu", "--property_file", str(props),
           "--sub_queries", "query3,query42"]
    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    journal = tmp_path / "time.csv.progress.jsonl"
    deadline = time.monotonic() + 180
    try:
        while time.monotonic() < deadline:
            if journal.exists() and "query3" in journal.read_text():
                break
            if proc.poll() is not None:
                break
            time.sleep(0.05)
    finally:
        proc.kill()      # SIGKILL: no atexit, no flush, no cleanup
        proc.wait()
    recs = [json.loads(line)
            for line in journal.read_text().splitlines()]
    assert any(r["query"] == "query3" for r in recs)

    r = subprocess.run(cmd + ["--resume"], check=True, env=env,
                       capture_output=True, text=True)
    assert "Skip query3 (resume: already completed)" in r.stdout
    sidecar = json.loads(
        (tmp_path / "time.csv.metrics.json").read_text())
    assert "query3" in sidecar["resumed"]
    assert "query42" in time_log.read_text()
