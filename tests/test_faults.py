"""Robustness layer: injector grammar/determinism, failure taxonomy,
retry/quarantine contract, atomic artifact writes, bench RUN_STATE
journal, sentinel failed-<taxonomy> verdicts, and the throughput
restart-once path (docs/ROBUSTNESS.md)."""

import json
import os
import sys
import textwrap

import pytest

from ndstpu import faults
from ndstpu.faults import injector, retry, taxonomy
from ndstpu.harness import runstate, throughput
from ndstpu.io import atomic
from ndstpu.obs import ledger as ledger_mod
from ndstpu.obs import sentinel


@pytest.fixture(autouse=True)
def _no_ambient_faults(monkeypatch):
    """Tests own the injector: clear any ambient spec, and never leak
    an installed one into other test modules."""
    monkeypatch.delenv(injector.ENV_VAR, raising=False)
    faults.uninstall()
    yield
    faults.uninstall()


# ------------------------------------------------------------ injector

def test_parse_rule_full_grammar():
    r = injector._parse_rule("execute:transient:0.25:seed7:times=3:hang=2")
    assert (r.site, r.kind, r.prob) == ("execute", "transient", 0.25)
    assert r.seed == "7" and r.times == 3 and r.hang_s == 2.0
    assert r.describe() == "execute:transient:0.25:seed7:times=3"


def test_parse_spec_env_string_multi():
    rules = faults.parse_spec(
        "execute:transient:0.2:seed7, io.write:permanent:0.05")
    assert [(r.site, r.kind) for r in rules] == \
        [("execute", "transient"), ("io.write", "permanent")]
    assert faults.parse_spec(None) == [] and faults.parse_spec("") == []


def test_parse_spec_yaml_forms():
    # single mapping, list of mappings, and list of strings all parse
    one = faults.parse_spec({"site": "plan", "kind": "permanent",
                             "prob": 0.5, "seed": 9})
    assert len(one) == 1 and one[0].seed == "9"
    mixed = faults.parse_spec([
        {"site": "compile", "times": 2},
        "stream.worker:hang:1.0:hang=0.1",
    ])
    assert mixed[0].kind == "transient" and mixed[0].prob == 1.0
    assert mixed[1].kind == "hang" and mixed[1].hang_s == 0.1


@pytest.mark.parametrize("bad", [
    "nosuchsite:transient:1.0",
    "execute:explode:1.0",
    "execute:transient:1.5",
    "execute:transient",
    "execute:transient:often",
    "execute:transient:1.0:wat=1",
])
def test_parse_spec_rejects_malformed(bad):
    with pytest.raises(faults.FaultSpecError):
        faults.parse_spec(bad)
    with pytest.raises(faults.FaultSpecError):
        faults.parse_spec([{"kind": "transient"}])  # no site


def test_fire_decision_is_deterministic_per_seed():
    a = injector.FaultRule("execute", "transient", 0.3, seed="7")
    b = injector.FaultRule("execute", "transient", 0.3, seed="7")
    seq_a = [a.should_fire(i) for i in range(200)]
    seq_b = [b.should_fire(i) for i in range(200)]
    assert seq_a == seq_b and any(seq_a) and not all(seq_a)
    c = injector.FaultRule("execute", "transient", 0.3, seed="8")
    assert seq_a != [c.should_fire(i) for i in range(200)]


def test_prob_bounds_always_and_never():
    always = injector.FaultRule("plan", "permanent", 1.0)
    never = injector.FaultRule("plan", "permanent", 0.0)
    assert all(always.should_fire(i) for i in range(10))
    assert not any(never.should_fire(i) for i in range(10))


def test_times_bounds_injections_and_counters():
    inj = injector.Injector(
        faults.parse_spec("execute:transient:1.0:times=2"), out=lambda s: None)
    for _ in range(2):
        with pytest.raises(faults.InjectedTransient):
            inj.check("execute", key="q")
    inj.check("execute")  # budget spent: probe is a no-op now
    assert inj.injected == {"execute": 2} and inj.calls["execute"] == 3


def test_sites_are_independent():
    inj = injector.Injector(faults.parse_spec("execute:permanent:1.0"),
                            out=lambda s: None)
    inj.check("plan")
    inj.check("io.write")
    with pytest.raises(faults.InjectedPermanent) as ei:
        inj.check("execute")
    assert ei.value.site == "execute" and ei.value.kind == "permanent"


def test_hang_sleeps_instead_of_raising():
    slept = []
    inj = injector.Injector(
        faults.parse_spec("compile:hang:1.0:hang=5"),
        sleep=slept.append, out=lambda s: None)
    inj.check("compile")  # returns normally after the simulated wedge
    assert slept == [5.0]


def test_module_probe_noop_until_installed():
    faults.check("execute")  # nothing installed: no-op
    faults.install("execute:transient:1.0")
    with pytest.raises(faults.InjectedTransient):
        faults.check("execute", key="query1")
    faults.uninstall()
    faults.check("execute")
    assert faults.active() is None


def test_install_from_env(monkeypatch):
    monkeypatch.setenv(injector.ENV_VAR, "plan:permanent:1.0:seed3")
    inj = faults.install_from_env()
    assert inj is faults.active() and inj.rules[0].seed == "3"
    monkeypatch.delenv(injector.ENV_VAR)
    assert faults.install_from_env() is None


# ------------------------------------------------------------ taxonomy

def test_classify_injected_faults():
    assert taxonomy.classify(
        faults.InjectedTransient("x", "execute")) == taxonomy.TRANSIENT
    assert taxonomy.classify(
        faults.InjectedPermanent("x", "plan")) == taxonomy.PERMANENT


@pytest.mark.parametrize("exc,klass", [
    (TimeoutError("watchdog abandoned query"), taxonomy.TRANSIENT),
    (ConnectionResetError("peer"), taxonomy.TRANSIENT),
    (ValueError("bad literal"), taxonomy.PERMANENT),
    (NotImplementedError("rollup"), taxonomy.PERMANENT),
    (RuntimeError("DEADLINE EXCEEDED while waiting"), taxonomy.TRANSIENT),
    (RuntimeError("segfault in kernel"), taxonomy.PERMANENT),  # unknown
])
def test_classify_types_and_messages(exc, klass):
    assert taxonomy.classify(exc) == klass


def test_classify_kind_attribute_wins():
    e = RuntimeError("mystery")
    e.kind = "transient"
    assert taxonomy.classify(e) == taxonomy.TRANSIENT


def test_classify_name_sentinel_path():
    # permanent type names beat transient message keywords
    assert taxonomy.classify_name("PlanError", "timed out") == \
        taxonomy.PERMANENT
    assert taxonomy.classify_name("JaxRuntimeError",
                                  "connection reset by peer") == \
        taxonomy.TRANSIENT
    assert taxonomy.classify_name("SomethingNew") == taxonomy.PERMANENT


# -------------------------------------------------------------- retry

def _policy(n):
    return retry.RetryPolicy(max_attempts=n)


def test_retry_recovers_transient():
    calls, sleeps = [], []
    def fn():
        calls.append(1)
        if len(calls) == 1:
            raise faults.InjectedTransient("flaky", "execute")
        return 42
    result, attempts = retry.run_with_retry(
        fn, "query1", policy=_policy(2), sleep=sleeps.append,
        out=lambda s: None)
    assert (result, attempts) == (42, 2)
    assert sleeps == [0.05]  # deterministic: base backoff, no jitter


def test_retry_permanent_raises_immediately():
    calls = []
    def fn():
        calls.append(1)
        raise faults.InjectedPermanent("broken", "plan")
    with pytest.raises(faults.InjectedPermanent) as ei:
        retry.run_with_retry(fn, "query1", policy=_policy(3),
                             sleep=lambda s: None, out=lambda s: None)
    assert len(calls) == 1
    assert ei.value.taxonomy == taxonomy.PERMANENT
    assert ei.value.attempts == 1


def test_retry_exhausted_with_deterministic_backoff():
    sleeps = []
    def fn():
        raise TimeoutError("rpc deadline")
    with pytest.raises(TimeoutError) as ei:
        retry.run_with_retry(fn, "query1", policy=_policy(3),
                             sleep=sleeps.append, out=lambda s: None)
    assert sleeps == [0.05, 0.1]  # pure doubling
    assert ei.value.taxonomy == taxonomy.TRANSIENT
    assert ei.value.attempts == 3


def test_retry_policy_backoff_cap_and_env():
    p = retry.RetryPolicy()
    assert p.backoff_s(10) == retry.DEFAULT_MAX_BACKOFF_S
    assert retry.RetryPolicy.from_env({"NDSTPU_RETRY_MAX": "5"}) \
        .max_attempts == 5
    assert retry.RetryPolicy.from_env({"NDSTPU_RETRY_MAX": "zero"}) \
        .max_attempts == retry.DEFAULT_MAX_ATTEMPTS
    assert retry.RetryPolicy.from_env({"NDSTPU_RETRY_MAX": "0"}) \
        .max_attempts == 1  # clamped: at least one attempt
    with pytest.raises(ValueError):
        retry.RetryPolicy(max_attempts=0)


def test_quarantine_poison_list():
    q = retry.Quarantine(max_failures=2)
    assert not q.note_failure("query5", "transient")
    assert not q.is_quarantined("query5")
    assert q.note_failure("query5", "permanent")  # tips into quarantine
    assert q.is_quarantined("query5")
    assert q.failures("query5") == ["transient", "permanent"]
    assert not q.note_failure("query6", "transient")
    assert not q.is_quarantined("query6")  # keys are independent
    assert "max_failures=2" in q.reason("query5")
    assert list(q.snapshot()) == ["query5"]  # only quarantined keys


def test_retry_feeds_quarantine():
    q = retry.Quarantine(max_failures=2)
    def fn():
        raise faults.InjectedPermanent("broken", "execute")
    for _ in range(2):
        with pytest.raises(faults.InjectedPermanent):
            retry.run_with_retry(fn, "query9", policy=_policy(1),
                                 quarantine=q, sleep=lambda s: None,
                                 out=lambda s: None)
    assert q.is_quarantined("query9")
    assert q.snapshot()["query9"] == ["permanent", "permanent"]


# ------------------------------------------------------------- atomic

def test_atomic_write_and_read_back(tmp_path):
    p = tmp_path / "a" / "doc.json"
    atomic.atomic_write_json(str(p), {"k": [1, 2]})
    with open(p) as f:
        assert json.load(f) == {"k": [1, 2]}
    atomic.atomic_write_text(str(p), "hello\n")
    assert p.read_text() == "hello\n"
    atomic.atomic_write_bytes(str(p), b"\x00\x01")
    assert p.read_bytes() == b"\x00\x01"


def test_atomic_writer_refuses_append(tmp_path):
    with pytest.raises(ValueError):
        with atomic.atomic_writer(str(tmp_path / "x"), "a"):
            pass


def test_atomic_writer_leaves_no_partial_file(tmp_path):
    p = tmp_path / "doc.json"
    atomic.atomic_write_text(str(p), "old complete artifact")
    with pytest.raises(RuntimeError):
        with atomic.atomic_writer(str(p)) as f:
            f.write("half of the new")
            raise RuntimeError("crash mid-write")
    # old artifact intact, temp file cleaned up
    assert p.read_text() == "old complete artifact"
    assert [x.name for x in tmp_path.iterdir()] == ["doc.json"]


def test_append_jsonl_and_torn_tail(tmp_path):
    j = str(tmp_path / "journal.jsonl")
    atomic.append_jsonl(j, {"n": 1})
    atomic.append_jsonl(j, {"n": 2})
    with open(j, "a") as f:
        f.write('{"n": 3, "tr')  # crash mid-append: torn final line
    assert atomic.read_jsonl(j) == [{"n": 1}, {"n": 2}]
    assert atomic.read_jsonl(str(tmp_path / "missing.jsonl")) == []
    # a torn line that is NOT final means corruption, not a crash
    with open(j, "a") as f:
        f.write('uncated\n{"n": 4}\n')
    with pytest.raises(ValueError):
        atomic.read_jsonl(j)


def test_io_write_fault_fires_through_atomic_helpers(tmp_path):
    faults.install("io.write:permanent:1.0:times=1")
    p = str(tmp_path / "doc.json")
    with pytest.raises(faults.InjectedPermanent):
        atomic.atomic_write_json(p, {"k": 1})
    assert not os.path.exists(p)  # fault fired before any bytes
    atomic.atomic_write_json(p, {"k": 1})  # times=1: budget spent
    assert os.path.exists(p)


# ----------------------------------------------------------- runstate

def _bench_params(**over):
    params = {
        "power_test": {"engine": "cpu", "scale_factor": 0.01,
                       "budget_s": 60},
        "load_test": {"warehouse": "/wh"},
        "observability": {"ledger": "/tmp/led.jsonl"},
        "metrics": {"metrics_report": "/tmp/m.csv"},
    }
    params.update(over)
    return params


def test_config_fingerprint_ignores_obs_and_budget():
    fp = runstate.config_fingerprint(_bench_params())
    assert fp == runstate.config_fingerprint(_bench_params(
        observability={"ledger": "/elsewhere.jsonl"}))
    assert fp == runstate.config_fingerprint(_bench_params(
        power_test={"engine": "cpu", "scale_factor": 0.01,
                    "budget_s": 5}))
    # a real config change (engine) must invalidate the journal
    assert fp != runstate.config_fingerprint(_bench_params(
        power_test={"engine": "tpu", "scale_factor": 0.01}))


def test_runstate_mark_completed_reset(tmp_path):
    path = str(tmp_path / runstate.DEFAULT_BASENAME)
    st = runstate.RunState(path, "fp1")
    assert st.completed_phases() == set()
    st.mark("load_test", artifacts=["/wh"])
    st.mark("power_test")
    assert st.completed_phases() == {"load_test", "power_test"}
    assert st.records()[0]["artifacts"] == ["/wh"]
    # a different fingerprint never splices in another config's phases
    assert runstate.RunState(path, "fp2").completed_phases() == set()
    st.reset()
    assert not os.path.exists(path) and st.completed_phases() == set()


# ----------------------------------------------- sentinel taxonomy split

def test_sentinel_splits_failed_by_taxonomy():
    led = ledger_mod.Ledger(path=None)
    qsums = [
        {"query": "query1", "wall_s": 0.1,
         "attrs": {"error": "InjectedTransient: flaky",
                   "error_taxonomy": "transient", "error_attempts": 2}},
        {"query": "query2", "wall_s": 0.1,
         "attrs": {"error": "PlanError: no",
                   "error_taxonomy": "permanent", "error_attempts": 1}},
        # a failure that never went through the retry layer keeps the
        # bare verdict (tests/test_ledger.py pins this invariant too)
        {"query": "query3", "wall_s": 0.1, "attrs": {"error": "boom"}},
    ]
    res = sentinel.classify_run(qsums, led, engine="cpu",
                                scale_factor="1")
    assert res["counts"] == {"failed-transient": 1,
                             "failed-permanent": 1, "failed": 1}
    by_q = {v["query"]: v for v in res["verdicts"]}
    assert by_q["query1"]["attempts"] == 2
    assert res["regressions"] == []
    md = sentinel.markdown_table(res)
    assert "failed-transient" in md and "failed-permanent" in md


# ------------------------------------------- throughput restart-once

def _flaky_stream_script(tmp_path, fail_rc, then_succeed):
    """A stand-in stream process: exits ``fail_rc`` on the first run
    for a given stream id and, when ``then_succeed``, 0 afterwards."""
    script = tmp_path / "stream.py"
    script.write_text(textwrap.dedent(f"""\
        import pathlib, sys
        marker = pathlib.Path(sys.argv[1]) / ("ran_" + sys.argv[2])
        if marker.exists() and {then_succeed!r}:
            sys.exit(0)
        marker.touch()
        sys.exit({fail_rc})
        """))
    return str(script)


def test_throughput_restarts_failed_stream_once(tmp_path, capsys):
    script = _flaky_stream_script(tmp_path, fail_rc=3, then_succeed=True)
    report = str(tmp_path / "overlap.json")
    rc = throughput.run_throughput(
        ["0", "1"], [sys.executable, script, str(tmp_path), "{}"],
        overlap_report=report)
    assert rc == 0  # both streams recovered on their restart
    out = capsys.readouterr().out
    assert "restarting once (taxonomy: transient)" in out
    with open(report) as f:
        doc = json.load(f)
    assert len(doc["streams"]) == 2
    for rec in doc["streams"]:
        assert rec["returncode"] == 0 and rec["restarts"] == 1
        assert rec["first_attempt"]["returncode"] == 3
        assert rec["taxonomy"] == taxonomy.TRANSIENT


def test_throughput_restart_exhausted_is_permanent(tmp_path):
    script = _flaky_stream_script(tmp_path, fail_rc=4, then_succeed=False)
    report = str(tmp_path / "overlap.json")
    rc = throughput.run_throughput(
        ["0"], [sys.executable, script, str(tmp_path), "{}"],
        overlap_report=report)
    assert rc == 4  # restart budget is ONE: second failure is final
    with open(report) as f:
        rec = json.load(f)["streams"][0]
    assert rec["restarts"] == 1 and rec["returncode"] == 4
    assert rec["taxonomy"] == taxonomy.PERMANENT


# ------------------------------------------- crash-consistent ingest


def _tiny_lake(tmp_path, tables=("alpha", "beta"), fmt="ndslake"):
    import numpy as np
    import pyarrow as pa

    from ndstpu.io import lake
    wh = str(tmp_path / "wh")
    os.makedirs(wh, exist_ok=True)
    for t in tables:
        at = pa.table({"k": np.arange(6, dtype=np.int64)})
        lake.create_table(fmt, str(tmp_path / "wh" / t), at)
    return wh


def test_ingest_commit_fault_leaves_old_state_current(tmp_path):
    """An injected ingest.commit fault fires with the manifest written
    but CURRENT unpublished: the table stays at the OLD snapshot —
    never torn — and GC restores the version numbering."""
    import pyarrow as pa

    from ndstpu.io import lake
    wh = _tiny_lake(tmp_path, tables=("alpha",))
    root = os.path.join(wh, "alpha")
    v0 = lake.current_version(root)

    faults.install("ingest.commit:transient:1.0:times=1")
    with pytest.raises(faults.InjectedTransient):
        lake.append(root, pa.table({"k": pa.array([99])}))
    faults.uninstall()

    # old snapshot is still CURRENT and fully readable: not torn
    assert lake.current_version(root) == v0
    assert lake.read(root).num_rows == 6
    # the unpublished manifest is GC-able garbage, not corruption
    removed = lake.gc_orphan_manifests(root)
    assert removed, "fault before publish left no orphan manifest"
    lake.append(root, pa.table({"k": pa.array([99])}))
    assert lake.current_version(root) == v0 + 1  # clean-run numbering


def test_commit_conflict_classified_transient():
    from ndstpu.io.commit import CommitConflict
    exc = CommitConflict("/wh/t", 3, 5)
    assert taxonomy.classify(exc) == taxonomy.TRANSIENT
    assert exc.expected == 3 and exc.found == 5


def test_ingestor_journals_intent_and_done(tmp_path):
    import pyarrow as pa

    from ndstpu.harness.ingest import MicroBatchIngestor
    from ndstpu.io import lake
    wh = _tiny_lake(tmp_path)
    ing = MicroBatchIngestor(wh)

    def batch():
        for t in ("alpha", "beta"):
            root = os.path.join(wh, t)
            lake.append(root, pa.table({"k": pa.array([7])}))

    rec = ing.apply_batch("b0", batch)
    assert rec["attempts"] == 1
    events = [r["event"] for r in ing.records()]
    assert events == ["intent", "done"]
    assert ing.records()[0]["pre_versions"] == {"alpha": 0, "beta": 0}
    assert rec["post_versions"] == {"alpha": 1, "beta": 1}
    assert ing.pending_intent() is None
    assert ing.done_funcs() == ["b0"]


def test_ingestor_retries_injected_commit_fault(tmp_path):
    """A transient ingest.commit fault inside a batch is absorbed by
    retract-and-retry, landing on the same versions as a clean run."""
    import pyarrow as pa

    from ndstpu import obs
    from ndstpu.harness.ingest import MicroBatchIngestor
    from ndstpu.io import lake
    wh = _tiny_lake(tmp_path)
    ing = MicroBatchIngestor(wh)

    def batch():
        for t in ("alpha", "beta"):
            lake.append(os.path.join(wh, t),
                        pa.table({"k": pa.array([7])}))

    before = dict(obs.counters_snapshot())
    faults.install("ingest.commit:transient:1.0:times=1")
    try:
        rec = ing.apply_batch("b0", batch)
    finally:
        faults.uninstall()
    assert rec["attempts"] == 2
    assert rec["post_versions"] == {"alpha": 1, "beta": 1}
    after = dict(obs.counters_snapshot())
    assert after.get("engine.ingest.retries", 0) - \
        before.get("engine.ingest.retries", 0) >= 1


def test_ingestor_resume_retracts_crashed_batch(tmp_path):
    """intent-without-done + partially committed tables == crash
    mid-batch: resume() retracts to the recorded pre-versions (no
    rollback snapshot — the clean-run version trajectory survives)."""
    import pyarrow as pa

    from ndstpu.harness.ingest import MicroBatchIngestor
    from ndstpu.io import lake
    wh = _tiny_lake(tmp_path)
    ing = MicroBatchIngestor(wh)

    class Crash(RuntimeError):
        pass

    def partial():
        lake.append(os.path.join(wh, "alpha"),
                    pa.table({"k": pa.array([7])}))
        raise Crash("died mid-batch")

    with pytest.raises(Crash):
        ing.apply_batch("b0", partial)
    assert lake.versions_vector(wh) == {"alpha": 1, "beta": 0}
    assert ing.pending_intent() is not None

    assert ing.resume() == "b0"  # the batch must be re-applied
    assert lake.versions_vector(wh) == {"alpha": 0, "beta": 0}
    assert lake.read(os.path.join(wh, "alpha")).num_rows == 6
    assert [r["event"] for r in ing.records()] == \
        ["intent", "rolled_back"]
    # a clean journal resumes to nothing
    assert ing.resume() is None


def test_ingestor_run_skips_journaled_done(tmp_path):
    import pyarrow as pa

    from ndstpu.harness.ingest import MicroBatchIngestor
    from ndstpu.io import lake
    wh = _tiny_lake(tmp_path, tables=("alpha",))
    applied = []

    def mk(name):
        def apply():
            applied.append(name)
            lake.append(os.path.join(wh, "alpha"),
                        pa.table({"k": pa.array([1])}))
        return apply

    ing = MicroBatchIngestor(wh)
    ing.run([("b0", mk("b0"))])
    # a fresh ingestor (new process) over the same journal skips b0
    ing2 = MicroBatchIngestor(wh)
    ing2.run([("b0", mk("b0")), ("b1", mk("b1"))], resume=True)
    assert applied == ["b0", "b1"]
    assert lake.versions_vector(wh) == {"alpha": 2}


def test_ingest_grows_global_dicts_pinned_reads_survive(tmp_path):
    """A refresh batch carrying never-seen strings grows the global
    dictionary append-only: new loads see the grown value set, pinned
    snapshot readers keep decoding with the dict matching their pin,
    and the warehouse epoch moves so epoch-keyed caches drop stale
    entries (engine.snapshot.stale_drops)."""
    import numpy as np
    import pyarrow as pa

    from ndstpu import obs
    from ndstpu.engine import columnar
    from ndstpu.engine import spine as rt_spine
    from ndstpu.harness.ingest import MicroBatchIngestor
    from ndstpu.io import gdict, lake
    from ndstpu.io.loader import LakeChunkSource

    wh = str(tmp_path / "wh")
    root = os.path.join(wh, "alpha")
    lake.create_table("ndslake", root, pa.table(
        {"s": pa.array(["birch", "ash", "birch"])}))
    gdict.grow_for_table(root, "alpha")
    pin0 = lake.current_version(root)
    d0 = gdict.table_dicts(root, "alpha")["s"]
    assert list(d0.values) == ["ash", "birch"]

    cache = rt_spine.SpineCache(64 << 20)
    state0 = (lake.warehouse_epoch(wh), ())
    cache.put("vk", state0, columnar.Table(
        {"v": columnar.Column.from_numpy(
            np.arange(4, dtype=np.int64), columnar.INT64)}))

    ing = MicroBatchIngestor(wh)
    ing.apply_batch("b0", lambda: lake.append(
        root, pa.table({"s": pa.array(["cedar", "ash"])})))

    # new loads see the grown, re-sorted dict (a NEW frozen version)
    d1 = gdict.table_dicts(root, "alpha")["s"]
    assert list(d1.values) == ["ash", "birch", "cedar"]
    assert d1.version == d0.version + 1
    # pinned snapshots keep decoding against their matching version
    dp = gdict.table_dicts(root, "alpha", pin_table_version=pin0)["s"]
    assert list(dp.values) == list(d0.values)
    src = LakeChunkSource(root, "alpha", version=pin0)
    codes, valid = src.read(0, src.num_rows)["s"]
    assert valid.all()
    assert [str(dp.values[c]) for c in codes] == \
        ["birch", "ash", "birch"]

    # dict growth rides the snapshot epoch: the pre-ingest cache entry
    # is dropped, not served
    state1 = (lake.warehouse_epoch(wh), ())
    assert state1 != state0
    before = obs.counters_snapshot()
    assert cache.get("vk", state1) is None
    assert obs.counter_delta(before).get(
        "engine.snapshot.stale_drops", 0) >= 1


def test_ingest_crash_retracts_dict_versions(tmp_path):
    """A crash after the dict grew but before the batch journaled done
    retracts the dict versions with the lake commits: resume() leaves
    the sidecar on the clean-run trajectory, so a re-applied batch
    regrows identically."""
    import pyarrow as pa

    from ndstpu.harness.ingest import MicroBatchIngestor
    from ndstpu.io import gdict, lake

    wh = str(tmp_path / "wh")
    root = os.path.join(wh, "alpha")
    lake.create_table("ndslake", root, pa.table(
        {"s": pa.array(["birch", "ash"])}))
    gdict.grow_for_table(root, "alpha")
    ing = MicroBatchIngestor(wh)

    class Crash(RuntimeError):
        pass

    def partial():
        lake.append(root, pa.table({"s": pa.array(["dogwood"])}))
        gdict.grow_for_table(root, "alpha")  # grew, then died pre-done
        raise Crash("died mid-batch")

    with pytest.raises(Crash):
        ing.apply_batch("b0", partial)
    assert "dogwood" in list(gdict.table_dicts(root, "alpha")["s"].values)

    assert ing.resume() == "b0"
    d = gdict.table_dicts(root, "alpha")["s"]
    assert list(d.values) == ["ash", "birch"]

    # the re-applied batch converges: same rows, same dict versions
    ing.apply_batch("b0", lambda: lake.append(
        root, pa.table({"s": pa.array(["dogwood"])})))
    d2 = gdict.table_dicts(root, "alpha")["s"]
    assert list(d2.values) == ["ash", "birch", "dogwood"]
    assert d2.table_version == lake.current_version(root)
