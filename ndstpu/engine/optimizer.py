"""Logical plan optimizer.

Round-1 rule set (the ones that dominate NDS star-join performance):

1. predicate pushdown — through rename-Projects, split across Join sides,
   finally merged into Scan.predicate (evaluated on the raw table before
   anything else touches it; the TPU path also uses it for partition
   pruning on date_sk).
2. projection pruning — each operator keeps only columns its ancestors
   need; Scans record the narrowed column list (Scan.columns).

Both operate on the planner's invariant that all non-generated column names
are globally unique ("alias.col"), which makes substitution trivial.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from ndstpu.engine import expr as ex, plan as lp


# -- helpers -----------------------------------------------------------------


def _conjuncts(e: Optional[ex.Expr]) -> List[ex.Expr]:
    if e is None:
        return []
    if isinstance(e, ex.BinOp) and e.op == "and":
        return _conjuncts(e.left) + _conjuncts(e.right)
    return [e]


def _conjoin(parts) -> Optional[ex.Expr]:
    out = None
    for p in parts:
        out = p if out is None else ex.BinOp("and", out, p)
    return out


def _refs(e: ex.Expr) -> Set[str]:
    return {n.name for n in e.walk() if isinstance(n, ex.ColumnRef)}


def _substitute(e: ex.Expr, mapping: Dict[str, ex.Expr]) -> ex.Expr:
    if isinstance(e, ex.ColumnRef):
        return mapping.get(e.name, e)
    if isinstance(e, ex.BinOp):
        return ex.BinOp(e.op, _substitute(e.left, mapping),
                        _substitute(e.right, mapping))
    if isinstance(e, ex.UnaryOp):
        return ex.UnaryOp(e.op, _substitute(e.operand, mapping))
    if isinstance(e, ex.Cast):
        return ex.Cast(_substitute(e.operand, mapping), e.target)
    if isinstance(e, ex.Func):
        return ex.Func(e.name, tuple(_substitute(a, mapping) for a in e.args))
    if isinstance(e, ex.InList):
        return ex.InList(_substitute(e.operand, mapping), e.values, e.negated)
    if isinstance(e, ex.Case):
        return ex.Case(tuple((_substitute(c, mapping), _substitute(v, mapping))
                             for c, v in e.whens),
                       _substitute(e.default, mapping)
                       if e.default is not None else None)
    if isinstance(e, ex.AggExpr):
        if isinstance(e.arg, ex.Star):
            return e
        return ex.AggExpr(e.func, _substitute(e.arg, mapping), e.distinct)
    if isinstance(e, ex.WindowExpr):
        return ex.WindowExpr(
            e.func,
            None if e.arg is None or isinstance(e.arg, ex.Star)
            else _substitute(e.arg, mapping),
            tuple(_substitute(p, mapping) for p in e.partition_by),
            tuple((_substitute(o, mapping), a) for o, a in e.order_by),
            e.frame)
    return e


def _output_names(p: lp.Plan) -> List[str]:
    if isinstance(p, lp.Project):
        return [n for n, _ in p.exprs]
    if isinstance(p, lp.Aggregate):
        return [n for n, _ in p.group_by] + [n for n, _ in p.aggs]
    if isinstance(p, (lp.Filter, lp.Sort, lp.Limit, lp.Distinct)):
        return _output_names(p.child)
    if isinstance(p, lp.SetOp):
        return _output_names(p.left)
    if isinstance(p, lp.InlineTable):
        return list(p.table.column_names)
    if isinstance(p, lp.Window):
        return _output_names(p.child) + [n for n, _ in p.exprs]
    if isinstance(p, lp.Join):
        if p.kind == "mark":
            return _output_names(p.left) + [p.mark]
        if p.kind in ("semi", "anti", "nullaware_anti"):
            return _output_names(p.left)
        return _output_names(p.left) + _output_names(p.right)
    if isinstance(p, lp.Scan):
        raise RuntimeError("bare Scan in optimizer (planner wraps in Project)")
    if isinstance(p, lp.SubqueryAlias):
        return _output_names(p.child)
    raise RuntimeError(f"output names of {type(p).__name__}")


# -- predicate pushdown ------------------------------------------------------


def _factor_common(e: ex.Expr) -> ex.Expr:
    """Factor conjuncts common to every branch of a disjunction:
    (E and A) or (E and B)  ->  E and (A or B).

    The TPC-DS demographic-OR pattern (q13/q48/q85) repeats the join
    equalities inside each OR branch; factoring them out lets the join
    extraction below find the equi keys instead of cross-joining."""
    if isinstance(e, ex.BinOp) and e.op == "and":
        return ex.BinOp("and", _factor_common(e.left),
                        _factor_common(e.right))
    if not (isinstance(e, ex.BinOp) and e.op == "or"):
        return e
    branches: List[ex.Expr] = []

    def disjuncts(x: ex.Expr):
        if isinstance(x, ex.BinOp) and x.op == "or":
            disjuncts(x.left)
            disjuncts(x.right)
        else:
            branches.append(x)

    disjuncts(e)
    branch_conjs = [_conjuncts(b) for b in branches]
    common_repr = set(repr(c) for c in branch_conjs[0])
    for bc in branch_conjs[1:]:
        common_repr &= {repr(c) for c in bc}
    if not common_repr:
        return e
    common = [c for c in branch_conjs[0] if repr(c) in common_repr]
    residuals = []
    for bc in branch_conjs:
        rest = [c for c in bc if repr(c) not in common_repr]
        residuals.append(_conjoin(rest))
    if any(r is None for r in residuals):
        return _conjoin(common)  # some branch is exactly the common part
    disj = residuals[0]
    for r in residuals[1:]:
        disj = ex.BinOp("or", disj, r)
    return _conjoin(common + [disj])


def push_filters(p: lp.Plan) -> lp.Plan:
    if isinstance(p, lp.Filter):
        child = push_filters(p.child)
        conjs = _conjuncts(_factor_common(p.condition))
        return _push_conjuncts(child, conjs)
    for attr in ("child", "left", "right"):
        if hasattr(p, attr):
            setattr(p, attr, push_filters(getattr(p, attr)))
    return p


def _push_conjuncts(p: lp.Plan, conjs: List[ex.Expr]) -> lp.Plan:
    if not conjs:
        return p
    if isinstance(p, lp.Project):
        # only push through pure-rename/deterministic projections
        mapping = {n: e for n, e in p.exprs}
        pushable, stay = [], []
        for c in conjs:
            if all(r in mapping and not isinstance(
                    mapping[r], (ex.AggExpr, ex.WindowExpr))
                   for r in _refs(c)) and not _has_subquery(c):
                pushable.append(_substitute(c, mapping))
            else:
                stay.append(c)
        if pushable:
            p.child = _push_conjuncts(p.child, pushable)
        return lp.Filter(p, _conjoin(stay)) if stay else p
    if isinstance(p, lp.Join):
        lcols = set(_output_names(p.left))
        rcols = set(_output_names(p.right))
        lpush, rpush, stay = [], [], []
        for c in conjs:
            refs = _refs(c)
            # turn cross/inner joins + cross-side equality into equi-joins —
            # this is what makes comma-join star queries feasible
            if p.kind in ("cross", "inner") and \
                    isinstance(c, ex.BinOp) and c.op == "=":
                lr = _refs(c.left)
                rr = _refs(c.right)
                if lr and rr:
                    if lr <= lcols and rr <= rcols:
                        p.keys.append((c.left, c.right))
                        p.kind = "inner"
                        continue
                    if lr <= rcols and rr <= lcols:
                        p.keys.append((c.right, c.left))
                        p.kind = "inner"
                        continue
            if refs <= lcols and p.kind in ("inner", "left", "semi", "anti",
                                            "nullaware_anti", "cross",
                                            "mark"):
                lpush.append(c)
            elif refs <= rcols and p.kind in ("inner", "cross"):
                rpush.append(c)
            else:
                stay.append(c)
        if lpush:
            p.left = _push_conjuncts(p.left, lpush)
        if rpush:
            p.right = _push_conjuncts(p.right, rpush)
        return lp.Filter(p, _conjoin(stay)) if stay else p
    if isinstance(p, lp.Filter):
        return _push_conjuncts(p.child, conjs + _conjuncts(p.condition))
    if isinstance(p, lp.Scan):
        existing = _conjuncts(p.predicate)
        p.predicate = _conjoin(existing + conjs)
        return p
    if isinstance(p, (lp.Sort, lp.Limit)):
        # pushing past Limit changes semantics; past Sort is fine
        if isinstance(p, lp.Sort):
            p.child = _push_conjuncts(p.child, conjs)
            return p
        return lp.Filter(p, _conjoin(conjs))
    if isinstance(p, lp.Distinct):
        p.child = _push_conjuncts(p.child, conjs)
        return p
    return lp.Filter(p, _conjoin(conjs))


def _has_subquery(e: ex.Expr) -> bool:
    return any(isinstance(x, ex.SubqueryExpr) for x in e.walk())


# -- projection pruning ------------------------------------------------------


def prune(p: lp.Plan, needed: Optional[Set[str]] = None) -> lp.Plan:
    """Drop unused columns; `needed` = columns the parent requires
    (None = keep all outputs)."""
    if isinstance(p, lp.Project):
        if needed is not None:
            kept = [(n, e) for n, e in p.exprs if n in needed]
            if not kept and p.exprs:
                # keep one column as the row-count carrier (count(*) case)
                kept = [p.exprs[0]]
            p.exprs = kept
        child_needed: Set[str] = set()
        for _n, e in p.exprs:
            child_needed |= _refs(e)
        p.child = prune(p.child, child_needed)
        return p
    if isinstance(p, lp.Scan):
        if needed is not None:
            cols = set(needed)
            if p.predicate is not None:
                cols |= _refs(p.predicate)
            p.columns = sorted(cols)
        return p
    if isinstance(p, lp.Filter):
        child_needed = None if needed is None else \
            set(needed) | _refs(p.condition)
        p.child = prune(p.child, child_needed)
        return p
    if isinstance(p, lp.Join):
        if needed is None:
            p.left = prune(p.left, None)
            p.right = prune(p.right, None)
            return p
        child_needed = set(needed)
        for le, re_ in p.keys:
            child_needed |= _refs(le) | _refs(re_)
        if p.extra is not None:
            child_needed |= _refs(p.extra)
        lcols = set(_output_names(p.left))
        rcols = set(_output_names(p.right))
        p.left = prune(p.left, child_needed & lcols)
        p.right = prune(p.right, child_needed & rcols)
        return p
    if isinstance(p, lp.Aggregate):
        child_needed = set()
        for _n, e in p.group_by:
            child_needed |= _refs(e)
        for _n, e in p.aggs:
            child_needed |= _refs(e)
        p.child = prune(p.child, child_needed)
        return p
    if isinstance(p, lp.Window):
        child_needed = None if needed is None else set(needed)
        if child_needed is not None:
            for _n, e in p.exprs:
                child_needed |= _refs(e)
            child_needed &= set(_output_names(p.child))
        p.child = prune(p.child, child_needed)
        return p
    if isinstance(p, lp.Sort):
        child_needed = None if needed is None else set(needed)
        if child_needed is not None:
            for entry in p.keys:
                child_needed |= _refs(entry[0])
        p.child = prune(p.child, child_needed)
        return p
    if isinstance(p, (lp.Limit, lp.Distinct)):
        p.child = prune(p.child, needed if not isinstance(p, lp.Distinct)
                        else None)
        return p
    if isinstance(p, lp.SetOp):
        # set ops compare whole rows: keep all columns
        p.left = prune(p.left, None)
        p.right = prune(p.right, None)
        return p
    if isinstance(p, lp.SubqueryAlias):
        p.child = prune(p.child, needed)
        return p
    return p


# -- join reordering ---------------------------------------------------------


def _estimate_rows(p: lp.Plan, catalog) -> float:
    """Crude cardinality estimate for join ordering (no stats yet):
    base table rows, decimated by pushed predicates."""
    if isinstance(p, lp.Scan):
        n = float(catalog.get(p.table).num_rows) if catalog is not None \
            and p.table in catalog else 1e6
        return max(n / 20.0, 1.0) if p.predicate is not None else n
    if isinstance(p, lp.Project):
        return _estimate_rows(p.child, catalog)
    if isinstance(p, lp.Filter):
        return max(_estimate_rows(p.child, catalog) / 20.0, 1.0)
    if isinstance(p, (lp.Sort, lp.Distinct, lp.Window)):
        return _estimate_rows(p.child, catalog)
    if isinstance(p, lp.Limit):
        return min(float(p.n), _estimate_rows(p.child, catalog))
    if isinstance(p, lp.Aggregate):
        return max(_estimate_rows(p.child, catalog) / 100.0, 1.0)
    if isinstance(p, lp.Join):
        l = _estimate_rows(p.left, catalog)
        r = _estimate_rows(p.right, catalog)
        if p.kind in ("semi", "anti", "nullaware_anti", "mark"):
            return l
        return max(l, r)
    if isinstance(p, lp.InlineTable):
        return float(p.table.num_rows)
    if isinstance(p, lp.SetOp):
        return _estimate_rows(p.left, catalog) + \
            _estimate_rows(p.right, catalog)
    return 1e6


def reorder_joins(p: lp.Plan, catalog) -> lp.Plan:
    """Flatten chains of inner/cross joins and rebuild greedily: start from
    the largest relation (the fact table), then repeatedly join the smallest
    key-connected relation — TPC-DS star/snowflake shapes resolve to
    fact-with-filtered-dims pipelines with no accidental cross joins."""
    for attr in ("child", "left", "right"):
        if hasattr(p, attr):
            setattr(p, attr, reorder_joins(getattr(p, attr), catalog))
    if not (isinstance(p, lp.Join) and p.kind in ("inner", "cross")):
        return p

    leaves: List[lp.Plan] = []
    keys: List[Tuple[ex.Expr, ex.Expr]] = []
    extras: List[ex.Expr] = []

    def flatten(n: lp.Plan):
        if isinstance(n, lp.Join) and n.kind in ("inner", "cross"):
            flatten(n.left)
            flatten(n.right)
            keys.extend(n.keys)
            if n.extra is not None:
                extras.append(n.extra)
        elif isinstance(n, lp.Filter) and isinstance(n.child, lp.Join) \
                and n.child.kind in ("inner", "cross"):
            # filters commute with inner joins: lift a mid-tree residual
            # (e.g. q72's inv_quantity_on_hand < cs_quantity, pushed onto
            # the syntactic cs x inventory join) so it cannot glue a
            # catastrophic join pair together; it is re-applied as soon
            # as its refs are joined below.
            extras.extend(_conjuncts(n.condition))
            flatten(n.child)
        else:
            leaves.append(n)

    flatten(p)
    if len(leaves) <= 2:
        return p

    cols: List[Set[str]] = [set(_output_names(l)) for l in leaves]
    sizes = [_estimate_rows(l, catalog) for l in leaves]

    def leaf_of(refs: Set[str]) -> Optional[int]:
        for i, cs in enumerate(cols):
            if refs <= cs:
                return i
        return None

    # key edges between leaves
    edges = []  # (li, ri, left_expr, right_expr) with li side expr first
    residual_keys = []
    for le, re_ in keys:
        li = leaf_of(_refs(le))
        ri = leaf_of(_refs(re_))
        if li is None or ri is None or li == ri:
            residual_keys.append((le, re_))
            continue
        edges.append((li, ri, le, re_))

    start = max(range(len(leaves)), key=lambda i: sizes[i])
    joined = {start}
    current: lp.Plan = leaves[start]
    remaining = set(range(len(leaves))) - joined
    used = [False] * len(edges)

    # residual-key equalities + lifted filters, applied as soon as every
    # referenced column is available (early filtering keeps expanding
    # joins like q72's inventory chain from materializing unfiltered)
    pending = [ex.BinOp("=", le, re_) for le, re_ in residual_keys] + extras
    avail = set(cols[start])

    def apply_ready(cur: lp.Plan) -> lp.Plan:
        nonlocal pending
        ready = [c for c in pending if _refs(c) <= avail]
        if ready:
            pending = [c for c in pending if not (_refs(c) <= avail)]
            cur = lp.Filter(cur, _conjoin(ready))
        return cur

    current = apply_ready(current)
    while remaining:
        # candidates connected to the joined set
        cand: Dict[int, List[int]] = {}
        for k, (li, ri, _le, _re) in enumerate(edges):
            if used[k]:
                continue
            if li in joined and ri in remaining:
                cand.setdefault(ri, []).append(k)
            elif ri in joined and li in remaining:
                cand.setdefault(li, []).append(k)
        if cand:
            nxt = min(cand, key=lambda i: sizes[i])
            pair_keys = []
            for k in cand[nxt]:
                li, ri, le, re_ = edges[k]
                used[k] = True
                if li in joined:
                    pair_keys.append((le, re_))
                else:
                    pair_keys.append((re_, le))
            current = lp.Join(current, leaves[nxt], "inner", pair_keys)
        else:
            nxt = min(remaining, key=lambda i: sizes[i])
            current = lp.Join(current, leaves[nxt], "cross", [])
        joined.add(nxt)
        remaining.discard(nxt)
        avail |= set(cols[nxt])
        current = apply_ready(current)

    cond = _conjoin(pending)
    return lp.Filter(current, cond) if cond is not None else current


def _plan_exprs(p: lp.Plan) -> List[ex.Expr]:
    if isinstance(p, lp.Scan):
        return [p.predicate] if p.predicate is not None else []
    if isinstance(p, lp.Filter):
        return [p.condition]
    if isinstance(p, lp.Project):
        return [e for _n, e in p.exprs]
    if isinstance(p, lp.Join):
        out = [e for pair in p.keys for e in pair]
        if p.extra is not None:
            out.append(p.extra)
        return out
    if isinstance(p, lp.Aggregate):
        return [e for _n, e in p.group_by] + [e for _n, e in p.aggs]
    if isinstance(p, lp.Window):
        return [e for _n, e in p.exprs]
    if isinstance(p, lp.Sort):
        return [entry[0] for entry in p.keys]
    return []


def _pivot_sum_case(e: ex.Expr):
    """Match ``sum(CASE WHEN scrut = lit THEN value END)`` (the TPC-DS
    day-of-week / channel pivot idiom); -> (scrut, lit, value) or None."""
    if not isinstance(e, ex.AggExpr) or e.func != "sum" or e.distinct:
        return None
    c = e.arg
    if not isinstance(c, ex.Case) or len(c.whens) != 1:
        return None
    if c.default is not None and not (
            isinstance(c.default, ex.Literal) and c.default.value is None):
        return None
    cond, val = c.whens[0]
    if not (isinstance(cond, ex.BinOp) and cond.op == "="):
        return None
    if isinstance(cond.right, ex.Literal) and \
            not isinstance(cond.left, ex.Literal):
        return cond.left, cond.right, val
    if isinstance(cond.left, ex.Literal) and \
            not isinstance(cond.right, ex.Literal):
        return cond.right, cond.left, val
    return None


def _try_pivot(p: lp.Aggregate) -> Optional[lp.Plan]:
    if p.grouping_sets is not None or not p.aggs:
        return None
    pivots: Dict[int, tuple] = {}
    plains: Dict[int, ex.AggExpr] = {}
    for i, (_name, e) in enumerate(p.aggs):
        pat = _pivot_sum_case(e)
        if pat is not None:
            pivots[i] = pat
        elif isinstance(e, ex.AggExpr) and not e.distinct and \
                e.func in ("sum", "count", "min", "max"):
            plains[i] = e
        else:
            return None
    if len(pivots) < 3:
        return None
    scrut = None
    for s, _lit, _v in pivots.values():
        if scrut is None:
            scrut = s
        elif s != scrut:  # frozen expr dataclasses: structural equality
            return None
    vals: List[ex.Expr] = []
    for _s, _lit, v in pivots.values():
        if all(v != u for u in vals):
            vals.append(v)

    l1_aggs: List[tuple] = [
        (f"__pv_v{j}", ex.AggExpr("sum", v)) for j, v in enumerate(vals)]
    for i, e in plains.items():
        l1_aggs.append((f"__pv_p{i}", ex.AggExpr(e.func, e.arg)))
    l1 = lp.Aggregate(p.child, list(p.group_by) + [("__pv_s", scrut)],
                      l1_aggs, None)

    l2_groups = [(n, ex.ColumnRef(n)) for n, _e in p.group_by]
    l2_aggs: List[tuple] = []
    for i, (name, e) in enumerate(p.aggs):
        if i in pivots:
            _s, lit, v = pivots[i]
            j = next(j for j, u in enumerate(vals) if u == v)
            cond = ex.BinOp("=", ex.ColumnRef("__pv_s"), lit)
            l2_aggs.append((name, ex.AggExpr(
                "sum", ex.Case(((cond, ex.ColumnRef(f"__pv_v{j}")),),
                               ex.Literal(None, None)))))
        else:
            e = plains[i]
            # counts recombine by SUM; min/max by min/max.  Partial
            # counts are never NULL, but a KEYLESS rewrite over empty
            # input has zero partial rows and sum-over-nothing is NULL
            # where count must be 0 — coalesce restores the contract
            # (grouped aggregates can't hit this: empty groups don't
            # exist on either side).
            func = "sum" if e.func in ("sum", "count") else e.func
            recombined: ex.Expr = ex.AggExpr(
                func, ex.ColumnRef(f"__pv_p{i}"))
            if e.func == "count" and not p.group_by:
                recombined = ex.Func(
                    "coalesce", (recombined, ex.Literal(0, None)))
            l2_aggs.append((name, recombined))
    return lp.Aggregate(l1, l2_groups, l2_aggs, None)


def _refs_counter(p: lp.Plan, out) -> None:
    for e in _plan_exprs(p):
        for n in e.walk():
            if isinstance(n, ex.ColumnRef):
                out[n.name] += 1
    for c in p.children():
        _refs_counter(c, out)


def null_filter_to_anti(p: lp.Plan) -> lp.Plan:
    """``Filter(right_key IS NULL, LEFT JOIN)`` -> ANTI JOIN.

    The q78-family refresh-exclusion idiom (``left join store_returns
    on sr_ticket_number = ss_ticket_number ... where sr_ticket_number
    is null``) materializes the full joined width with duplicate-key
    run expansion, then throws the matches away; an anti join is a
    mask over the probe side.  Sound because equality keys never match
    NULLs: a surviving row's right columns are all NULL, so the
    conversion wraps the anti join in a Project restoring each right
    KEY column as a NULL literal (prune drops the unreferenced ones).
    A reference to any NON-key right column — from the remaining
    conjuncts OR any ancestor node (the select list may legally emit
    an all-NULL right column) — blocks the rewrite: that name would no
    longer resolve.  Ancestor references are detected by ref-count
    difference against the whole tree (planner invariant: column names
    are globally unique)."""
    import collections
    while True:
        total = collections.Counter()
        _refs_counter(p, total)
        p, changed = _null_filter_to_anti(p, total)
        if not changed:
            return p


def _null_filter_to_anti(p: lp.Plan, total):
    """One rewrite per call (the ref-count snapshot goes stale once the
    tree changes); returns (plan, changed)."""
    for f in dataclasses.fields(p):
        v = getattr(p, f.name)
        if isinstance(v, lp.Plan):
            nv, changed = _null_filter_to_anti(v, total)
            if changed:
                setattr(p, f.name, nv)
                return p, True
    if not (isinstance(p, lp.Filter) and isinstance(p.child, lp.Join)
            and p.child.kind == "left" and p.child.keys
            and p.child.extra is None):
        return p, False
    j = p.child
    try:
        right_names = set(_output_names(j.right))
        left_names = _output_names(j.left)
    except RuntimeError:
        return p, False
    right_keys = {e.name for _l, e in j.keys
                  if isinstance(e, ex.ColumnRef)}
    if len(right_keys) != len(j.keys):
        return p, False  # a computed right key: cannot restore as NULL
    rest = []
    fired = False
    for c in _conjuncts(p.condition):
        if not fired and isinstance(c, ex.UnaryOp) and \
                c.op == "isnull" and \
                isinstance(c.operand, ex.ColumnRef) and \
                c.operand.name in right_keys:
            fired = True
            continue
        rest.append(c)
    if not fired or any(_refs(c) & (right_names - right_keys)
                        for c in rest):
        return p, False
    # ancestor-reference guard: every reference to a non-key right
    # column must live inside THIS subtree (conjuncts already checked
    # reference none, so any count surplus is an ancestor's)
    import collections
    inside = collections.Counter()
    _refs_counter(p, inside)
    for name in right_names - right_keys:
        if total[name] > inside[name]:
            return p, False
    j.kind = "anti"
    out: lp.Plan = lp.Project(
        j, [(n, ex.ColumnRef(n)) for n in left_names] +
           [(n, ex.Literal(None, None)) for n in sorted(right_keys)])
    remaining = _conjoin(rest)
    if remaining is not None:
        out = lp.Filter(out, remaining)
    return out, True


def pivot_case_aggregates(p: lp.Plan) -> lp.Plan:
    """Rewrite N-way masked-sum pivots into ONE composite-key
    aggregation plus a tiny re-aggregation.

    q2/q59-class aggregates compute 7 ``sum(case when d_day_name='X'
    then price end)`` columns: each is a full-capacity masked segment
    sum over the fact spine, and exact decimals make every sum an
    int64-emulated scatter (54 scatter ops, ~3.7 s device time on q2 at
    SF1).  Grouping by (keys..., scrutinee) instead computes ONE sum
    over the spine; the second-level re-aggregation runs over the
    compacted (keys x scrutinee-domain) partial table (~10k rows).
    Decimal sums recombine exactly (sum of int64-scaled sums); NULL
    semantics are preserved: a (g, s) partial is NULL iff it saw no
    valid value, and absent combinations contribute no rows, so the
    outer sum is NULL exactly when the direct masked sum would be.
    Float-mode sums change association order; the differential
    harness's epsilon (1e-5 relative) covers that drift."""
    for f in dataclasses.fields(p):
        v = getattr(p, f.name)
        if isinstance(v, lp.Plan):
            setattr(p, f.name, pivot_case_aggregates(v))
    if isinstance(p, lp.Aggregate):
        out = _try_pivot(p)
        if out is not None:
            return out
    return p


def _unwrap_renames(p: lp.Plan):
    """Peel pure-rename Projects; return (inner plan, outer->inner name
    map composed across the chain, or None if no Project was peeled —
    the caller treats None as identity)."""
    mapping: Optional[Dict[str, str]] = None
    while isinstance(p, lp.Project) and \
            all(isinstance(e, ex.ColumnRef) for _n, e in p.exprs):
        layer = {n: e.name for n, e in p.exprs}
        if mapping is None:
            mapping = layer
        else:
            mapping = {n: layer[v] for n, v in mapping.items()
                       if v in layer}
        p = p.child
    return p, mapping


@dataclasses.dataclass
class _ScalarAggLeaf:
    table: str
    alias: str
    columns: Optional[List[str]]
    conjs: List[ex.Expr]            # scan-native names
    # visible output name -> (func, distinct, native arg column)
    outputs: List[Tuple[str, str, bool, str]]


def _match_scalar_agg_leaf(leaf: lp.Plan) -> Optional[_ScalarAggLeaf]:
    agg, out_map = _unwrap_renames(leaf)
    if not (isinstance(agg, lp.Aggregate) and not agg.group_by
            and agg.grouping_sets is None and agg.aggs):
        return None
    src, in_map = _unwrap_renames(agg.child)
    if not isinstance(src, lp.Scan):
        return None
    agg_names = {n for n, _e in agg.aggs}
    if out_map is None:
        # declaration order, NOT set order — outputs feed the content
        # hash that names the fused columns, which must be a pure
        # function of the plan (set iteration varies per process)
        out_map = {n: n for n, _e in agg.aggs}
    if in_map is None:
        in_map = {}
        for _n, e in agg.aggs:
            if isinstance(e, ex.AggExpr) and \
                    isinstance(e.arg, ex.ColumnRef):
                in_map[e.arg.name] = e.arg.name
    if set(out_map.values()) != agg_names or \
            len(out_map) != len(agg_names):
        return None  # rename chain must be a bijection onto the aggs
    by_name = dict(agg.aggs)
    outputs: List[Tuple[str, str, bool, str]] = []
    for vis, internal in out_map.items():
        e = by_name[internal]
        if not (isinstance(e, ex.AggExpr) and
                e.func in ("sum", "count", "min", "max", "avg")):
            return None
        if e.distinct and e.func in ("min", "max"):
            return None
        if isinstance(e.arg, ex.Star):
            if e.func != "count" or e.distinct:
                return None
            native = "*"
        elif isinstance(e.arg, ex.ColumnRef):
            native = in_map.get(e.arg.name)
            if native is None:
                return None
        else:
            return None
        outputs.append((vis, e.func, e.distinct, native))
    return _ScalarAggLeaf(src.table, src.alias, src.columns,
                          _conjuncts(src.predicate), outputs)


def _interval_of(conjs: List[ex.Expr]):
    """Parse conjuncts as one closed interval on one column; returns
    (column name, lo, hi) or None.  Only >=/<=/= against numeric
    literals — the disjointness proof needs exact endpoint arithmetic."""
    col, lo, hi = None, None, None
    for c in conjs:
        if not (isinstance(c, ex.BinOp) and
                isinstance(c.left, ex.ColumnRef) and
                isinstance(c.right, ex.Literal) and
                isinstance(c.right.value, (int, float)) and
                not isinstance(c.right.value, bool) and
                c.op in (">=", "<=", "=")):
            return None
        if col is None:
            col = c.left.name
        elif col != c.left.name:
            return None
        v = c.right.value
        if c.op in (">=", "="):
            lo = v if lo is None else max(lo, v)
        if c.op in ("<=", "="):
            hi = v if hi is None else min(hi, v)
    if col is None or lo is None or hi is None or lo > hi:
        return None
    return col, lo, hi


def fuse_sibling_scalar_aggregates(
        p: lp.Plan, _used: Optional[Set[str]] = None) -> lp.Plan:
    """Fuse N cross-joined keyless aggregates over the SAME table whose
    filters differ only by pairwise-disjoint intervals on one column
    into ONE grouped aggregation.

    The q28 idiom: six scalar-subquery scans of store_sales, each
    keeping a disjoint ``ss_quantity`` bucket plus a shared OR filter,
    each computing avg/count/count-distinct over the full fact spine —
    six passes (and six presence-bitmap distinct reductions) where one
    suffices.  Rewrite: one scan filtered to the union of buckets, a
    CASE bucket id, ONE Aggregate grouped by bucket (count-distinct
    rides the grouped presence-bitmap path), then a keyless extraction
    aggregate pulling each branch's scalars out of its bucket row.

    Soundness: the intervals are proven pairwise disjoint on literal
    endpoints, so every row lands in at most one bucket — each bucket
    group sees exactly the rows its original branch scanned.  A branch
    with no surviving rows has no bucket row: the extraction
    ``max(case when bucket=i ...)`` over zero matches is NULL, matching
    the scalar aggregate's NULL (counts coalesce to 0, matching
    count-over-nothing).  Mirrors the reference's q28 single-pass GPU
    plan shape (rapids combines the branches into one kernel sweep)."""
    if _used is None:
        _used = set()

    def is_cross(n: lp.Plan) -> bool:
        return isinstance(n, lp.Join) and n.kind == "cross" and \
            not n.keys and n.extra is None and n.mark is None

    if not is_cross(p):
        for f in dataclasses.fields(p):
            v = getattr(p, f.name)
            if isinstance(v, lp.Plan):
                setattr(p, f.name,
                        fuse_sibling_scalar_aggregates(v, _used))
        return p

    # flatten the WHOLE cross-join spine before matching — recursing
    # join-child-first would fuse the innermost pair and hide the rest
    # of the siblings from the 6-way q28 match
    leaves: List[lp.Plan] = []

    def flatten(n: lp.Plan):
        if is_cross(n):
            flatten(n.left)
            flatten(n.right)
        else:
            leaves.append(n)

    flatten(p)
    leaves = [fuse_sibling_scalar_aggregates(l, _used) for l in leaves]

    def rebuild(parts: List[lp.Plan]) -> lp.Plan:
        out = parts[0]
        for nxt in parts[1:]:
            out = lp.Join(out, nxt, "cross", [])
        return out
    matched = [(i, m) for i, leaf in enumerate(leaves)
               if (m := _match_scalar_agg_leaf(leaf)) is not None]
    # fuse EVERY qualifying table group (groups are over disjoint leaf
    # sets, so the rewrites compose)
    by_table: Dict[str, List[Tuple[int, _ScalarAggLeaf]]] = {}
    for i, m in matched:
        by_table.setdefault(m.table, []).append((i, m))
    fused_nodes: List[lp.Plan] = []
    fused_idx: Set[int] = set()
    for group in by_table.values():
        if len(group) < 2:
            continue
        # shared conjuncts: structurally present in EVERY branch
        shared = [c for c in group[0][1].conjs
                  if all(any(c == d for d in m.conjs)
                         for _i, m in group[1:])]
        ivals = []
        ok = True
        for _i, m in group:
            spec = [c for c in m.conjs if all(c != s for s in shared)]
            iv = _interval_of(spec)
            if iv is None:
                ok = False
                break
            ivals.append((iv, spec))
        if not ok or len({iv[0] for iv, _s in ivals}) != 1:
            continue
        spans = sorted((lo, hi) for (_c, lo, hi), _s in ivals)
        if any(a[1] >= b[0] for a, b in zip(spans, spans[1:])):
            continue  # overlapping buckets: rows could belong to two
        fused_nodes.append(_build_fused(group, shared, ivals, _used))
        fused_idx |= {i for i, _m in group}
    if not fused_nodes:
        return rebuild(leaves)
    rest = [leaf for i, leaf in enumerate(leaves) if i not in fused_idx]
    return rebuild(fused_nodes + rest)


def _build_fused(group, shared, ivals, used: Set[str]) -> lp.Plan:
    """Materialize one fused subtree for a qualifying sibling group."""
    # generated names must be a pure function of the plan: persisted
    # compile records and the XLA persistent cache key on plan
    # fingerprints, so a process-varying counter here would make every
    # replan recompile.  Content-hash the fused group; uniquify
    # deterministically (traversal order is a function of the plan too).
    import hashlib
    m0 = group[0][1]
    desc = repr((m0.table,
                 [[repr(c) for c in m.conjs] for _i, m in group],
                 [m.outputs for _i, m in group]))
    tag = "__ssa" + hashlib.md5(desc.encode()).hexdigest()[:8]
    while tag in used:
        tag += "x"
    used.add(tag)
    bucket = f"{tag}_b"
    cols = None if any(m.columns is None for _i, m in group) else \
        sorted({c for _i, m in group for c in m.columns})
    branch_conds = [_conjoin(spec) for _iv, spec in ivals]
    union = branch_conds[0]
    for c in branch_conds[1:]:
        union = ex.BinOp("or", union, c)
    scan = lp.Scan(m0.table, m0.alias, cols,
                   _conjoin(list(shared) + [union]))
    # one level-1 agg per distinct (func, distinct, native arg)
    l1_key: Dict[Tuple[str, bool, str], str] = {}
    l1_aggs: List[Tuple[str, ex.Expr]] = []
    need_cols = set()
    for _i, m in group:
        for _vis, func, dist, native in m.outputs:
            k = (func, dist, native)
            if k not in l1_key:
                l1_key[k] = f"{tag}_a{len(l1_key)}"
                arg = ex.Star() if native == "*" else \
                    ex.ColumnRef(native)
                l1_aggs.append(
                    (l1_key[k], ex.AggExpr(func, arg, dist)))
            if native != "*":
                need_cols.add(native)
    proj = lp.Project(scan, [(bucket, ex.Case(
        tuple((cond, ex.Literal(j, None))
              for j, cond in enumerate(branch_conds)),
        ex.Literal(None, None)))] +
        [(c, ex.ColumnRef(c)) for c in sorted(need_cols)])
    l1 = lp.Aggregate(proj, [(bucket, ex.ColumnRef(bucket))],
                      l1_aggs, None)
    l2_aggs: List[Tuple[str, ex.Expr]] = []
    for j, (_i, m) in enumerate(group):
        for vis, func, dist, native in m.outputs:
            pick = ex.AggExpr("max", ex.Case(
                ((ex.BinOp("=", ex.ColumnRef(bucket),
                           ex.Literal(j, None)),
                  ex.ColumnRef(l1_key[(func, dist, native)])),),
                ex.Literal(None, None)))
            if func == "count":
                pick = ex.Func("coalesce", (pick, ex.Literal(0, None)))
            l2_aggs.append((vis, pick))
    return lp.Aggregate(l1, [], l2_aggs, None)


def _optimize_embedded(p: lp.Plan, catalog) -> None:
    """Optimize plans embedded in SubqueryExpr leaves (uncorrelated scalar /
    IN subqueries survive planning as expressions — without this their join
    trees stay cross joins, q24's HAVING subquery)."""
    for e in _plan_exprs(p):
        for x in e.walk():
            if isinstance(x, ex.SubqueryExpr) and x.plan is not None:
                object.__setattr__(x, "plan", optimize(x.plan, catalog))
    for c in p.children():
        _optimize_embedded(c, catalog)


def optimize(p: lp.Plan, catalog=None) -> lp.Plan:
    p = push_filters(p)
    p = reorder_joins(p, catalog)
    p = pivot_case_aggregates(p)
    p = fuse_sibling_scalar_aggregates(p)
    p = null_filter_to_anti(p)
    p = prune(p, None)
    _optimize_embedded(p, catalog)
    return p
