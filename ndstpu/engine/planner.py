"""Planner: SQL AST -> logical plan (name binding, aggregate extraction,
subquery decorrelation).

Binding model: every base-table column is exposed under the globally unique
internal name ``"{alias}.{col}"`` (a Project over each Scan does the rename),
so self-joins like ``date_dim d1, date_dim d2`` need no special casing.
Derived tables and CTEs expose ``"{alias}.{output}"``.

Decorrelation rewrites (the reference corpus' patterns):
  * ``x IN (subquery)``            -> semi join   (NOT IN -> anti join)
  * ``EXISTS (corr. subquery)``    -> semi join on extracted equality keys
  * ``x <op> (corr. scalar agg)``  -> group subquery by its correlation keys,
                                      inner join, filter (TPC-DS q1/q6 shape)
  * uncorrelated scalar subqueries stay as SubqueryExpr leaves, resolved by
    the executor pre-pass (physical plans execute them once and inline).
"""

from __future__ import annotations

import dataclasses
import difflib
from typing import Dict, List, Optional, Sequence, Tuple

from ndstpu.engine import columnar, expr as ex, plan as lp
from ndstpu.engine.columnar import DATE, DType, FLOAT64, INT32, INT64, STRING
from ndstpu.engine.sql import ast
from ndstpu.schema import decimal as decimal_t

import numpy as np


class PlanError(Exception):
    pass


def _suggest(col: str, candidates: List[str]) -> str:
    """Near-miss suffix for unresolved-column errors — a typo'd
    reference names its likely targets instead of a bare failure."""
    close = difflib.get_close_matches(col, candidates, n=3, cutoff=0.6)
    return f" (did you mean: {', '.join(close)}?)" if close else ""


def _parse_type(name: str) -> DType:
    base = name.split("(")[0]
    if base in ("int", "integer", "smallint", "tinyint"):
        return INT32
    if base in ("bigint", "long"):
        return INT64
    if base in ("double", "float", "real"):
        return FLOAT64
    if base in ("decimal", "numeric"):
        if "(" in name:
            args = name[name.index("(") + 1:-1].split(",")
            p = int(args[0])
            s = int(args[1]) if len(args) > 1 else 0
            return decimal_t(p, s)
        return decimal_t(10, 0)
    if base == "date":
        return DATE
    if base in ("string", "char", "varchar", "text"):
        return STRING
    raise PlanError(f"unsupported cast type {name}")


def _date_to_days(s: str) -> int:
    return columnar.parse_date_days(s)


@dataclasses.dataclass
class Source:
    """One FROM source: its visible alias and output columns."""
    alias: str
    columns: List[str]  # base column names (unqualified)

    def internal(self, col: str) -> str:
        return f"{self.alias}.{col}"


class Scope:
    def __init__(self, parent: Optional["Scope"] = None):
        self.sources: List[Source] = []
        self.parent = parent
        self.outer_refs: List[str] = []  # internal names resolved via parent

    def add(self, src: Source) -> None:
        for s in self.sources:
            if s.alias == src.alias:
                raise PlanError(f"duplicate alias {src.alias}")
        self.sources.append(src)

    def resolve(self, table: Optional[str], col: str) -> Tuple[str, bool]:
        """-> (internal name, is_outer).  A resolution that climbs to the
        parent is recorded as an outer ref on EVERY scope it climbs through,
        so any enclosing query level can see that its subquery is
        correlated."""
        if table is not None:
            for s in self.sources:
                if s.alias == table:
                    if col not in s.columns:
                        raise PlanError(f"no column {col} in {table}"
                                        + _suggest(col, s.columns))
                    return s.internal(col), False
        else:
            hits = [s for s in self.sources if col in s.columns]
            if len(hits) > 1:
                raise PlanError(f"ambiguous column {col}")
            if hits:
                return hits[0].internal(col), False
        if self.parent is not None:
            try:
                name, _ = self.parent.resolve(table, col)
            except PlanError as e:
                if getattr(e, "unresolved", False):
                    # re-raise with THIS scope's (wider) candidate set:
                    # the innermost frame unwinds last, so the surfaced
                    # message suggests over everything the reference
                    # could actually see
                    raise self._unresolved(table, col) from None
                raise
            self.outer_refs.append(name)
            return name, True
        raise self._unresolved(table, col)

    def _unresolved(self, table: Optional[str], col: str) -> PlanError:
        where = f"{table}.{col}" if table else col
        e = PlanError(f"cannot resolve column {where}"
                      + _suggest(col, self._candidates()))
        e.unresolved = True
        return e

    def _candidates(self) -> List[str]:
        """Every column name visible from this scope (chain upward)."""
        out: List[str] = []
        sc: Optional["Scope"] = self
        while sc is not None:
            for s in sc.sources:
                for c in s.columns:
                    if c not in out:
                        out.append(c)
            sc = sc.parent
        return out


class Planner:
    def __init__(self, catalog, views: Optional[Dict] = None):
        """catalog: ndstpu.io.loader.Catalog (or any object with .tables
        dict of engine Tables); views: name -> logical Plan."""
        self.catalog = catalog
        self.views: Dict[str, lp.Plan] = views if views is not None else {}
        self._gen = 0

    def fresh(self, prefix: str) -> str:
        self._gen += 1
        return f"#{prefix}{self._gen}"

    # -- entry ---------------------------------------------------------------

    def plan_query(self, q: ast.Query,
                   scope: Optional[Scope] = None) -> Tuple[lp.Plan, List[str]]:
        """-> (plan, output column internal names == display names)."""
        cte_saved = {}
        for name, col_aliases, sub in q.ctes:
            plan, cols = self.plan_query(sub)
            if col_aliases:
                plan = lp.Project(plan, [
                    (a, ex.ColumnRef(c)) for a, c in zip(col_aliases, cols)])
                cols = list(col_aliases)
            cte_saved[name] = self.views.get(name)
            self.views[name] = lp.Project(plan, [
                (c, ex.ColumnRef(c0)) for c, c0 in
                zip(self._display_names(cols), cols)])
        try:
            if isinstance(q.body, ast.Select):
                plan, cols = self._plan_select(q.body, scope, q.order_by)
            else:
                plan, cols = self._plan_body(q.body, scope)
                if q.order_by:
                    plan = self._apply_order(plan, cols, q.order_by, scope)
            if q.limit is not None:
                plan = lp.Limit(plan, q.limit)
            return plan, cols
        finally:
            for name, _ca, _s in q.ctes:
                if cte_saved.get(name) is None:
                    self.views.pop(name, None)
                else:
                    self.views[name] = cte_saved[name]

    @staticmethod
    def _display_names(cols: List[str]) -> List[str]:
        out = []
        for c in cols:
            base = c.split(".")[-1] if "." in c and not c.startswith("#") \
                else c
            out.append(base)
        return out

    def _plan_body(self, body: ast.Node,
                   scope: Optional[Scope]) -> Tuple[lp.Plan, List[str]]:
        if isinstance(body, ast.SetExpr):
            lplan, lcols = self._plan_body(body.left, scope)
            rplan, rcols = self._plan_body(body.right, scope)
            if len(lcols) != len(rcols):
                raise PlanError("set operation column count mismatch")
            return lp.SetOp(body.kind, lplan, rplan, body.all), lcols
        if isinstance(body, ast.Select):
            return self._plan_select(body, scope)
        if isinstance(body, ast.SubqueryRef):  # parenthesized query
            return self.plan_query(body.query, scope)
        raise PlanError(f"unsupported query body {type(body).__name__}")

    # -- FROM ----------------------------------------------------------------

    def _plan_from(self, node: Optional[ast.Node],
                   scope: Scope) -> lp.Plan:
        if node is None:
            import numpy as _np
            from ndstpu.engine.columnar import Column, Table
            one = Table({"#dummy": Column(_np.zeros(1, _np.int32), INT32)})
            return lp.InlineTable(one, "dual")
        if isinstance(node, ast.TableRef):
            alias = node.alias or node.name
            if node.name in self.views:
                # each reference gets its own copy: the optimizer mutates
                # plans in place (predicates/column pruning)
                sub = lp.copy_plan(self.views[node.name])
                cols = self._plan_output_names(sub)
                src = Source(alias, cols)
                scope.add(src)
                return lp.Project(sub, [
                    (src.internal(c), ex.ColumnRef(c)) for c in cols])
            if node.name not in self.catalog.tables:
                raise PlanError(f"unknown table {node.name}")
            base_cols = self.catalog.tables[node.name].column_names
            src = Source(alias, list(base_cols))
            scope.add(src)
            scan = lp.Scan(node.name, alias)
            return lp.Project(scan, [
                (src.internal(c), ex.ColumnRef(c)) for c in base_cols])
        if isinstance(node, ast.SubqueryRef):
            sub, cols = self.plan_query(node.query, scope)
            names = node.column_aliases or self._display_names(cols)
            src = Source(node.alias, names)
            scope.add(src)
            return lp.Project(sub, [
                (src.internal(n), ex.ColumnRef(c))
                for n, c in zip(names, cols)])
        if isinstance(node, ast.JoinRef):
            left = self._plan_from(node.left, scope)
            right = self._plan_from(node.right, scope)
            if node.kind == "cross" and node.condition is None:
                return lp.Join(left, right, "cross", [])
            cond = self._bind(node.condition, scope) \
                if node.condition is not None else None
            keys, extra = self._split_equi_keys(cond, left, right)
            if not keys and node.kind in ("left", "right", "full"):
                raise PlanError(f"non-equi {node.kind} join unsupported")
            return lp.Join(left, right, node.kind, keys, extra)
        raise PlanError(f"unsupported FROM node {type(node).__name__}")

    def _plan_output_names(self, p: lp.Plan) -> List[str]:
        if isinstance(p, lp.Project):
            return [n for n, _ in p.exprs]
        if isinstance(p, lp.Aggregate):
            return [n for n, _ in p.group_by] + [n for n, _ in p.aggs]
        if isinstance(p, (lp.Filter, lp.Sort, lp.Limit, lp.Distinct)):
            return self._plan_output_names(p.child)
        if isinstance(p, lp.SetOp):
            return self._plan_output_names(p.left)
        if isinstance(p, lp.InlineTable):
            return list(p.table.column_names)
        if isinstance(p, lp.Window):
            return self._plan_output_names(p.child) + [n for n, _ in p.exprs]
        if isinstance(p, lp.Join):
            if p.kind == "mark":
                return self._plan_output_names(p.left) + [p.mark]
            if p.kind in ("semi", "anti", "nullaware_anti"):
                return self._plan_output_names(p.left)
            return (self._plan_output_names(p.left) +
                    self._plan_output_names(p.right))
        if isinstance(p, lp.SubqueryAlias):
            return self._plan_output_names(p.child)
        raise PlanError(f"output names of {type(p).__name__}")

    def _plan_columns(self, p: lp.Plan) -> set:
        return set(self._plan_output_names(p))

    def _split_equi_keys(self, cond: Optional[ex.Expr], left: lp.Plan,
                         right: lp.Plan):
        """Split a bound join condition into equi-key pairs + residual."""
        if cond is None:
            return [], None
        lcols = self._plan_columns(left)
        rcols = self._plan_columns(right)
        keys: List[Tuple[ex.Expr, ex.Expr]] = []
        residual: List[ex.Expr] = []

        def side(e: ex.Expr) -> Optional[str]:
            cols = [n.name for n in e.walk() if isinstance(n, ex.ColumnRef)]
            if not cols:
                return "either"
            if all(c in lcols for c in cols):
                return "l"
            if all(c in rcols for c in cols):
                return "r"
            return None

        for conj in _conjuncts(cond):
            if isinstance(conj, ex.BinOp) and conj.op == "=":
                ls, rs = side(conj.left), side(conj.right)
                if ls == "l" and rs == "r":
                    keys.append((conj.left, conj.right))
                    continue
                if ls == "r" and rs == "l":
                    keys.append((conj.right, conj.left))
                    continue
            residual.append(conj)
        extra = _conjoin(residual)
        return keys, extra

    # -- SELECT --------------------------------------------------------------

    def _plan_select(self, sel: ast.Select, parent: Optional[Scope],
                     order_by=None) -> Tuple[lp.Plan, List[str]]:
        scope = Scope(parent)
        plan = self._plan_from(sel.from_, scope)

        if sel.where is not None:
            plan = self._apply_where(plan, sel.where, scope)

        # expand stars
        items: List[Tuple[Optional[str], ast.Node]] = []
        for it in sel.items:
            if isinstance(it.expr, ast.StarExpr):
                for s in scope.sources:
                    if it.expr.table is None or it.expr.table == s.alias:
                        for c in s.columns:
                            items.append((c, ast.Col(s.alias, c)))
            else:
                items.append((it.alias, it.expr))

        bound: List[Tuple[Optional[str], ex.Expr]] = []
        has_agg = sel.group is not None or sel.having is not None
        has_window = False
        for alias, e in items:
            be = self._bind(e, scope)
            if _contains_agg(be):
                has_agg = True
            if _contains_window(be):
                has_window = True
            bound.append((alias, be))

        if has_agg:
            plan, cols = self._plan_aggregate(plan, sel, scope, items, bound,
                                              order_by)
            if sel.distinct:
                plan = lp.Distinct(plan)
            return plan, cols

        if has_window:
            plan, cols = self._plan_window_select(plan, scope, items, bound)
        else:
            exprs = []
            cols = []
            seen: Dict[str, int] = {}
            for i, (alias, be) in enumerate(bound):
                name = alias or self._expr_display(items[i][1], i)
                if name in seen:
                    seen[name] += 1
                    name = f"{name}_{seen[name]}"
                else:
                    seen[name] = 0
                exprs.append((name, be))
                cols.append(name)
            plan = lp.Project(plan, exprs)
        if sel.distinct:
            plan = lp.Distinct(plan)
        if order_by:
            # resolve keys against output; unresolvable keys become hidden
            # projected columns bound in the select scope
            keys: List[Tuple[ex.Expr, bool]] = []
            hidden: List[Tuple[str, ex.Expr]] = []
            for e, asc, nf in order_by:
                try:
                    keys.append((self._resolve_order_key(e, cols, bound,
                                                         items), asc, nf))
                except PlanError:
                    if sel.distinct:
                        raise
                    name = self.fresh("o")
                    hidden.append((name, self._bind(e, scope)))
                    keys.append((ex.ColumnRef(name), asc, nf))
            if hidden:
                assert isinstance(plan, lp.Plan)
                # widen the projection, sort, then narrow back
                inner = plan
                if isinstance(inner, lp.Project):
                    inner.exprs = inner.exprs + hidden
                    plan = lp.Project(lp.Sort(inner, keys),
                                      [(c, ex.ColumnRef(c)) for c in cols])
                else:
                    plan = lp.Project(
                        lp.Sort(lp.Project(inner, [
                            (c, ex.ColumnRef(c)) for c in cols] + hidden),
                            keys),
                        [(c, ex.ColumnRef(c)) for c in cols])
            else:
                plan = lp.Sort(plan, keys)
        return plan, cols

    def _resolve_order_key(self, e: ast.Node, cols: List[str], bound,
                           items) -> ex.Expr:
        """Match an ORDER BY key against the select output (position, alias,
        unique base name, or identical expression)."""
        if isinstance(e, ast.Lit) and isinstance(e.value, int):
            return ex.ColumnRef(cols[e.value - 1])
        # identical expression to some select item
        try:
            scope_free = self._bind_against_output(e, cols)
            return scope_free
        except PlanError:
            pass
        raise PlanError("order key not in output")

    def _expr_display(self, e: ast.Node, i: int) -> str:
        if isinstance(e, ast.Col):
            return e.name
        return f"#c{i}"

    # -- WHERE + decorrelation ----------------------------------------------

    def _apply_where(self, plan: lp.Plan, where: ast.Node,
                     scope: Scope) -> lp.Plan:
        plain: List[ex.Expr] = []
        for conj in _ast_conjuncts(where):
            handled, plan = self._try_subquery_conjunct(plan, conj, scope)
            if handled:
                continue
            if _ast_contains_exists(conj):
                # EXISTS under OR (q10/q35 shape): plan each EXISTS as a
                # mark join producing a boolean column, then filter on the
                # rewritten predicate referencing the marks
                plan, conj = self._rewrite_exists_marks(plan, conj, scope)
            plain.append(self._bind(conj, scope))
        cond = _conjoin(plain)
        if cond is not None:
            plan = lp.Filter(plan, cond)
        return plan

    def _rewrite_exists_marks(self, plan: lp.Plan, node: ast.Node,
                              scope: Scope) -> Tuple[lp.Plan, ast.Node]:
        """Replace every EXISTS inside an arbitrary boolean expression with
        a MarkRef to a mark-join column appended to `plan`."""
        import dataclasses as _dc

        def walk(n):
            nonlocal plan
            if isinstance(n, ast.Exists):
                name = self.fresh("mark")
                plan = self._plan_exists_mark(plan, n.query, scope, name)
                ref: ast.Node = ast.MarkRef(name)
                return ast.Un("not", ref) if n.negated else ref
            if isinstance(n, (ast.ScalarQuery, ast.InQuery, ast.Query)):
                return n
            if isinstance(n, ast.Node):
                kw = {f.name: walk_val(getattr(n, f.name))
                      for f in _dc.fields(n)}
                return type(n)(**kw)
            return n

        def walk_val(v):
            if isinstance(v, ast.Node):
                return walk(v)
            if isinstance(v, list):
                return [walk_val(x) for x in v]
            if isinstance(v, tuple):
                return tuple(walk_val(x) for x in v)
            return v

        rewritten = walk(node)  # mutates `plan` via nonlocal
        return plan, rewritten

    def _plan_exists_mark(self, plan: lp.Plan, q: ast.Query, scope: Scope,
                          name: str) -> lp.Plan:
        sub_scope = Scope(scope)
        sub_plan, _cols = self.plan_query(q, sub_scope)
        if not sub_scope.outer_refs:
            raise PlanError("uncorrelated EXISTS unsupported")
        sub_plan, corr, residual = self._extract_correlation(
            sub_plan, scope, collect_residual=True)
        if not corr:
            raise PlanError("EXISTS without equality correlation unsupported")
        keys = [(ex.ColumnRef(o), ex.ColumnRef(i)) for o, i in corr]
        return lp.Join(plan, sub_plan, "mark", keys, _conjoin(residual), name)

    def _try_subquery_conjunct(self, plan: lp.Plan, conj: ast.Node,
                               scope: Scope) -> Tuple[bool, lp.Plan]:
        # x IN (subquery) / x NOT IN (subquery)
        if isinstance(conj, ast.InQuery):
            return True, self._plan_in_subquery(plan, conj, scope)
        if isinstance(conj, ast.Un) and conj.op == "not" and \
                isinstance(conj.operand, ast.InQuery):
            inner = conj.operand
            return True, self._plan_in_subquery(
                plan, ast.InQuery(inner.operand, inner.query,
                                  not inner.negated), scope)
        # EXISTS / NOT EXISTS
        if isinstance(conj, ast.Exists):
            return True, self._plan_exists(plan, conj.query, conj.negated,
                                           scope)
        if isinstance(conj, ast.Un) and conj.op == "not" and \
                isinstance(conj.operand, ast.Exists):
            return True, self._plan_exists(plan, conj.operand.query,
                                           not conj.operand.negated, scope)
        # comparison against a (possibly arithmetic-wrapped) correlated
        # scalar aggregate: x > (sub), x > 1.2 * (sub), ...
        if isinstance(conj, ast.Bin) and conj.op in ("=", "<>", "<", "<=",
                                                     ">", ">="):
            for this, other, flip in ((conj.right, conj.left, False),
                                      (conj.left, conj.right, True)):
                sub = _find_scalar_subquery(this)
                if sub is None:
                    continue
                sub_scope = Scope(scope)
                sub_plan, sub_cols = self.plan_query(sub.query, sub_scope)
                if sub_scope.outer_refs:
                    op = conj.op if not flip else _flip_op(conj.op)
                    # wrapper expression around the subquery value
                    marker = "__scalar__"
                    wrapped_ast = _replace_scalar_subquery(
                        this, sub, ast.Col(None, marker))
                    return True, self._plan_corr_scalar_cmp(
                        plan, other, op, sub_plan, sub_cols, scope,
                        wrapped_ast, marker)
                # uncorrelated: leave as SubqueryExpr literal
                be = ex.BinOp(
                    conj.op,
                    self._bind(conj.left, scope),
                    self._bind(conj.right, scope))
                return True, lp.Filter(plan, be)
        return False, plan

    def _plan_in_subquery(self, plan: lp.Plan, node: ast.InQuery,
                          scope: Scope) -> lp.Plan:
        operand = self._bind(node.operand, scope)
        sub_scope = Scope(scope)
        sub_plan, sub_cols = self.plan_query(node.query, sub_scope)
        if len(sub_cols) != 1:
            raise PlanError("IN subquery must produce one column")
        if sub_scope.outer_refs:
            # correlated IN: extract equality correlation from the subplan
            sub_plan, corr = self._extract_correlation(sub_plan, scope)
            keys = [(operand, ex.ColumnRef(sub_cols[0]))] + \
                [(ex.ColumnRef(o), ex.ColumnRef(i)) for o, i in corr]
            return lp.Join(plan, sub_plan,
                           "anti" if node.negated else "semi", keys)
        kind = "nullaware_anti" if node.negated else "semi"
        return lp.Join(plan, sub_plan, kind,
                       [(operand, ex.ColumnRef(sub_cols[0]))])

    def _plan_exists(self, plan: lp.Plan, q: ast.Query, negated: bool,
                     scope: Scope) -> lp.Plan:
        sub_scope = Scope(scope)
        sub_plan, _cols = self.plan_query(q, sub_scope)
        if not sub_scope.outer_refs:
            raise PlanError("uncorrelated EXISTS unsupported")
        sub_plan, corr, residual = self._extract_correlation(
            sub_plan, scope, collect_residual=True)
        if not corr:
            raise PlanError("EXISTS without equality correlation unsupported")
        keys = [(ex.ColumnRef(o), ex.ColumnRef(i)) for o, i in corr]
        return lp.Join(plan, sub_plan, "anti" if negated else "semi", keys,
                       _conjoin(residual))

    def _extract_correlation(self, sub_plan: lp.Plan, outer_scope: Scope,
                             collect_residual: bool = False):
        """Pull `outer_col = inner_col` predicates out of the subplan's
        filters.  Returns (rewritten subplan, [(outer_internal,
        inner_internal)]) — plus a residual predicate list when
        `collect_residual` (non-equi correlated conjuncts like
        ``cs1.cs_warehouse_sk <> cs2.cs_warehouse_sk`` in q16/q94, which
        become the semi/anti join's `extra`)."""
        outer_cols = set()
        sc = outer_scope
        while sc is not None:
            for s in sc.sources:
                for c in s.columns:
                    outer_cols.add(s.internal(c))
            sc = sc.parent

        corr: List[Tuple[str, str]] = []
        residual: List[ex.Expr] = []
        residual_inner: List[str] = []  # inner cols the residual needs

        def rewrite(p: lp.Plan) -> lp.Plan:
            if isinstance(p, lp.Filter):
                child = rewrite(p.child)
                child_cols = self._plan_columns(child)
                keep: List[ex.Expr] = []
                for conj in _conjuncts(_factor_or_common(p.condition)):
                    if isinstance(conj, ex.BinOp) and conj.op == "=" and \
                            isinstance(conj.left, ex.ColumnRef) and \
                            isinstance(conj.right, ex.ColumnRef):
                        # the outer side must NOT be producible by the
                        # subplan itself (membership in outer_cols alone is
                        # ambiguous when inner and outer scan the same
                        # unaliased table, e.g. q32/q92 catalog_sales)
                        l, r = conj.left.name, conj.right.name
                        if l in outer_cols and l not in child_cols and \
                                r in child_cols:
                            corr.append((l, r))
                            continue
                        if r in outer_cols and r not in child_cols and \
                                l in child_cols:
                            corr.append((r, l))
                            continue
                    if collect_residual:
                        refs = {n.name for n in conj.walk()
                                if isinstance(n, ex.ColumnRef)}
                        out_refs = refs & (outer_cols - child_cols)
                        if out_refs and (refs - out_refs) <= child_cols:
                            residual.append(conj)
                            residual_inner.extend(refs & child_cols)
                            continue
                    keep.append(conj)
                cond = _conjoin(keep)
                return lp.Filter(child, cond) if cond is not None else child
            if isinstance(p, lp.Project):
                # push through projects that just rename
                return lp.Project(rewrite(p.child), p.exprs)
            for attr in ("child",):
                if hasattr(p, attr):
                    setattr(p, attr, rewrite(getattr(p, attr)))
                    return p
            return p

        sub_plan = rewrite(sub_plan)
        # correlation columns must be visible in subplan output for the join:
        # wrap subplan in a project exposing them
        sub_cols = self._plan_output_names(sub_plan)
        missing = [i for i in
                   dict.fromkeys([i for _o, i in corr] + residual_inner)
                   if i not in sub_cols]
        if missing:
            sub_plan = _expose_columns(sub_plan, missing)
        if collect_residual:
            return sub_plan, corr, residual
        return sub_plan, corr

    def _plan_corr_scalar_cmp(self, plan: lp.Plan, other_ast: ast.Node,
                              op: str, sub_plan: lp.Plan,
                              sub_cols: List[str], scope: Scope,
                              wrapper_ast: Optional[ast.Node] = None,
                              marker: Optional[str] = None) -> lp.Plan:
        """outer_expr <op> f(correlated scalar aggregate subquery) — f is an
        optional arithmetic wrapper with the subquery replaced by `marker`."""
        sub_plan, corr = self._extract_correlation(sub_plan, scope)
        if not corr:
            raise PlanError("correlated scalar subquery without equality "
                            "correlation unsupported")
        # the subplan must be an Aggregate (possibly under projects); group it
        # by its correlation keys
        agg = _find_aggregate(sub_plan)
        if agg is None:
            raise PlanError("correlated scalar subquery must aggregate")
        inner_keys = [i for _o, i in corr]
        agg.group_by = agg.group_by + [(k, ex.ColumnRef(k))
                                      for k in inner_keys
                                      if k not in [n for n, _ in agg.group_by]]
        sub_plan = _expose_columns(sub_plan, inner_keys)
        other = self._bind(other_ast, scope)
        val_col = sub_cols[0]
        keys = [(ex.ColumnRef(o), ex.ColumnRef(i)) for o, i in corr]
        joined = lp.Join(plan, sub_plan, "inner", keys)
        if wrapper_ast is not None and not (
                isinstance(wrapper_ast, ast.Col) and
                wrapper_ast.name == marker):
            value = self._bind(wrapper_ast, scope,
                               alias_map={marker: ex.ColumnRef(val_col)})
        else:
            value = ex.ColumnRef(val_col)
        cond = ex.BinOp(op, other, value)
        filtered = lp.Filter(joined, cond)
        # project away subquery columns
        keep = self._plan_output_names(plan)
        return lp.Project(filtered, [(c, ex.ColumnRef(c)) for c in keep])

    # -- aggregate select ----------------------------------------------------

    def _plan_aggregate(self, plan: lp.Plan, sel: ast.Select, scope: Scope,
                        items, bound,
                        order_by=None) -> Tuple[lp.Plan, List[str]]:
        group_keys: List[Tuple[str, ex.Expr]] = []
        key_repr: Dict[str, str] = {}  # repr(bound expr) -> key name
        gsets: Optional[List[List[int]]] = None
        alias_map = {alias: be for (alias, _e), (a2, be) in
                     zip([(a, e) for a, e in items], bound) if alias}
        if sel.group is not None:
            gexprs = []
            for e in sel.group.exprs:
                # group-by alias or position
                if isinstance(e, ast.Col) and e.table is None and \
                        e.name in alias_map:
                    be = alias_map[e.name]
                elif isinstance(e, ast.Lit) and isinstance(e.value, int):
                    be = bound[e.value - 1][1]
                else:
                    be = self._bind(e, scope)
                gexprs.append(be)
            for i, be in enumerate(gexprs):
                name = self.fresh("g")
                group_keys.append((name, be))
                key_repr[repr(be)] = name
            if sel.group.kind == "rollup":
                n = len(group_keys)
                gsets = [list(range(k)) for k in range(n, -1, -1)]
            elif sel.group.kind == "cube":
                n = len(group_keys)
                gsets = [[i for i in range(n) if (m >> i) & 1]
                         for m in range(2 ** n - 1, -1, -1)]
            elif sel.group.kind == "sets":
                gsets = []
                for s in sel.group.sets:
                    idxs = []
                    for e in s:
                        be = self._bind(e, scope)
                        if repr(be) not in key_repr:
                            raise PlanError("grouping set expr not in keys")
                        idxs.append([n for n, _ in group_keys].index(
                            key_repr[repr(be)]))
                    gsets.append(idxs)

        aggs: List[Tuple[str, ex.Expr]] = []
        wexprs: List[Tuple[str, ex.Expr]] = []  # windows over the aggregate
        out_names: List[str] = []
        out_exprs: List[Tuple[str, ex.Expr]] = []

        agg_seen: Dict[str, str] = {}  # repr(AggExpr) -> hidden column name

        def hidden_agg(be: ex.Expr) -> ex.Expr:
            r = repr(be)
            if r not in agg_seen:
                h = self.fresh("a")
                aggs.append((h, be))
                agg_seen[r] = h
            return ex.ColumnRef(agg_seen[r])

        def to_agg_output(be: ex.Expr) -> ex.Expr:
            """Rewrite a select expression into one over the aggregate's
            OUTPUT columns: group-key subtrees -> key refs, AggExprs (and
            grouping()) -> hidden aggregate columns, windows hoisted above
            the Aggregate node."""
            r = repr(be)
            if r in key_repr:
                return ex.ColumnRef(key_repr[r])
            if isinstance(be, ex.AggExpr):
                return hidden_agg(be)
            if isinstance(be, ex.Func) and be.name == "grouping":
                # rewrite the argument to the generated group-key name so the
                # executor can match it against the grouping-set subset
                return hidden_agg(ex.Func(
                    "grouping", (to_agg_output(be.args[0]),)))
            if isinstance(be, ex.BinOp):
                return ex.BinOp(be.op, to_agg_output(be.left),
                                to_agg_output(be.right))
            if isinstance(be, ex.Cast):
                return ex.Cast(to_agg_output(be.operand), be.target)
            if isinstance(be, ex.Func):
                return ex.Func(be.name,
                               tuple(to_agg_output(a) for a in be.args))
            if isinstance(be, ex.InList):
                return ex.InList(to_agg_output(be.operand), be.values,
                                 be.negated)
            if isinstance(be, ex.Case):
                return ex.Case(tuple((to_agg_output(c), to_agg_output(v))
                                     for c, v in be.whens),
                               to_agg_output(be.default)
                               if be.default is not None else None)
            if isinstance(be, (ex.Literal,)):
                return be
            if isinstance(be, ex.SubqueryExpr) and not be.correlated_predicates:
                # uncorrelated scalar subquery (e.g. q44's HAVING
                # `avg(x) > 0.9 * (select ...)`) — a constant at exec time
                return be
            if isinstance(be, ex.UnaryOp):
                return ex.UnaryOp(be.op, to_agg_output(be.operand))
            if isinstance(be, ex.WindowExpr):
                # window over the aggregate output (revenue-ratio pattern):
                # components become post-aggregate exprs, the WindowExpr is
                # hoisted above the Aggregate node
                w2 = ex.WindowExpr(
                    be.func,
                    None if be.arg is None or isinstance(be.arg, ex.Star)
                    else to_agg_output(be.arg),
                    tuple(to_agg_output(x) for x in be.partition_by),
                    tuple((to_agg_output(o), asc)
                          for o, asc in be.order_by),
                    be.frame)
                name = self.fresh("w")
                wexprs.append((name, w2))
                return ex.ColumnRef(name)
            raise PlanError(
                f"select expr not derivable from group keys/aggregates: {be}")

        seen_names: Dict[str, int] = {}
        for i, (alias, be) in enumerate(bound):
            name = alias or self._expr_display(items[i][1], i)
            if name in seen_names:
                seen_names[name] += 1
                name = f"{name}_{seen_names[name]}"
            else:
                seen_names[name] = 0
            out_exprs.append((name, to_agg_output(be)))
            out_names.append(name)

        agg_plan = lp.Aggregate(plan, group_keys, aggs, gsets)

        if sel.having is not None:
            hb = self._bind(sel.having, scope, allow_aggs=True,
                            alias_map=alias_map)
            agg_plan = lp.Filter(agg_plan, to_agg_output(hb))

        keys: List[Tuple] = []
        hidden: List[Tuple[str, ex.Expr]] = []
        if order_by:
            for e, asc, nf in order_by:
                try:
                    keys.append((self._resolve_order_key(e, out_names, bound,
                                                         items), asc, nf))
                    continue
                except PlanError:
                    pass
                be = self._bind(e, scope, allow_aggs=True,
                                alias_map=alias_map)
                # to_agg_output registers new aggregates on the shared aggs
                # list (the Aggregate node holds the same object) and may
                # hoist new window exprs — the Window node is built below,
                # after all select AND order-by expressions are processed
                name = self.fresh("o")
                hidden.append((name, to_agg_output(be)))
                keys.append((ex.ColumnRef(name), asc, nf))

        if wexprs:
            # windows computed over the (filtered) aggregate output
            agg_plan = lp.Window(agg_plan, wexprs)

        if order_by:
            proj = lp.Project(lp.Sort(
                lp.Project(agg_plan, out_exprs + hidden), keys),
                [(n, ex.ColumnRef(n)) for n in out_names])
            return proj, out_names
        proj = lp.Project(agg_plan, out_exprs)
        return proj, out_names

    def _plan_window_select(self, plan: lp.Plan, scope: Scope, items,
                            bound) -> Tuple[lp.Plan, List[str]]:
        wexprs: List[Tuple[str, ex.Expr]] = []
        out_exprs: List[Tuple[str, ex.Expr]] = []
        out_names: List[str] = []

        def hoist(be: ex.Expr) -> ex.Expr:
            if isinstance(be, ex.WindowExpr):
                name = self.fresh("w")
                wexprs.append((name, be))
                return ex.ColumnRef(name)
            if isinstance(be, ex.BinOp):
                return ex.BinOp(be.op, hoist(be.left), hoist(be.right))
            if isinstance(be, ex.Cast):
                return ex.Cast(hoist(be.operand), be.target)
            if isinstance(be, ex.Func):
                return ex.Func(be.name, tuple(hoist(a) for a in be.args))
            return be

        for i, (alias, be) in enumerate(bound):
            name = alias or self._expr_display(items[i][1], i)
            out_exprs.append((name, hoist(be)))
            out_names.append(name)
        wplan = lp.Window(plan, wexprs)
        return lp.Project(wplan, out_exprs), out_names

    # -- ORDER BY ------------------------------------------------------------

    def _apply_order(self, plan: lp.Plan, cols: List[str], order_by,
                     scope: Optional[Scope]) -> lp.Plan:
        keys: List[Tuple] = []
        for e, asc, nf in order_by:
            if isinstance(e, ast.Lit) and isinstance(e.value, int):
                keys.append((ex.ColumnRef(cols[e.value - 1]), asc, nf))
                continue
            if isinstance(e, ast.Col) and e.table is None and e.name in cols:
                keys.append((ex.ColumnRef(e.name), asc, nf))
                continue
            try:
                be = self._bind_against_output(e, cols)
                keys.append((be, asc, nf))
            except PlanError:
                if scope is None:
                    raise
                keys.append((self._bind(e, scope), asc, nf))
        return lp.Sort(plan, keys)

    def _bind_against_output(self, e: ast.Node, cols: List[str]) -> ex.Expr:
        if isinstance(e, ast.Col) and e.table is None:
            if e.name in cols:
                return ex.ColumnRef(e.name)
            raise PlanError(f"order-by column {e.name} not in output")
        if isinstance(e, ast.Col):
            # qualified ref: the projection dropped the qualifier — match by
            # base name if unambiguous (ORDER BY s.qty after SELECT s.qty)
            if f"{e.table}.{e.name}" in cols:
                return ex.ColumnRef(f"{e.table}.{e.name}")
            hits = [c for c in cols if c == e.name or
                    c.split(".")[-1] == e.name]
            if len(hits) == 1:
                return ex.ColumnRef(hits[0])
            raise PlanError("qualified order-by ref not in output")
        if isinstance(e, ast.Bin):
            return ex.BinOp(e.op, self._bind_against_output(e.left, cols),
                            self._bind_against_output(e.right, cols))
        if isinstance(e, ast.Lit):
            return ex.Literal(e.value)
        if isinstance(e, ast.FuncCall):
            return ex.Func(e.name, tuple(
                self._bind_against_output(a, cols) for a in e.args))
        raise PlanError(f"unsupported order-by expr {type(e).__name__}")

    # -- expression binding --------------------------------------------------

    _AGG_FUNCS = {"sum", "avg", "count", "min", "max", "stddev_samp",
                  "stddev", "var_samp", "variance"}
    _WINDOW_FUNCS = {"rank", "dense_rank", "row_number"}

    def _bind(self, e: ast.Node, scope: Scope, allow_aggs: bool = True,
              alias_map: Optional[Dict[str, ex.Expr]] = None) -> ex.Expr:
        b = lambda x: self._bind(x, scope, allow_aggs, alias_map)  # noqa: E731
        if isinstance(e, ast.Col):
            if alias_map and e.table is None and e.name in alias_map:
                return alias_map[e.name]
            name, _outer = scope.resolve(e.table, e.name)
            return ex.ColumnRef(name)
        if isinstance(e, ast.MarkRef):
            return ex.ColumnRef(e.name)
        if isinstance(e, ast.Lit):
            return ex.Literal(e.value)
        if isinstance(e, ast.DateLit):
            return ex.Literal(_date_to_days(e.value), DATE)
        if isinstance(e, ast.Interval):
            if e.unit != "days":
                raise PlanError(f"interval unit {e.unit} unsupported")
            return ex.Literal(e.n)
        if isinstance(e, ast.Bin):
            if e.op.endswith(("_all", "_any", "_some")):
                return self._bind_quantified(e, scope)
            return ex.BinOp(e.op, b(e.left), b(e.right))
        if isinstance(e, ast.Un):
            return ex.UnaryOp("not" if e.op == "not" else "neg", b(e.operand))
        if isinstance(e, ast.IsNull):
            return ex.UnaryOp("isnotnull" if e.negated else "isnull",
                              b(e.operand))
        if isinstance(e, ast.Between):
            lo = ex.BinOp(">=", b(e.operand), b(e.lo))
            hi = ex.BinOp("<=", b(e.operand), b(e.hi))
            both = ex.BinOp("and", lo, hi)
            return ex.UnaryOp("not", both) if e.negated else both
        if isinstance(e, ast.InVals):
            vals = []
            for v in e.values:
                if isinstance(v, ast.Lit):
                    vals.append(v.value)
                elif isinstance(v, ast.DateLit):
                    vals.append(_date_to_days(v.value))
                elif isinstance(v, ast.Un) and v.op == "neg" and \
                        isinstance(v.operand, ast.Lit):
                    vals.append(-v.operand.value)
                else:
                    # non-literal IN list: expand to OR chain
                    ors = None
                    for v2 in e.values:
                        eq = ex.BinOp("=", b(e.operand), b(v2))
                        ors = eq if ors is None else ex.BinOp("or", ors, eq)
                    return ex.UnaryOp("not", ors) if e.negated else ors
            return ex.InList(b(e.operand), tuple(vals), e.negated)
        if isinstance(e, ast.LikeOp):
            like = ex.Func("like", (b(e.operand), ex.Literal(e.pattern)))
            return ex.UnaryOp("not", like) if e.negated else like
        if isinstance(e, ast.CaseExpr):
            if e.operand is not None:
                whens = tuple(
                    (ex.BinOp("=", b(e.operand), b(c)), b(v))
                    for c, v in e.whens)
            else:
                whens = tuple((b(c), b(v)) for c, v in e.whens)
            return ex.Case(whens, b(e.default) if e.default is not None
                           else None)
        if isinstance(e, ast.CastExpr):
            return ex.Cast(b(e.operand), _parse_type(e.type_name))
        if isinstance(e, ast.FuncCall):
            if e.name in self._AGG_FUNCS:
                if not allow_aggs:
                    raise PlanError(f"aggregate {e.name} not allowed here")
                arg = ex.Star() if e.star else b(e.args[0])
                fname = "stddev_samp" if e.name == "stddev" else e.name
                return ex.AggExpr(fname, arg, e.distinct)
            if e.name == "grouping":
                return ex.Func("grouping", (b(e.args[0]),))
            return ex.Func(e.name, tuple(b(a) for a in e.args))
        if isinstance(e, ast.WindowCall):
            fc = e.func
            arg = None
            if fc.star:
                arg = ex.Star()
            elif fc.args:
                arg = b(fc.args[0])
            return ex.WindowExpr(
                fc.name, arg,
                tuple(b(p) for p in e.partition_by),
                tuple((b(o), asc) for o, asc in e.order_by),
                e.frame)
        if isinstance(e, ast.ScalarQuery):
            sub_scope = Scope(scope)
            sub_plan, sub_cols = self.plan_query(e.query, sub_scope)
            if sub_scope.outer_refs:
                raise PlanError("correlated scalar subquery in this position "
                                "unsupported")
            return ex.SubqueryExpr("scalar", sub_plan)
        if isinstance(e, ast.InQuery):
            sub_scope = Scope(scope)
            sub_plan, sub_cols = self.plan_query(e.query, sub_scope)
            if sub_scope.outer_refs:
                raise PlanError("correlated IN in this position unsupported")
            return ex.SubqueryExpr("in", sub_plan, self._bind(e.operand,
                                                              scope),
                                   e.negated)
        if isinstance(e, ast.Exists):
            raise PlanError("EXISTS only supported as a WHERE conjunct")
        raise PlanError(f"unsupported expression {type(e).__name__}")

    def _bind_quantified(self, e: ast.Bin, scope: Scope) -> ex.Expr:
        """x <op> ALL/ANY (subquery) -> comparison against min/max of the
        subquery (empty-subquery edge: yields NULL instead of TRUE for ALL —
        acceptable for the benchmark corpus, which never hits it)."""
        op, quant = e.op.rsplit("_", 1)
        if quant == "some":
            quant = "any"
        assert isinstance(e.right, ast.ScalarQuery)
        if quant == "any" and op == "=":
            return self._bind(ast.InQuery(e.left, e.right.query, False),
                              scope)
        agg = {("<", "all"): "min", ("<=", "all"): "min",
               (">", "all"): "max", (">=", "all"): "max",
               ("<", "any"): "max", ("<=", "any"): "max",
               (">", "any"): "min", (">=", "any"): "min"}.get((op, quant))
        if agg is None:
            raise PlanError(f"unsupported quantified comparison {e.op}")
        sub_scope = Scope(scope)
        sub_plan, sub_cols = self.plan_query(e.right.query, sub_scope)
        if sub_scope.outer_refs:
            raise PlanError("correlated quantified subquery unsupported")
        name = self.fresh("q")
        agg_plan = lp.Aggregate(sub_plan, [], [
            (name, ex.AggExpr(agg, ex.ColumnRef(sub_cols[0])))])
        return ex.BinOp(op, self._bind(e.left, scope),
                        ex.SubqueryExpr("scalar", agg_plan))


# -- helpers -----------------------------------------------------------------


def _conjuncts(e: Optional[ex.Expr]) -> List[ex.Expr]:
    if e is None:
        return []
    if isinstance(e, ex.BinOp) and e.op == "and":
        return _conjuncts(e.left) + _conjuncts(e.right)
    return [e]


def _ast_conjuncts(e: ast.Node) -> List[ast.Node]:
    if isinstance(e, ast.Bin) and e.op == "and":
        return _ast_conjuncts(e.left) + _ast_conjuncts(e.right)
    return [e]


def _ast_contains_exists(e) -> bool:
    """True if an EXISTS occurs anywhere inside the expression (without
    descending into nested sub-queries, whose own planning handles them)."""
    import dataclasses as _dc
    if isinstance(e, ast.Exists):
        return True
    if isinstance(e, (ast.ScalarQuery, ast.InQuery, ast.Query)):
        return False
    if isinstance(e, ast.Node):
        return any(_ast_contains_exists(getattr(e, f.name))
                   for f in _dc.fields(e))
    if isinstance(e, (list, tuple)):
        return any(_ast_contains_exists(x) for x in e)
    return False


def _factor_or_common(e: ex.Expr) -> ex.Expr:
    """Factor conjuncts common to both branches of an OR:
    ``(A and X) or (A and Y)`` -> ``A and (X or Y)`` (recursively).  Makes
    equality correlations inside disjunctions visible to the decorrelator
    (q41 shape)."""
    if isinstance(e, ex.BinOp) and e.op == "and":
        return ex.BinOp("and", _factor_or_common(e.left),
                        _factor_or_common(e.right))
    if isinstance(e, ex.BinOp) and e.op == "or":
        l = _factor_or_common(e.left)
        r = _factor_or_common(e.right)
        lc, rc = _conjuncts(l), _conjuncts(r)
        common = [c for c in lc if c in rc]
        if not common:
            return ex.BinOp("or", l, r)
        lrest = [c for c in lc if c not in common]
        rrest = [c for c in rc if c not in common]
        if not lrest or not rrest:
            # (A) or (A and X)  ->  A
            return _conjoin(common)
        return _conjoin(common + [ex.BinOp("or", _conjoin(lrest),
                                           _conjoin(rrest))])
    return e


def _conjoin(parts: Sequence[ex.Expr]) -> Optional[ex.Expr]:
    out: Optional[ex.Expr] = None
    for p in parts:
        out = p if out is None else ex.BinOp("and", out, p)
    return out


def _flip_op(op: str) -> str:
    return {"<": ">", "<=": ">=", ">": "<", ">=": "<=",
            "=": "=", "<>": "<>"}[op]


def _find_scalar_subquery(e: ast.Node):
    """First ScalarQuery inside an arithmetic wrapper (Bin/Un/Cast chains)."""
    if isinstance(e, ast.ScalarQuery):
        return e
    if isinstance(e, ast.Bin):
        return _find_scalar_subquery(e.left) or \
            _find_scalar_subquery(e.right)
    if isinstance(e, ast.Un):
        return _find_scalar_subquery(e.operand)
    if isinstance(e, ast.CastExpr):
        return _find_scalar_subquery(e.operand)
    return None


def _replace_scalar_subquery(e: ast.Node, target, replacement) -> ast.Node:
    if e is target:
        return replacement
    if isinstance(e, ast.Bin):
        return ast.Bin(e.op,
                       _replace_scalar_subquery(e.left, target, replacement),
                       _replace_scalar_subquery(e.right, target, replacement))
    if isinstance(e, ast.Un):
        return ast.Un(e.op,
                      _replace_scalar_subquery(e.operand, target,
                                               replacement))
    if isinstance(e, ast.CastExpr):
        return ast.CastExpr(
            _replace_scalar_subquery(e.operand, target, replacement),
            e.type_name)
    return e


def _contains_agg(e: ex.Expr) -> bool:
    return any(isinstance(x, ex.AggExpr) for x in e.walk())


def _contains_window(e: ex.Expr) -> bool:
    return any(isinstance(x, ex.WindowExpr) for x in e.walk())


def _find_aggregate(p: lp.Plan) -> Optional[lp.Aggregate]:
    if isinstance(p, lp.Aggregate):
        return p
    for c in p.children():
        if isinstance(c, (lp.Aggregate, lp.Project, lp.Filter)):
            found = _find_aggregate(c)
            if found is not None:
                return found
    return None


def _expose_columns(p: lp.Plan, names: List[str]) -> lp.Plan:
    """Ensure `names` appear in p's output by widening trailing Projects."""
    if isinstance(p, lp.Project):
        have = {n for n, _ in p.exprs}
        child_cols = set()
        try:
            child_cols = set(Planner._plan_output_names(Planner, p.child))  # type: ignore
        except Exception:
            pass
        extra = [(n, ex.ColumnRef(n)) for n in names
                 if n not in have and n in child_cols]
        missing = [n for n in names if n not in have and n not in child_cols]
        if missing:
            p.child = _expose_columns(p.child, missing)
            extra += [(n, ex.ColumnRef(n)) for n in missing]
        p.exprs = p.exprs + extra
        return p
    if isinstance(p, lp.Aggregate):
        have = {n for n, _ in p.group_by} | {n for n, _ in p.aggs}
        for n in names:
            if n not in have:
                p.group_by = p.group_by + [(n, ex.ColumnRef(n))]
        return p
    if isinstance(p, (lp.Filter, lp.Sort, lp.Limit, lp.Distinct)):
        p.child = _expose_columns(p.child, names)
        return p
    return p
