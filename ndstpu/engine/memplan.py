"""Spill-aware streaming memory planner.

Sizes the out-of-core scan (``chunk_rows``) and the H2D prefetch depth
from what the hardware actually reports instead of a hand-tuned
constant: the per-device HBM budget (``Device.memory_stats()`` where
the platform exposes it, ``NDSTPU_HBM_BYTES`` override, a conservative
default otherwise) divided by the plan's scanned row width (the same
per-column byte widths the plan-lint schema analysis uses — data
itemsize + one validity byte per column + one alive byte per row).

The working-set model is deliberately simple and explicit::

    per-device bytes  =  chunk_bytes * (COMPUTE_MULT + depth + 1)

``COMPUTE_MULT`` covers the traced spine's intermediates (sort keys,
gather indices, segment buffers — empirically < 6x the resident chunk
for the corpus aggregates), ``depth + 1`` covers the resident chunk
plus the staged prefetch ring.  When even the whole fact fits under the
budget the planner returns ``chunk_rows=None`` (stay whole-fact
resident); otherwise it picks the largest power-of-two chunk that
fits (stable shapes -> stable compile cache keys) and the deepest
prefetch ring that still fits, capped at ``max_depth``.

Session wires this in via ``spmd_chunk_rows="auto"``; the distributed
executor re-plans per fact (column subsets differ per query).  See
docs/ARCHITECTURE.md "Streaming out-of-core pipeline".
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Iterable, Optional, Tuple

#: fallback per-device budget when the platform reports no memory stats
#: (CPU meshes in tests/CI) and no NDSTPU_HBM_BYTES override is set
DEFAULT_BUDGET_BYTES = 2 << 30

#: fraction of the reported budget the planner is allowed to commit
SAFETY = 0.5

#: working-set multiplier for traced-spine intermediates over one
#: resident chunk (sort keys, gathers, segment buffers)
COMPUTE_MULT = 6

#: smallest chunk worth compiling a streaming program for
MIN_CHUNK_ROWS = 4096

#: modeled device working set of one admitted serve query when the
#: caller has nothing better (override: NDSTPU_SERVE_QUERY_BYTES) —
#: sized for the tiny-corpus serve tier; real fleets pass the fact's
#: schema_row_bytes * chunk estimate instead
DEFAULT_QUERY_WORKING_SET_BYTES = 64 << 20

#: admission depth clamps: at least one query must always be
#: admittable, and no memory model justifies queueing thousands
ADMISSION_MIN_DEPTH = 1
ADMISSION_MAX_DEPTH = 256

#: deepest staging ring the planner will ask for
DEFAULT_MAX_DEPTH = 2


@dataclass(frozen=True)
class StreamPlan:
    """One planned streaming configuration for a (fact, mesh) pair."""

    chunk_rows: Optional[int]    # None = whole fact fits resident
    prefetch_depth: int
    bytes_per_row: int
    budget_bytes: int
    budget_source: str           # memory_stats | env | default

    def describe(self) -> str:
        mode = ("resident" if self.chunk_rows is None
                else f"chunk_rows={self.chunk_rows}"
                     f" depth={self.prefetch_depth}")
        return (f"{mode} row_bytes={self.bytes_per_row} "
                f"budget={self.budget_bytes >> 20}MiB"
                f"({self.budget_source})")


def row_bytes(itemsizes: Iterable[int]) -> int:
    """Scanned row width: per-column data itemsize + 1 validity byte
    each, + 1 alive byte per row (the streaming arg layout)."""
    sizes = list(itemsizes)
    return sum(s + 1 for s in sizes) + 1


def schema_row_bytes(schema, columns: Optional[Iterable[str]] = None
                     ) -> int:
    """Row width from a declared :class:`ndstpu.schema.TableSchema`
    (what plan-lint sees before any data is loaded).  String columns
    count their int32 dictionary-code width — the form the device
    streams — not the encoded text."""
    import numpy as np

    from ndstpu.engine import columnar
    want = set(columns) if columns is not None else None
    sizes = [np.dtype(columnar.numpy_dtype(c.dtype)).itemsize
             for c in schema.columns
             if want is None or c.name in want]
    return row_bytes(sizes)


def device_budget_bytes(device=None) -> Tuple[int, str]:
    """Per-device byte budget and where it came from.

    ``NDSTPU_HBM_BYTES`` wins (operator pin / tests); then the
    platform's ``memory_stats()`` (``bytes_limit`` less live
    allocations); then :data:`DEFAULT_BUDGET_BYTES`.
    """
    env = os.environ.get("NDSTPU_HBM_BYTES")
    if env:
        return max(int(env), 1), "env"
    if device is None:
        try:
            import jax
            device = jax.local_devices()[0]
        except Exception:  # noqa: BLE001 — no backend yet
            return DEFAULT_BUDGET_BYTES, "default"
    try:
        stats = device.memory_stats()
    except Exception:  # noqa: BLE001 — platform without stats
        stats = None
    if stats and stats.get("bytes_limit"):
        free = int(stats["bytes_limit"]) - int(stats.get("bytes_in_use",
                                                         0))
        if free > 0:
            return free, "memory_stats"
    return DEFAULT_BUDGET_BYTES, "default"


def admission_budget(bytes_per_query: Optional[int] = None,
                     budget_bytes: Optional[int] = None,
                     budget_source: str = "caller",
                     min_depth: int = ADMISSION_MIN_DEPTH,
                     max_depth: int = ADMISSION_MAX_DEPTH) -> dict:
    """Admission budget query for the serve layer: how many
    concurrently-admitted queries the device-memory model supports.

    The same ``SAFETY``-discounted per-device budget that sizes
    streaming chunks is divided by the modeled per-query working set
    (``bytes_per_query``; default :data:`DEFAULT_QUERY_WORKING_SET_BYTES`
    or the ``NDSTPU_SERVE_QUERY_BYTES`` override) and clamped to
    ``[min_depth, max_depth]``.  A clamped ``NDSTPU_HBM_BYTES`` thus
    shrinks the serve queue directly: a memory-starved replica sheds
    (``Overloaded``) instead of queueing work it cannot hold.
    """
    if budget_bytes is None:
        budget_bytes, budget_source = device_budget_bytes()
    if bytes_per_query is None:
        env = os.environ.get("NDSTPU_SERVE_QUERY_BYTES")
        bytes_per_query = (max(int(env), 1) if env
                           else DEFAULT_QUERY_WORKING_SET_BYTES)
    usable = max(int(budget_bytes * SAFETY), 1)
    depth = usable // max(int(bytes_per_query), 1)
    depth = max(int(min_depth), min(int(depth), int(max_depth)))
    return {"depth": depth,
            "budget_bytes": int(budget_bytes),
            "budget_source": budget_source,
            "bytes_per_query": int(bytes_per_query),
            "usable_bytes": usable}


def _pow2_floor(n: int) -> int:
    return 1 << (max(n, 1).bit_length() - 1)


def plan_stream(n_rows: int, bytes_per_row: int, n_dev: int,
                budget_bytes: Optional[int] = None,
                budget_source: str = "caller",
                max_depth: int = DEFAULT_MAX_DEPTH,
                dict_bytes: int = 0,
                resident_bytes: int = 0) -> StreamPlan:
    """Size ``chunk_rows`` (total across the mesh) and the prefetch
    depth for streaming ``n_rows`` of ``bytes_per_row`` over ``n_dev``
    devices under the per-device budget.

    ``dict_bytes`` is the resident footprint of the scanned string
    columns' frozen global dictionaries (codes stream per chunk, but
    the dictionary itself is a whole-query constant on every device),
    carved out of the usable budget before chunks are sized.

    ``resident_bytes`` is the predicted whole-query working set pinned
    on every device beyond the streamed chunk itself — today the
    broadcast-join build sides the cost advisor placed resident
    (analysis/cost.py) — carved out the same way, so a query with fat
    replicated builds streams in smaller chunks instead of spilling.
    """
    if budget_bytes is None:
        budget_bytes, budget_source = device_budget_bytes()
    usable = max(int(budget_bytes * SAFETY) - max(int(dict_bytes), 0)
                 - max(int(resident_bytes), 0), 1)
    bytes_per_row = max(bytes_per_row, 1)
    shard_rows = -(-max(n_rows, 1) // max(n_dev, 1))
    if shard_rows * bytes_per_row * COMPUTE_MULT <= usable:
        return StreamPlan(None, 0, bytes_per_row, budget_bytes,
                          budget_source)
    depth = max(int(max_depth), 0)
    while True:
        per_dev_chunk = usable // (COMPUTE_MULT + depth + 1)
        chunk_dev_rows = per_dev_chunk // bytes_per_row
        if chunk_dev_rows * n_dev >= MIN_CHUNK_ROWS or depth == 0:
            break
        depth -= 1   # spill-aware: shallower ring buys bigger chunks
    chunk_rows = _pow2_floor(max(int(chunk_dev_rows), 1) * n_dev)
    chunk_rows = max(min(chunk_rows, int(n_rows)), n_dev)
    return StreamPlan(chunk_rows, depth, bytes_per_row, budget_bytes,
                      budget_source)
