"""Runtime spine-materialization cache (cross-query MQO).

The analysis half (``analysis/spines.py``) proves which canonical plan
subtrees recur across corpus parts; this module is the runtime half:
an LRU table cache keyed on the subtree's *value key* (canonical
fingerprint + hash over all slot values — a spine binding different
literals is a different materialized table).  The first query to
execute a flagged spine materializes the subtree and publishes the
result; later queries splice the cached table in place of the subtree
(``Session._splice_spines``) instead of recomputing the scan/filter/
join work.

Admission is byte-budgeted with the memory-planner's model: entries
evict LRU-first so the cache never holds more than ``budget_bytes``,
and a table bigger than the whole budget is simply not cached (the
query still runs — it just doesn't share).  Entries carry the session
state (views epoch + catalog versions) they were built under and are
dropped on mismatch, mirroring ``Session._plan_cache`` semantics.

Counters: ``engine.spine.hit`` / ``engine.spine.miss`` per flagged-site
lookup, ``engine.spine.bytes`` cumulative bytes served from cache (the
bytes-saved proxy), ``engine.spine.evict`` per eviction — all flowing
into the obs sidecars and the run ledger.  ``NDSTPU_SPINES=0`` is the
kill switch (checked by the splicer and the scheduler installer).
"""

from __future__ import annotations

import dataclasses
import os
import threading
from collections import OrderedDict
from typing import Dict, Optional, Set, Tuple

from ndstpu.engine import memplan, plan as lp
from ndstpu.engine.latch import KeyedLatch


def enabled() -> bool:
    """NDSTPU_SPINES=0 kills all spine sharing (analysis still runs)."""
    return os.environ.get("NDSTPU_SPINES", "1") not in ("", "0")


def runtime_budget_bytes() -> Tuple[int, str]:
    """Byte budget for the runtime cache: NDSTPU_SPINE_BUDGET_BYTES
    wins (tests / operator pin), else the memory planner's per-device
    budget scaled by its SAFETY fraction — the spine cache competes
    with resident chunks for the same HBM."""
    env = os.environ.get("NDSTPU_SPINE_BUDGET_BYTES")
    if env:
        return max(int(env), 1), "env"
    budget, source = memplan.device_budget_bytes()
    return max(int(budget * memplan.SAFETY), 1), source


def table_bytes(t) -> int:
    """Materialized size of a columnar.Table under the planner's model:
    data + validity mask, plus the actual UTF-8 text bytes of string
    dictionaries (8 B/entry only counted the object pointers, so wide
    string spines silently overran the LRU budget)."""
    from ndstpu.io.gdict import dictionary_nbytes
    n = 0
    for c in t.columns.values():
        n += int(c.data.nbytes)
        if c.valid is not None:
            n += int(c.valid.nbytes)
        if c.dictionary is not None:
            n += dictionary_nbytes(c.dictionary) + 8 * len(c.dictionary)
    return n


class SpineCache:
    """Byte-budgeted LRU of materialized spine tables.

    ``flagged`` is the set of value keys worth publishing (the scheduler
    flags keys that occur >= 2 times across its streams); ``None`` means
    every eligible site publishes (tests).  Thread-safe; the per-key
    latch gives materialize-once semantics to callers that publish
    outside the session's execution lock."""

    def __init__(self, budget_bytes: int,
                 flagged: Optional[Set[str]] = None):
        self.budget_bytes = max(int(budget_bytes), 0)
        self.flagged = flagged
        self._lock = threading.RLock()
        self._latch = KeyedLatch()
        # value_key -> [state, table, nbytes]; insertion order = LRU
        self._entries: "OrderedDict[str, list]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def eligible(self, value_key: str) -> bool:
        return self.flagged is None or value_key in self.flagged

    def holding(self, value_key: str):
        return self._latch.holding(value_key)

    @property
    def total_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def get(self, value_key: str, state):
        """The cached table for ``value_key`` built under ``state``, or
        None.  A stale-state entry is dropped (DML/view churn), exactly
        like the session's plan cache."""
        with self._lock:
            ent = self._entries.get(value_key)
            if ent is None:
                return None
            if ent[0] != state:
                # the catalog epoch moved under this entry (ingest
                # commit / DML / view churn): a hit here would serve a
                # pre-ingest spine to a post-ingest query
                self._drop(value_key)
                _obs_inc("engine.snapshot.stale_drops")
                return None
            self._entries.move_to_end(value_key)
            return ent[1]

    def put(self, value_key: str, state, table) -> bool:
        """Publish a materialized spine; returns False when the table
        alone exceeds the whole budget (not cached — the publisher's
        query still ran, nothing is lost but the sharing)."""
        nbytes = table_bytes(table)
        with self._lock:
            if nbytes > self.budget_bytes:
                return False
            self._drop(value_key)
            while self._bytes + nbytes > self.budget_bytes and \
                    self._entries:
                old, _ = self._entries.popitem(last=False)
                self._bytes -= _[2]
                self.evictions += 1
                _obs_inc("engine.spine.evict")
            self._entries[value_key] = [state, table, nbytes]
            self._bytes += nbytes
            return True

    def _drop(self, value_key: str) -> None:
        ent = self._entries.pop(value_key, None)
        if ent is not None:
            self._bytes -= ent[2]


def _obs_inc(name: str, value: float = 1) -> None:
    from ndstpu import obs
    obs.inc(name, value)


def replace_nodes(plan: lp.Plan,
                  mapping: Dict[int, lp.Plan]) -> lp.Plan:
    """Non-mutating rebuild of ``plan`` with ``mapping[id(node)]``
    swapped in where present.  The cached plan object is shared across
    streams (Session._plan_cache), so splicing must never touch it."""
    r = mapping.get(id(plan))
    if r is not None:
        return r
    if isinstance(plan, (lp.Join, lp.SetOp)):
        return dataclasses.replace(
            plan,
            left=replace_nodes(plan.left, mapping),
            right=replace_nodes(plan.right, mapping))
    child = getattr(plan, "child", None)
    if isinstance(child, lp.Plan):
        return dataclasses.replace(plan,
                                   child=replace_nodes(child, mapping))
    return plan
