"""The TPU columnar SQL engine.

Pipeline: SQL text -> AST (sql/) -> logical plan (planner) -> optimized plan
(optimizer) -> physical execution (physical/kernels) on numpy (reference
interpreter) or JAX/XLA (TPU path).
"""
