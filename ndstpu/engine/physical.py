"""Physical execution of logical plans — numpy reference interpreter.

This is the engine's exact-semantics path: it executes any supported plan on
host numpy arrays with Spark-compatible NULL, decimal and ordering semantics.
It doubles as the differential baseline for the TPU path (the analog of the
reference's CPU-Spark-vs-GPU-rapids validation, nds_validate.py).

Algorithms are all vectorized columnar:
  joins        sort+searchsorted two-sided expansion (supports N:M)
  group-by     per-key factorize -> mixed-radix combine -> bincount/reduceat
  rollup       re-aggregation per grouping set
  windows      partition factorize -> lexsort -> segmented scans
  sort         numpy lexsort, Spark null ordering (asc=NULLS FIRST)
"""

from __future__ import annotations

import fnmatch
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ndstpu.engine import columnar, expr as ex, plan as lp
from ndstpu.engine.columnar import (
    BOOL,
    FLOAT64,
    INT32,
    INT64,
    STRING,
    Column,
    Table,
    decimal,
)


def scalar_subquery_literal(t: Table,
                            too_many: type = RuntimeError) -> "ex.Expr":
    """First column of a (<=1)-row table as a Literal — the inlining
    step for uncorrelated scalar subqueries, shared by the host
    interpreter and the distributed offload path (dplan)."""
    col = t.columns[t.column_names[0]]
    if t.num_rows == 0:
        return ex.Literal(None, col.ctype)
    vals = col.to_pylist()
    if len(vals) > 1:
        raise too_many("scalar subquery returned >1 row")
    return ex.Literal(vals[0], col.ctype)


class Executor:
    def __init__(self, catalog):
        self.catalog = catalog
        self._subq_cache: Dict[int, ex.Expr] = {}

    # -- entry ---------------------------------------------------------------

    def execute(self, p: lp.Plan) -> Table:
        m = getattr(self, "_exec_" + type(p).__name__.lower())
        return m(p)

    # -- leaves --------------------------------------------------------------

    def _exec_scan(self, p: lp.Scan) -> Table:
        t = self.catalog.get(p.table)
        if p.predicate is not None:
            t = t.filter(ex.eval_predicate(
                t, self._resolve_subqueries(p.predicate)))
        if p.columns is not None:
            cols = list(p.columns) or t.column_names[:1]  # row-count carrier
            t = t.select(cols)
        return t

    def _exec_inlinetable(self, p: lp.InlineTable) -> Table:
        return p.table

    def _exec_subqueryalias(self, p: lp.SubqueryAlias) -> Table:
        t = self.execute(p.child)
        if p.column_aliases:
            t = Table(dict(zip(p.column_aliases, t.columns.values())))
        return t

    # -- subquery resolution -------------------------------------------------

    def _resolve_subqueries(self, e: ex.Expr) -> ex.Expr:
        """Execute uncorrelated scalar/IN subqueries once and inline the
        result (the planner leaves them as SubqueryExpr leaves)."""
        if isinstance(e, ex.SubqueryExpr):
            if id(e) in self._subq_cache:
                return self._subq_cache[id(e)]
            resolved = self._resolve_subquery_once(e)
            self._subq_cache[id(e)] = resolved
            return resolved
        if isinstance(e, ex.BinOp):
            return ex.BinOp(e.op, self._resolve_subqueries(e.left),
                            self._resolve_subqueries(e.right))
        if isinstance(e, ex.UnaryOp):
            return ex.UnaryOp(e.op, self._resolve_subqueries(e.operand))
        if isinstance(e, ex.Cast):
            return ex.Cast(self._resolve_subqueries(e.operand), e.target)
        if isinstance(e, ex.Func):
            return ex.Func(e.name, tuple(self._resolve_subqueries(a)
                                         for a in e.args))
        if isinstance(e, ex.Case):
            return ex.Case(
                tuple((self._resolve_subqueries(c),
                       self._resolve_subqueries(v)) for c, v in e.whens),
                self._resolve_subqueries(e.default)
                if e.default is not None else None)
        if isinstance(e, ex.InList):
            return ex.InList(self._resolve_subqueries(e.operand), e.values,
                             e.negated)
        return e

    def _resolve_subquery_once(self, e: ex.SubqueryExpr) -> ex.Expr:
        t = self.execute(e.plan)
        col = t.columns[t.column_names[0]]
        if e.kind == "scalar":
            return scalar_subquery_literal(t)
        if e.kind == "in":
            pyvals = col.to_pylist()
            has_null = any(v is None for v in pyvals)
            vals = tuple(v for v in pyvals if v is not None)
            if e.negated and has_null:
                # SQL 3VL: x NOT IN (..., NULL) is never TRUE
                return ex.Literal(False)
            return ex.InList(self._resolve_subqueries(e.operand), vals,
                             e.negated)
        raise NotImplementedError(f"subquery kind {e.kind}")

    # -- row ops -------------------------------------------------------------

    def _exec_filter(self, p: lp.Filter) -> Table:
        t = self.execute(p.child)
        return t.filter(ex.eval_predicate(
            t, self._resolve_subqueries(p.condition)))

    def _exec_project(self, p: lp.Project) -> Table:
        t = self.execute(p.child)
        ev = ex.Evaluator(t)
        return Table({name: ev.eval(self._resolve_subqueries(e))
                      for name, e in p.exprs})

    def _exec_limit(self, p: lp.Limit) -> Table:
        return self.execute(p.child).head(p.n)

    # -- join ----------------------------------------------------------------

    def _join_key_array(self, t: Table, exprs: Sequence[ex.Expr],
                        other: Optional[List[Column]] = None):
        """Evaluate join key exprs to comparable numpy arrays + validity."""
        ev = ex.Evaluator(t)
        cols = [ev.eval(e) for e in exprs]
        return cols

    def _align_key_pair(self, lc: Column, rc: Column):
        if lc.ctype.kind == "string" or rc.ctype.kind == "string":
            merged = columnar.merge_dictionaries([lc, rc])
            return (columnar.translate_codes(lc, merged).astype(np.int64),
                    columnar.translate_codes(rc, merged).astype(np.int64))
        if lc.ctype.kind == "decimal" or rc.ctype.kind == "decimal":
            s = max(lc.ctype.scale if lc.ctype.kind == "decimal" else 0,
                    rc.ctype.scale if rc.ctype.kind == "decimal" else 0)
            t = decimal(38, s)
            return (ex.cast_column(lc, t).data.astype(np.int64),
                    ex.cast_column(rc, t).data.astype(np.int64))
        return lc.data.astype(np.int64), rc.data.astype(np.int64)

    def _composite_keys(self, lt: Table, rt: Table,
                        keys: List[Tuple[ex.Expr, ex.Expr]]):
        lcols = [ex.Evaluator(lt).eval(le) for le, _ in keys]
        rcols = [ex.Evaluator(rt).eval(re_) for _, re_ in keys]
        lvalid = np.ones(lt.num_rows, dtype=bool)
        rvalid = np.ones(rt.num_rows, dtype=bool)
        lparts, rparts = [], []
        for lc, rc in zip(lcols, rcols):
            la, ra = self._align_key_pair(lc, rc)
            lvalid &= lc.validity()
            rvalid &= rc.validity()
            lparts.append(la)
            rparts.append(ra)
        # factorize each part jointly so composite fits in int64
        lkey = np.zeros(lt.num_rows, dtype=np.int64)
        rkey = np.zeros(rt.num_rows, dtype=np.int64)
        for la, ra in zip(lparts, rparts):
            both = np.concatenate([la, ra])
            uniq, inv = np.unique(both, return_inverse=True)
            k = len(uniq) + 1
            lkey = lkey * k + inv[:len(la)] + 1
            rkey = rkey * k + inv[len(la):] + 1
        return lkey, rkey, lvalid, rvalid

    def _exec_join(self, p: lp.Join) -> Table:
        lt = self.execute(p.left)
        rt = self.execute(p.right)
        kind = p.kind
        if kind == "cross" or not p.keys:
            out = self._cross_join(lt, rt)
            if p.extra is not None and kind in ("inner", "cross"):
                out = out.filter(ex.eval_predicate(out, p.extra))
                return out
            if kind in ("inner", "cross"):
                return out
            # non-equi outer joins: fall back to per-kind handling below
            raise NotImplementedError(f"non-equi {kind} join")
        lkey, rkey, lvalid, rvalid = self._composite_keys(lt, rt, p.keys)
        if kind == "nullaware_anti":
            # NOT IN semantics: any NULL on the subquery side -> no row can
            # satisfy NOT IN; a NULL probe value never qualifies either —
            # unless the subquery is EMPTY, where NOT IN is vacuously TRUE
            # for every probe including NULL.
            if bool((~rvalid).any()):
                return lt.filter(np.zeros(lt.num_rows, dtype=bool))
            kind = "anti"
            if rt.num_rows > 0:
                lt = lt.filter(lvalid)
                lkey = lkey[lvalid]
                lvalid = np.ones(len(lkey), dtype=bool)
        # null keys never match
        lkey = np.where(lvalid, lkey, -1)
        rkey = np.where(rvalid, rkey, -2)

        order = np.argsort(rkey, kind="stable")
        rsorted = rkey[order]
        lo = np.searchsorted(rsorted, lkey, side="left")
        hi = np.searchsorted(rsorted, lkey, side="right")
        counts = (hi - lo)
        matched = counts > 0

        if kind == "mark":
            mask = matched
            if p.extra is not None:
                inner = self._expand_join(lt, rt, order, lo, hi, counts)
                keep = ex.eval_predicate(inner, p.extra)
                li = self._expand_left_indices(counts)[keep]
                mask = np.zeros(lt.num_rows, dtype=bool)
                mask[li] = True
            return Table({**lt.columns,
                          p.mark: Column(mask, BOOL)})

        if kind in ("semi", "anti"):
            mask = matched if kind == "semi" else ~matched
            if p.extra is not None and kind == "semi":
                # re-run as inner join + distinct-left for residual predicate
                inner = self._expand_join(lt, rt, order, lo, hi, counts)
                keep = ex.eval_predicate(inner, p.extra)
                li = self._expand_left_indices(counts)[keep]
                mask = np.zeros(lt.num_rows, dtype=bool)
                mask[li] = True
            elif p.extra is not None and kind == "anti":
                inner = self._expand_join(lt, rt, order, lo, hi, counts)
                keep = ex.eval_predicate(inner, p.extra)
                li = self._expand_left_indices(counts)[keep]
                mask = np.ones(lt.num_rows, dtype=bool)
                mask[li] = False
            return lt.filter(mask)

        if kind == "inner":
            out = self._expand_join(lt, rt, order, lo, hi, counts)
            if p.extra is not None:
                out = out.filter(ex.eval_predicate(out, p.extra))
            return out

        if kind == "left":
            return self._left_join(lt, rt, order, lo, hi, counts, p.extra)
        if kind == "right":
            flipped = lp.Join(p.right, p.left, "left",
                              [(r, l) for l, r in p.keys], p.extra)
            out = self._exec_join_pre(rt, lt, flipped)
            # restore column order: left table columns first
            names = list(lt.columns) + list(rt.columns)
            return Table({n: out.columns[n] for n in names})
        if kind == "full":
            left_part = self._left_join(lt, rt, order, lo, hi, counts, p.extra)
            # right rows with no left match
            rorder = np.argsort(lkey, kind="stable")
            lsorted = lkey[rorder]
            rmatched = np.searchsorted(lsorted, rkey, "left") != \
                np.searchsorted(lsorted, rkey, "right")
            runmatched = rt.filter(~rmatched)
            nullleft = self._null_table(lt, runmatched.num_rows)
            bottom = Table({**nullleft.columns, **runmatched.columns})
            return Table.concat([left_part, bottom])
        raise NotImplementedError(f"join kind {kind}")

    def _exec_join_pre(self, lt, rt, p: lp.Join) -> Table:
        lkey, rkey, lvalid, rvalid = self._composite_keys(lt, rt, p.keys)
        lkey = np.where(lvalid, lkey, -1)
        rkey = np.where(rvalid, rkey, -2)
        order = np.argsort(rkey, kind="stable")
        rsorted = rkey[order]
        lo = np.searchsorted(rsorted, lkey, side="left")
        hi = np.searchsorted(rsorted, lkey, side="right")
        counts = hi - lo
        return self._left_join(lt, rt, order, lo, hi, counts, p.extra)

    @staticmethod
    def _expand_left_indices(counts: np.ndarray) -> np.ndarray:
        return np.repeat(np.arange(len(counts)), counts)

    @staticmethod
    def _expand_right_positions(lo, counts) -> np.ndarray:
        total = int(counts.sum())
        if total == 0:
            return np.empty(0, dtype=np.int64)
        # ragged arange: for each left row i, positions lo[i]..lo[i]+counts[i]
        idx = np.repeat(lo, counts)
        within = np.arange(total) - np.repeat(
            np.cumsum(counts) - counts, counts)
        return idx + within

    def _expand_join(self, lt, rt, order, lo, hi, counts) -> Table:
        li = self._expand_left_indices(counts)
        rpos = self._expand_right_positions(lo, counts)
        ri = order[rpos]
        return Table({**lt.gather(li).columns, **rt.gather(ri).columns})

    def _left_join(self, lt, rt, order, lo, hi, counts, extra) -> Table:
        li = self._expand_left_indices(counts)
        rpos = self._expand_right_positions(lo, counts)
        ri = order[rpos]
        matched_tbl = Table({**lt.gather(li).columns,
                             **rt.gather(ri).columns})
        if extra is not None:
            keep = ex.eval_predicate(matched_tbl, extra)
            matched_tbl = matched_tbl.filter(keep)
            li = li[keep]
        # left rows with zero surviving matches
        hitcount = np.bincount(li, minlength=lt.num_rows)
        unmatched = lt.filter(hitcount == 0)
        nullright = self._null_table(rt, unmatched.num_rows)
        bottom = Table({**unmatched.columns, **nullright.columns})
        return Table.concat([matched_tbl, bottom])

    @staticmethod
    def _null_table(template: Table, n: int) -> Table:
        cols = {}
        for name, c in template.columns.items():
            data = np.zeros(n, dtype=c.data.dtype)
            cols[name] = Column(data, c.ctype, np.zeros(n, dtype=bool),
                                c.dictionary)
        return Table(cols)

    def _cross_join(self, lt: Table, rt: Table) -> Table:
        li = np.repeat(np.arange(lt.num_rows), rt.num_rows)
        ri = np.tile(np.arange(rt.num_rows), lt.num_rows)
        return Table({**lt.gather(li).columns, **rt.gather(ri).columns})

    # -- aggregate -----------------------------------------------------------

    def _factorize(self, cols: List[Column]) -> Tuple[np.ndarray, np.ndarray]:
        """Composite group ids + representative first-row index per group."""
        n = len(cols[0].data) if cols else 0
        gid = np.zeros(n, dtype=np.int64)
        for c in cols:
            data = c.data.astype(np.int64)
            data = np.where(c.validity(), data, np.int64(-(2**62)))
            uniq, inv = np.unique(data, return_inverse=True)
            gid = gid * (len(uniq) + 1) + inv
            if len(uniq) + 1 > 2**31:
                uniq2, gid = np.unique(gid, return_inverse=True)
        uniq, first, inv = np.unique(gid, return_index=True,
                                     return_inverse=True)
        return inv.astype(np.int64), first

    def _exec_aggregate(self, p: lp.Aggregate) -> Table:
        t = self.execute(p.child)
        if p.grouping_sets is None:
            return self._aggregate_once(t, p.group_by, p.aggs, None)
        parts = []
        for subset in p.grouping_sets:
            parts.append(self._aggregate_once(t, p.group_by, p.aggs, subset))
        return Table.concat(parts)

    def _aggregate_once(self, t: Table, group_by, aggs,
                        subset: Optional[List[int]]) -> Table:
        ev = ex.Evaluator(t)
        key_cols = []
        for i, (name, e) in enumerate(group_by):
            c = ev.eval(e)
            if subset is not None and i not in subset:
                # excluded key in this grouping set -> all NULL
                c = Column(np.zeros_like(c.data), c.ctype,
                           np.zeros(len(c.data), dtype=bool), c.dictionary)
            key_cols.append((name, c))
        n = t.num_rows
        if key_cols:
            gids, first = self._factorize([c for _, c in key_cols])
            ngroups = len(first)
        else:
            gids = np.zeros(n, dtype=np.int64)
            first = np.array([0], dtype=np.int64) if n else np.array([0])
            ngroups = 1
        out: Dict[str, Column] = {}
        for name, c in key_cols:
            if n:
                out[name] = c.gather(first)
            else:
                out[name] = Column(np.zeros(0, c.data.dtype), c.ctype,
                                   np.zeros(0, dtype=bool), c.dictionary)
        self._grouping_ctx = ([n for n, _ in group_by], subset)
        for name, e in aggs:
            out[name] = self._eval_agg(t, e, gids, ngroups, n)
        if not key_cols and n == 0:
            # global aggregate over empty input still yields one row
            pass
        return Table(out)

    def _eval_agg(self, t: Table, e: ex.Expr, gids, ngroups, n) -> Column:
        """Evaluate an aggregate output expression — either a bare AggExpr or
        an arithmetic expression over AggExprs (e.g. sum(a)/sum(b))."""
        if isinstance(e, ex.AggExpr):
            return self._agg_column(t, e, gids, ngroups, n)
        if isinstance(e, ex.BinOp):
            lc = self._eval_agg(t, e.left, gids, ngroups, n)
            rc = self._eval_agg(t, e.right, gids, ngroups, n)
            tbl = Table({"__l": lc, "__r": rc})
            return ex.Evaluator(tbl).eval(
                ex.BinOp(e.op, ex.ColumnRef("__l"), ex.ColumnRef("__r")))
        if isinstance(e, ex.Cast):
            return ex.cast_column(
                self._eval_agg(t, e.operand, gids, ngroups, n), e.target)
        if isinstance(e, ex.Func):
            if e.name == "grouping":
                # grouping(key) = 0 when the key participates in this
                # grouping set, 1 when it was rolled up (Spark semantics)
                names, subset = self._grouping_ctx
                arg = e.args[0]
                idx = names.index(arg.name) if isinstance(
                    arg, ex.ColumnRef) and arg.name in names else -1
                active = subset is None or idx in subset
                return Column(
                    np.full(ngroups, 0 if active else 1, np.int32), INT32)
            cols = {f"__a{i}": self._eval_agg(t, a, gids, ngroups, n)
                    for i, a in enumerate(e.args)}
            tbl = Table(cols)
            return ex.Evaluator(tbl).eval(
                ex.Func(e.name, tuple(ex.ColumnRef(f"__a{i}")
                                      for i in range(len(e.args)))))
        if isinstance(e, ex.Case):
            # CASE over aggregate results
            whens = []
            cols = {}
            idx = 0

            def sub(expr):
                nonlocal idx
                name = f"__c{idx}"
                idx += 1
                cols[name] = self._eval_agg(t, expr, gids, ngroups, n)
                return ex.ColumnRef(name)
            whens = tuple((sub(c), sub(v)) for c, v in e.whens)
            default = sub(e.default) if e.default is not None else None
            return ex.Evaluator(Table(cols)).eval(ex.Case(whens, default))
        if isinstance(e, ex.Literal):
            return ex.literal_column(e.value, ngroups, e.ctype)
        if isinstance(e, ex.Param):
            vals = ex.active_params()
            if vals is None or e.shape:
                raise NotImplementedError(f"unbound parameter S{e.slot}")
            return ex.literal_column(vals[e.slot], ngroups, e.ctype)
        raise NotImplementedError(f"aggregate output expr {e}")

    def _agg_column(self, t: Table, a: ex.AggExpr, gids, ngroups,
                    n) -> Column:
        func = a.func
        if isinstance(a.arg, ex.Star):
            counts = np.bincount(gids, minlength=ngroups) if n else \
                np.zeros(ngroups, dtype=np.int64)
            return Column(counts.astype(np.int64), INT64)
        c = ex.Evaluator(t).eval(a.arg)
        valid = c.validity()
        if a.distinct:
            # keep one row per (gid, value); the dedup key must not lose
            # precision — float64 dedups on its bit pattern (matching the
            # device path's _key_i64), never an int cast
            vidx = np.nonzero(valid)[0] if n else np.zeros(0, np.int64)
            g = gids[vidx]
            v = c.data[vidx]
            if c.ctype.kind == "float64":
                # bit-pattern key with -0.0 folded onto +0.0 and NaNs
                # canonicalized (SQL equality; matches the device path's
                # _key_i64 float handling)
                vc = np.where(np.isnan(v), np.finfo(np.float64).max, v)
                key = np.where(vc == 0, np.int64(0), vc.view(np.int64))
            else:
                key = v.astype(np.int64)
            comp = np.stack([g, key], axis=1) if len(vidx) else \
                np.zeros((0, 2), dtype=np.int64)
            _, uidx = np.unique(comp, axis=0, return_index=True)
            sub_g = g[uidx]
            sub_v = v[uidx]
            if func == "count":
                counts = np.bincount(sub_g, minlength=ngroups)
                return Column(counts.astype(np.int64), INT64)
            got = np.bincount(sub_g, minlength=ngroups) > 0
            if func == "sum":
                if c.ctype.kind in ("decimal", "int32", "int64"):
                    sums = np.zeros(ngroups, dtype=np.int64)
                    np.add.at(sums, sub_g, sub_v.astype(np.int64))
                else:
                    sums = np.bincount(
                        sub_g, weights=sub_v.astype(np.float64),
                        minlength=ngroups)
                return self._sum_result(c, sums, got)
            if func == "avg":
                sums = np.bincount(sub_g, weights=sub_v.astype(np.float64),
                                   minlength=ngroups)
                cnts = np.bincount(sub_g, minlength=ngroups)
                return self._avg_result(c, sums, cnts)
            raise NotImplementedError(f"distinct {func}")
        if func == "count":
            counts = np.bincount(gids[valid], minlength=ngroups) if n else \
                np.zeros(ngroups, dtype=np.int64)
            return Column(counts.astype(np.int64), INT64)
        got = (np.bincount(gids[valid], minlength=ngroups) > 0) if n else \
            np.zeros(ngroups, dtype=bool)
        if func == "sum":
            if n:
                if c.ctype.kind in ("decimal", "int32", "int64"):
                    sums = np.zeros(ngroups, dtype=np.int64)
                    np.add.at(sums, gids[valid],
                              c.data[valid].astype(np.int64))
                else:
                    sums = np.bincount(
                        gids[valid],
                        weights=c.data[valid].astype(np.float64),
                        minlength=ngroups)
            else:
                sums = np.zeros(ngroups)
            return self._sum_result(c, sums, got)
        if func == "avg":
            if n:
                sums = np.bincount(gids[valid],
                                   weights=c.data[valid].astype(np.float64),
                                   minlength=ngroups)
                cnts = np.bincount(gids[valid], minlength=ngroups)
            else:
                sums = np.zeros(ngroups)
                cnts = np.zeros(ngroups, dtype=np.int64)
            return self._avg_result(c, sums, cnts)
        if func in ("min", "max"):
            if not n:
                return Column(np.zeros(ngroups, c.data.dtype), c.ctype,
                              np.zeros(ngroups, dtype=bool), c.dictionary)
            if c.ctype.kind == "string":
                data = c.data.astype(np.int64)
            else:
                data = c.data
            out = np.zeros(ngroups, dtype=data.dtype)
            init = (np.iinfo(data.dtype).max if data.dtype.kind in "iu"
                    else np.inf) if func == "min" else \
                   (np.iinfo(data.dtype).min if data.dtype.kind in "iu"
                    else -np.inf)
            out[:] = init
            opfn = np.minimum if func == "min" else np.maximum
            opfn.at(out, gids[valid], data[valid])
            return Column(out.astype(c.data.dtype), c.ctype,
                          None if got.all() else got, c.dictionary)
        if func in ("stddev_samp", "var_samp", "stddev", "variance"):
            # shifted two-pass moments: raw E[x^2]-E[x]^2 cancels
            # catastrophically when mean >> stddev (nds_validate's 1e-5
            # epsilon fails at large SF); centering by the group mean
            # keeps full precision, with the (sum d)^2/n correction
            # absorbing the mean's own rounding.
            x = ex.cast_column(c, FLOAT64).data
            if n:
                cnt = np.bincount(gids[valid], minlength=ngroups)
                s1 = np.bincount(gids[valid], weights=x[valid],
                                 minlength=ngroups)
                mean = s1 / np.maximum(cnt, 1)
                d = x[valid] - mean[gids[valid]]
                d1 = np.bincount(gids[valid], weights=d,
                                 minlength=ngroups)
                d2 = np.bincount(gids[valid], weights=d * d,
                                 minlength=ngroups)
            else:
                d1 = d2 = np.zeros(ngroups)
                cnt = np.zeros(ngroups, dtype=np.int64)
            ok = cnt > 1
            denom = np.where(ok, cnt - 1, 1)
            var = np.maximum(
                (d2 - np.where(cnt > 0, d1 ** 2 / np.maximum(cnt, 1), 0.0)),
                0.0) / denom
            data = var if func in ("var_samp", "variance") else np.sqrt(var)
            return Column(data, FLOAT64, None if ok.all() else ok)
        raise NotImplementedError(f"aggregate {func}")

    def _sum_result(self, c: Column, sums: np.ndarray,
                    got: np.ndarray) -> Column:
        vopt = None if got.all() else got
        if c.ctype.kind == "decimal":
            return Column(sums.astype(np.int64),
                          decimal(38, c.ctype.scale), vopt)
        if c.ctype.kind in ("int32", "int64"):
            return Column(sums.astype(np.int64), INT64, vopt)
        return Column(sums.astype(np.float64), FLOAT64, vopt)

    def _avg_result(self, c: Column, sums: np.ndarray,
                    cnts: np.ndarray) -> Column:
        got = cnts > 0
        denom = np.maximum(cnts, 1)
        if c.ctype.kind == "decimal":
            data = sums / denom / (10 ** c.ctype.scale)
        else:
            data = sums / denom
        return Column(data, FLOAT64, None if got.all() else got)

    # -- distinct / set ops --------------------------------------------------

    def _row_ids(self, t: Table) -> np.ndarray:
        gids, _ = self._factorize(list(t.columns.values()))
        return gids

    def _exec_distinct(self, p: lp.Distinct) -> Table:
        t = self.execute(p.child)
        if t.num_rows == 0:
            return t
        gids, first = self._factorize(list(t.columns.values()))
        return t.gather(np.sort(first))

    def _exec_setop(self, p: lp.SetOp) -> Table:
        lt = self.execute(p.left)
        rt = self.execute(p.right)
        rt = Table(dict(zip(lt.column_names, rt.columns.values())))
        if p.kind == "union":
            both = Table.concat([lt, rt])
            if p.all:
                return both
            return self._exec_distinct(lp.Distinct(lp.InlineTable(both)))
        both = Table.concat([lt, rt])
        gids, first = self._factorize(list(both.columns.values()))
        nl = lt.num_rows
        in_left = np.zeros(gids.max() + 1 if len(gids) else 0, dtype=bool)
        in_right = np.zeros_like(in_left)
        if len(gids):
            in_left[gids[:nl]] = True
            in_right[gids[nl:]] = True
        if p.kind == "intersect":
            keepg = in_left & in_right
        else:  # except
            keepg = in_left & ~in_right
        # representative first row from the left side per kept group
        lt_gids = gids[:nl]
        seen = np.zeros_like(in_left)
        keep_rows = np.zeros(nl, dtype=bool)
        if nl:
            firstl = np.full(len(in_left), -1, dtype=np.int64)
            # first occurrence per group on left side
            rev = np.arange(nl - 1, -1, -1)
            firstl[lt_gids[rev]] = rev
            sel = firstl[(firstl >= 0) & keepg[np.arange(len(firstl))]] \
                if len(firstl) else np.empty(0, np.int64)
            keep_rows[sel.astype(np.int64)] = True
        return lt.filter(keep_rows)

    # -- window --------------------------------------------------------------

    def _exec_window(self, p: lp.Window) -> Table:
        t = self.execute(p.child)
        out = dict(t.columns)
        for name, e in p.exprs:
            assert isinstance(e, ex.WindowExpr)
            out[name] = self._window_column(t, e)
        return Table(out)

    def _window_column(self, t: Table, w: ex.WindowExpr) -> Column:
        n = t.num_rows
        ev = ex.Evaluator(t)
        if w.partition_by:
            pcols = [ev.eval(e) for e in w.partition_by]
            pid, _ = self._factorize(pcols)
        else:
            pid = np.zeros(n, dtype=np.int64)
        okeys = [self._order_key(ev.eval(e), asc) for e, asc in w.order_by]
        # lexsort: LAST key is primary -> (reversed order keys, then pid)
        order = np.lexsort(okeys[::-1] + [pid]) if n else np.zeros(0, np.int64)
        inv = np.empty(n, dtype=np.int64)
        inv[order] = np.arange(n)
        pid_s = pid[order]
        newpart = np.ones(n, dtype=bool)
        if n > 1:
            newpart[1:] = pid_s[1:] != pid_s[:-1]
        pos_in_part = np.arange(n) - np.maximum.accumulate(
            np.where(newpart, np.arange(n), 0))
        if w.func == "row_number":
            return Column((pos_in_part + 1)[inv].astype(np.int64), INT64)
        if w.func in ("rank", "dense_rank"):
            okeys = [a[order] for a in okeys]
            tie = np.zeros(n, dtype=bool)
            if n > 1:
                tie[1:] = np.ones(n - 1, dtype=bool)
                for a in okeys:
                    tie[1:] &= a[1:] == a[:-1]
                tie[1:] &= ~newpart[1:]
            if w.func == "rank":
                # rank = 1 + pos of the first row of the current tie run;
                # tie is False at partition starts so the forward fill of the
                # last non-tie index never crosses partitions
                idx = np.arange(n)
                last_nontie = np.maximum.accumulate(np.where(~tie, idx, -1))
                ranks = pos_in_part[last_nontie] + 1
            else:
                incr = (~tie).astype(np.int64)
                incr = np.where(newpart, 0, incr)
                # dense rank: cumulative distinct count within partition
                c = np.cumsum(incr)
                base = np.maximum.accumulate(np.where(newpart, c, 0))
                ranks = c - base + 1
            return Column(ranks[inv].astype(np.int64), INT64)
        # aggregate window: whole partition without ORDER BY; with ORDER BY
        # a running UNBOUNDED PRECEDING..CURRENT ROW frame (Spark default
        # RANGE — peers share the run value; explicit ROWS = per-row)
        arg = ev.eval(w.arg) if w.arg is not None and \
            not isinstance(w.arg, ex.Star) else None
        if w.order_by:
            return self._running_window(w, arg, pid, order, inv, newpart,
                                        okeys)
        if w.func == "count" and arg is None:
            cnt = np.bincount(pid, minlength=int(pid.max()) + 1 if n else 0)
            return Column(cnt[pid].astype(np.int64), INT64)
        valid = arg.validity()
        x = arg.data.astype(np.float64)
        ng = int(pid.max()) + 1 if n else 0
        sums = np.bincount(pid[valid], weights=x[valid], minlength=ng)
        cnts = np.bincount(pid[valid], minlength=ng)
        if w.func == "sum":
            got = cnts[pid] > 0
            if arg.ctype.kind == "decimal":
                tot = np.zeros(ng, dtype=np.int64)
                np.add.at(tot, pid[valid], arg.data[valid].astype(np.int64))
                return Column(tot[pid], decimal(38, arg.ctype.scale),
                              None if got.all() else got)
            return Column(sums[pid], FLOAT64, None if got.all() else got)
        if w.func == "avg":
            got = cnts[pid] > 0
            mean = sums / np.maximum(cnts, 1)
            if arg.ctype.kind == "decimal":
                mean = mean / (10 ** arg.ctype.scale)
            return Column(mean[pid], FLOAT64, None if got.all() else got)
        if w.func in ("min", "max"):
            data = arg.data
            out = np.full(ng, np.iinfo(np.int64).max if func_min(w.func)
                          else np.iinfo(np.int64).min, dtype=np.int64)
            opfn = np.minimum if w.func == "min" else np.maximum
            opfn.at(out, pid[valid], data[valid].astype(np.int64))
            got = cnts[pid] > 0
            return Column(out[pid].astype(arg.data.dtype), arg.ctype,
                          None if got.all() else got, arg.dictionary)
        if w.func == "count":
            return Column(cnts[pid].astype(np.int64), INT64)
        raise NotImplementedError(f"window {w.func}")

    def _running_window(self, w: ex.WindowExpr, arg: Optional[Column],
                        pid: np.ndarray, order: np.ndarray,
                        inv: np.ndarray, newpart: np.ndarray,
                        okeys: List[np.ndarray]) -> Column:
        """UNBOUNDED PRECEDING..CURRENT ROW running aggregate (q51 shape).
        RANGE (the default) lets peer rows share the value of the last row
        of their tie-run; explicit ROWS is strictly per-row."""
        n = len(pid)
        idx = np.arange(n)
        pstart = np.maximum.accumulate(np.where(newpart, idx, 0))
        use_peers = w.frame != "rows"
        if use_peers:
            okeys_s = [a[order] for a in okeys]
            tie = np.zeros(n, dtype=bool)
            if n > 1:
                t = np.ones(n - 1, dtype=bool)
                for a in okeys_s:
                    t &= a[1:] == a[:-1]
                tie[1:] = t & ~newpart[1:]
            end_marker = np.ones(n, dtype=bool)
            if n > 1:
                end_marker[:-1] = ~tie[1:]
            run_end = np.minimum.accumulate(
                np.where(end_marker, idx, n)[::-1])[::-1]
        else:
            run_end = idx

        def seg_cumsum(x):
            cs = np.cumsum(x)
            base = np.where(pstart > 0, cs[np.maximum(pstart - 1, 0)], 0)
            return cs - base

        if arg is None:  # count(*)
            run = seg_cumsum(np.ones(n, dtype=np.int64))[run_end]
            return Column(run[inv].astype(np.int64), INT64)
        valid_s = arg.validity()[order]
        data_s = arg.data[order]
        rcnt = seg_cumsum(valid_s.astype(np.int64))[run_end]
        got = rcnt > 0
        gv = None if got.all() else got[inv]
        if w.func == "count":
            return Column(rcnt[inv].astype(np.int64), INT64)
        if w.func == "sum" and arg.ctype.kind == "decimal":
            run = seg_cumsum(
                np.where(valid_s, data_s.astype(np.int64), 0))[run_end]
            return Column(run[inv], decimal(38, arg.ctype.scale), gv)
        if w.func in ("sum", "avg"):
            x = np.where(valid_s, data_s.astype(np.float64), 0.0)
            if arg.ctype.kind == "decimal":
                x = x / (10 ** arg.ctype.scale)
            run = seg_cumsum(x)[run_end]
            if w.func == "avg":
                run = run / np.maximum(rcnt, 1)
            return Column(run[inv], FLOAT64, gv)
        if w.func in ("min", "max"):
            is_min = w.func == "min"
            opfn = np.minimum if is_min else np.maximum
            if arg.ctype.kind == "float64":
                sent = np.inf if is_min else -np.inf
                x = np.where(valid_s, data_s.astype(np.float64), sent)
            else:
                sent = np.iinfo(np.int64).max if is_min \
                    else np.iinfo(np.int64).min
                x = np.where(valid_s, data_s.astype(np.int64), sent)
            out = x.copy()
            shift = 1
            while shift < n:
                cand = np.empty_like(out)
                cand[shift:] = out[:-shift]
                cand[:shift] = sent
                take = (idx - shift) >= pstart
                out = np.where(take, opfn(out, cand), out)
                shift *= 2
            out = out[run_end]
            return Column(out[inv].astype(arg.data.dtype), arg.ctype, gv,
                          arg.dictionary)
        raise NotImplementedError(f"running window {w.func}")

    # -- sort ----------------------------------------------------------------

    def _order_key(self, c: Column, asc: bool,
                   nulls_first: Optional[bool] = None) -> np.ndarray:
        """Sortable key array.  Spark default null ordering: ASC -> NULLS
        FIRST, DESC -> NULLS LAST; explicit NULLS FIRST/LAST overrides."""
        if nulls_first is None:
            nulls_first = asc
        v = c.validity()
        if c.ctype.kind == "float64":
            data = c.data.astype(np.float64)
            key = data if asc else -data
            return np.where(v, key, -np.inf if nulls_first else np.inf)
        data = c.data.astype(np.int64)
        key = data if asc else -data
        return np.where(v, key,
                        np.int64(-2**62) if nulls_first else np.int64(2**62))

    def _exec_sort(self, p: lp.Sort) -> Table:
        t = self.execute(p.child)
        if t.num_rows == 0:
            return t
        ev = ex.Evaluator(t)
        keys = []
        for entry in p.keys:
            e, asc = entry[0], entry[1]
            nf = entry[2] if len(entry) > 2 else None
            keys.append(self._order_key(ev.eval(e), asc, nf))
        order = np.lexsort(keys[::-1])
        return t.gather(order)


def func_min(name: str) -> bool:
    return name == "min"


def execute(plan: lp.Plan, catalog) -> Table:
    return Executor(catalog).execute(plan)
