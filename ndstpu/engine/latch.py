"""Per-key in-flight latch: "compile once, others wait".

The in-process throughput scheduler (ndstpu/harness/scheduler.py) runs
N stream threads against ONE Session/JaxExecutor.  Two streams hitting
the same query text concurrently must not both pay the plan/compile —
the first holds the key's latch while it builds, later arrivals block
on the latch and then find the entry in the (now-populated) cache.

A failed build must not poison anything: the latch is released in
``finally`` and nothing is cached, so the next arrival simply retries
the build itself.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Dict


class KeyedLatch:
    """A dynamic set of per-key re-entrant mutexes.

    ``holding(key)`` is a context manager that serializes all holders
    of the same key while holders of different keys proceed
    concurrently.  Re-entrant per thread (a query plan that recurses
    into the session under the same key must not self-deadlock).
    Lock objects are refcounted and dropped when the last holder
    leaves, so the map cannot grow beyond the live key set.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._latches: Dict[object, list] = {}  # key -> [RLock, refcount]

    @contextlib.contextmanager
    def holding(self, key):
        with self._lock:
            ent = self._latches.get(key)
            if ent is None:
                ent = self._latches[key] = [threading.RLock(), 0]
            ent[1] += 1
        ent[0].acquire()
        try:
            yield
        finally:
            ent[0].release()
            with self._lock:
                ent[1] -= 1
                if ent[1] == 0 and self._latches.get(key) is ent:
                    del self._latches[key]

    def __len__(self) -> int:
        with self._lock:
            return len(self._latches)
