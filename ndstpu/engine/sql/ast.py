"""SQL AST nodes (parser output, planner input)."""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple


class Node:
    pass


# -- expressions -------------------------------------------------------------


@dataclasses.dataclass
class Col(Node):
    table: Optional[str]
    name: str

    def __repr__(self):
        return f"{self.table}.{self.name}" if self.table else self.name


@dataclasses.dataclass
class Lit(Node):
    value: object  # int/float/str/bool/None

    def __repr__(self):
        return repr(self.value)


@dataclasses.dataclass
class DateLit(Node):
    value: str  # 'YYYY-MM-DD'


@dataclasses.dataclass
class Interval(Node):
    n: int
    unit: str  # days, months, years


@dataclasses.dataclass
class Bin(Node):
    op: str
    left: Node
    right: Node


@dataclasses.dataclass
class Un(Node):
    op: str  # not, neg
    operand: Node


@dataclasses.dataclass
class IsNull(Node):
    operand: Node
    negated: bool


@dataclasses.dataclass
class Between(Node):
    operand: Node
    lo: Node
    hi: Node
    negated: bool


@dataclasses.dataclass
class InVals(Node):
    operand: Node
    values: List[Node]
    negated: bool


@dataclasses.dataclass
class InQuery(Node):
    operand: Node
    query: "Query"
    negated: bool


@dataclasses.dataclass
class Exists(Node):
    query: "Query"
    negated: bool


@dataclasses.dataclass
class ScalarQuery(Node):
    query: "Query"


@dataclasses.dataclass
class MarkRef(Node):
    """Planner-internal: reference to a mark-join boolean column (the
    residue of an EXISTS planned as a mark join).  Never produced by the
    parser."""
    name: str


@dataclasses.dataclass
class LikeOp(Node):
    operand: Node
    pattern: str
    negated: bool


@dataclasses.dataclass
class FuncCall(Node):
    name: str
    args: List[Node]
    distinct: bool = False
    star: bool = False  # count(*)


@dataclasses.dataclass
class WindowCall(Node):
    func: FuncCall
    partition_by: List[Node]
    order_by: List[Tuple[Node, bool]]  # (expr, asc)
    # explicit frame: "rows" | "range" (UNBOUNDED PRECEDING..CURRENT ROW);
    # None = default (running RANGE frame when order_by present, Spark)
    frame: "str | None" = None


@dataclasses.dataclass
class CaseExpr(Node):
    operand: Optional[Node]  # CASE x WHEN v ... (simple form)
    whens: List[Tuple[Node, Node]]
    default: Optional[Node]


@dataclasses.dataclass
class CastExpr(Node):
    operand: Node
    type_name: str  # e.g. "integer", "decimal(7,2)", "date", "char(10)"


@dataclasses.dataclass
class StarExpr(Node):
    table: Optional[str] = None  # t.* or *


# -- relations ---------------------------------------------------------------


@dataclasses.dataclass
class TableRef(Node):
    name: str
    alias: Optional[str]


@dataclasses.dataclass
class SubqueryRef(Node):
    query: "Query"
    alias: str
    column_aliases: Optional[List[str]] = None


@dataclasses.dataclass
class JoinRef(Node):
    left: Node
    right: Node
    kind: str  # inner, left, right, full, cross
    condition: Optional[Node]  # ON expr


# -- query -------------------------------------------------------------------


@dataclasses.dataclass
class SelectItem(Node):
    expr: Node
    alias: Optional[str]


@dataclasses.dataclass
class GroupSpec(Node):
    exprs: List[Node]
    kind: str = "plain"  # plain, rollup, cube, sets
    sets: Optional[List[List[Node]]] = None  # for grouping sets


@dataclasses.dataclass
class Select(Node):
    items: List[SelectItem]
    from_: Optional[Node]  # TableRef/SubqueryRef/JoinRef (comma joins folded)
    where: Optional[Node]
    group: Optional[GroupSpec]
    having: Optional[Node]
    distinct: bool = False


@dataclasses.dataclass
class Query(Node):
    """select_core (set ops)* with optional CTEs, ORDER BY, LIMIT."""
    ctes: List[Tuple[str, Optional[List[str]], "Query"]]
    body: Node  # Select or SetExpr
    order_by: List[Tuple[Node, bool, Optional[bool]]]  # expr, asc, nulls_first
    limit: Optional[int]


@dataclasses.dataclass
class SetExpr(Node):
    kind: str  # union, intersect, except
    left: Node  # Select/SetExpr
    right: Node
    all: bool


# -- statements (DM / DDL) ---------------------------------------------------


@dataclasses.dataclass
class CreateView(Node):
    name: str
    query: Query
    temp: bool = True
    or_replace: bool = True


@dataclasses.dataclass
class CreateTableAs(Node):
    name: str
    query: Query


@dataclasses.dataclass
class InsertInto(Node):
    table: str
    query: Query


@dataclasses.dataclass
class DeleteFrom(Node):
    table: str
    where: Optional[Node]


@dataclasses.dataclass
class DropRel(Node):
    name: str
    kind: str  # view, table
    if_exists: bool = False
