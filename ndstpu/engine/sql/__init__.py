"""SQL frontend: lexer, AST, recursive-descent parser (Spark SQL dialect
subset covering the NDS query corpus and data-maintenance statements)."""

from ndstpu.engine.sql.parser import parse_statement, parse_statements  # noqa: F401
