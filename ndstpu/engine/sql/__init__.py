"""SQL frontend: lexer, AST, recursive-descent parser (Spark SQL dialect
subset covering the NDS query corpus and data-maintenance statements)."""

from ndstpu.engine.sql.parser import parse_statement, parse_statements  # noqa: F401


def normalize_sql_key(text: str) -> str:
    """Canonical cache-key form of a SQL statement: strip boundary
    comment lines (the stream files' ``-- start/end query`` markers)
    and the trailing semicolon.  The SAME query must key identically
    whether it arrived via direct template rendering (bench, warm) or
    a parsed stream file (power CLI) — a cosmetic difference silently
    missed every persisted compile record and re-ran eager discovery
    per query on the device."""
    lines = text.strip().splitlines()
    while lines and (lines[0].lstrip().startswith("--")
                     or not lines[0].strip()):
        lines.pop(0)
    while lines and (lines[-1].lstrip().startswith("--")
                     or not lines[-1].strip()):
        lines.pop()
    s = "\n".join(lines).strip()
    while s.endswith(";"):
        s = s[:-1].rstrip()
    return s
