"""SQL lexer: case-insensitive keywords, quoted identifiers, comments."""

from __future__ import annotations

import dataclasses
from typing import List


@dataclasses.dataclass
class Token:
    kind: str  # KW, IDENT, NUMBER, STRING, OP, EOF
    value: str
    pos: int

    def __repr__(self):
        return f"{self.kind}:{self.value}"


KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "as", "and", "or", "not", "in", "between", "like", "is", "null",
    "case", "when", "then", "else", "end", "cast", "join", "inner", "left",
    "right", "full", "outer", "cross", "on", "union", "intersect", "except",
    "all", "distinct", "exists", "with", "rollup", "cube", "grouping",
    "sets", "asc", "desc", "interval", "date", "over", "partition",
    "rows", "preceding", "following", "unbounded", "current", "row",
    "create", "table", "view", "temp", "temporary", "insert", "into",
    "delete", "drop", "values", "top", "any", "some", "semi", "anti",
    "nulls", "first", "last", "using", "replace", "if",
}

MULTI_OPS = ["<>", "<=", ">=", "!=", "||"]
SINGLE_OPS = "+-*/%(),.=<>;"


def tokenize(sql: str) -> List[Token]:
    toks: List[Token] = []
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c in " \t\r\n":
            i += 1
            continue
        if c == "-" and i + 1 < n and sql[i + 1] == "-":
            j = sql.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if c == "/" and i + 1 < n and sql[i + 1] == "*":
            j = sql.find("*/", i + 2)
            if j < 0:
                raise SyntaxError("unterminated block comment")
            i = j + 2
            continue
        if c == "'":
            j = i + 1
            buf = []
            while j < n:
                if sql[j] == "'" and j + 1 < n and sql[j + 1] == "'":
                    buf.append("'")
                    j += 2
                elif sql[j] == "'":
                    break
                else:
                    buf.append(sql[j])
                    j += 1
            if j >= n:
                raise SyntaxError(f"unterminated string at {i}")
            toks.append(Token("STRING", "".join(buf), i))
            i = j + 1
            continue
        if c == '"' or c == "`":
            close = c
            j = sql.find(close, i + 1)
            if j < 0:
                raise SyntaxError(f"unterminated quoted identifier at {i}")
            toks.append(Token("IDENT", sql[i + 1:j], i))
            i = j + 1
            continue
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            j = i
            seen_dot = seen_e = False
            while j < n:
                ch = sql[j]
                if ch.isdigit():
                    j += 1
                elif ch == "." and not seen_dot and not seen_e:
                    # ".." would be an error; a lone trailing dot ends number
                    seen_dot = True
                    j += 1
                elif ch in "eE" and not seen_e and j + 1 < n and (
                        sql[j + 1].isdigit() or sql[j + 1] in "+-"):
                    seen_e = True
                    j += 2
                else:
                    break
            toks.append(Token("NUMBER", sql[i:j], i))
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] == "_"):
                j += 1
            word = sql[i:j]
            if word.lower() in KEYWORDS:
                toks.append(Token("KW", word.lower(), i))
            else:
                toks.append(Token("IDENT", word, i))
            i = j
            continue
        two = sql[i:i + 2]
        if two in MULTI_OPS:
            toks.append(Token("OP", "<>" if two == "!=" else two, i))
            i += 2
            continue
        if c in SINGLE_OPS:
            toks.append(Token("OP", c, i))
            i += 1
            continue
        raise SyntaxError(f"unexpected character {c!r} at {i}")
    toks.append(Token("EOF", "", n))
    return toks
