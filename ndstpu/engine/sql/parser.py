"""Recursive-descent SQL parser (Spark SQL dialect subset).

Covers the constructs exercised by the NDS/TPC-DS query corpus and the
data-maintenance SQL: CTEs, set operations, derived tables, explicit and
comma joins, ROLLUP/CUBE/GROUPING SETS, window functions, CASE, CAST,
(NOT) IN / BETWEEN / LIKE / EXISTS, scalar subqueries, interval and date
literals, ORDER BY with NULLS FIRST/LAST and positional refs, LIMIT, and
the DM statements CREATE TEMP VIEW / CREATE TABLE AS / INSERT INTO /
DELETE FROM / DROP.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ndstpu.engine.sql import ast
from ndstpu.engine.sql.lexer import Token, tokenize


class Parser:
    def __init__(self, sql: str):
        self.toks = tokenize(sql)
        self.i = 0

    # -- token helpers -------------------------------------------------------

    def peek(self, ahead: int = 0) -> Token:
        return self.toks[min(self.i + ahead, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def at_kw(self, *kws: str) -> bool:
        t = self.peek()
        return t.kind == "KW" and t.value in kws

    def at_op(self, *ops: str) -> bool:
        t = self.peek()
        return t.kind == "OP" and t.value in ops

    def accept_kw(self, *kws: str) -> bool:
        if self.at_kw(*kws):
            self.next()
            return True
        return False

    def accept_op(self, *ops: str) -> bool:
        if self.at_op(*ops):
            self.next()
            return True
        return False

    def expect_kw(self, kw: str) -> None:
        if not self.accept_kw(kw):
            raise SyntaxError(f"expected {kw.upper()}, got {self.peek()}")

    def expect_op(self, op: str) -> None:
        if not self.accept_op(op):
            raise SyntaxError(f"expected {op!r}, got {self.peek()}")

    def expect_ident(self) -> str:
        t = self.next()
        if t.kind not in ("IDENT", "KW"):
            raise SyntaxError(f"expected identifier, got {t}")
        return t.value

    # -- statements ----------------------------------------------------------

    def parse_statement(self) -> ast.Node:
        if self.at_kw("create"):
            return self._create()
        if self.at_kw("insert"):
            return self._insert()
        if self.at_kw("delete"):
            return self._delete()
        if self.at_kw("drop"):
            return self._drop()
        return self.parse_query()

    def _create(self) -> ast.Node:
        self.expect_kw("create")
        or_replace = False
        if self.accept_kw("or"):
            self.expect_kw("replace")
            or_replace = True
        temp = self.accept_kw("temp") or self.accept_kw("temporary")
        if self.accept_kw("view"):
            name = self.expect_ident()
            self.expect_kw("as")
            return ast.CreateView(name, self.parse_query(), temp, or_replace)
        if self.accept_kw("table"):
            name = self.expect_ident()
            self.expect_kw("as")
            return ast.CreateTableAs(name, self.parse_query())
        raise SyntaxError(f"CREATE: expected VIEW or TABLE at {self.peek()}")

    def _insert(self) -> ast.Node:
        self.expect_kw("insert")
        self.expect_kw("into")
        if self.accept_kw("table"):
            pass  # Spark allows INSERT INTO TABLE t
        name = self.expect_ident()
        return ast.InsertInto(name, self.parse_query())

    def _delete(self) -> ast.Node:
        self.expect_kw("delete")
        self.expect_kw("from")
        name = self.expect_ident()
        where = None
        if self.accept_kw("where"):
            where = self.expr()
        return ast.DeleteFrom(name, where)

    def _drop(self) -> ast.Node:
        self.expect_kw("drop")
        kind = "view" if self.accept_kw("view") else (
            "table" if self.accept_kw("table") else None)
        if kind is None:
            raise SyntaxError("DROP: expected VIEW or TABLE")
        if_exists = False
        if self.accept_kw("if"):
            self.expect_kw("exists")
            if_exists = True
        return ast.DropRel(self.expect_ident(), kind, if_exists)

    # -- query ---------------------------------------------------------------

    def parse_query(self) -> ast.Query:
        ctes: List[Tuple[str, Optional[List[str]], ast.Query]] = []
        if self.accept_kw("with"):
            while True:
                name = self.expect_ident()
                col_aliases = None
                if self.at_op("("):
                    self.next()
                    col_aliases = [self.expect_ident()]
                    while self.accept_op(","):
                        col_aliases.append(self.expect_ident())
                    self.expect_op(")")
                self.expect_kw("as")
                self.expect_op("(")
                q = self.parse_query()
                self.expect_op(")")
                ctes.append((name, col_aliases, q))
                if not self.accept_op(","):
                    break
        body = self._set_expr()
        order_by = []
        if self.accept_kw("order"):
            self.expect_kw("by")
            order_by = self._order_list()
        limit = None
        if self.accept_kw("limit"):
            t = self.next()
            if t.kind != "NUMBER":
                raise SyntaxError(f"LIMIT expects number, got {t}")
            limit = int(t.value)
        return ast.Query(ctes, body, order_by, limit)

    def _order_list(self):
        out = []
        while True:
            e = self.expr()
            asc = True
            if self.accept_kw("asc"):
                asc = True
            elif self.accept_kw("desc"):
                asc = False
            nulls_first = None
            if self.accept_kw("nulls"):
                if self.accept_kw("first"):
                    nulls_first = True
                else:
                    self.expect_kw("last")
                    nulls_first = False
            out.append((e, asc, nulls_first))
            if not self.accept_op(","):
                break
        return out

    def _set_expr(self) -> ast.Node:
        left = self._select_core()
        while self.at_kw("union", "intersect", "except"):
            kind = self.next().value
            allf = self.accept_kw("all")
            if not allf:
                self.accept_kw("distinct")
            right = self._select_core()
            left = ast.SetExpr(kind, left, right, allf)
        return left

    def _select_core(self) -> ast.Node:
        if self.at_op("("):
            # parenthesized query body
            self.next()
            q = self.parse_query()
            self.expect_op(")")
            # a bare parenthesized query at set-op level: unwrap if trivial
            if not q.ctes and not q.order_by and q.limit is None:
                return q.body
            return ast.SubqueryRef(q, alias="__paren__")
        self.expect_kw("select")
        distinct = False
        if self.accept_kw("distinct"):
            distinct = True
        elif self.accept_kw("all"):
            pass
        if self.accept_kw("top"):
            # non-standard; tolerate TOP n as LIMIT
            t = self.next()
            _ = int(t.value)
        items = [self._select_item()]
        while self.accept_op(","):
            items.append(self._select_item())
        from_ = None
        if self.accept_kw("from"):
            from_ = self._from_clause()
        where = None
        if self.accept_kw("where"):
            where = self.expr()
        group = None
        if self.accept_kw("group"):
            self.expect_kw("by")
            group = self._group_spec()
        having = None
        if self.accept_kw("having"):
            having = self.expr()
        return ast.Select(items, from_, where, group, having, distinct)

    def _select_item(self) -> ast.SelectItem:
        if self.at_op("*"):
            self.next()
            return ast.SelectItem(ast.StarExpr(), None)
        # t.* ?
        if self.peek().kind in ("IDENT",) and self.peek(1).kind == "OP" and \
                self.peek(1).value == "." and self.peek(2).kind == "OP" and \
                self.peek(2).value == "*":
            t = self.next().value
            self.next()
            self.next()
            return ast.SelectItem(ast.StarExpr(t), None)
        e = self.expr()
        alias = None
        if self.accept_kw("as"):
            alias = self.expect_ident()
        elif self.peek().kind == "IDENT":
            alias = self.next().value
        return ast.SelectItem(e, alias)

    def _group_spec(self) -> ast.GroupSpec:
        if self.accept_kw("rollup"):
            self.expect_op("(")
            exprs = [self.expr()]
            while self.accept_op(","):
                exprs.append(self.expr())
            self.expect_op(")")
            return ast.GroupSpec(exprs, "rollup")
        if self.accept_kw("cube"):
            self.expect_op("(")
            exprs = [self.expr()]
            while self.accept_op(","):
                exprs.append(self.expr())
            self.expect_op(")")
            return ast.GroupSpec(exprs, "cube")
        if self.accept_kw("grouping"):
            self.expect_kw("sets")
            return self._grouping_sets([])
        exprs = [self.expr()]
        while self.accept_op(","):
            exprs.append(self.expr())
        if self.accept_kw("grouping"):
            self.expect_kw("sets")
            return self._grouping_sets(exprs)
        if self.accept_kw("with"):
            self.expect_kw("rollup")
            return ast.GroupSpec(exprs, "rollup")
        return ast.GroupSpec(exprs, "plain")

    def _grouping_sets(self, base: List[ast.Node]) -> ast.GroupSpec:
        self.expect_op("(")
        sets: List[List[ast.Node]] = []
        while True:
            if self.accept_op("("):
                one: List[ast.Node] = []
                if not self.at_op(")"):
                    one.append(self.expr())
                    while self.accept_op(","):
                        one.append(self.expr())
                self.expect_op(")")
                sets.append(one)
            else:
                sets.append([self.expr()])
            if not self.accept_op(","):
                break
        self.expect_op(")")
        # collect the union of grouping exprs as the key list
        exprs = list(base)
        for s in sets:
            for e in s:
                if not any(repr(e) == repr(x) for x in exprs):
                    exprs.append(e)
        return ast.GroupSpec(exprs, "sets", sets)

    # -- FROM ----------------------------------------------------------------

    def _from_clause(self) -> ast.Node:
        left = self._join_chain()
        while self.accept_op(","):
            right = self._join_chain()
            left = ast.JoinRef(left, right, "cross", None)
        return left

    def _join_chain(self) -> ast.Node:
        left = self._table_primary()
        while True:
            if self.accept_kw("cross"):
                self.expect_kw("join")
                right = self._table_primary()
                left = ast.JoinRef(left, right, "cross", None)
                continue
            kind = None
            if self.at_kw("join", "inner"):
                self.accept_kw("inner")
                self.expect_kw("join")
                kind = "inner"
            elif self.at_kw("left"):
                self.next()
                self.accept_kw("semi") and (kind := "semi")
                self.accept_kw("anti") and (kind := "anti")
                if kind is None:
                    self.accept_kw("outer")
                    kind = "left"
                self.expect_kw("join")
            elif self.at_kw("right"):
                self.next()
                self.accept_kw("outer")
                self.expect_kw("join")
                kind = "right"
            elif self.at_kw("full"):
                self.next()
                self.accept_kw("outer")
                self.expect_kw("join")
                kind = "full"
            else:
                break
            right = self._table_primary()
            cond = None
            if self.accept_kw("on"):
                cond = self.expr()
            left = ast.JoinRef(left, right, kind, cond)
        return left

    def _table_primary(self) -> ast.Node:
        if self.at_op("("):
            self.next()
            q = self.parse_query()
            self.expect_op(")")
            self.accept_kw("as")
            alias = self.expect_ident()
            col_aliases = None
            if self.at_op("("):
                self.next()
                col_aliases = [self.expect_ident()]
                while self.accept_op(","):
                    col_aliases.append(self.expect_ident())
                self.expect_op(")")
            return ast.SubqueryRef(q, alias, col_aliases)
        name = self.expect_ident()
        alias = None
        if self.accept_kw("as"):
            alias = self.expect_ident()
        elif self.peek().kind == "IDENT":
            alias = self.next().value
        return ast.TableRef(name, alias)

    # -- expressions ---------------------------------------------------------

    def expr(self) -> ast.Node:
        return self._or()

    def _or(self) -> ast.Node:
        left = self._and()
        while self.accept_kw("or"):
            left = ast.Bin("or", left, self._and())
        return left

    def _and(self) -> ast.Node:
        left = self._not()
        while self.accept_kw("and"):
            left = ast.Bin("and", left, self._not())
        return left

    def _not(self) -> ast.Node:
        if self.accept_kw("not"):
            return ast.Un("not", self._not())
        return self._predicate()

    def _predicate(self) -> ast.Node:
        left = self._additive()
        while True:
            negated = False
            if self.at_kw("not") and self.peek(1).kind == "KW" and \
                    self.peek(1).value in ("in", "between", "like", "exists"):
                self.next()
                negated = True
            if self.accept_kw("is"):
                neg = self.accept_kw("not")
                self.expect_kw("null")
                left = ast.IsNull(left, neg)
                continue
            if self.accept_kw("between"):
                lo = self._additive()
                self.expect_kw("and")
                hi = self._additive()
                left = ast.Between(left, lo, hi, negated)
                continue
            if self.accept_kw("like"):
                t = self.next()
                if t.kind != "STRING":
                    raise SyntaxError(f"LIKE expects string, got {t}")
                left = ast.LikeOp(left, t.value, negated)
                continue
            if self.accept_kw("in"):
                self.expect_op("(")
                if self.at_kw("select", "with"):
                    q = self.parse_query()
                    self.expect_op(")")
                    left = ast.InQuery(left, q, negated)
                else:
                    vals = [self._additive()]
                    while self.accept_op(","):
                        vals.append(self._additive())
                    self.expect_op(")")
                    left = ast.InVals(left, vals, negated)
                continue
            if self.at_op("=", "<>", "<", "<=", ">", ">="):
                op = self.next().value
                # ANY/SOME/ALL subquery comparison
                if self.at_kw("any", "some", "all"):
                    quant = self.next().value
                    self.expect_op("(")
                    q = self.parse_query()
                    self.expect_op(")")
                    left = ast.Bin(f"{op}_{quant}", left, ast.ScalarQuery(q))
                else:
                    left = ast.Bin(op, left, self._additive())
                continue
            break
        return left

    def _additive(self) -> ast.Node:
        left = self._multiplicative()
        while True:
            if self.at_op("+", "-"):
                op = self.next().value
                left = ast.Bin(op, left, self._multiplicative())
            elif self.at_op("||"):
                self.next()
                left = ast.Bin("||", left, self._multiplicative())
            else:
                break
        return left

    def _multiplicative(self) -> ast.Node:
        left = self._unary()
        while self.at_op("*", "/", "%"):
            op = self.next().value
            left = ast.Bin(op, left, self._unary())
        return left

    def _unary(self) -> ast.Node:
        if self.accept_op("-"):
            return ast.Un("neg", self._unary())
        if self.accept_op("+"):
            return self._unary()
        return self._primary()

    def _primary(self) -> ast.Node:
        t = self.peek()
        if t.kind == "NUMBER":
            self.next()
            if "." in t.value or "e" in t.value.lower():
                return ast.Lit(float(t.value))
            return ast.Lit(int(t.value))
        if t.kind == "STRING":
            self.next()
            return ast.Lit(t.value)
        if self.accept_kw("null"):
            return ast.Lit(None)
        if self.accept_kw("date"):
            s = self.next()
            if s.kind != "STRING":
                raise SyntaxError("DATE expects a string literal")
            return ast.DateLit(s.value)
        if self.accept_kw("interval"):
            v = self.next()
            if v.kind == "STRING":
                n = int(v.value)
            elif v.kind == "NUMBER":
                n = int(v.value)
            else:
                raise SyntaxError(f"INTERVAL expects number, got {v}")
            unit_tok = self.next()
            unit = unit_tok.value.lower().rstrip("s") + "s"
            if unit not in ("days", "months", "years"):
                raise SyntaxError(f"unsupported interval unit {unit_tok.value}")
            return ast.Interval(n, unit)
        if self.accept_kw("case"):
            return self._case()
        if self.accept_kw("cast"):
            self.expect_op("(")
            e = self.expr()
            self.expect_kw("as")
            type_name = self._type_name()
            self.expect_op(")")
            return ast.CastExpr(e, type_name)
        if self.accept_kw("exists"):
            self.expect_op("(")
            q = self.parse_query()
            self.expect_op(")")
            return ast.Exists(q, False)
        if self.at_op("("):
            self.next()
            if self.at_kw("select", "with"):
                q = self.parse_query()
                self.expect_op(")")
                return ast.ScalarQuery(q)
            e = self.expr()
            self.expect_op(")")
            return e
        if t.kind in ("IDENT", "KW"):
            # grouping(col) is a KW; allow KW-named functions
            name = self.next().value
            if self.at_op("("):
                return self._func_call(name)
            if self.accept_op("."):
                col = self.expect_ident()
                return ast.Col(name, col)
            lowered = name.lower()
            if lowered == "true":
                return ast.Lit(True)
            if lowered == "false":
                return ast.Lit(False)
            return ast.Col(None, name)
        raise SyntaxError(f"unexpected token {t}")

    def _type_name(self) -> str:
        base = self.expect_ident().lower()
        if self.at_op("("):
            self.next()
            args = [self.next().value]
            while self.accept_op(","):
                args.append(self.next().value)
            self.expect_op(")")
            return f"{base}({','.join(args)})"
        return base

    def _case(self) -> ast.Node:
        operand = None
        if not self.at_kw("when"):
            operand = self.expr()
        whens = []
        while self.accept_kw("when"):
            c = self.expr()
            self.expect_kw("then")
            v = self.expr()
            whens.append((c, v))
        default = None
        if self.accept_kw("else"):
            default = self.expr()
        self.expect_kw("end")
        return ast.CaseExpr(operand, whens, default)

    def _func_call(self, name: str) -> ast.Node:
        self.expect_op("(")
        distinct = False
        star = False
        args: List[ast.Node] = []
        if self.at_op("*"):
            self.next()
            star = True
        elif not self.at_op(")"):
            distinct = self.accept_kw("distinct")
            args.append(self.expr())
            while self.accept_op(","):
                args.append(self.expr())
        self.expect_op(")")
        fc = ast.FuncCall(name.lower(), args, distinct, star)
        if self.accept_kw("over"):
            self.expect_op("(")
            partition_by: List[ast.Node] = []
            order_by: List[Tuple[ast.Node, bool]] = []
            if self.accept_kw("partition"):
                self.expect_kw("by")
                partition_by.append(self.expr())
                while self.accept_op(","):
                    partition_by.append(self.expr())
            if self.accept_kw("order"):
                self.expect_kw("by")
                for e, asc, _nf in self._order_list():
                    order_by.append((e, asc))
            frame = None
            if self.at_kw("rows") or self.at_kw("range"):
                frame = self.peek().value.lower()
                self.next()
                # only the running frame the TPC-DS corpus uses (q51):
                #   BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW
                self.expect_kw("between")
                self.expect_kw("unbounded")
                self.expect_kw("preceding")
                self.expect_kw("and")
                self.expect_kw("current")
                self.expect_kw("row")
            self.expect_op(")")
            return ast.WindowCall(fc, partition_by, order_by, frame)
        return fc


def parse_statement(sql: str) -> ast.Node:
    p = Parser(sql)
    stmt = p.parse_statement()
    p.accept_op(";")
    if p.peek().kind != "EOF":
        raise SyntaxError(f"trailing tokens: {p.peek()}")
    return stmt


def parse_statements(sql: str) -> List[ast.Node]:
    """Split on top-level ';' and parse each statement."""
    p = Parser(sql)
    out: List[ast.Node] = []
    while p.peek().kind != "EOF":
        if p.accept_op(";"):
            continue
        out.append(p.parse_statement())
    return out
